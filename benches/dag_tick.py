"""Compiled-DAG tick microbench: the µs-scale execution path, A/B'd
against per-call actor task submission on the same box.

Three modes per chain length, all over REAL worker processes (multiprocess
cluster, same host):

- **task_path** — the per-call baseline: each tick submits one actor task
  per stage (spec encode → push → execute → result seal), chained by
  ObjectRef. What PRs 1–2 made fast; still a full control-plane round
  trip per stage per tick.
- **compiled_serial** — one resident compiled DAG, one tick in flight:
  ``execute(x).get()`` per tick. Measures the pure channel hand-off
  latency (no pipelining).
- **compiled_pipelined** — the steady-state shape: a sliding window of
  in-flight ticks keeps every stage busy, so per-tick wall time collapses
  to the bottleneck stage + channel cost. Run at the configured
  ``dag_channel_slots`` ring depth AND at ``slots=1`` (the old capacity-1
  seqlock channel) — the multi-slot ring is what lets >1 tick ride each
  edge, which is the whole burst-throughput win.

Usage:: python benches/dag_tick.py [--ticks 300] [--quick] [--round 1]
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

# Control-plane benchmark: always CPU (a wedged TPU tunnel must not hang
# the bench at jax init — see core_perf.py).
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["PALLAS_AXON_POOL_IPS"] = ""
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import ray_tpu  # noqa: E402
from ray_tpu.core import runtime as runtime_mod  # noqa: E402
from ray_tpu.core.cluster import Cluster, connect  # noqa: E402
from ray_tpu.dag import InputNode  # noqa: E402


def _percentiles(samples_s):
    samples_us = sorted(s * 1e6 for s in samples_s)
    n = len(samples_us)
    return (statistics.median(samples_us),
            samples_us[min(n - 1, int(n * 0.9))])


def _row(stages, mode, slots, samples_s, window=1):
    p50, p90 = _percentiles(samples_s)
    total = sum(samples_s)
    return {
        "metric": "dag_tick",
        "stages": stages,
        "mode": mode,
        "slots": slots,
        "window": window,
        "ticks": len(samples_s),
        "tick_us_p50": round(p50, 1),
        "tick_us_p90": round(p90, 1),
        "ticks_per_s": round(len(samples_s) / total, 1),
    }


def bench_chain(stages: int, ticks: int, slots_list) -> list:
    """All modes for one chain length inside one cluster (same workers)."""
    cluster = Cluster(num_nodes=1,
                      resources_per_node={"CPU": stages + 2})
    rows = []
    try:
        core = connect(cluster.gcs_address)
        try:
            @ray_tpu.remote
            class Echo:
                def apply(self, x):
                    return x

            # -- task path: per-call actor submission, chained refs ------
            actors = [Echo.remote() for _ in range(stages)]
            ray_tpu.get([a.apply.remote(0) for a in actors], timeout=120)
            samples = []
            for i in range(max(20, ticks // 4)):
                t0 = time.perf_counter()
                ref = i
                for a in actors:
                    ref = a.apply.remote(ref)
                ray_tpu.get(ref, timeout=60)
                samples.append(time.perf_counter() - t0)
            rows.append(_row(stages, "task_path", 0, samples))

            for slots in slots_list:
                dag_actors = [Echo.remote() for _ in range(stages)]
                ray_tpu.get([a.apply.remote(0) for a in dag_actors],
                            timeout=120)
                node = InputNode()
                for a in dag_actors:
                    node = a.apply.bind(node)
                compiled = node.experimental_compile(channel_slots=slots)
                try:
                    assert compiled.execute(-1).get(timeout=60) == -1  # warm
                    # -- serial: one tick in flight ----------------------
                    samples = []
                    for i in range(ticks):
                        t0 = time.perf_counter()
                        assert compiled.execute(i).get(timeout=60) == i
                        samples.append(time.perf_counter() - t0)
                    rows.append(_row(stages, "compiled_serial", slots,
                                     samples))
                    # -- pipelined: sliding window of in-flight ticks ----
                    # Window sized to the ring so submission never parks
                    # on a full pipeline (capacity-1 gets the widest
                    # window IT can sustain: one tick per edge).
                    window = max(2, min(16, slots * 2))
                    refs = [compiled.execute(i) for i in range(window)]
                    samples = []
                    for i in range(ticks):
                        t0 = time.perf_counter()
                        assert refs[0].get(timeout=60) == i
                        refs.pop(0)
                        refs.append(compiled.execute(window + i))
                        samples.append(time.perf_counter() - t0)
                    for r in refs:
                        r.get(timeout=60)
                    rows.append(_row(stages, "compiled_pipelined", slots,
                                     samples, window=window))
                finally:
                    compiled.teardown()
        finally:
            core.shutdown()
            runtime_mod._global_runtime = None
    finally:
        cluster.shutdown()
    return rows


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--ticks", type=int, default=300)
    parser.add_argument("--stages", default="2,4",
                        help="comma list of chain lengths")
    parser.add_argument("--quick", action="store_true",
                        help="CI smoke: one short 2-stage sweep")
    parser.add_argument("--round", type=int, default=0,
                        help="write BENCH_dag_rNN.json at repo root")
    args = parser.parse_args()
    from ray_tpu.core.config import config

    default_slots = int(config().dag_channel_slots)
    if args.quick:
        stage_list, ticks = [2], 40
        slots_per_chain = {2: [default_slots]}
    else:
        stage_list = [int(s) for s in args.stages.split(",")]
        ticks = args.ticks
        # The multi-slot-vs-capacity-1 burst A/B rides the LONGEST chain
        # (where pipelining matters most).
        slots_per_chain = {s: [default_slots] for s in stage_list}
        slots_per_chain[max(stage_list)] = [1, default_slots]
    results = []
    for stages in stage_list:
        for r in bench_chain(stages, ticks, slots_per_chain[stages]):
            r["cpus"] = os.cpu_count()
            print(json.dumps(r), flush=True)
            results.append(r)
    if args.round:
        path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            f"BENCH_dag_r{args.round:02d}.json")
        existing = []
        if os.path.exists(path):
            with open(path) as f:
                existing = json.load(f).get("results", [])
        with open(path, "w") as f:
            json.dump({"results": existing + results}, f, indent=1)
        print(f"wrote {path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
