"""Flash vs dense attention, forward + backward, on the real chip.

The long-context story: the Pallas kernels (block-512, O(L) memory) against
the XLA dense path (O(L²) memory) across sequence lengths. Measured v5e
results (B=4, H=12, D=64, bf16, causal):

    L=1024: flash fwd ~5.9ms  grad ~4.3ms  | dense fwd ~6.0ms  grad ~7.7ms
    L=2048: flash fwd ~6.7ms  grad ~7.6ms  | dense fwd ~11.6ms grad ~15.4ms
    L=4096: flash fwd ~15.7ms grad ~20.7ms | dense fwd ~24.4ms grad ~51.9ms

Also benches the paged-attention decode kernel (block-table-native, scalar
prefetch) against the gather reference that materializes the whole
``[S, max_len, H, D]`` cache per step — the serve-engine roofline story.

Prints one JSON line per sequence length / pool geometry. ``--quick`` runs
a single tiny geometry with 1 timed iteration as a CI smoke.
"""

from __future__ import annotations

import argparse
import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.ops.flash_attention import _dense_reference, flash_attention
from ray_tpu.ops.paged_attention import (paged_attention,
                                         paged_attention_reference)

B, H, D = 4, 12, 64


def _bench(fn, *args, iters=20):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters * 1e3


def bench_paged(quick: bool) -> None:
    """Paged decode attention: Pallas kernel vs gather reference.

    On CPU the kernel runs in interpret mode — absolute numbers are
    meaningless there (interpret is a correctness twin, not a perf path),
    so the gather row is the one to read; on TPU both rows are compiled
    and the speedup column is the roofline result.
    """
    on_tpu = jax.devices()[0].platform != "cpu"
    geoms = [(4, 8, 16)] if quick else (
        [(8, 16, 128), (16, 16, 128)] if on_tpu else [(4, 8, 32)])
    for S, nb_seq, bt in geoms:
        rng = np.random.default_rng(0)
        pool = S * nb_seq + 1  # + trash block 0
        q = jnp.asarray(rng.standard_normal((S, 1, H, D)), jnp.float32)
        k_pool = jnp.asarray(rng.standard_normal((pool, bt, H, D)),
                             jnp.float32)
        v_pool = jnp.asarray(rng.standard_normal((pool, bt, H, D)),
                             jnp.float32)
        tables = jnp.asarray(
            np.arange(1, S * nb_seq + 1, dtype=np.int32).reshape(S, nb_seq))
        lengths = jnp.asarray(
            np.full((S,), nb_seq * bt - 1, dtype=np.int32))
        kern = jax.jit(lambda *a: paged_attention(*a, interpret=not on_tpu))
        ref = jax.jit(paged_attention_reference)
        iters = 1 if quick else (20 if on_tpu else 3)
        rec = {
            "metric": f"paged_attention_s{S}_ctx{nb_seq * bt}",
            "kernel_ms": round(_bench(kern, q, k_pool, v_pool, tables,
                                      lengths, iters=iters), 2),
            "gather_ms": round(_bench(ref, q, k_pool, v_pool, tables,
                                      lengths, iters=iters), 2),
            "kernel_mode": "pallas" if on_tpu else "interpret",
            "platform": jax.devices()[0].platform,
        }
        if on_tpu:
            rec["speedup"] = round(rec["gather_ms"] / rec["kernel_ms"], 2)
        print(json.dumps(rec))


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--quick", action="store_true",
                    help="single tiny geometry, 1 iter — CI smoke")
    ap.add_argument("--skip-flash", action="store_true",
                    help="bench only the paged-attention rows")
    args = ap.parse_args()
    bench_paged(args.quick)
    if args.skip_flash or args.quick:
        return
    on_tpu = jax.devices()[0].platform != "cpu"
    seqs = (1024, 2048, 4096) if on_tpu else (256,)
    for L in seqs:
        ks = jax.random.split(jax.random.key(0), 3)
        q, k, v = (jax.random.normal(kk, (B, L, H, D), jnp.bfloat16) for kk in ks)
        g = jax.random.normal(jax.random.key(9), (B, L, H, D), jnp.bfloat16)
        interp = not on_tpu

        def loss_f(q, k, v):
            return jnp.sum(
                flash_attention(q, k, v, True, None, 512, 512, interp)
                .astype(jnp.float32) * g.astype(jnp.float32))

        def loss_d(q, k, v):
            return jnp.sum(
                _dense_reference(q, k, v, scale=D**-0.5, causal=True)
                .astype(jnp.float32) * g.astype(jnp.float32))

        fwd_f = jax.jit(lambda q, k, v: flash_attention(
            q, k, v, True, None, 512, 512, interp))
        fwd_d = jax.jit(lambda q, k, v: _dense_reference(
            q, k, v, scale=D**-0.5, causal=True))
        grad_f = jax.jit(jax.grad(loss_f, argnums=(0, 1, 2)))
        grad_d = jax.jit(jax.grad(loss_d, argnums=(0, 1, 2)))
        iters = 20 if on_tpu else 2
        rec = {
            "metric": f"flash_attention_seq{L}",
            "flash_fwd_ms": round(_bench(fwd_f, q, k, v, iters=iters), 2),
            "dense_fwd_ms": round(_bench(fwd_d, q, k, v, iters=iters), 2),
            "flash_grad_ms": round(_bench(grad_f, q, k, v, iters=iters), 2),
            "dense_grad_ms": round(_bench(grad_d, q, k, v, iters=iters), 2),
            "platform": jax.devices()[0].platform,
        }
        rec["fwd_speedup"] = round(rec["dense_fwd_ms"] / rec["flash_fwd_ms"], 2)
        rec["grad_speedup"] = round(rec["dense_grad_ms"] / rec["flash_grad_ms"], 2)
        print(json.dumps(rec))


if __name__ == "__main__":
    main()
