"""Core runtime microbenchmarks — the ray_perf analog.

Mirrors the reference's microbenchmark suite
(``python/ray/_private/ray_perf.py:93``, run by
``release/microbenchmark/run_microbenchmark.py``): trivial-task throughput,
actor-call latency/throughput (sync + pipelined), object put/get bandwidth,
and a multi-node broadcast — run against BOTH runtimes (the in-process
``Runtime`` and the multiprocess cluster) so control-plane cost is visible.

Writes one JSON line per metric and aggregates into
``BENCH_core_r{N}.json`` at the repo root when ``--round N`` is given.

Usage::

    python benches/core_perf.py [--round 3] [--quick]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

# Control-plane benchmark: always CPU. Overriding (not setdefault) matters —
# the TPU plugin's sitecustomize force-registers the axon platform and a
# wedged tunnel then hangs ANY jax.devices() call (this cost round 4 its
# headline number); the config re-pin defeats the sitecustomize override.
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["PALLAS_AXON_POOL_IPS"] = ""
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import ray_tpu


def timed(fn, *, repeat: int = 1):
    t0 = time.perf_counter()
    for _ in range(repeat):
        fn()
    return (time.perf_counter() - t0) / repeat


def bench_tasks(results: dict, n_seq: int, n_par: int) -> None:
    @ray_tpu.remote
    def nop():
        return None

    # Warmup: force worker spawns + lease acquisition out of the timing.
    ray_tpu.get([nop.remote() for _ in range(32)], timeout=300)

    t = timed(lambda: ray_tpu.get(nop.remote(), timeout=60), repeat=n_seq)
    results["task_seq_latency_us"] = round(t * 1e6, 1)
    results["task_seq_per_s"] = round(1.0 / t, 1)

    def burst():
        ray_tpu.get([nop.remote() for _ in range(n_par)], timeout=600)

    burst()  # warm leases for the burst width
    dt = timed(burst)
    results["task_throughput_per_s"] = round(n_par / dt, 1)


def bench_actors(results: dict, n_seq: int, n_par: int) -> None:
    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.x = 0

        def incr(self):
            self.x += 1
            return self.x

    c = Counter.remote()
    ray_tpu.get(c.incr.remote(), timeout=120)

    t = timed(lambda: ray_tpu.get(c.incr.remote(), timeout=60), repeat=n_seq)
    results["actor_call_latency_us"] = round(t * 1e6, 1)
    results["actor_call_per_s"] = round(1.0 / t, 1)

    def pipelined():
        ray_tpu.get([c.incr.remote() for _ in range(n_par)], timeout=600)

    pipelined()
    dt = timed(pipelined)
    results["actor_pipelined_per_s"] = round(n_par / dt, 1)

    @ray_tpu.remote
    class AsyncActor:
        async def hit(self):
            return 1

    a = AsyncActor.options(max_concurrency=32).remote()
    ray_tpu.get(a.hit.remote(), timeout=120)

    def async_burst():
        ray_tpu.get([a.hit.remote() for _ in range(n_par)], timeout=600)

    async_burst()
    dt = timed(async_burst)
    results["async_actor_per_s"] = round(n_par / dt, 1)


def bench_objects(results: dict, big_mb: int, n_small: int) -> None:
    big = np.random.default_rng(0).random(big_mb * 1024 * 1024 // 8)

    t0 = time.perf_counter()
    ref = ray_tpu.put(big)
    put_s = time.perf_counter() - t0
    results["put_gbps"] = round(big.nbytes / put_s / 1e9, 3)

    @ray_tpu.remote
    def touch(arr):
        return float(arr[0])  # forces a cross-process fetch of the buffer

    t0 = time.perf_counter()
    ray_tpu.get(touch.remote(ref), timeout=600)
    fetch_s = time.perf_counter() - t0
    results["object_fetch_gbps"] = round(big.nbytes / fetch_s / 1e9, 3)
    results["object_size_mb"] = big_mb
    del ref

    payload = b"x" * 1024
    t0 = time.perf_counter()
    refs = [ray_tpu.put(payload) for _ in range(n_small)]
    ray_tpu.get(refs, timeout=600)
    dt = time.perf_counter() - t0
    results["small_put_get_per_s"] = round(2 * n_small / dt, 1)


def bench_object_plane(results: dict, core, cluster, quick: bool) -> None:
    """Parallel object-plane read-path metrics (multiprocess runtime only):

    - ``get_batch_per_s``: one ``get([64 refs])`` where every ref is owned
      by another process (owner-served fetches) — the batched-get fan-out
      vs the serial per-ref loop.
    - ``multi_source_pull_gbps``: a 64 MB chunked pull with TWO replica
      daemons available — the multi-source stripe vs a single source.
    - ``seal_wakeup_latency_us``: time from a remote seal to get() return
      on a waiting consumer — location-push wakeup vs the poll backoff.
    """
    import threading

    from ray_tpu.core.ids import ObjectID
    from ray_tpu.core.object_ref import ObjectRef

    # -- batched multi-ref get -----------------------------------------------
    @ray_tpu.remote
    class Holder:
        def make(self, n, size):
            return [ray_tpu.put(os.urandom(size)) for _ in range(n)]

        def seal_after(self, oid_bytes, delay, size):
            from ray_tpu.core import serialization as _ser
            from ray_tpu.core.ids import ObjectID as _OID
            from ray_tpu.core.runtime import get_runtime

            payload = _ser.serialize(b"x" * size).to_bytes()
            time.sleep(delay)
            # Timestamp BEFORE the seal: the push can wake the waiter
            # before this method even returns from seal_payload (the
            # daemon note is one-way), so an after-seal stamp underflows.
            t_seal = time.monotonic()
            get_runtime().seal_payload(_OID(oid_bytes), payload)
            return t_seal

    holder = Holder.remote()
    n_refs = 64
    refs = ray_tpu.get(holder.make.remote(n_refs, 4096), timeout=120)
    reps = 10 if quick else 30

    def batch_get():
        # Values re-fetch from the owner each pass: drop the local cache.
        with core._cache_lock:
            for r in refs:
                core._cache.pop(r.id, None)
        ray_tpu.get(refs, timeout=120)

    batch_get()  # warm connections
    dt = timed(batch_get, repeat=reps)
    results["get_batch_per_s"] = round(n_refs / dt, 1)
    results["get_batch_latency_us"] = round(dt * 1e6, 1)

    # -- multi-source chunked pull -------------------------------------------
    mb = 64
    blob = np.random.default_rng(1).random(mb * 1024 * 1024 // 8)
    ref = ray_tpu.put(blob)
    origin = core._gcs_rpc.call("locate_object", ref.id.binary())[0][0]
    other = next(h for h in cluster.nodes if h.node_id != origin)

    @ray_tpu.remote(scheduling_strategy=ray_tpu.NodeAffinitySchedulingStrategy(
        node_id=other.node_id, soft=False))
    def replicate(refs):
        # Pull the object AND seal a replica on this node explicitly —
        # heap-fallback pulls don't auto-register new locations (only
        # shm-landing pulls do), and the bench needs a guaranteed second
        # source either way.
        from ray_tpu.core import serialization as _ser
        from ray_tpu.core.runtime import get_runtime

        value = ray_tpu.get(refs[0])
        get_runtime().seal_serialized(refs[0].id, _ser.serialize(value))
        return True

    ray_tpu.get(replicate.remote([ref]), timeout=600)
    deadline = time.time() + 60
    while (len(core._gcs_rpc.call("locate_object", ref.id.binary())) < 2
           and time.time() < deadline):
        time.sleep(0.2)
    n_srcs = len(core._gcs_rpc.call("locate_object", ref.id.binary()))

    def pull():
        with core._cache_lock:
            core._cache.pop(ref.id, None)
        ray_tpu.get(ref, timeout=600)

    pull()
    dt = timed(pull, repeat=2 if quick else 4)
    results["multi_source_pull_gbps"] = round(blob.nbytes / dt / 1e9, 3)
    results["multi_source_pull_sources"] = n_srcs
    del ref, blob

    # -- seal-to-wakeup latency ----------------------------------------------
    stats_fn = getattr(core, "get_stats", None)
    lat = []
    sleeps0 = stats_fn()["backoff_sleeps"] if stats_fn else 0
    for _ in range(5 if quick else 10):
        oid = ObjectID.for_put()
        seal_fut = holder.seal_after.remote(oid.binary(), 0.05, 256 * 1024)
        ray_tpu.get(ObjectRef(oid), timeout=60)
        t_ret = time.monotonic()
        t_seal = ray_tpu.get(seal_fut, timeout=60)
        lat.append(t_ret - t_seal)
    lat.sort()
    results["seal_wakeup_latency_us"] = round(lat[len(lat) // 2] * 1e6, 1)
    if stats_fn:
        s = stats_fn()
        results["get_backoff_sleeps"] = s["backoff_sleeps"] - sleeps0
        results["get_push_wakeups"] = s.get("push_wakeups", 0)


def bench_broadcast(results: dict, mb: int, n_nodes: int) -> None:
    """1-to-N object broadcast across node daemons (the reference's 1 GiB
    broadcast envelope row, release/benchmarks/README.md:17-19)."""
    blob = np.ones(mb * 1024 * 1024 // 8)
    ref = ray_tpu.put(blob)

    @ray_tpu.remote(scheduling_strategy=ray_tpu.SpreadSchedulingStrategy())
    def consume(arr):
        return float(arr.sum())

    # Warm the spread lease on every node first (with a TINY object, so the
    # payload itself is not pre-distributed): the timed pass must measure
    # the transfer plane, not interpreter spawns on nodes that have never
    # run a task (ray_perf warms the same way).
    warm = ray_tpu.put(np.ones(8))
    ray_tpu.get([consume.remote(warm) for _ in range(n_nodes)], timeout=600)
    del warm

    t0 = time.perf_counter()
    out = ray_tpu.get([consume.remote(ref) for _ in range(n_nodes)],
                      timeout=600)
    dt = time.perf_counter() - t0
    assert all(abs(v - blob.sum()) < 1e-6 for v in out)
    results["broadcast_mb"] = mb
    results["broadcast_nodes"] = n_nodes
    results["broadcast_gbps"] = round(n_nodes * blob.nbytes / dt / 1e9, 3)


# Regression floors for the multiprocess runtime on the 1-core CI box —
# the standing perf gate (VERDICT r3 #1). Values are deliberately below
# current measurements (put ~1.8-3.5 GB/s, broadcast ~0.3, actor ~550-850us
# depending on box load) so only real regressions trip them.
FLOORS = {
    "put_gbps": ("min", 1.0),
    # r5 zero-copy transfer lifted 4-node 64MB broadcast to ~1.0-1.4 GB/s;
    # the floor locks in a conservative slice of that (r4's was 0.15).
    "broadcast_gbps": ("min", 0.5),
    "object_fetch_gbps": ("min", 0.3),
    "small_put_get_per_s": ("min", 50_000),
    # Settled-box actor call measures ~280-550µs (PROFILE_NOTES.md); 700
    # trips on structural regressions while riding out 1-core box jitter.
    "actor_call_latency_us": ("max", 700.0),
    "task_seq_latency_us": ("max", 900.0),
}


# Floors that only hold with the native shm arena loaded: on containers
# where the store .so cannot load (glibc mismatch -> heap fallback), the
# zero-copy object plane is off and bandwidth collapses for EVERY build —
# gating on it would fail seed and candidate alike. They are reported as
# skipped (with the reason) instead of violated; the latency/throughput
# floors still gate.
SHM_DEPENDENT_FLOORS = {"put_gbps", "broadcast_gbps", "object_fetch_gbps"}


def check_floors(results: dict, shm_available: bool = True) -> list:
    violations = []
    skipped = []
    for key, (kind, bound) in FLOORS.items():
        if key not in results:
            continue
        if not shm_available and key in SHM_DEPENDENT_FLOORS:
            skipped.append(key)
            continue
        v = results[key]
        if (kind == "min" and v < bound) or (kind == "max" and v > bound):
            violations.append(f"{key}={v} violates {kind} {bound}")
    if skipped:
        results["floors_skipped_no_shm"] = skipped
    return violations


def run_suite(runtime: str, quick: bool) -> dict:
    results: dict = {"runtime": runtime}
    n_seq = 100 if quick else 300
    n_par = 500 if quick else 2000
    big_mb = 64 if quick else 256

    bench_tasks(results, n_seq, n_par)
    bench_actors(results, n_seq, n_par)
    bench_objects(results, big_mb, 200 if quick else 1000)
    if runtime == "multiprocess":
        bench_broadcast(results, 16 if quick else 64, 4)
    return results


def _settle(core, cluster, timeout: float = 120.0) -> None:
    """Wait for every daemon's prestarted workers to finish booting —
    interpreter spawns (~2s of imports each) otherwise steal the box's CPU
    mid-measurement and the bench reads as contention, not transport."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        stats = [core._daemons.get(h.address).call("stats", timeout=10)
                 for h in cluster.nodes]
        if all(s["idle"] >= 2 for s in stats):
            break
        time.sleep(1.0)
    time.sleep(2.0)


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--round", type=int, default=0)
    parser.add_argument("--quick", action="store_true")
    parser.add_argument("--runtime", choices=["local", "multiprocess", "both"],
                        default="both")
    args = parser.parse_args()

    all_results = []

    if args.runtime in ("local", "both"):
        ray_tpu.init(num_nodes=1)
        r = run_suite("local", args.quick)
        ray_tpu.shutdown()
        print(json.dumps(r), flush=True)
        all_results.append(r)

    if args.runtime in ("multiprocess", "both"):
        from ray_tpu.core import rpc as rpc_mod
        from ray_tpu.core.cluster import Cluster, connect

        cluster = Cluster(num_nodes=4, resources_per_node={"CPU": 2})
        core = connect(cluster.gcs_address)
        try:
            _settle(core, cluster)
            rpc_mod.reset_send_stats()  # measure the suite, not the boot
            r = run_suite("multiprocess", args.quick)
            bench_object_plane(r, core, cluster, args.quick)
            # Control-plane fast-path health: how many frames each sendmsg
            # carried (driver-side) and how often steady-state calls skipped
            # the task-spec template (see README "Control-plane performance").
            send = rpc_mod.send_stats()
            r["frames_per_syscall"] = round(send["frames_per_syscall"], 3)
            spec = core.spec_cache_stats()
            r["spec_cache_hit_rate"] = round(spec["hit_rate"], 4)
            stats = [core._daemons.get(h.address).call("node_stats",
                                                       timeout=10)
                     for h in cluster.nodes]
            shm_ok = any(s.get("store_capacity", 0) > 0 for s in stats)
            r["native_store"] = shm_ok
            violations = check_floors(r, shm_available=shm_ok)
            r["floors"] = {k: v[1] for k, v in FLOORS.items()}
            r["floor_violations"] = violations
            print(json.dumps(r), flush=True)
            all_results.append(r)
        finally:
            core.shutdown()
            cluster.shutdown()

    if args.round:
        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), f"BENCH_core_r{args.round:02d}.json")
        with open(path, "w") as f:
            json.dump({"results": all_results}, f, indent=1)
        print(f"wrote {path}")
    # The floor gate is only meaningful if it can FAIL the run.
    for r in all_results:
        if r.get("floor_violations"):
            print(f"FLOOR VIOLATIONS: {r['floor_violations']}",
                  file=sys.stderr)
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
