"""Eager-collective microbench: ring allreduce across actor processes.

Prints one JSON line per (world_size, MB) cell. The headline property of
the ring backend (vs the hub it replaced) is that per-rank traffic is
2*(N-1)/N * size — CONSTANT in world size — so on real multi-host
hardware wall time stays flat as N grows; on a single box total bytes
still grow with N, so compare `per_rank_mb_moved` (the scalable quantity)
alongside wall time.

Usage:: python benches/collectives_bench.py [--mb 16] [--worlds 2,4]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

# Host-collective benchmark: always CPU (see core_perf.py — a wedged TPU
# tunnel must not hang the control-plane benches at jax init).
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["PALLAS_AXON_POOL_IPS"] = ""
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import ray_tpu
from ray_tpu.core.cluster import Cluster, connect
from ray_tpu.core import runtime as runtime_mod


def bench_world(world: int, mb: int) -> dict:
    cluster = Cluster(num_nodes=1, resources_per_node={"CPU": world})
    try:
        core = connect(cluster.gcs_address)
        try:
            @ray_tpu.remote
            class Member:
                def __init__(self, rank, world):
                    from ray_tpu.parallel import collectives as c

                    c.init_collective_group(world, rank, backend="gloo",
                                            group_name="bench")
                    self.rank = rank

                def allreduce(self, mb, repeat):
                    from ray_tpu.parallel import collectives as c

                    arr = np.ones(mb * 1024 * 1024 // 8)
                    c.allreduce(arr, group_name="bench")  # warm
                    t0 = time.perf_counter()
                    for _ in range(repeat):
                        c.allreduce(arr, group_name="bench")
                    return (time.perf_counter() - t0) / repeat

            members = [Member.options(num_cpus=1).remote(r, world)
                       for r in range(world)]
            repeat = 3
            times = ray_tpu.get(
                [m.allreduce.remote(mb, repeat) for m in members],
                timeout=600)
            dt = max(times)
            size = mb * 1024 * 1024
            return {
                "metric": "ring_allreduce",
                "world": world,
                "mb": mb,
                "wall_s": round(dt, 4),
                "per_rank_mb_moved": round(2 * (world - 1) / world * mb, 2),
                "per_rank_gbps": round(2 * (world - 1) / world * size
                                       / dt / 1e9, 3),
            }
        finally:
            core.shutdown()
            runtime_mod._global_runtime = None
    finally:
        cluster.shutdown()


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--mb", type=int, default=16)
    parser.add_argument("--worlds", default="2,4")
    parser.add_argument("--round", type=int, default=0,
                        help="write BENCH_collectives_rNN.json at repo root")
    args = parser.parse_args()
    results = []
    for world in [int(w) for w in args.worlds.split(",")]:
        r = bench_world(world, args.mb)
        oob = os.environ.get("RAY_TPU_RPC_OOB", "1") != "0"
        shm = os.environ.get("RAY_TPU_COLLECTIVE_SHM", "1") != "0"
        r["transport"] = (("oob" if oob else "pickled") + "-socket"
                          + ("+shm" if shm else ""))
        print(json.dumps(r), flush=True)
        results.append(r)
    if args.round:
        path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            f"BENCH_collectives_r{args.round:02d}.json")
        existing = []
        if os.path.exists(path):
            with open(path) as f:
                existing = json.load(f).get("results", [])
        with open(path, "w") as f:
            json.dump({"results": existing + results}, f, indent=1)
        print(f"wrote {path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
