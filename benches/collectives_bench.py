"""Eager-collective microbench: allreduce across actor processes, per
topology and schedule.

Prints one JSON line per (world, nodes, hierarchy, MB) cell. Two schedules
are compared on the same box:

- **flat** (``collective_hierarchy_enabled=0``): the topology-blind ring —
  per-rank traffic is 2*(N-1)/N * size, constant in world size.
- **hier**: the two-level schedule — ranks sharing a node store reduce
  intra-node through shm at a leader, node leaders run the segmented
  pipelined ring (size/num_nodes bytes per node across the DCN analog),
  results fan back out by shm key.

``per_rank_gbps`` keeps the r05-comparable ring-algorithm definition
(2*(N-1)/N * size / wall) so rounds are comparable across rounds;
``cross_store_mb`` is the instrumented DCN-analog byte counter summed over
ranks — the quantity the hierarchy minimizes.

Usage:: python benches/collectives_bench.py [--mb 64] [--worlds 4]
            [--topos 1,2] [--quick] [--round 6]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

# Host-collective benchmark: always CPU (see core_perf.py — a wedged TPU
# tunnel must not hang the control-plane benches at jax init).
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["PALLAS_AXON_POOL_IPS"] = ""
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import ray_tpu  # noqa: E402
from ray_tpu.core.cluster import Cluster, connect  # noqa: E402
from ray_tpu.core import runtime as runtime_mod  # noqa: E402


def bench_world(world: int, mb: int, nodes: int = 1, hierarchy: bool = True,
                repeat: int = 3) -> dict:
    assert world % nodes == 0, (world, nodes)
    per_node = world // nodes
    cluster = Cluster(
        num_nodes=nodes, resources_per_node={"CPU": per_node},
        system_config={"collective_hierarchy_enabled": hierarchy})
    try:
        core = connect(cluster.gcs_address)
        try:
            @ray_tpu.remote
            class Member:
                def __init__(self, rank, world):
                    from ray_tpu.parallel import collectives as c

                    c.init_collective_group(world, rank, backend="gloo",
                                            group_name="bench")
                    self.rank = rank

                def allreduce(self, mb, repeat):
                    from ray_tpu.parallel import collectives as c

                    arr = np.ones(mb * 1024 * 1024 // 8)
                    c.allreduce(arr, group_name="bench")  # warm
                    stats0 = c.get_group_stats("bench")
                    t0 = time.perf_counter()
                    for _ in range(repeat):
                        c.allreduce(arr, group_name="bench")
                    dt = (time.perf_counter() - t0) / repeat
                    stats1 = c.get_group_stats("bench")
                    delta = {k: (stats1[k] - stats0[k]) / repeat
                             for k in stats1}
                    return dt, delta

            # Pin ranks CONTIGUOUSLY across nodes (rank r on node
            # r*nodes/world) so the store grouping is deterministic.
            members = []
            for r in range(world):
                node = cluster.nodes[r * nodes // world]
                members.append(Member.options(
                    num_cpus=1,
                    scheduling_strategy=ray_tpu.NodeAffinitySchedulingStrategy(
                        node_id=node.node_id)).remote(r, world))
            results = ray_tpu.get(
                [m.allreduce.remote(mb, repeat) for m in members],
                timeout=600)
            dt = max(t for t, _ in results)
            cross = sum(d.get("bytes_cross_store", 0) for _, d in results)
            hier_rounds = sum(d.get("hier_rounds", 0) for _, d in results)
            size = mb * 1024 * 1024
            return {
                "metric": "ring_allreduce",
                "world": world,
                "nodes": nodes,
                "topology": f"{nodes}x{per_node}",
                "hierarchy": bool(hierarchy and hier_rounds),
                "mb": mb,
                "wall_s": round(dt, 4),
                "per_rank_mb_moved": round(2 * (world - 1) / world * mb, 2),
                "per_rank_gbps": round(2 * (world - 1) / world * size
                                       / dt / 1e9, 3),
                "cross_store_mb": round(cross / 1e6, 2),
            }
        finally:
            core.shutdown()
            runtime_mod._global_runtime = None
    finally:
        cluster.shutdown()


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--mb", type=int, default=64)
    parser.add_argument("--worlds", default="4")
    parser.add_argument("--topos", default="1,2",
                        help="comma list of node counts per cell")
    parser.add_argument("--quick", action="store_true",
                        help="one small-size smoke per topology (CI: no "
                             "multi-hundred-MB sweeps)")
    parser.add_argument("--round", type=int, default=0,
                        help="write BENCH_collectives_rNN.json at repo root")
    args = parser.parse_args()
    oob = os.environ.get("RAY_TPU_RPC_OOB", "1") != "0"
    shm = os.environ.get("RAY_TPU_COLLECTIVE_SHM", "1") != "0"
    transport = (("oob" if oob else "pickled") + "-socket"
                 + ("+shm" if shm else ""))
    worlds = [int(w) for w in args.worlds.split(",")]
    topos = [int(t) for t in args.topos.split(",")]
    cells = []
    for world in worlds:
        for nodes in topos:
            if world % nodes:
                continue
            for hierarchy in (False, True):
                if args.quick and not hierarchy:
                    continue  # quick mode: one smoke per topology
                mb = 4 if args.quick else args.mb
                repeat = 1 if args.quick else 3
                cells.append((world, mb, nodes, hierarchy, repeat))
    results = []
    for world, mb, nodes, hierarchy, repeat in cells:
        r = bench_world(world, mb, nodes=nodes, hierarchy=hierarchy,
                        repeat=repeat)
        r["transport"] = transport
        print(json.dumps(r), flush=True)
        results.append(r)
    if args.round:
        path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            f"BENCH_collectives_r{args.round:02d}.json")
        existing = []
        if os.path.exists(path):
            with open(path) as f:
                existing = json.load(f).get("results", [])
        with open(path, "w") as f:
            json.dump({"results": existing + results}, f, indent=1)
        print(f"wrote {path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
