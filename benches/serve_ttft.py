"""Serve p50 TTFT + decode-rate benchmark (north-star metric #3).

A KV-cache LLM replica (``serve/llm.py``: bucketed prefill + cached decode)
served through the full data plane (handle → pow-2 router → replica actor),
measuring time-to-first-token and steady-state decode tokens/s of streaming
generate calls. Runs on whatever device is present (real TPU chip under the
driver; CPU elsewhere).

Prints one JSON line: {"metric": "serve_p50_ttft_ms", ...}
"""

from __future__ import annotations

import json
import time

import numpy as np


def main():
    import jax

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.models import transformer
    from ray_tpu.serve.llm import llm_deployment

    on_tpu = any(d.platform == "tpu" for d in jax.devices())
    cfg = (
        transformer.gpt2_small(max_seq_len=256)
        if on_tpu
        else transformer.tiny(max_seq_len=64)
    )

    LM = llm_deployment(
        cfg,
        lambda: transformer.init_params(cfg, jax.random.key(0)),
        name="LM",
        max_ongoing_requests=4,
    )

    ray_tpu.init()
    handle = serve.run(LM.bind())

    # measure TTFT + decode rate over sequential requests
    ttfts, decode_tps = [], 0.0
    n_new = 16 if on_tpu else 4
    for _ in range(20):
        t0 = time.perf_counter()
        stream = iter(handle.options(stream=True).remote(
            {"prompt_len": 16, "max_new_tokens": n_new}))
        next(stream)
        ttfts.append((time.perf_counter() - t0) * 1000)
        for item in stream:
            decode_tps = item["decode_tps"]
    p50 = float(np.percentile(ttfts, 50))
    p99 = float(np.percentile(ttfts, 99))

    # Device-side numbers (tunnel RTT excluded): what a colocated production
    # host sees. The e2e p50 above includes ~100ms of axon-tunnel round trip
    # on this rig (measured: a no-op jit result fetch costs ~110ms here).
    from ray_tpu.serve.llm import LLMEngine
    from ray_tpu.models import transformer as _t
    probe = LLMEngine(_t.init_params(cfg, jax.random.key(0)), cfg)
    probe.warmup()
    dev = probe.device_metrics(prompt_len=16)

    print(
        json.dumps(
            {
                "metric": "serve_p50_ttft_ms",
                "value": round(p50, 2),
                "unit": "ms",
                "p99_ms": round(p99, 2),
                "decode_tokens_per_sec_per_replica": decode_tps,
                **dev,
                "platform": "tpu" if on_tpu else "cpu",
            }
        )
    )
    serve.shutdown()
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
