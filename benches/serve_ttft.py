"""Serve p50 TTFT benchmark (north-star metric #3, BASELINE.json).

A JAX transformer replica served through the full data plane (handle →
pow-2 router → replica actor), measuring time-to-first-token of a streaming
generate call. Runs on whatever device is present (real TPU chip under the
driver; CPU elsewhere).

Prints one JSON line: {"metric": "serve_p50_ttft_ms", ...}
"""

from __future__ import annotations

import json
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.models import transformer

    on_tpu = any(d.platform == "tpu" for d in jax.devices())
    cfg = (
        transformer.gpt2_small(max_seq_len=256)
        if on_tpu
        else transformer.tiny(max_seq_len=64)
    )

    @serve.deployment(max_ongoing_requests=4)
    class LM:
        def __init__(self):
            self.cfg = cfg
            self.params = transformer.init_params(cfg, jax.random.key(0))

            def step(params, tokens):
                logits = transformer.forward(params, tokens, cfg)
                return jnp.argmax(logits[:, -1], axis=-1)

            self._step = jax.jit(step)
            # warm the cache so TTFT measures serving, not compilation
            t = jnp.zeros((1, cfg.max_seq_len), jnp.int32)
            np.asarray(self._step(self.params, t))

        def __call__(self, payload):
            # greedy generate: fixed-window resample (static shapes)
            prompt_len = int(payload.get("prompt_len", 16))
            n_new = int(payload.get("max_new_tokens", 8))
            tokens = np.zeros((1, self.cfg.max_seq_len), np.int32)
            tokens[0, :prompt_len] = 1
            for i in range(n_new):
                nxt = int(np.asarray(self._step(self.params, jnp.asarray(tokens)))[0])
                pos = min(prompt_len + i, self.cfg.max_seq_len - 1)
                tokens[0, pos] = nxt
                yield {"token": nxt, "index": i}

    ray_tpu.init()
    handle = serve.run(LM.bind())

    # measure TTFT over sequential requests
    ttfts = []
    for _ in range(20):
        t0 = time.perf_counter()
        stream = iter(handle.options(stream=True).remote({"prompt_len": 16, "max_new_tokens": 4}))
        next(stream)
        ttfts.append((time.perf_counter() - t0) * 1000)
        for _ in stream:
            pass
    p50 = float(np.percentile(ttfts, 50))
    p99 = float(np.percentile(ttfts, 99))
    print(
        json.dumps(
            {
                "metric": "serve_p50_ttft_ms",
                "value": round(p50, 2),
                "unit": "ms",
                "p99_ms": round(p99, 2),
                "platform": "tpu" if on_tpu else "cpu",
            }
        )
    )
    serve.shutdown()
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
