"""RL throughput bench — Podracer transports and inference placement.

Measures IMPALA env-steps/s over the 2x2 grid
{task path, DAG rollout lane} x {runner-local (Anakin), inference actor
(Sebulba)} at two scales, plus the LLM post-training smoke
(``rllib/llm_rl.py`` — mean reward must strictly improve under a fixed
seed). Results go to ``BENCH_rl_r01.json``.

Why the scale point looks the way it does: on the per-fragment task
path the driver pays ``ray_tpu.wait`` + an ObjectRef hop + a fresh
``sample.remote`` per fragment, and a weight broadcast is N
``set_weights`` RPCs (~1ms each: pickle + per-runner device_put). Both
costs scale with runner count and with fragment RATE, not with steps,
so many runners on short fragments is exactly where the lane transport
(one compiled-DAG tick, weights ride the tick payload) and the
inference pool (broadcast touches K actors, not N runners) earn their
keep. The small-scale row is the honesty check: at few runners on long
fragments the transports are near parity and the bench records that.

Configurations alternate A/B/A/B across repetitions so drift (thermal,
page cache, background load) hits every config equally; the recorded
number is the per-config median.

Usage:: python benches/rl_throughput.py [--quick] [--out PATH]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def cartpole():
    import gymnasium as gym

    return gym.make("CartPole-v1")


# (label, rollout_lanes, num_inference_actors)
GRID = [
    ("task_local", False, 0),
    ("task_infer", False, 1),
    ("lanes_local", True, 0),
    ("lanes_infer", True, 1),
]


def measure(num_runners: int, frag: int, envs: int, lanes: bool,
            infer: int, *, warmup: int, iters: int) -> dict:
    """One IMPALA run: build, warm, time ``iters`` train() calls, stop.
    Returns env-steps/s plus the learner-utilization split (sampling-bound
    time is ``learner_idle_s`` accumulated around the fragment wait)."""
    from ray_tpu.rllib import IMPALA, ImpalaConfig

    cfg = ImpalaConfig(env=cartpole, num_env_runners=num_runners,
                       num_envs_per_runner=envs,
                       rollout_fragment_length=frag, num_learners=0,
                       seed=0, rollout_lanes=lanes,
                       num_inference_actors=infer)
    algo = IMPALA(cfg)
    try:
        for _ in range(warmup):
            algo.train()
        idle = 0.0
        t0 = time.perf_counter()
        s0 = algo._timesteps
        for _ in range(iters):
            idle += algo.train()["learner_idle_s"]
        wall = time.perf_counter() - t0
        steps = algo._timesteps - s0
    finally:
        algo.stop()
    return {
        "env_steps_per_sec": steps / wall,
        "wall_s": wall,
        "learner_idle_s": idle,
        # Fraction of the iteration loop spent waiting for fragments —
        # the sampling-bound share. The remainder is learner + transport.
        "learner_idle_frac": idle / wall if wall > 0 else 0.0,
    }


def run_grid(num_runners: int, frag: int, envs: int, *, reps: int,
             warmup: int, iters: int) -> dict:
    results = {label: [] for label, _, _ in GRID}
    # A/B/A/B interleave: one full grid pass per rep.
    for rep in range(reps):
        for label, lanes, infer in GRID:
            r = measure(num_runners, frag, envs, lanes, infer,
                        warmup=warmup, iters=iters)
            results[label].append(r)
            print(json.dumps({"progress": label, "rep": rep,
                              "steps_per_sec":
                                  round(r["env_steps_per_sec"], 1)}),
                  flush=True)
    out = {"num_runners": num_runners, "fragment_length": frag,
           "envs_per_runner": envs}
    for label, runs in results.items():
        rates = sorted(r["env_steps_per_sec"] for r in runs)
        med = rates[len(rates) // 2]
        idle = sorted(r["learner_idle_frac"] for r in runs)[len(runs) // 2]
        out[label] = {"env_steps_per_sec_median": round(med, 1),
                      "env_steps_per_sec_all": [round(x, 1) for x in rates],
                      "learner_idle_frac_median": round(idle, 4)}
    out["speedup_lanes_infer_vs_task_local"] = round(
        out["lanes_infer"]["env_steps_per_sec_median"]
        / out["task_local"]["env_steps_per_sec_median"], 3)
    return out


def run_llm_rl(iters: int) -> dict:
    """LLM post-training smoke: fixed seed, mean sampled reward over the
    first third vs last third of iterations must strictly improve."""
    from ray_tpu.rllib import LLMRL, LLMRLConfig

    algo = LLMRL(LLMRLConfig(seed=0, num_generators=2))
    try:
        rewards = []
        for _ in range(iters):
            rewards.append(algo.train()["reward_mean"])
    finally:
        algo.stop()
    k = max(1, len(rewards) // 3)
    start, end = sum(rewards[:k]) / k, sum(rewards[-k:]) / k
    return {"iterations": iters,
            "reward_mean_first": round(start, 4),
            "reward_mean_last": round(end, 4),
            "reward_improved": bool(end > start),
            "rewards": [round(r, 4) for r in rewards]}


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--quick", action="store_true",
                        help="CI smoke: tiny grid, one rep, few iterations")
    parser.add_argument("--out", default=None,
                        help="output path (default: repo-root "
                             "BENCH_rl_r01.json)")
    args = parser.parse_args()

    import ray_tpu
    from ray_tpu.core.config import config as _cfg

    # Pool pacing at the measured sweet spot (see config.py doc comments):
    # flush quorum 4, window of roughly one env-step.
    _cfg().rl_inference_max_batch = 4
    _cfg().rl_inference_window_s = 0.0003
    ray_tpu.init(resources={"CPU": 64, "TPU": 8})
    try:
        if args.quick:
            scale = run_grid(4, 8, 4, reps=1, warmup=1, iters=2)
            small = None
            llm = run_llm_rl(4)
        else:
            # Headline scale point: many runners, short fragments — the
            # fragment-rate-bound regime the transports are for.
            scale = run_grid(16, 4, 8, reps=3, warmup=2, iters=5)
            # Parity check at modest scale.
            small = run_grid(4, 16, 8, reps=3, warmup=2, iters=5)
            llm = run_llm_rl(10)
    finally:
        ray_tpu.shutdown()

    payload = {"bench": "rl_throughput", "quick": args.quick,
               "scale": scale, "small": small, "llm_rl": llm}
    out_path = args.out or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "BENCH_rl_r01.json")
    if not args.quick:
        with open(out_path, "w") as f:
            json.dump(payload, f, indent=2)
    print(json.dumps({
        "bench": "rl_throughput", "quick": args.quick,
        "scale_speedup": scale["speedup_lanes_infer_vs_task_local"],
        "scale_task_local":
            scale["task_local"]["env_steps_per_sec_median"],
        "scale_lanes_infer":
            scale["lanes_infer"]["env_steps_per_sec_median"],
        "llm_reward_improved": llm["reward_improved"],
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
