"""PPO env-steps/sec benchmark (north-star metric #2, BASELINE.json).

CartPole PPO through the full stack (EnvRunner sampling + GAE + learner SGD
epochs), reporting end-to-end environment steps per second.

Prints one JSON line: {"metric": "ppo_env_steps_per_sec", ...}
"""

from __future__ import annotations

import json

import numpy as np


def main():
    import ray_tpu
    from ray_tpu.rllib import PPOConfig

    def cartpole():
        import gymnasium as gym

        return gym.make("CartPole-v1")

    ray_tpu.init()
    algo = (
        PPOConfig()
        .environment(cartpole)
        .env_runners(num_envs_per_env_runner=16)
        .training(
            rollout_fragment_length=128,
            num_epochs=2,
            minibatch_size=256,
            seed=0,
        )
        .build()
    )
    algo.train()  # warmup: jit compiles
    rates = []
    for _ in range(3):
        result = algo.train()
        rates.append(result["env_steps_per_sec"])
    algo.stop()
    ray_tpu.shutdown()
    print(
        json.dumps(
            {
                "metric": "ppo_env_steps_per_sec",
                "value": round(float(np.mean(rates)), 1),
                "unit": "env_steps/s",
                "last_return": round(float(result["episode_return_mean"]), 1),
            }
        )
    )


if __name__ == "__main__":
    main()
