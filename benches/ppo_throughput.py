"""RL throughput benchmarks (north-star metric #2, BASELINE.json).

Three lines of JSON:

- CartPole PPO through the full stack (EnvRunner sampling + GAE + learner
  SGD epochs) — end-to-end env steps/sec;
- Atari-style pixel PPO (conv RLModule; real ALE when installed, the
  synthetic Pong stand-in otherwise — ``rllib/envs.py``) — the north star's
  actual workload shape: conv inference per env step, pixel batches through
  the object plane, conv training on device;
- IMPALA async (V-trace, in-flight sampling) on the same pixel env.
"""

from __future__ import annotations

import json

import numpy as np


def _cartpole():
    import gymnasium as gym

    return gym.make("CartPole-v1")


def _atari():
    from ray_tpu.rllib.envs import make_atari

    return make_atari()


def _run(algo, iters=3):
    algo.train()  # warmup: jit compiles
    rates = []
    for _ in range(iters):
        result = algo.train()
        rates.append(result["env_steps_per_sec"])
    algo.stop()
    return rates, result


def main():
    import ray_tpu
    from ray_tpu.rllib import ImpalaConfig, PPOConfig

    ray_tpu.init()

    algo = (
        PPOConfig()
        .environment(_cartpole)
        .env_runners(num_envs_per_env_runner=16)
        .training(rollout_fragment_length=128, num_epochs=2,
                  minibatch_size=256, seed=0)
        .build()
    )
    rates, result = _run(algo)
    print(json.dumps({
        "metric": "ppo_env_steps_per_sec",
        "value": round(float(np.mean(rates)), 1),
        "unit": "env_steps/s",
        "last_return": round(float(result["episode_return_mean"]), 1),
    }))

    env_kind = "ale" if _is_ale() else "synthetic"
    algo = (
        PPOConfig()
        .environment(_atari)
        .env_runners(num_envs_per_env_runner=4)
        .training(rollout_fragment_length=32, num_epochs=1,
                  minibatch_size=128, hidden=(), seed=0)
        .build()
    )
    rates, result = _run(algo)
    print(json.dumps({
        "metric": "ppo_atari_env_steps_per_sec",
        "value": round(float(np.mean(rates)), 1),
        "unit": "env_steps/s",
        "env": env_kind,
    }))

    algo = (
        ImpalaConfig()
        .environment(_atari)
        .env_runners(num_env_runners=2, num_envs_per_env_runner=2)
        .training(rollout_fragment_length=32, seed=0)
        .build()
    )
    rates, result = _run(algo)
    print(json.dumps({
        "metric": "impala_atari_env_steps_per_sec",
        "value": round(float(np.mean(rates)), 1),
        "unit": "env_steps/s",
        "env": env_kind,
    }))
    ray_tpu.shutdown()


def _is_ale() -> bool:
    # Label by what make_atari ACTUALLY builds (it falls back to the
    # synthetic env on missing ROMs, not just missing packages).
    from ray_tpu.rllib.envs import SyntheticAtariEnv, make_atari

    probe = make_atari()
    try:
        return not isinstance(probe, SyntheticAtariEnv)
    finally:
        probe.close()


if __name__ == "__main__":
    main()
