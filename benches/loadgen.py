"""Open-loop, trace-shaped load harness for ray_tpu serve (ISSUE 13).

Drives a serve deployment with OPEN-LOOP traffic — arrivals fire on the
trace's clock, never gated on completions, so the harness measures how the
system degrades under offered load instead of politely backing off with it
(closed-loop generators hide overload; see the "coordinated omission"
literature). The trace generator is fully seeded and pure: the same
:class:`TraceConfig` always produces byte-identical request sequences.

Traffic shape:

- **Arrivals** — seeded Poisson, or a two-state Markov-modulated process
  (calm/burst) whose burst state multiplies the arrival rate.
- **Lengths** — heavy-tailed (clamped lognormal) prompt and output lengths.
- **Shared prefixes** — a fraction of requests lead with one of a small
  pool of common prefixes (system prompts), exercising prefix-affinity
  routing and KV reuse.
- **Multi-turn sessions** — a fraction of requests open sessions whose
  follow-up turns carry the full synthesized history; histories are baked
  at trace-build time so the generator stays open-loop and deterministic.
- **Tenants** — requests carry a tenant drawn from a weighted mix, feeding
  the per-tenant admission quotas (serve/admission.py).

Per request the harness records TTFT, TPOT, completion time and outcome
("ok" | "shed_saturated" | "shed_quota" | "error:<type>") straight off the
streaming contract, and emits p99-TTFT-vs-offered-load SLO curves for
{fixed-1-replica, fixed-N-replica, autoscaled} plus a tenant-isolation
A/B into ``BENCH_slo_r01.json``.

The default target is a **simulated** LLM deployment (sleep-per-token
engine with real slot/queue accounting and the real stream contract) so
the bench measures the serving layer — router, admission, autoscaling —
against a crisp, machine-independent capacity. The full data plane
(handle → router → replica actor → autoscaled controller) is real.

Usage:: python benches/loadgen.py [--quick] [--out PATH] [--seed N]
"""

from __future__ import annotations

import argparse
import json
import math
import os
import random
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# -- trace synthesis ----------------------------------------------------------


@dataclass
class TraceConfig:
    """Knobs for one synthetic trace. Everything derives from ``seed``."""

    seed: int = 0
    duration_s: float = 5.0
    rate_rps: float = 8.0
    arrival: str = "poisson"  # "poisson" | "bursty"
    # bursty: two-state Markov chain; burst state multiplies the rate.
    burst_factor: float = 4.0
    p_calm_to_burst: float = 0.05
    p_burst_to_calm: float = 0.2
    # clamped-lognormal lengths (heavy right tail)
    prompt_len_mu: float = math.log(24.0)
    prompt_len_sigma: float = 0.6
    prompt_len_min: int = 4
    prompt_len_max: int = 64
    output_len_mu: float = math.log(12.0)
    output_len_sigma: float = 0.7
    output_len_min: int = 2
    output_len_max: int = 32
    # shared-prefix mix (system prompts)
    shared_prefix_frac: float = 0.3
    prefix_pool: int = 4
    prefix_len: int = 16
    # multi-turn sessions: follow-ups carry the synthesized history
    multi_turn_frac: float = 0.15
    max_turns: int = 3
    turn_gap_s: float = 0.6
    history_cap_tokens: int = 128
    # tenant -> weight
    tenants: Dict[str, float] = field(
        default_factory=lambda: {"default": 1.0})
    vocab: int = 250


@dataclass
class TraceRequest:
    t: float  # arrival offset from trace start, seconds
    prompt_ids: List[int]
    max_new_tokens: int
    tenant: str
    session: str
    turn: int = 0


def _lognormal_int(rng: random.Random, mu: float, sigma: float,
                   lo: int, hi: int) -> int:
    return max(lo, min(hi, int(round(rng.lognormvariate(mu, sigma)))))


def synth_trace(cfg: TraceConfig) -> List[TraceRequest]:
    """Build the full request sequence for ``cfg``, sorted by arrival time.
    Pure function of the config (seeded RNG, no wall clock)."""
    rng = random.Random(cfg.seed)
    prefixes = [[rng.randrange(1, cfg.vocab + 1)
                 for _ in range(cfg.prefix_len)]
                for _ in range(cfg.prefix_pool)]
    names = list(cfg.tenants)
    weights = [cfg.tenants[n] for n in names]
    out: List[TraceRequest] = []
    t = 0.0
    bursting = False
    session = 0
    while True:
        rate = cfg.rate_rps * (cfg.burst_factor if bursting else 1.0)
        t += rng.expovariate(rate)
        if t >= cfg.duration_s:
            break
        if cfg.arrival == "bursty":
            flip = rng.random()
            bursting = (flip >= cfg.p_burst_to_calm if bursting
                        else flip < cfg.p_calm_to_burst)
        tenant = rng.choices(names, weights)[0]
        plen = _lognormal_int(rng, cfg.prompt_len_mu, cfg.prompt_len_sigma,
                              cfg.prompt_len_min, cfg.prompt_len_max)
        body = [rng.randrange(1, cfg.vocab + 1) for _ in range(plen)]
        if rng.random() < cfg.shared_prefix_frac:
            body = rng.choice(prefixes) + body
        nout = _lognormal_int(rng, cfg.output_len_mu, cfg.output_len_sigma,
                              cfg.output_len_min, cfg.output_len_max)
        session += 1
        sid = f"s{session}"
        out.append(TraceRequest(t, body, nout, tenant, sid, 0))
        if rng.random() < cfg.multi_turn_frac:
            # Follow-up turns: history = prior prompt + a SYNTHESIZED
            # assistant reply + the new user turn, baked now — the harness
            # never waits on a real response to build the next turn.
            history = list(body)
            tt = t
            for turn in range(1, rng.randint(2, cfg.max_turns)):
                tt += cfg.turn_gap_s * (0.5 + rng.random())
                if tt >= cfg.duration_s:
                    break
                reply = [rng.randrange(1, cfg.vocab + 1)
                         for _ in range(nout)]
                user = [rng.randrange(1, cfg.vocab + 1) for _ in range(
                    _lognormal_int(rng, cfg.prompt_len_mu,
                                   cfg.prompt_len_sigma,
                                   cfg.prompt_len_min, cfg.prompt_len_max))]
                history = (history + reply + user)[-cfg.history_cap_tokens:]
                nout = _lognormal_int(
                    rng, cfg.output_len_mu, cfg.output_len_sigma,
                    cfg.output_len_min, cfg.output_len_max)
                out.append(TraceRequest(tt, list(history), nout, tenant,
                                        sid, turn))
    out.sort(key=lambda r: r.t)
    return out


# -- simulated LLM deployment -------------------------------------------------


def sim_llm_deployment(name: str = "SIMLLM", *, slots: int = 4,
                       prefill_s_per_token: float = 0.0003,
                       decode_s_per_token: float = 0.02,
                       max_queue: Optional[int] = None,
                       **deployment_kwargs):
    """A serve deployment that behaves like the LLM engines — slot-bounded
    concurrency, bounded admission queue that sheds :class:`Saturated`,
    the real streaming contract, ``get_engine_stats`` feeding the
    controller, TTFT observed into the cluster rollup — but burns wall
    clock (``time.sleep`` per token, GIL released) instead of FLOPs. The
    serving layer under test is real; only the model is simulated."""
    from ray_tpu import serve
    from ray_tpu.core.config import config as knobs
    from ray_tpu.serve.errors import Saturated

    q_limit = int(max_queue if max_queue is not None
                  else knobs().serve_admission_queue_limit)
    deployment_kwargs.setdefault(
        "max_concurrency", slots + max(q_limit, 4) + 4)

    @serve.deployment(name=name, **deployment_kwargs)
    class SimLLM:
        def __init__(self):
            self._cv = threading.Condition(threading.Lock())
            self._busy = 0
            self._waiting = 0

        def __call__(self, payload):
            from ray_tpu.core.metrics_export import (metrics_enabled,
                                                     observe_shed,
                                                     serve_ttft_hist)

            prompt = payload.get("prompt_ids") or [1] * int(
                payload.get("prompt_len", 8))
            n = int(payload.get("max_new_tokens", 8))
            t0 = time.perf_counter()
            with self._cv:
                if q_limit and self._waiting >= q_limit:
                    observe_shed(name, "saturated")
                    raise Saturated(
                        f"engine {name}: {self._waiting} requests already "
                        f"waiting (serve_admission_queue_limit={q_limit})",
                        retry_after_s=self._waiting
                        * knobs().serve_retry_after_item_s)
                self._waiting += 1
                try:
                    while self._busy >= slots:
                        self._cv.wait(timeout=0.05)
                finally:
                    self._waiting -= 1
                self._busy += 1
            try:
                time.sleep(prefill_s_per_token * len(prompt))
                ttft = time.perf_counter() - t0
                if metrics_enabled():
                    serve_ttft_hist().observe(
                        ttft, {"deployment": name, "phase": "total"})
                for i in range(max(1, n)):
                    time.sleep(decode_s_per_token)
                    item = {"token": (i % 250) + 1, "index": i,
                            "decode_tps": round(1.0 / decode_s_per_token, 1)}
                    if i == max(1, n) - 1:
                        item["finish_reason"] = "stop"
                        item["ttft_s"] = ttft
                    yield item
            finally:
                with self._cv:
                    self._busy -= 1
                    self._cv.notify_all()

        def get_engine_stats(self):
            with self._cv:
                return {"slots_total": slots, "slots_busy": self._busy,
                        "queue_depth": self._waiting}

    return SimLLM


# -- open-loop runner ---------------------------------------------------------


def _classify(exc: BaseException):
    """Map a raised exception to an outcome, walking the cause chain (shed
    errors may arrive wrapped after the replica -> client hop)."""
    from ray_tpu.serve.errors import Saturated

    cur: Optional[BaseException] = exc
    while cur is not None:
        if isinstance(cur, Saturated):
            reason = "shed_quota" if cur.reason == "quota" \
                else "shed_saturated"
            return reason, cur.retry_after_s
        cur = cur.__cause__
    return f"error:{type(exc).__name__}", None


def run_trace(handle, trace: List[TraceRequest],
              join_timeout_s: float = 60.0) -> List[dict]:
    """Fire ``trace`` at ``handle`` open-loop: a scheduler walks arrivals
    on the wall clock and hands each request to its own worker thread —
    a slow or shedding server NEVER slows the offered load. Returns one
    record per request."""
    records: List[dict] = []
    lock = threading.Lock()
    threads: List[threading.Thread] = []
    start = time.perf_counter()

    def worker(req: TraceRequest) -> None:
        rec = {"t": req.t, "tenant": req.tenant, "turn": req.turn,
               "outcome": "ok", "ttft_s": None, "tpot_s": None,
               "total_s": None, "tokens": 0, "retry_after_s": None}
        t0 = time.perf_counter()
        try:
            first = None
            count = 0
            for item in handle.options(stream=True).remote(
                    {"prompt_ids": req.prompt_ids,
                     "max_new_tokens": req.max_new_tokens,
                     "tenant": req.tenant}):
                now = time.perf_counter()
                if first is None:
                    first = now - t0
                count += 1
                assert {"token", "index", "decode_tps"} <= set(item)
            total = time.perf_counter() - t0
            rec["ttft_s"] = first
            rec["total_s"] = total
            rec["tokens"] = count
            if count > 1 and first is not None:
                rec["tpot_s"] = (total - first) / (count - 1)
        except BaseException as exc:  # noqa: BLE001 — classified below
            rec["outcome"], rec["retry_after_s"] = _classify(exc)
        with lock:
            records.append(rec)

    for req in trace:
        delay = req.t - (time.perf_counter() - start)
        if delay > 0:
            time.sleep(delay)
        th = threading.Thread(target=worker, args=(req,), daemon=True)
        th.start()
        threads.append(th)
    deadline = time.perf_counter() + join_timeout_s
    for th in threads:
        th.join(timeout=max(0.0, deadline - time.perf_counter()))
    with lock:
        return list(records)


def _percentile(values: List[float], q: float) -> Optional[float]:
    if not values:
        return None
    vals = sorted(values)
    idx = min(len(vals) - 1, int(math.ceil(q / 100.0 * len(vals))) - 1)
    return vals[max(0, idx)]


def summarize(records: List[dict], slo_s: float,
              warmup_s: float = 0.0) -> dict:
    """Aggregate one load level. ``warmup_s`` drops requests that ARRIVED
    before it — steady-state measurement, standard warm-up exclusion (the
    autoscaled scenario needs a few seconds to react; the curve reports
    the system it scaled INTO, the raw shed counts still show the cost)."""
    measured = [r for r in records if r["t"] >= warmup_s]
    ok = [r for r in measured if r["outcome"] == "ok"
          and r["ttft_s"] is not None]
    ttfts = [r["ttft_s"] for r in ok]
    tpots = [r["tpot_s"] for r in ok if r["tpot_s"] is not None]
    n = len(measured)
    within = sum(1 for r in ok if r["ttft_s"] <= slo_s)
    shed_sat = sum(1 for r in measured
                   if r["outcome"] == "shed_saturated")
    shed_quota = sum(1 for r in measured if r["outcome"] == "shed_quota")
    errors = sorted({r["outcome"] for r in measured
                     if r["outcome"].startswith("error:")})
    return {
        "requests": n,
        "ok": len(ok),
        "shed_saturated": shed_sat,
        "shed_quota": shed_quota,
        "error_kinds": errors,
        "errors": sum(1 for r in measured
                      if r["outcome"].startswith("error:")),
        "ttft_p50_s": _percentile(ttfts, 50),
        "ttft_p99_s": _percentile(ttfts, 99),
        "tpot_p50_s": _percentile(tpots, 50),
        # SLO attainment over ALL offered requests: a shed request is a
        # missed SLO, not a excused one.
        "slo_attainment": (within / n) if n else None,
    }


# -- scenarios ----------------------------------------------------------------

SLOTS = 4
DECODE_S = 0.02
SLO_TTFT_S = 0.3
MAX_REPLICAS = 3
IDLE_TIMEOUT_S = 2.5


def _autoscaling_config():
    return {
        "min_replicas": 1, "max_replicas": MAX_REPLICAS,
        "target_ongoing_requests": float(SLOTS), "target_queue_depth": 2.0,
        "upscale_delay_s": 0.0, "downscale_delay_s": 1.0,
        "ttft_p99_slo_s": SLO_TTFT_S, "idle_timeout_s": IDLE_TIMEOUT_S,
        "hysteresis": 0.1,
    }


def _replica_count(name: str) -> int:
    import ray_tpu
    from ray_tpu.serve.controller import get_or_create_controller

    info = ray_tpu.get(
        get_or_create_controller().list_deployments.remote())
    return int(info.get(name, {}).get("num_replicas", 0))


def run_slo_curve(mode: str, rates: List[float], duration_s: float,
                  seed: int) -> dict:
    """One p99-TTFT-vs-offered-load curve: deploy the sim LLM in ``mode``
    ({fixed1, fixedN, autoscaled}) and sweep offered rates low -> high
    against the same deployment (the autoscaled run carries its scale
    between levels, like real traffic ramps do)."""
    from ray_tpu import serve

    dep_name = f"sim-{mode}"
    sim = sim_llm_deployment(dep_name, slots=SLOTS,
                             decode_s_per_token=DECODE_S)
    if mode == "fixed1":
        app = sim.options(num_replicas=1)
    elif mode == "fixedN":
        app = sim.options(num_replicas=MAX_REPLICAS)
    elif mode == "autoscaled":
        app = sim.options(num_replicas=1,
                          autoscaling_config=_autoscaling_config())
    else:
        raise ValueError(mode)
    handle = serve.run(app.bind(), name=mode)
    curve = []
    try:
        for i, rate in enumerate(rates):
            cfg = TraceConfig(seed=seed + i, rate_rps=rate,
                              duration_s=duration_s, arrival="bursty",
                              burst_factor=2.0)
            records = run_trace(handle, cfg_trace := synth_trace(cfg))
            level = summarize(records, SLO_TTFT_S,
                              warmup_s=duration_s * 0.5)
            level["offered_rps"] = rate
            level["offered_requests"] = len(cfg_trace)
            level["replicas_at_end"] = _replica_count(dep_name)
            curve.append(level)
        result = {"mode": mode, "curve": curve}
        if mode == "autoscaled":
            # Burst over: the deployment must fall back to min_replicas
            # within ~one idle timeout (plus signal/poll latency).
            t0 = time.perf_counter()
            budget = IDLE_TIMEOUT_S + 4.0
            while _replica_count(dep_name) > 1 \
                    and time.perf_counter() - t0 < budget:
                time.sleep(0.1)
            back_s = time.perf_counter() - t0
            result["scale_back_s"] = round(back_s, 2)
            result["scaled_back_to_min"] = _replica_count(dep_name) == 1
        return result
    finally:
        serve.shutdown()


def sustained_rps(curve: List[dict], attainment: float = 0.99) -> float:
    """Highest offered rate the system sustained at the SLO: p99-TTFT
    attainment over ALL offered requests >= ``attainment``."""
    best = 0.0
    for level in curve:
        att = level.get("slo_attainment")
        if att is not None and att >= attainment:
            best = max(best, level["offered_rps"])
    return best


def run_tenant_isolation(duration_s: float, seed: int) -> dict:
    """Quota A/B: tenant A offered far over its admission quota, tenant B
    in quota — B's SLO attainment must stay within 10% of B's solo run on
    the same deployment shape."""
    from ray_tpu import serve

    def deploy(tag: str):
        sim = sim_llm_deployment(f"sim-tenants-{tag}", slots=SLOTS,
                                 decode_s_per_token=DECODE_S)
        app = sim.options(num_replicas=2,
                          tenant_quotas={"A": 2.0, "*": 10_000.0})
        return serve.run(app.bind(), name=f"tenants-{tag}")

    b_cfg = TraceConfig(seed=seed + 100, rate_rps=6.0,
                        duration_s=duration_s,
                        tenants={"B": 1.0})
    a_cfg = TraceConfig(seed=seed + 200, rate_rps=12.0,
                        duration_s=duration_s,
                        tenants={"A": 1.0})

    handle = deploy("mixed")
    try:
        mixed_trace = sorted(synth_trace(b_cfg) + synth_trace(a_cfg),
                             key=lambda r: r.t)
        mixed = run_trace(handle, mixed_trace)
    finally:
        serve.shutdown()
    handle = deploy("solo")
    try:
        solo = run_trace(handle, synth_trace(b_cfg))
    finally:
        serve.shutdown()

    warm = duration_s * 0.25
    b_mixed = summarize([r for r in mixed if r["tenant"] == "B"],
                        SLO_TTFT_S, warmup_s=warm)
    a_mixed = summarize([r for r in mixed if r["tenant"] == "A"],
                        SLO_TTFT_S, warmup_s=warm)
    b_solo = summarize(solo, SLO_TTFT_S, warmup_s=warm)
    att_mixed = b_mixed["slo_attainment"] or 0.0
    att_solo = b_solo["slo_attainment"] or 0.0
    return {
        "tenant_b_mixed": b_mixed,
        "tenant_b_solo": b_solo,
        "tenant_a_mixed": a_mixed,
        "quota_sheds": a_mixed["shed_quota"],
        "b_attainment_delta": round(att_solo - att_mixed, 4),
        "isolation_within_10pct": att_mixed >= att_solo - 0.10,
    }


# -- entry point --------------------------------------------------------------

def run_all(quick: bool, seed: int) -> dict:
    if quick:
        rates, duration = [4.0, 8.0, 16.0], 5.0
    else:
        rates, duration = [4.0, 8.0, 12.0, 16.0, 24.0, 32.0], 10.0
    curves = {}
    for mode in ("fixed1", "fixedN", "autoscaled"):
        curves[mode] = run_slo_curve(mode, rates, duration, seed)
        print(json.dumps({"progress": mode,
                          "levels": len(curves[mode]["curve"])}),
              flush=True)
    tenants = run_tenant_isolation(duration, seed)

    f1 = sustained_rps(curves["fixed1"]["curve"])
    auto = sustained_rps(curves["autoscaled"]["curve"])
    unexplained = sum(level["errors"] for c in curves.values()
                      for level in c["curve"])
    unexplained += tenants["tenant_b_mixed"]["errors"] \
        + tenants["tenant_a_mixed"]["errors"] \
        + tenants["tenant_b_solo"]["errors"]
    acceptance = {
        "slo_ttft_s": SLO_TTFT_S,
        "fixed1_sustained_rps": f1,
        "fixedN_sustained_rps": sustained_rps(curves["fixedN"]["curve"]),
        "autoscaled_sustained_rps": auto,
        "autoscaled_vs_fixed1": round(auto / f1, 2) if f1 else None,
        "autoscaled_ge_1p5x_fixed1": bool(f1 and auto >= 1.5 * f1),
        "scale_back_s": curves["autoscaled"].get("scale_back_s"),
        "scaled_back_to_min": curves["autoscaled"].get(
            "scaled_back_to_min"),
        "quota_sheds": tenants["quota_sheds"],
        "tenant_isolation_within_10pct": tenants["isolation_within_10pct"],
        "unexplained_errors": unexplained,
    }
    return {"slo_curves": curves, "tenant_isolation": tenants,
            "acceptance": acceptance}


def check_schema(results: dict) -> None:
    """--quick smoke contract: the curve file has the promised shape and
    zero unexplained (non-shed) errors."""
    assert set(results) >= {"slo_curves", "tenant_isolation", "acceptance"}
    for mode in ("fixed1", "fixedN", "autoscaled"):
        curve = results["slo_curves"][mode]["curve"]
        assert curve, f"empty curve for {mode}"
        for level in curve:
            assert {"offered_rps", "ttft_p99_s", "slo_attainment",
                    "requests"} <= set(level)
    acc = results["acceptance"]
    assert acc["unexplained_errors"] == 0, \
        f"unexplained errors: {acc['unexplained_errors']}"
    assert acc["quota_sheds"] > 0, "quota scenario never shed"


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--quick", action="store_true",
                        help="small sweep + schema/zero-error smoke")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--out", default=None,
                        help="output path (default: repo-root "
                             "BENCH_slo_r01.json)")
    args = parser.parse_args()

    # Fresh rollups: the SLO loop's TTFT read needs sub-second exports.
    os.environ.setdefault("RAY_TPU_METRICS_EXPORT_INTERVAL_S", "0.5")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import ray_tpu

    ray_tpu.init()
    try:
        results = run_all(args.quick, args.seed)
    finally:
        ray_tpu.shutdown()
    if args.quick:
        check_schema(results)

    out = args.out or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "BENCH_slo_r01.json")
    with open(out, "w") as f:
        json.dump({"results": results}, f, indent=2)
    print(json.dumps({"bench": "slo_loadgen", "quick": args.quick,
                      **results["acceptance"]}), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
