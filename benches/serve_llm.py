"""Continuous-batching LLM serving benchmark (ISSUE 9 tentpole metric).

A/B of the slotted continuous-batching ``LLMEngine`` against the same engine
pinned to one slot (the batch-1 replica baseline it replaced): aggregate
tokens/s and client-observed p50/p99 TTFT at concurrency 1/4/16 on the same
box. Clients are threads issuing sequential streaming ``generate`` calls —
the same call pattern a Serve replica sees from its actor threads — so the
numbers include scheduler + admission overhead, not just device time.

``--quick`` is the serve smoke path: it additionally deploys the engine
through ``llm_deployment`` and streams concurrent requests over the full
data plane (handle → pow-2 router → replica), checking the streaming
response contract end to end.

Usage:: python benches/serve_llm.py [--quick] [--round 1]
"""

from __future__ import annotations

import argparse
import json
import os
import threading
import time
from typing import List

import numpy as np

PROMPT_LEN = 8
NEW_TOKENS = 48  # prompt bucket 16 + 48 decode == tiny max_seq_len 64


def _prompt(client: int, rep: int) -> List[int]:
    return [(client * 31 + rep * 7 + j) % 250 + 1 for j in range(PROMPT_LEN)]


def bench_engine(eng, concurrency: int, reps: int) -> dict:
    """Drive one engine with ``concurrency`` client threads, each streaming
    ``reps`` sequential requests; returns aggregate tokens/s + TTFT tails."""
    ttfts: List[float] = []
    counts = [0] * concurrency
    errors: List[BaseException] = []
    lock = threading.Lock()

    def client(i: int) -> None:
        try:
            for r in range(reps):
                t0 = time.perf_counter()
                first = None
                for _tok in eng.stream(_prompt(i, r),
                                       max_new_tokens=NEW_TOKENS):
                    if first is None:
                        first = time.perf_counter() - t0
                    counts[i] += 1
                with lock:
                    ttfts.append(first)
        except BaseException as e:  # noqa: BLE001 — surfaced below
            with lock:
                errors.append(e)

    threads = [threading.Thread(target=client, args=(i,), name=f"cli-{i}")
               for i in range(concurrency)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    if errors:
        raise errors[0]
    return {
        "requests": concurrency * reps,
        "tokens": sum(counts),
        "tokens_per_s": round(sum(counts) / wall, 1),
        "ttft_ms_p50": round(float(np.percentile(ttfts, 50)) * 1e3, 2),
        "ttft_ms_p99": round(float(np.percentile(ttfts, 99)) * 1e3, 2),
    }


def bench_modes(concurrencies, reps: int, slots: int, chunk: int) -> List[dict]:
    import jax

    from ray_tpu.models import transformer
    from ray_tpu.serve.llm import LLMEngine

    on_tpu = any(d.platform == "tpu" for d in jax.devices())
    cfg = (transformer.gpt2_small(max_seq_len=256) if on_tpu
           else transformer.tiny(max_seq_len=64))
    params = transformer.init_params(cfg, jax.random.key(0))

    results = []
    # max_queue=0: no admission shedding — the A/B measures throughput of
    # admitted work, and the baseline must accept the same request count.
    engines = {
        "batch1": LLMEngine(params, cfg, chunk=chunk, slots=1,
                            max_queue=0, name="bench-b1"),
        "continuous": LLMEngine(params, cfg, chunk=chunk, slots=slots,
                                max_queue=0, name="bench-cb"),
    }
    for eng in engines.values():
        eng.warmup()
    base_tps = {}
    for conc in concurrencies:
        for mode, eng in engines.items():
            row = {
                "metric": "serve_llm",
                "mode": mode,
                "slots": eng.slots,
                "chunk": chunk,
                "concurrency": conc,
                "new_tokens": NEW_TOKENS,
                **bench_engine(eng, conc, reps),
                "platform": "tpu" if on_tpu else "cpu",
            }
            if mode == "batch1":
                base_tps[conc] = row["tokens_per_s"]
            else:
                row["speedup_vs_batch1"] = round(
                    row["tokens_per_s"] / base_tps[conc], 2)
            print(json.dumps(row), flush=True)
            results.append(row)
    return results


def smoke_dataplane(concurrency: int = 4, reps: int = 2) -> dict:
    """Serve smoke: stream concurrent requests through the FULL data plane
    (handle → router → replica actor → engine) and check the contract."""
    import jax

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.models import transformer
    from ray_tpu.serve.llm import llm_deployment

    cfg = transformer.tiny(max_seq_len=64)
    LM = llm_deployment(
        cfg, lambda: transformer.init_params(cfg, jax.random.key(0)),
        name="LM", slots=4, chunk=4)

    ray_tpu.init()
    handle = serve.run(LM.bind())
    counts = [0] * concurrency
    errors: List[BaseException] = []

    def client(i: int) -> None:
        try:
            for r in range(reps):
                last = None
                for item in handle.options(stream=True).remote(
                        {"prompt_ids": _prompt(i, r), "max_new_tokens": 8}):
                    assert {"token", "index", "decode_tps"} <= set(item)
                    counts[i] += 1
                    last = item
                assert last is not None and "finish_reason" in last
        except BaseException as e:  # noqa: BLE001 — surfaced below
            errors.append(e)

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(concurrency)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    serve.shutdown()
    ray_tpu.shutdown()
    if errors:
        raise errors[0]
    row = {
        "metric": "serve_llm_dataplane_smoke",
        "concurrency": concurrency,
        "tokens": sum(counts),
        "tokens_per_s": round(sum(counts) / wall, 1),
        "ok": True,
    }
    print(json.dumps(row), flush=True)
    return row


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--quick", action="store_true",
                        help="CI smoke: short engine A/B + data-plane check")
    parser.add_argument("--reps", type=int, default=8,
                        help="sequential requests per client thread")
    parser.add_argument("--slots", type=int, default=8)
    parser.add_argument("--chunk", type=int, default=8)
    parser.add_argument("--round", type=int, default=0,
                        help="write BENCH_serve_rNN.json at repo root")
    args = parser.parse_args()

    if args.quick:
        results = bench_modes([4], reps=2, slots=4, chunk=args.chunk)
        results.append(smoke_dataplane())
    else:
        results = bench_modes([1, 4, 16], reps=args.reps,
                              slots=args.slots, chunk=args.chunk)

    if args.round:
        path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            f"BENCH_serve_r{args.round:02d}.json")
        existing = []
        if os.path.exists(path):
            with open(path) as f:
                existing = json.load(f).get("results", [])
        with open(path, "w") as f:
            json.dump({"results": existing + results}, f, indent=1)
        print(f"wrote {path}")
    return 0


if __name__ == "__main__":
    main()
