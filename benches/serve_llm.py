"""Continuous-batching LLM serving benchmark (ISSUE 9 + ISSUE 11 metrics).

Round 1 (ISSUE 9): A/B of the slotted continuous-batching ``LLMEngine``
against the same engine pinned to one slot (the batch-1 replica baseline it
replaced): aggregate tokens/s and client-observed p50/p99 TTFT at
concurrency 1/4/16 on the same box. Clients are threads issuing sequential
streaming ``generate`` calls — the same call pattern a Serve replica sees
from its actor threads — so the numbers include scheduler + admission
overhead, not just device time.

Round 2 (ISSUE 11): paged-vs-slotted A/B AT EQUAL SLOTS under prefix-heavy
traffic — the workload paged KV + prefix reuse targets:

- ``shared_prefix``: every request = one fixed system prefix (half the
  context) + a short unique user suffix. The paged engine prefills the
  prefix once and serves the rest from cache.
- ``multiturn``: each client runs N-turn conversations whose prompt is the
  full prior history; the paged engine re-prefills only the newest turn.

Paged rows record the measured cache hit rate; the headline metrics are
``speedup_tokens_vs_slotted`` and ``ttft_p50_speedup_vs_slotted``.

Round 4 (ISSUE 17): cluster-wide KV tier A/B on two engines sharing one
tier — cross-replica hit rate with the tier on vs the per-replica
baseline, cold-engine first-request TTFT from the store vs recompute for
a ≥4-block chain, and a mid-run drain migration (victim → survivor over
the KV handoff lane) with token-identical post-drain streams.

``--quick`` is the serve smoke path: a short A/B, a paged-engine COW-fork
smoke, a KV-tier spill/fetch/migrate round trip, and a deploy through
``llm_deployment`` streaming concurrent requests over the full data plane
(handle → pow-2 router → replica).

Usage:: python benches/serve_llm.py [--quick] [--round 2]
"""

from __future__ import annotations

import argparse
import json
import os
import threading
import time
from typing import List

import numpy as np

PROMPT_LEN = 8
NEW_TOKENS = 48  # prompt bucket 16 + 48 decode == tiny max_seq_len 64


def _prompt(client: int, rep: int) -> List[int]:
    return [(client * 31 + rep * 7 + j) % 250 + 1 for j in range(PROMPT_LEN)]


def bench_engine(eng, concurrency: int, reps: int) -> dict:
    """Drive one engine with ``concurrency`` client threads, each streaming
    ``reps`` sequential requests; returns aggregate tokens/s + TTFT tails."""
    ttfts: List[float] = []
    counts = [0] * concurrency
    errors: List[BaseException] = []
    lock = threading.Lock()

    def client(i: int) -> None:
        try:
            for r in range(reps):
                t0 = time.perf_counter()
                first = None
                for _tok in eng.stream(_prompt(i, r),
                                       max_new_tokens=NEW_TOKENS):
                    if first is None:
                        first = time.perf_counter() - t0
                    counts[i] += 1
                with lock:
                    ttfts.append(first)
        except BaseException as e:  # noqa: BLE001 — surfaced below
            with lock:
                errors.append(e)

    threads = [threading.Thread(target=client, args=(i,), name=f"cli-{i}")
               for i in range(concurrency)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    if errors:
        raise errors[0]
    return {
        "requests": concurrency * reps,
        "tokens": sum(counts),
        "tokens_per_s": round(sum(counts) / wall, 1),
        "ttft_ms_p50": round(float(np.percentile(ttfts, 50)) * 1e3, 2),
        "ttft_ms_p99": round(float(np.percentile(ttfts, 99)) * 1e3, 2),
    }


def bench_modes(concurrencies, reps: int, slots: int, chunk: int) -> List[dict]:
    import jax

    from ray_tpu.models import transformer
    from ray_tpu.serve.llm import LLMEngine

    on_tpu = any(d.platform == "tpu" for d in jax.devices())
    cfg = (transformer.gpt2_small(max_seq_len=256) if on_tpu
           else transformer.tiny(max_seq_len=64))
    params = transformer.init_params(cfg, jax.random.key(0))

    results = []
    # max_queue=0: no admission shedding — the A/B measures throughput of
    # admitted work, and the baseline must accept the same request count.
    engines = {
        "batch1": LLMEngine(params, cfg, chunk=chunk, slots=1,
                            max_queue=0, name="bench-b1"),
        "continuous": LLMEngine(params, cfg, chunk=chunk, slots=slots,
                                max_queue=0, name="bench-cb"),
    }
    for eng in engines.values():
        eng.warmup()
    base_tps = {}
    for conc in concurrencies:
        for mode, eng in engines.items():
            row = {
                "metric": "serve_llm",
                "mode": mode,
                "slots": eng.slots,
                "chunk": chunk,
                "concurrency": conc,
                "new_tokens": NEW_TOKENS,
                **bench_engine(eng, conc, reps),
                "platform": "tpu" if on_tpu else "cpu",
            }
            if mode == "batch1":
                base_tps[conc] = row["tokens_per_s"]
            else:
                row["speedup_vs_batch1"] = round(
                    row["tokens_per_s"] / base_tps[conc], 2)
            print(json.dumps(row), flush=True)
            results.append(row)
    return results


def _model(mid: bool = False):
    import jax

    from ray_tpu.models import transformer

    on_tpu = any(d.platform == "tpu" for d in jax.devices())
    if on_tpu:
        cfg = transformer.gpt2_small(max_seq_len=256)
    elif mid:
        # Prefix-reuse A/B needs prefill COMPUTE to dominate dispatch
        # overhead, or cached-prefix savings vanish into scheduler noise —
        # a mid-size config keeps CPU runs honest and fast enough.
        cfg = transformer.tiny(d_model=256, n_layers=4, n_heads=8,
                               d_ff=1024, max_seq_len=128)
    else:
        cfg = transformer.tiny(max_seq_len=64)
    return cfg, transformer.init_params(cfg, jax.random.key(0)), on_tpu


def bench_traffic(eng, traffic: str, concurrency: int, reps: int,
                  max_len: int) -> dict:
    """Prefix-heavy traffic generator: ``shared_prefix`` requests reuse one
    system prefix; ``multiturn`` conversations resend their full history
    each turn. Token ids stay within the tiny vocab (256)."""
    prefix = [(j * 13 + 5) % 250 + 1 for j in range(max_len // 2)]
    user_len = max(2, max_len // 16)
    turn_new = user_len + 2
    turns = 3
    ttfts: List[float] = []
    counts = [0] * concurrency
    errors: List[BaseException] = []
    lock = threading.Lock()

    def one(i: int, prompt: List[int], n: int) -> List[int]:
        t0 = time.perf_counter()
        first = None
        out = []
        for tok in eng.stream(prompt, max_new_tokens=n):
            if first is None:
                first = time.perf_counter() - t0
            out.append(tok)
            counts[i] += 1
        with lock:
            ttfts.append(first)
        return out

    def client(i: int) -> None:
        try:
            if traffic == "shared_prefix":
                sfx_len = max(2, max_len // 8)
                for r in range(reps):
                    sfx = [(i * 37 + r * 11 + j) % 250 + 1
                           for j in range(sfx_len)]
                    one(i, prefix + sfx, max_len // 8 + 4)
            else:  # multiturn
                short_prefix = prefix[:max_len // 4]
                for r in range(reps):
                    history = list(short_prefix)
                    for turn in range(turns):
                        history += [(i * 41 + r * 17 + turn * 5 + j) % 250 + 1
                                    for j in range(user_len)]
                        history += one(i, history, turn_new)
        except BaseException as e:  # noqa: BLE001 — surfaced below
            with lock:
                errors.append(e)

    threads = [threading.Thread(target=client, args=(i,), name=f"cli-{i}")
               for i in range(concurrency)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    if errors:
        raise errors[0]
    return {
        "requests": len(ttfts),
        "tokens": sum(counts),
        "tokens_per_s": round(sum(counts) / wall, 1),
        "ttft_ms_p50": round(float(np.percentile(ttfts, 50)) * 1e3, 2),
        "ttft_ms_p99": round(float(np.percentile(ttfts, 99)) * 1e3, 2),
    }


def bench_prefix_modes(concurrencies, reps: int, slots: int,
                       chunk: int) -> List[dict]:
    """ISSUE 11 A/B: paged (prefix cache + COW) vs slotted at EQUAL slots
    under shared-prefix and multi-turn traffic."""
    from ray_tpu.serve.llm import LLMEngine, PagedLLMEngine

    cfg, params, on_tpu = _model(mid=True)
    engines = {
        "slotted": LLMEngine(params, cfg, chunk=chunk, slots=slots,
                             max_queue=0, name="bench-slotted"),
        "paged": PagedLLMEngine(params, cfg, chunk=chunk, slots=slots,
                                max_queue=0, name="bench-paged"),
    }
    for eng in engines.values():
        eng.warmup()
    results = []
    for conc in concurrencies:
        for traffic in ("shared_prefix", "multiturn"):
            base = {}
            for mode, eng in engines.items():
                kv0 = eng.kv.stats() if mode == "paged" else None
                row = {
                    "metric": "serve_llm_prefix",
                    "mode": mode,
                    "traffic": traffic,
                    "slots": slots,
                    "chunk": chunk,
                    "concurrency": conc,
                    **bench_traffic(eng, traffic, conc, reps,
                                    cfg.max_seq_len),
                    "platform": "tpu" if on_tpu else "cpu",
                }
                if mode == "slotted":
                    base = row
                else:
                    kv1 = eng.kv.stats()
                    hit = kv1["kv_hit_tokens"] - kv0["kv_hit_tokens"]
                    miss = kv1["kv_miss_tokens"] - kv0["kv_miss_tokens"]
                    row["kv_hit_rate"] = round(hit / max(1.0, hit + miss), 3)
                    row["kv_cow_copies"] = kv1["kv_cow_copies"]
                    row["speedup_tokens_vs_slotted"] = round(
                        row["tokens_per_s"] / base["tokens_per_s"], 2)
                    row["ttft_p50_speedup_vs_slotted"] = round(
                        base["ttft_ms_p50"] / row["ttft_ms_p50"], 2)
                print(json.dumps(row), flush=True)
                results.append(row)
    return results


def _tpot_traffic(eng, concurrency: int, reps: int, new_tokens: int) -> dict:
    """Decode-heavy traffic: short prompts, long generations; returns
    tokens/s plus per-request TPOT (decode seconds / decode token)."""
    tpots: List[float] = []
    counts = [0] * concurrency
    errors: List[BaseException] = []
    lock = threading.Lock()

    def client(i: int) -> None:
        try:
            for r in range(reps):
                res: dict = {}
                for _tok in eng.stream(_prompt(i, r), max_new_tokens=new_tokens,
                                       result=res):
                    counts[i] += 1
                with lock:
                    if res.get("decode_tps"):
                        tpots.append(1e3 / res["decode_tps"])
        except BaseException as e:  # noqa: BLE001 — surfaced below
            with lock:
                errors.append(e)

    threads = [threading.Thread(target=client, args=(i,), name=f"cli-{i}")
               for i in range(concurrency)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    if errors:
        raise errors[0]
    return {
        "requests": concurrency * reps,
        "tokens": sum(counts),
        "tokens_per_s": round(sum(counts) / wall, 1),
        "tpot_ms_p50": round(float(np.percentile(tpots, 50)), 2),
        "tpot_ms_p99": round(float(np.percentile(tpots, 99)), 2),
    }


def bench_spec_modes(concurrency: int, reps: int, chunk: int,
                     slots: int = 4) -> List[dict]:
    """ISSUE 16 round 3: speculative decoding A/B on the paged engine,
    decode-heavy traffic, equal quality (greedy spec is token-identical
    to the baseline by construction — asserted below, not assumed).

    The aligned-family rows share ONE target model: the mid config with
    layers 1..3's residual output projections zeroed, so the whole stack
    computes exactly what its layer-0 slice computes while still paying
    4 layers of FLOPs — the draft (that 1-layer slice, sharing embeddings)
    then proposes what the target would have said, pinning acceptance at
    ~1.0. That isolates the SCHEDULING win (tokens per verify dispatch)
    from draft quality, which is model-dependent. The misaligned row uses
    a random 1-layer draft against the REAL 4-layer target to show the
    acceptance-EWMA gate demoting a useless draft back to ~baseline
    throughput instead of melting down.
    """
    import jax

    from ray_tpu.models import transformer
    from ray_tpu.serve.llm import PagedLLMEngine

    cfg, params, on_tpu = _model(mid=True)
    # Unscaled random inits collapse greedy decode onto a repeat-last-token
    # attractor, which would make ANY two models "agree" and fake high
    # acceptance; 3x scaling breaks the attractor so agreement is earned.
    params = jax.tree.map(lambda p: p * 3.0, params)
    draft_cfg = transformer.tiny(d_model=cfg.d_model, n_layers=1,
                                 n_heads=cfg.n_heads, d_ff=cfg.d_ff,
                                 max_seq_len=cfg.max_seq_len)

    def slice_draft(p):
        return {**{k: v for k, v in p.items() if k != "blocks"},
                "blocks": jax.tree.map(lambda a: a[:1], p["blocks"])}

    def zero_tail_layers(p):
        def z(path_key, a):
            if path_key in ("wo", "bo", "w_down", "b_down"):
                return a.at[1:].set(0.0)
            return a
        return {**p, "blocks": {k: z(k, v) for k, v in p["blocks"].items()}}

    aligned_target = zero_tail_layers(params)
    aligned_draft = slice_draft(aligned_target)
    random_draft = slice_draft(jax.tree.map(
        lambda p: p * 3.0, transformer.init_params(cfg, jax.random.key(99))))

    kw = dict(chunk=chunk, slots=slots, max_queue=0)
    results = []

    def decode_len(k: int) -> int:
        """Largest request length that (a) divides evenly into whole
        dispatches — a partially-used last dispatch still pays for the
        full ``chunk*(k+1)`` verify and would bill phantom compute to
        TPOT — and (b) keeps the spec headroom gate open to the end."""
        per = chunk * (k + 1)
        cap = cfg.max_seq_len - PROMPT_LEN - per
        return min(88, cap) // per * per

    def run(mode, target, extra_kw, base_row=None, **tags):
        k = extra_kw.get("spec_tokens", 0)
        new_tokens = decode_len(k)
        eng = PagedLLMEngine(target, cfg, name=f"bench-{mode}", **kw,
                             **extra_kw)
        eng.warmup()
        row = {
            "metric": "serve_llm_spec", "mode": mode, "slots": slots,
            "chunk": chunk, "concurrency": concurrency,
            "new_tokens": new_tokens, **tags,
            **_tpot_traffic(eng, concurrency, reps, new_tokens),
            "platform": "tpu" if on_tpu else "cpu",
        }
        if k:
            st = eng.stats()
            row["spec_accept_ratio"] = round(st["spec_accept_ratio"], 3)
        if base_row is not None:
            row["tpot_speedup_vs_baseline"] = round(
                base_row["tpot_ms_p50"] / row["tpot_ms_p50"], 2)
            row["tokens_speedup_vs_baseline"] = round(
                row["tokens_per_s"] / base_row["tokens_per_s"], 2)
            # Equal quality is an assertion, not a caption: greedy spec
            # must reproduce the baseline engine's tokens exactly.
            base_eng = PagedLLMEngine(target, cfg, name=f"chk-{mode}", **kw)
            a = base_eng.generate(_prompt(0, 0), max_new_tokens=new_tokens)
            b = eng.generate(_prompt(0, 0), max_new_tokens=new_tokens)
            assert a == b, f"{mode}: spec diverged from baseline"
            row["quality"] = "token_identical_greedy"
        print(json.dumps(row), flush=True)
        results.append(row)
        return row

    base = run("pr11_baseline", aligned_target, {})
    run("spec_off_draft_loaded", aligned_target,
        dict(draft_params=aligned_draft, draft_config=draft_cfg,
             spec_tokens=0), base)
    for k in (2, 4, 8):
        run(f"spec_on_k{k}", aligned_target,
            dict(draft_params=aligned_draft, draft_config=draft_cfg,
                 spec_tokens=k), base, draft_aligned=True, draft_layers=1)
    real_base = run("baseline_real_target", params, {})
    run("spec_misaligned_k4", params,
        dict(draft_params=random_draft, draft_config=draft_cfg,
             spec_tokens=4), real_base, draft_aligned=False, draft_layers=1)
    return results


def smoke_paged_cow() -> dict:
    """Quick smoke: the paged engine serves a conversation, then two COW
    forks of its retired tail decode independently."""
    from ray_tpu.serve.llm import PagedLLMEngine

    cfg, params, _on_tpu = _model()
    eng = PagedLLMEngine(params, cfg, chunk=4, slots=2, max_queue=0,
                         name="smoke-paged")
    eng.warmup()
    base = [(7 * j + 3) % 250 + 1 for j in range(12)]
    chain = base + eng.generate(base, max_new_tokens=6)
    forks = [eng.generate(chain + [50 + i, 51, 52], max_new_tokens=6)
             for i in range(2)]
    st = eng.kv.stats()
    assert st["kv_hit_tokens"] > 0, "forks missed the retired chain"
    assert st["kv_cow_copies"] >= 1, "no COW copy on tail fork"
    assert eng.kv.active_blocks() == 0, "blocks leaked after retire"
    assert forks[0] != forks[1] or forks[0], "fork outputs empty"
    row = {
        "metric": "serve_llm_paged_cow_smoke",
        "kv_hit_tokens": st["kv_hit_tokens"],
        "kv_cow_copies": st["kv_cow_copies"],
        "ok": True,
    }
    print(json.dumps(row), flush=True)
    return row


def smoke_jit_warmup() -> dict:
    """Quick smoke: jitcheck-instrumented warmup — report how many XLA
    compilations warmup pays and their wall seconds, then assert a warmed
    mixed burst compiles NOTHING (the steady-state contract the TPOT
    numbers above rest on)."""
    import jax

    from ray_tpu.devtools import jitcheck
    from ray_tpu.serve.llm import PagedLLMEngine

    was = jitcheck.installed()
    if not was:
        jitcheck.install()
    try:
        cfg, params, _on_tpu = _model()
        t0 = time.perf_counter()
        n0, s0 = jitcheck.total_compiles(), jitcheck.total_compile_seconds()
        eng = PagedLLMEngine(params, cfg, chunk=4, slots=2, max_queue=0,
                             name="smoke-jit")
        eng.warmup()
        warm_s = time.perf_counter() - t0
        warm_compiles = jitcheck.total_compiles() - n0
        warm_compile_s = jitcheck.total_compile_seconds() - s0
        for i in range(3):  # mixed burst: greedy + sampled, varied lengths
            eng.generate([(7 * j + i) % 250 + 1 for j in range(6 + 4 * i)],
                         max_new_tokens=5, temperature=0.0 if i % 2 else 0.7,
                         seed=i)
        steady_compiles = jitcheck.total_compiles() - n0 - warm_compiles
        assert steady_compiles == 0, (
            f"warmed engine compiled {steady_compiles}x in steady state")
        row = {
            "metric": "serve_llm_jit_warmup_smoke",
            "warmup_compiles": warm_compiles,
            "warmup_compile_s": round(warm_compile_s, 3),
            "warmup_wall_s": round(warm_s, 3),
            "steady_state_compiles": steady_compiles,
            "ok": True,
        }
        print(json.dumps(row), flush=True)
        return row
    finally:
        if not was:
            jitcheck.uninstall()


def smoke_dataplane(concurrency: int = 4, reps: int = 2) -> dict:
    """Serve smoke: stream concurrent requests through the FULL data plane
    (handle → router → replica actor → engine) and check the contract."""
    import jax

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.models import transformer
    from ray_tpu.serve.llm import llm_deployment

    cfg = transformer.tiny(max_seq_len=64)
    LM = llm_deployment(
        cfg, lambda: transformer.init_params(cfg, jax.random.key(0)),
        name="LM", slots=4, chunk=4)

    ray_tpu.init()
    handle = serve.run(LM.bind())
    counts = [0] * concurrency
    errors: List[BaseException] = []

    def client(i: int) -> None:
        try:
            for r in range(reps):
                last = None
                for item in handle.options(stream=True).remote(
                        {"prompt_ids": _prompt(i, r), "max_new_tokens": 8}):
                    assert {"token", "index", "decode_tps"} <= set(item)
                    counts[i] += 1
                    last = item
                assert last is not None and "finish_reason" in last
        except BaseException as e:  # noqa: BLE001 — surfaced below
            errors.append(e)

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(concurrency)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    serve.shutdown()
    ray_tpu.shutdown()
    if errors:
        raise errors[0]
    row = {
        "metric": "serve_llm_dataplane_smoke",
        "concurrency": concurrency,
        "tokens": sum(counts),
        "tokens_per_s": round(sum(counts) / wall, 1),
        "ok": True,
    }
    print(json.dumps(row), flush=True)
    return row


def bench_kv_tier_modes(reps: int, slots: int, chunk: int) -> List[dict]:
    """ISSUE 17 round 4: cluster-wide KV tier A/B on two engines sharing
    one tier (the in-process stand-in for two replicas + the object store).

    Three measurements:

    - **Hit rate** — a 2-turn session mix whose turn 2 lands on the OTHER
      engine (rebalanced routing, the cross-replica reuse case): with the
      tier off every cross hit is a full re-prefill (per-replica hit rate);
      with it on, turn 2 pulls the spilled chain from the store
      (cluster-wide hit rate).
    - **Cold-engine TTFT** — a chain ≥4 blocks long spilled by engine A;
      a COLD engine's first-request TTFT fetching it from the store vs a
      tier-less engine recomputing the same prefix (the warm-up headline:
      fetch must beat recompute when prefill compute dominates).
    - **Drain migration** — victim ships its chains over the handoff lane
      to the survivor mid-run and retires; the survivor's turn-2 streams
      are asserted TOKEN-IDENTICAL to the victim's own (pre-drain) output
      and attribute their hits to ``migrated``.
    """
    import jax  # noqa: F401 — device probe via _model

    from ray_tpu.core.config import Config, set_config
    from ray_tpu.core.config import config as get_config
    from ray_tpu.serve import kv_tier
    from ray_tpu.serve.llm import PagedLLMEngine

    cfg, params, on_tpu = _model(mid=True)
    bt = int(get_config().serve_kv_block_tokens)
    prev_cfg = get_config()
    results: List[dict] = []
    platform = "tpu" if on_tpu else "cpu"

    def mk(name: str) -> PagedLLMEngine:
        eng = PagedLLMEngine(params, cfg, chunk=chunk, slots=slots,
                             max_queue=0, name=name)
        eng.warmup()
        return eng

    def timed_stream(eng, prompt, n):
        t0 = time.perf_counter()
        first = None
        toks = []
        for tok in eng.stream(list(prompt), max_new_tokens=n):
            if first is None:
                first = time.perf_counter() - t0
            toks.append(tok)
        return toks, first

    def session_mix(tier_on: bool) -> dict:
        kv_tier.reset_local_backend()
        set_config(Config({"kv_tier_enabled": tier_on}))
        a, b = mk("hit-a"), mk("hit-b")
        n_sessions = 2 * max(2, reps // 2)
        t1_len = 5 * bt // 2  # 2 full blocks + half a block of turn 1
        hist = []
        for i in range(n_sessions):
            p = [(i * 17 + j * 3) % 250 + 1 for j in range(t1_len)]
            eng = a if i % 2 == 0 else b
            hist.append(list(p) + eng.generate(list(p), max_new_tokens=8))
        ttfts = []
        for i, h in enumerate(hist):
            eng = b if i % 2 == 0 else a  # turn 2 on the OTHER replica
            _toks, first = timed_stream(eng, h + [9, 9], 8)
            ttfts.append(first)
        hit = miss = store = 0.0
        for eng in (a, b):
            st = eng.kv.stats()
            hit += st["kv_hit_tokens"]
            miss += st["kv_miss_tokens"]
            if tier_on:
                es = eng.stats()
                store += es["kv_tier_hits_store"]
                store += es["kv_tier_hits_migrated"]
        spilled = sum(e.stats().get("kv_tier_spilled_blocks", 0.0)
                      for e in (a, b)) if tier_on else 0.0
        a.close()
        b.close()
        return {
            "metric": "serve_llm_kv_tier_hit_rate",
            "mode": "cluster_tier" if tier_on else "per_replica",
            "sessions": n_sessions, "slots": slots, "chunk": chunk,
            # Cluster-wide rate counts store/migrated-sourced tokens as
            # hits; the per-replica baseline can only count local ones.
            "hit_rate": round((hit + store) / max(1.0, hit + miss), 3),
            "kv_tier_hit_tokens": store,
            "kv_tier_spilled_blocks": spilled,
            "ttft_ms_p50_turn2": round(
                float(np.percentile(ttfts, 50)) * 1e3, 2),
            "platform": platform,
        }

    try:
        base = session_mix(tier_on=False)
        tier = session_mix(tier_on=True)
        if base["hit_rate"] > 0:
            tier["hit_rate_vs_per_replica"] = round(
                tier["hit_rate"] / base["hit_rate"], 2)
        assert tier["hit_rate"] > base["hit_rate"], \
            "cluster tier did not beat the per-replica hit rate"
        for row in (base, tier):
            print(json.dumps(row), flush=True)
            results.append(row)

        # -- cold-engine warm-up: store fetch vs recompute, chain >= 4
        # blocks. Model sized so prefill COMPUTE dominates the fixed
        # per-request cost (decode chunk + scheduling) — the regime the
        # warm-up path targets; a toy config would drown the prefill
        # saving in dispatch noise.
        from ray_tpu.models import transformer

        cold_cfg = transformer.tiny(d_model=384, n_layers=6, n_heads=8,
                                    d_ff=1536, max_seq_len=256)
        cold_params = transformer.init_params(cold_cfg, jax.random.key(0))
        chain_blocks = 8
        long_p = [(j * 11 + 7) % 250 + 1
                  for j in range(chain_blocks * bt + 4)]

        def mk_cold(name: str) -> PagedLLMEngine:
            # Two buckets only: the full-prompt one (recompute pays it)
            # and the short-suffix one (the fetch path's prefill).
            eng = PagedLLMEngine(cold_params, cold_cfg, chunk=2, slots=2,
                                 max_queue=0, name=name,
                                 prompt_buckets=(16, 256))
            eng.warmup()
            return eng

        kv_tier.reset_local_backend()
        set_config(Config({"kv_tier_enabled": True}))
        warm = mk_cold("cold-src")
        out_warm = warm.generate(list(long_p), max_new_tokens=8)
        fetch_ttfts, recompute_ttfts = [], []
        n_rounds = max(2, min(4, reps // 2))
        for r in range(n_rounds):
            cold = mk_cold(f"cold-fetch-{r}")
            toks, first = timed_stream(cold, long_p, 8)
            assert toks == out_warm, "store-fetched decode diverged"
            assert cold.stats()["kv_tier_hits_store"] >= chain_blocks * bt, \
                "cold engine did not fetch the spilled chain"
            cold.close()
            fetch_ttfts.append(first)
        set_config(Config({"kv_tier_enabled": False}))
        for r in range(n_rounds):
            cold = mk_cold(f"cold-recompute-{r}")
            toks, first = timed_stream(cold, long_p, 8)
            assert toks == out_warm, "recompute decode diverged"
            cold.close()
            recompute_ttfts.append(first)
        set_config(Config({"kv_tier_enabled": True}))
        warm.close()
        fetch_ms = round(float(np.percentile(fetch_ttfts, 50)) * 1e3, 2)
        recompute_ms = round(
            float(np.percentile(recompute_ttfts, 50)) * 1e3, 2)
        row = {
            "metric": "serve_llm_kv_tier_cold_ttft",
            "chain_blocks": chain_blocks, "prompt_tokens": len(long_p),
            "ttft_ms_p50_store_fetch": fetch_ms,
            "ttft_ms_p50_recompute": recompute_ms,
            "fetch_speedup_vs_recompute": round(recompute_ms / fetch_ms, 2),
            "platform": platform,
        }
        print(json.dumps(row), flush=True)
        results.append(row)

        # -- drain migration: victim -> survivor over the handoff lane
        kv_tier.reset_local_backend()
        victim, survivor = mk("drain-victim"), mk("drain-survivor")
        n_sessions = 4
        t1_len = 3 * bt
        hist, baseline_t2 = [], []
        for i in range(n_sessions):
            p = [(i * 13 + j * 5) % 250 + 1 for j in range(t1_len)]
            h = list(p) + victim.generate(list(p), max_new_tokens=8)
            hist.append(h)
        for h in hist:  # the victim's own turn 2: the identity baseline
            baseline_t2.append(victim.generate(h + [9, 9],
                                               max_new_tokens=8))
        got: dict = {}
        th = threading.Thread(target=lambda: got.setdefault(
            "n", survivor.kv_migrate_in("bench-kvdrain")))
        th.start()
        sent = victim.kv_migrate_out("bench-kvdrain")
        th.join()
        victim.close()  # retire AFTER the chains shipped
        assert sent >= 1 and got.get("n", 0) >= 1, "drain moved no chains"
        ttfts = []
        for i, h in enumerate(hist):
            toks, first = timed_stream(survivor, h + [9, 9], 8)
            assert toks == baseline_t2[i], \
                "post-drain stream diverged from the victim's own output"
            ttfts.append(first)
        mig_hits = survivor.stats()["kv_tier_hits_migrated"]
        assert mig_hits > 0, "survivor attributed no hits to migration"
        survivor.close()
        row = {
            "metric": "serve_llm_kv_tier_drain",
            "sessions": n_sessions, "chains_migrated": got["n"],
            "kv_tier_hits_migrated": mig_hits,
            "ttft_ms_p50_turn2_after_drain": round(
                float(np.percentile(ttfts, 50)) * 1e3, 2),
            "quality": "token_identical_across_drain",
            "platform": platform,
        }
        print(json.dumps(row), flush=True)
        results.append(row)
    finally:
        set_config(prev_cfg)
        kv_tier.reset_local_backend()
    return results


def smoke_kv_tier() -> dict:
    """Quick smoke: spill → cross-engine store fetch round trip, plus one
    drain-migrated session, token-identical throughout."""
    from ray_tpu.core.config import Config, set_config
    from ray_tpu.core.config import config as get_config
    from ray_tpu.serve import kv_tier
    from ray_tpu.serve.llm import PagedLLMEngine

    cfg, params, _on_tpu = _model()
    prev_cfg = get_config()
    set_config(Config({"kv_tier_enabled": True}))
    kv_tier.reset_local_backend()
    try:
        kw = dict(chunk=4, slots=2, max_queue=0)
        a = PagedLLMEngine(params, cfg, name="smoke-tier-a", **kw)
        a.warmup()
        b = PagedLLMEngine(params, cfg, name="smoke-tier-b", **kw)
        b.warmup()
        p = [(7 * j + 3) % 250 + 1 for j in range(32)]
        out_a = a.generate(list(p), max_new_tokens=6)
        out_b = b.generate(list(p), max_new_tokens=6)
        assert out_a == out_b, "store-fetched decode diverged"
        store_hits = b.stats()["kv_tier_hits_store"]
        assert store_hits > 0, "no cluster-wide hit on the shared prompt"
        got: dict = {}
        th = threading.Thread(target=lambda: got.setdefault(
            "n", b.kv_migrate_in("smoke-kvdrain")))
        th.start()
        sent = a.kv_migrate_out("smoke-kvdrain")
        th.join()
        assert sent >= 1 and got.get("n", 0) >= 1, "migration moved nothing"
        a.close()
        h = list(p) + out_a + [9]
        out_t2 = b.generate(h, max_new_tokens=6)
        assert out_t2, "post-drain turn 2 produced nothing"
        mig_hits = b.stats()["kv_tier_hits_migrated"]
        b.close()
        backend_stats = kv_tier._local_backend().stats()
        assert backend_stats["prefix_dir_refs"] == 0, \
            "directory refs leaked after both engines closed"
        row = {
            "metric": "serve_llm_kv_tier_smoke",
            "kv_tier_store_hits": store_hits,
            "chains_migrated": got["n"],
            "kv_tier_hits_migrated": mig_hits,
            "ok": True,
        }
        print(json.dumps(row), flush=True)
        return row
    finally:
        set_config(prev_cfg)
        kv_tier.reset_local_backend()


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--quick", action="store_true",
                        help="CI smoke: short engine A/B + data-plane check")
    parser.add_argument("--reps", type=int, default=8,
                        help="sequential requests per client thread")
    parser.add_argument("--slots", type=int, default=8)
    parser.add_argument("--chunk", type=int, default=8)
    parser.add_argument("--round", type=int, default=0,
                        help="write BENCH_serve_rNN.json at repo root")
    args = parser.parse_args()

    if args.quick:
        results = bench_modes([4], reps=2, slots=4, chunk=args.chunk)
        results += bench_prefix_modes([4], reps=2, slots=4, chunk=args.chunk)
        results.append(smoke_paged_cow())
        results.append(smoke_kv_tier())
        results.append(smoke_jit_warmup())
        results.append(smoke_dataplane())
    elif args.round >= 4:
        # Round 4 (ISSUE 17): cluster-wide KV tier A/B — cross-replica hit
        # rate, cold-engine warm-up from the store, drain migration.
        results = bench_kv_tier_modes(reps=args.reps, slots=args.slots,
                                      chunk=args.chunk)
    elif args.round >= 3:
        # Round 3 (ISSUE 16): speculative-decoding TPOT A/B on the paged
        # engine — decode-heavy traffic, equal (asserted-identical) quality.
        results = bench_spec_modes(concurrency=4, reps=args.reps,
                                   chunk=args.chunk, slots=args.slots)
    else:
        results = bench_modes([1, 4, 16], reps=args.reps,
                              slots=args.slots, chunk=args.chunk)
        results += bench_prefix_modes([4, 16], reps=args.reps,
                                      slots=args.slots, chunk=args.chunk)

    if args.round:
        path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            f"BENCH_serve_r{args.round:02d}.json")
        existing = []
        if os.path.exists(path):
            with open(path) as f:
                existing = json.load(f).get("results", [])
        with open(path, "w") as f:
            json.dump({"results": existing + results}, f, indent=1)
        print(f"wrote {path}")
    return 0


if __name__ == "__main__":
    main()
