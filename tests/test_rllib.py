"""RLlib tests, modeled on the reference's ``rllib/tests`` + per-algorithm
tests: module forward/dist math, GAE correctness, env-runner sampling,
learner descent, distributed learner parity, and the PPO CartPole learning
gate (the reference's tuned-example regression style: "reaches reward R").
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import ray_tpu
from ray_tpu.rllib import (
    PPO,
    PPOConfig,
    PPOLearner,
    RLModule,
    RLModuleSpec,
    SingleAgentEnvRunner,
    compute_gae,
)


def cartpole():
    import gymnasium as gym

    return gym.make("CartPole-v1")


class TestRLModule:
    def test_forward_shapes_discrete(self):
        spec = RLModuleSpec(observation_dim=4, action_dim=2)
        m = RLModule(spec)
        params = m.init_params(jax.random.key(0))
        out = m.forward_train(params, jnp.zeros((7, 4)))
        assert out["action_dist_inputs"].shape == (7, 2)
        assert out["vf_preds"].shape == (7,)

    def test_sample_and_logp_consistent(self):
        spec = RLModuleSpec(observation_dim=4, action_dim=3)
        m = RLModule(spec)
        params = m.init_params(jax.random.key(0))
        obs = jax.random.normal(jax.random.key(1), (64, 4))
        a, logp, v = m.sample_action(params, obs, jax.random.key(2))
        logp2, ent, v2 = m.logp_and_entropy(params, obs, a)
        np.testing.assert_allclose(np.asarray(logp), np.asarray(logp2), rtol=1e-5)
        assert np.all(np.asarray(ent) > 0)

    def test_continuous_action_space(self):
        spec = RLModuleSpec(observation_dim=3, action_dim=2, discrete=False)
        m = RLModule(spec)
        params = m.init_params(jax.random.key(0))
        obs = jnp.zeros((5, 3))
        a, logp, v = m.sample_action(params, obs, jax.random.key(1))
        assert a.shape == (5, 2)
        logp2, ent, _ = m.logp_and_entropy(params, obs, a)
        np.testing.assert_allclose(np.asarray(logp), np.asarray(logp2), rtol=1e-4)


class TestGAE:
    def test_matches_manual_single_env(self):
        rewards = np.array([[1.0], [1.0], [1.0]], np.float32)
        values = np.array([[0.5], [0.5], [0.5]], np.float32)
        terms = np.zeros((3, 1), np.float32)
        boot = np.array([0.5], np.float32)
        adv, tgt = compute_gae(rewards, values, terms, boot, gamma=0.9, lambda_=1.0)
        # manual: delta_t = 1 + 0.9*V(t+1) - 0.5
        d2 = 1 + 0.9 * 0.5 - 0.5
        d1 = d2
        d0 = d2
        expected2 = d2
        expected1 = d1 + 0.9 * expected2
        expected0 = d0 + 0.9 * expected1
        np.testing.assert_allclose(adv[:, 0], [expected0, expected1, expected2], rtol=1e-5)
        np.testing.assert_allclose(tgt, adv + values)

    def test_termination_stops_bootstrap(self):
        rewards = np.ones((2, 1), np.float32)
        values = np.zeros((2, 1), np.float32)
        terms = np.array([[1.0], [0.0]], np.float32)
        boot = np.array([100.0], np.float32)
        adv, _ = compute_gae(rewards, values, terms, boot, gamma=0.9, lambda_=0.95)
        # t=0 terminated: no bootstrap from t=1 values
        assert adv[0, 0] == pytest.approx(1.0)



    def test_autoreset_step_cut_and_bootstrap(self):
        """gymnasium NEXT_STEP autoreset: the step after a done is a junk
        transition (action ignored, reward 0, obs = final obs of the old
        episode). valids must (a) zero its advantage, (b) cut the GAE trace
        so the new episode's deltas don't leak backward, and (c) leave
        V(final obs) as the truncation bootstrap for the preceding step."""
        gamma, lam = 0.9, 0.95
        # t=0: last real step of ep A (truncated); t=1: junk autoreset step
        # whose value is V(final obs of A); t=2: first real step of ep B.
        rewards = np.array([[1.0], [0.0], [2.0]], np.float32)
        values = np.array([[0.5], [0.7], [0.3]], np.float32)
        terms = np.zeros((3, 1), np.float32)
        valids = np.array([[1.0], [0.0], [1.0]], np.float32)
        boot = np.array([0.4], np.float32)
        adv, tgt = compute_gae(
            rewards, values, terms, boot, gamma=gamma, lambda_=lam, valids=valids
        )
        # t=2 (new episode): plain one-step + bootstrap
        d2 = 2.0 + gamma * 0.4 - 0.3
        assert adv[2, 0] == pytest.approx(d2, rel=1e-5)
        # t=1 (junk): advantage zeroed
        assert adv[1, 0] == 0.0
        # t=0 (truncated): bootstraps with V(final obs)=values[1], and the
        # trace does NOT include d2 (no cross-episode leak)
        d0 = 1.0 + gamma * 0.7 - 0.5
        assert adv[0, 0] == pytest.approx(d0, rel=1e-5)

    def test_no_valids_matches_legacy(self):
        rewards = np.ones((4, 2), np.float32)
        values = np.full((4, 2), 0.3, np.float32)
        terms = np.zeros((4, 2), np.float32)
        boot = np.full(2, 0.3, np.float32)
        a1, t1 = compute_gae(rewards, values, terms, boot, gamma=0.9, lambda_=0.9)
        a2, t2 = compute_gae(
            rewards, values, terms, boot, gamma=0.9, lambda_=0.9,
            valids=np.ones((4, 2), np.float32),
        )
        np.testing.assert_allclose(a1, a2)
        np.testing.assert_allclose(t1, t2)


class TestEnvRunner:

    def test_valids_mark_autoreset_steps(self):
        """The step AFTER each done must be flagged invalid (gymnasium
        NEXT_STEP autoreset: that step's action is ignored by the env)."""
        import gymnasium as gym

        def short_ep():
            return gym.make("CartPole-v1", max_episode_steps=4)

        r = SingleAgentEnvRunner(short_ep, num_envs=2, seed=0)
        batch = r.sample(12)
        valids = batch["valids"]
        rewards = batch["rewards"]
        assert valids.shape == (12, 2)
        # every invalid step has reward 0 (env ignored the action)
        assert np.all(rewards[valids == 0.0] == 0.0)
        # episodes cap at 4 steps -> dones occur -> some autoreset steps
        assert (valids == 0.0).sum() >= 2
        # an invalid step is always immediately preceded by a done step:
        # valid transitions and nonzero reward at t-1
        T, N = valids.shape
        for t in range(1, T):
            for n in range(N):
                if valids[t, n] == 0.0:
                    assert valids[t - 1, n] == 1.0  # never two junk in a row
        r.stop()

    def test_sample_shapes_and_metrics(self):
        r = SingleAgentEnvRunner(cartpole, num_envs=3, seed=0)
        batch = r.sample(20)
        assert batch["obs"].shape == (20, 3, 4)
        assert batch["actions"].shape == (20, 3)
        assert batch["bootstrap_value"].shape == (3,)
        r.sample(200)  # enough for some episodes to finish
        m = r.get_metrics()
        assert m["num_episodes"] > 0
        assert 5 < m["episode_return_mean"] < 100  # random policy range
        r.stop()


class TestLearner:
    def _fake_batch(self, n=128, obs_dim=4, n_act=2, seed=0):
        rng = np.random.default_rng(seed)
        return {
            "obs": rng.normal(size=(n, obs_dim)).astype(np.float32),
            "actions": rng.integers(0, n_act, n).astype(np.float32),
            "logp": np.full(n, -0.69, np.float32),
            "advantages": rng.normal(size=n).astype(np.float32),
            "value_targets": rng.normal(size=n).astype(np.float32),
        }

    def test_update_decreases_loss(self):
        spec = RLModuleSpec(observation_dim=4, action_dim=2)
        cfg = {"lr": 1e-2, "clip_param": 0.2, "vf_clip_param": 10.0,
               "vf_loss_coeff": 0.5, "entropy_coeff": 0.0, "grad_clip": 10.0}
        learner = PPOLearner(spec, cfg)
        batch = self._fake_batch()
        losses = [learner.update(batch)["loss"] for _ in range(20)]
        assert losses[-1] < losses[0]

    def test_learner_group_parity_local_vs_distributed(self, ray_start_regular):
        """2 distributed learners with gradient allreduce must track the
        local learner bit-for-bit on the same total batch."""
        from ray_tpu.rllib.learner import LearnerGroup

        spec = RLModuleSpec(observation_dim=4, action_dim=2)
        cfg = {"lr": 1e-2, "clip_param": 0.2, "vf_clip_param": 10.0,
               "vf_loss_coeff": 0.5, "entropy_coeff": 0.0, "grad_clip": 10.0}
        local = LearnerGroup(PPOLearner, spec, cfg, num_learners=0, seed=3)
        dist = LearnerGroup(PPOLearner, spec, cfg, num_learners=2,
                            group_name="test_lg", seed=3)
        batch = self._fake_batch(n=64, seed=5)
        for _ in range(3):
            local.update(batch)
            dist.update(batch)
        w_local = local.get_weights()
        w_dist = dist.get_weights()
        for a, b in zip(jax.tree.leaves(w_local), jax.tree.leaves(w_dist)):
            np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)
        dist.shutdown()


class TestPPOE2E:
    def test_cartpole_learns(self):
        """The learning-regression gate (reference:
        ``rllib/tuned_examples/ppo/cartpole-ppo.yaml`` — reach return R)."""
        algo = PPOConfig().environment(cartpole).env_runners(
            num_envs_per_env_runner=8
        ).training(
            rollout_fragment_length=128,
            num_epochs=6,
            minibatch_size=256,
            lr=3e-4,
            entropy_coeff=0.01,
            seed=1,
        ).build()
        best = 0.0
        for i in range(30):
            result = algo.train()
            r = result["episode_return_mean"]
            if not np.isnan(r):
                best = max(best, r)
            if best >= 120.0:
                break
        algo.stop()
        assert best >= 120.0, f"PPO failed to learn CartPole: best={best}"

    def test_remote_env_runners_and_checkpoint(self, ray_start_regular, tmp_path):
        algo = PPOConfig().environment(cartpole).env_runners(
            num_env_runners=2, num_envs_per_env_runner=2
        ).training(rollout_fragment_length=32, num_epochs=2,
                   minibatch_size=64, seed=0).build()
        r1 = algo.train()
        assert r1["timesteps_total"] == 2 * 2 * 32
        path = str(tmp_path / "ck")
        algo.save(path)
        w_before = algo.learner_group.get_weights()
        algo.train()
        algo.restore(path)
        w_after = algo.learner_group.get_weights()
        for a, b in zip(jax.tree.leaves(w_before), jax.tree.leaves(w_after)):
            np.testing.assert_array_equal(a, b)
        algo.stop()


class TestVtrace:
    def test_vtrace_matches_naive_reference(self):
        """Scan-based V-trace vs a direct O(T^2) transcription of Espeholt
        et al. eq. 1."""
        import numpy as np
        from ray_tpu.rllib.impala import vtrace

        rng = np.random.default_rng(0)
        T, N = 7, 3
        gamma, rho_bar, c_bar = 0.95, 1.0, 1.0
        b_logp = rng.normal(0, 0.3, (T, N)).astype(np.float32)
        t_logp = rng.normal(0, 0.3, (T, N)).astype(np.float32)
        rewards = rng.normal(0, 1, (T, N)).astype(np.float32)
        values = rng.normal(0, 1, (T, N)).astype(np.float32)
        bootstrap = rng.normal(0, 1, N).astype(np.float32)
        dones = (rng.random((T, N)) < 0.2).astype(np.float32)

        vs, pg_adv = vtrace(b_logp, t_logp, rewards, values, bootstrap,
                            dones, gamma=gamma, rho_bar=rho_bar, c_bar=c_bar)

        # naive: vs_t = V_t + sum_{k>=t} (prod_{i=t..k-1} disc_i c_i) delta_k
        rho = np.minimum(rho_bar, np.exp(t_logp - b_logp))
        c = np.minimum(c_bar, np.exp(t_logp - b_logp))
        disc = gamma * (1 - dones)
        nv = np.concatenate([values[1:], bootstrap[None]], axis=0)
        deltas = rho * (rewards + disc * nv - values)
        vs_naive = values.copy()
        for t in range(T):
            for k in range(t, T):
                coef = np.ones(N, np.float32)
                for i in range(t, k):
                    coef *= disc[i] * c[i]
                vs_naive[t] += coef * deltas[k]
        np.testing.assert_allclose(np.asarray(vs), vs_naive, rtol=1e-4, atol=1e-4)
        vs_next = np.concatenate([np.asarray(vs)[1:], bootstrap[None]], axis=0)
        adv_naive = rho * (rewards + disc * vs_next - values)
        np.testing.assert_allclose(np.asarray(pg_adv), adv_naive, rtol=1e-4, atol=1e-4)

    def test_vtrace_on_policy_reduces_to_discounted_returns(self):
        """With pi == mu and lambda-free targets, vs equals the n-step
        discounted return (no clipping active)."""
        import numpy as np
        from ray_tpu.rllib.impala import vtrace

        T, N = 5, 2
        logp = np.zeros((T, N), np.float32)
        rewards = np.ones((T, N), np.float32)
        values = np.zeros((T, N), np.float32)
        bootstrap = np.zeros(N, np.float32)
        dones = np.zeros((T, N), np.float32)
        vs, _ = vtrace(logp, logp, rewards, values, bootstrap, dones,
                       gamma=0.9)
        expect = np.array([sum(0.9 ** (k - t) for k in range(t, T))
                           for t in range(T)], np.float32)
        np.testing.assert_allclose(np.asarray(vs)[:, 0], expect, rtol=1e-5)


class TestConvModule:
    def test_conv_forward_shapes_and_grad(self):
        import numpy as np
        import jax
        from ray_tpu.rllib.rl_module import RLModule, RLModuleSpec

        spec = RLModuleSpec(observation_dim=84 * 84 * 4, action_dim=6,
                            discrete=True, conv=True, obs_shape=(84, 84, 4),
                            hidden=(512,))
        mod = RLModule(spec)
        params = mod.init_params(jax.random.key(0))
        obs = np.random.default_rng(0).integers(
            0, 255, (3, 84 * 84 * 4)).astype(np.float32)
        out = mod.forward_train(params, obs)
        assert out["action_dist_inputs"].shape == (3, 6)
        assert out["vf_preds"].shape == (3,)
        logp, ent, v = mod.logp_and_entropy(params, obs, np.array([0, 2, 5]))
        assert logp.shape == (3,)

    def test_spec_for_env_detects_pixels(self):
        from ray_tpu.rllib.envs import SyntheticAtariEnv
        from ray_tpu.rllib.rl_module import spec_for_env

        env = SyntheticAtariEnv()
        spec = spec_for_env(env)
        assert spec.conv and spec.obs_shape == (84, 84, 4)
        assert spec.action_dim == 6


class TestImpala:
    def test_impala_learns_cartpole(self, ray_start_regular):
        """Async IMPALA improves CartPole return (learning smoke gate)."""
        import gymnasium as gym
        import numpy as np
        from ray_tpu.rllib.impala import ImpalaConfig

        algo = (
            ImpalaConfig()
            .environment(lambda: gym.make("CartPole-v1"))
            .env_runners(num_env_runners=2, num_envs_per_env_runner=4)
            .training(rollout_fragment_length=64, lr=5e-3,
                      broadcast_interval=1)
            .build()
        )
        try:
            first = None
            best = -np.inf
            for i in range(12):
                result = algo.train()
                r = result["episode_return_mean"]
                if not np.isnan(r):
                    first = r if first is None else first
                    best = max(best, r)
            assert first is not None, "no episodes completed"
            assert best > max(first * 1.3, 40.0), (first, best)
        finally:
            algo.stop()

    def test_impala_with_aggregators(self, ray_start_regular):
        import gymnasium as gym
        from ray_tpu.rllib.impala import ImpalaConfig

        algo = (
            ImpalaConfig()
            .environment(lambda: gym.make("CartPole-v1"))
            .env_runners(num_env_runners=2, num_envs_per_env_runner=2)
            .training(rollout_fragment_length=32, num_aggregators=1,
                      train_batch_fragments=2)
            .build()
        )
        try:
            result = algo.train()
            assert result["num_updates"] >= 1
            assert result["timesteps_total"] > 0
        finally:
            algo.stop()


class TestSyntheticAtariPPO:
    def test_ppo_runs_on_pixels(self, ray_start_regular):
        """Conv PPO end-to-end on the Atari stand-in (throughput > 0)."""
        from ray_tpu.rllib.envs import SyntheticAtariEnv
        from ray_tpu.rllib.ppo import PPOConfig

        algo = (
            PPOConfig()
            .environment(lambda: SyntheticAtariEnv(max_steps=200))
            .env_runners(num_env_runners=0, num_envs_per_env_runner=2)
            .training(rollout_fragment_length=16, num_epochs=1,
                      minibatch_size=16, hidden=())
            .build()
        )
        try:
            result = algo.train()
            assert result["env_steps_per_sec"] > 0
            assert np.isfinite(result["loss"])
        finally:
            algo.stop()


class TestDQN:
    """DQN family (reference: rllib/algorithms/dqn/dqn.py)."""

    def test_replay_buffer_ring_and_sample(self):
        from ray_tpu.rllib import ReplayBuffer

        buf = ReplayBuffer(capacity=8, seed=0)
        buf.add_batch({"x": np.arange(6, dtype=np.float32)})
        assert len(buf) == 6
        buf.add_batch({"x": np.arange(10, 14, dtype=np.float32)})
        assert len(buf) == 8  # wrapped
        s = buf.sample(16)
        assert s["x"].shape == (16,)
        # wrapped slots hold the newest values
        assert set(np.unique(s["x"])) <= {2, 3, 4, 5, 10, 11, 12, 13}

    def test_td_targets_and_target_sync(self):
        """Double-DQN targets use the target net for evaluation; the target
        net only moves on the sync boundary."""
        from ray_tpu.rllib.dqn import DQNLearner
        from ray_tpu.rllib.rl_module import RLModuleSpec

        spec = RLModuleSpec(observation_dim=4, action_dim=2, hidden=(16,))
        lrn = DQNLearner(spec, {"lr": 1e-2, "gamma": 0.9,
                                "target_update_freq": 3}, seed=0)
        before = jax.tree.leaves(lrn.target_params)[0].copy()
        batch = {
            "obs": np.random.default_rng(0).normal(size=(32, 4)).astype(np.float32),
            "actions": np.zeros(32, np.int64),
            "rewards": np.ones(32, np.float32),
            "next_obs": np.random.default_rng(1).normal(size=(32, 4)).astype(np.float32),
            "terminateds": np.zeros(32, np.float32),
        }
        lrn.update(batch)
        lrn.update(batch)
        after2 = jax.tree.leaves(lrn.target_params)[0]
        np.testing.assert_array_equal(before, after2)  # not synced yet
        lrn.update(batch)  # 3rd update -> sync
        after3 = jax.tree.leaves(lrn.target_params)[0]
        assert not np.array_equal(before, after3)
        # terminal transitions: target == reward exactly
        t = lrn._targets_fn(lrn.target_params, lrn.params,
                            jnp.asarray(batch["next_obs"]),
                            jnp.asarray(batch["rewards"]),
                            jnp.ones(32),
                            jnp.full(32, 0.9))  # per-sample γ^s column
        np.testing.assert_allclose(np.asarray(t), batch["rewards"], rtol=1e-6)

    def test_dqn_learns_cartpole(self, ray_start_regular):
        """The learning-regression gate (reference:
        rllib/tuned_examples/dqn/cartpole-dqn.yaml — improve return)."""
        import gymnasium as gym

        from ray_tpu.rllib import DQNConfig

        algo = (
            DQNConfig()
            .environment(lambda: gym.make("CartPole-v1"))
            .env_runners(num_env_runners=1, num_envs_per_env_runner=8)
            .training(
                rollout_fragment_length=64,
                train_batch_size=64,
                updates_per_iteration=48,
                num_steps_sampled_before_learning=512,
                target_update_freq=60,
                epsilon_decay_timesteps=8_000,
                lr=1e-3,
                seed=3,
            )
            .build()
        )
        try:
            first, best = None, -np.inf
            for _ in range(25):
                result = algo.train()
                r = result["episode_return_mean"]
                if not np.isnan(r):
                    first = r if first is None else first
                    best = max(best, r)
                if best >= 100.0:
                    break
            assert first is not None, "no episodes completed"
            assert best >= max(first * 1.5, 60.0), (first, best)
        finally:
            algo.stop()

    def test_dqn_checkpoint_roundtrip(self, ray_start_regular, tmp_path):
        import gymnasium as gym

        from ray_tpu.rllib import DQNConfig

        algo = (DQNConfig()
                .environment(lambda: gym.make("CartPole-v1"))
                .training(num_steps_sampled_before_learning=64,
                          rollout_fragment_length=16, seed=0)
                .build())
        try:
            algo.train()
            path = algo.save(str(tmp_path / "ck"))
            w = algo.learner.get_weights()
            algo2 = (DQNConfig()
                     .environment(lambda: gym.make("CartPole-v1"))
                     .training(num_steps_sampled_before_learning=64,
                               rollout_fragment_length=16, seed=9)
                     .build())
            try:
                algo2.restore(path)
                w2 = algo2.learner.get_weights()
                for a, b in zip(jax.tree.leaves(w), jax.tree.leaves(w2)):
                    np.testing.assert_array_equal(a, b)
            finally:
                algo2.stop()
        finally:
            algo.stop()


class TestImpalaLearnerGroup:
    def test_two_learner_impala_matches_single(self, ray_start_regular):
        """2 remote learners fed IDENTICAL batch halves must produce exactly
        the update a single learner gets from one half (the ring-allreduce
        mean of two equal gradients IS that gradient) — proving the group's
        gradient sync, not just 'it runs'."""
        from ray_tpu.rllib import ImpalaLearner, LearnerGroup
        from ray_tpu.rllib.rl_module import RLModuleSpec

        spec = RLModuleSpec(observation_dim=4, action_dim=2, hidden=(16,))
        cfg = {"lr": 1e-2, "gamma": 0.99, "vf_loss_coeff": 0.5,
               "entropy_coeff": 0.01, "grad_clip": 40.0}
        T, N = 8, 2
        rng = np.random.default_rng(0)
        half = {
            "obs": rng.normal(size=(T, N, 4)).astype(np.float32),
            "actions": rng.integers(0, 2, (T, N)).astype(np.float32),
            "logp": rng.normal(size=(T, N)).astype(np.float32) * 0.1 - 0.7,
            "rewards": rng.normal(size=(T, N)).astype(np.float32),
            "terminateds": np.zeros((T, N), np.float32),
            "valids": np.ones((T, N), np.float32),
            "bootstrap_obs": rng.normal(size=(N, 4)).astype(np.float32),
        }
        double = {k: (np.concatenate([v, v], axis=1) if v.ndim >= 2 and k != "bootstrap_obs"
                      else np.concatenate([v, v], axis=0))
                  for k, v in half.items()}

        single = ImpalaLearner(spec, cfg, seed=0)
        single.update(half)
        expected = single.get_weights()

        group = LearnerGroup(
            ImpalaLearner, spec, cfg, num_learners=2,
            group_name="impala-parity", seed=0,
            shard_axes={"obs": 1, "actions": 1, "logp": 1, "values": 1,
                        "rewards": 1, "terminateds": 1, "valids": 1,
                        "bootstrap_obs": 0},
        )
        try:
            group.update(double)
            got = group.get_weights()
            for a, b in zip(jax.tree.leaves(expected), jax.tree.leaves(got)):
                np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)
        finally:
            group.shutdown()

    def test_impala_trains_with_learner_group(self, ray_start_regular):
        import gymnasium as gym

        from ray_tpu.rllib import ImpalaConfig

        algo = (ImpalaConfig()
                .environment(lambda: gym.make("CartPole-v1"))
                .env_runners(num_env_runners=2, num_envs_per_env_runner=2)
                .training(rollout_fragment_length=32, num_learners=2,
                          lr=5e-3)
                .build())
        try:
            for _ in range(3):
                result = algo.train()
            assert np.isfinite(result["loss"])
            assert result["timesteps_total"] > 0
        finally:
            algo.stop()


class TestPrioritizedReplay:
    def test_sum_tree_sampling_proportional(self):
        from ray_tpu.rllib.replay import _SumTree

        t = _SumTree(8)
        t.set(np.arange(4), np.array([1.0, 2.0, 3.0, 4.0]))
        assert abs(t.total - 10.0) < 1e-9
        rng = np.random.default_rng(0)
        idx = t.sample(rng.uniform(0, t.total, 20_000))
        counts = np.bincount(idx, minlength=8)[:4] / 20_000
        np.testing.assert_allclose(counts, [0.1, 0.2, 0.3, 0.4], atol=0.02)

    def test_per_prioritizes_high_error(self):
        from ray_tpu.rllib.replay import PrioritizedReplayBuffer

        buf = PrioritizedReplayBuffer(128, alpha=1.0, beta=0.4, seed=0)
        n = 64
        buf.add_batch({
            "obs": np.zeros((n, 4), np.float32),
            "rewards": np.arange(n, dtype=np.float32),
        })
        # Give transition 7 a huge TD error, everyone else tiny.
        errs = np.full(n, 0.01)
        errs[7] = 100.0
        buf.update_priorities(np.arange(n), errs)
        s = buf.sample(256)
        frac7 = float(np.mean(s["rewards"] == 7.0))
        assert frac7 > 0.5, frac7   # ~99% of the mass is on index 7
        assert s["weights"].min() > 0 and s["weights"].max() <= 1.0
        # The rare (low-priority) samples carry the LARGE correction weight.
        if (s["rewards"] != 7.0).any():
            assert (s["weights"][s["rewards"] != 7.0].min()
                    >= s["weights"][s["rewards"] == 7.0].max())

    def test_nstep_columns_chains_and_breaks(self):
        from ray_tpu.rllib.replay import nstep_columns

        # T=4, N=1: rewards 1,2,3,4; termination after step 1 (index 1).
        obs = np.arange(5, dtype=np.float32).reshape(5, 1, 1)[:4]
        rewards = np.array([[1.0], [2.0], [3.0], [4.0]], np.float32)
        terms = np.array([[0.0], [1.0], [0.0], [0.0]], np.float32)
        valids = np.ones((4, 1), np.float32)
        boot = np.array([[9.0]], np.float32)
        out = nstep_columns(obs, rewards, terms, valids, boot,
                            n_step=3, gamma=0.5)
        # t=0: chain crosses t=1 (terminal) -> R = 1 + 0.5*2, stops there.
        assert abs(out["rewards"][0] - 2.0) < 1e-6
        assert out["terminateds"][0] == 1.0
        assert abs(out["discounts"][0] - 0.25) < 1e-6  # gamma^2
        # t=2: full 2-chain to the fragment end: R = 3 + 0.5*4.
        assert abs(out["rewards"][2] - 5.0) < 1e-6
        assert out["next_obs"][2][0] == 9.0  # bootstrap obs
        # t=3: single step.
        assert abs(out["rewards"][3] - 4.0) < 1e-6

    def test_dqn_per_nstep_smoke(self, ray_start_regular):
        import gymnasium as gym

        from ray_tpu.rllib import DQNConfig

        algo = (DQNConfig()
                .environment(lambda: gym.make("CartPole-v1"))
                .training(num_steps_sampled_before_learning=64,
                          rollout_fragment_length=32,
                          updates_per_iteration=4,
                          replay="prioritized", n_step=3, seed=0)
                .build())
        try:
            r = algo.train()
            r = algo.train()
            assert np.isfinite(r["loss"])
            assert r["buffer_size"] > 0
        finally:
            algo.stop()


class TestSAC:
    def test_sac_module_squashing_and_logp(self):
        from ray_tpu.rllib.rl_module import RLModuleSpec
        from ray_tpu.rllib.sac import SACModule

        spec = RLModuleSpec(observation_dim=3, action_dim=1, discrete=False)
        m = SACModule(spec, np.array([-2.0], np.float32),
                      np.array([2.0], np.float32), hidden=(16,))
        params = m.init_params(jax.random.key(0))
        obs = jnp.zeros((32, 3))
        act, logp, unit = m.pi_sample(params["pi"], obs,
                                      jax.random.key(1))
        assert act.shape == (32, 1) and logp.shape == (32,)
        assert float(jnp.max(jnp.abs(act))) <= 2.0 + 1e-5
        q = m.q_value(params["q1"], obs, act)
        assert q.shape == (32,)

    def test_sac_learns_pendulum(self, ray_start_regular):
        """Continuous-control learning gate (reference:
        rllib/tuned_examples/sac/pendulum-sac.yaml — improve return)."""
        import gymnasium as gym

        from ray_tpu.rllib import SACConfig

        algo = (SACConfig()
                .environment(lambda: gym.make("Pendulum-v1"))
                .env_runners(num_env_runners=1, num_envs_per_env_runner=4)
                .training(
                    rollout_fragment_length=64,
                    train_batch_size=128,
                    updates_per_iteration=48,
                    num_steps_sampled_before_learning=512,
                    hidden=(64, 64),
                    lr=3e-3,
                    n_step=1,
                    seed=0,
                )
                .build())
        try:
            first, best = None, -np.inf
            for _ in range(30):
                result = algo.train()
                r = result["episode_return_mean"]
                if not np.isnan(r):
                    first = r if first is None else first
                    best = max(best, r)
                if best >= -300.0:
                    break
            assert first is not None, "no episodes completed"
            # Random policy sits near -1100 to -1400; learning must lift it.
            assert best >= first + 200.0 or best >= -400.0, (first, best)
        finally:
            algo.stop()

    def test_sac_checkpoint_roundtrip(self, ray_start_regular, tmp_path):
        import gymnasium as gym

        from ray_tpu.rllib import SACConfig

        algo = (SACConfig()
                .environment(lambda: gym.make("Pendulum-v1"))
                .training(num_steps_sampled_before_learning=32,
                          rollout_fragment_length=16,
                          updates_per_iteration=2,
                          train_batch_size=32, hidden=(16,), seed=0)
                .build())
        try:
            algo.train()
            path = algo.save(str(tmp_path / "sac_ck"))
            w = algo.learner.get_weights()
            algo2 = (SACConfig()
                     .environment(lambda: gym.make("Pendulum-v1"))
                     .training(num_steps_sampled_before_learning=32,
                               rollout_fragment_length=16,
                               updates_per_iteration=2,
                               train_batch_size=32, hidden=(16,), seed=5)
                     .build())
            try:
                algo2.restore(path)
                w2 = algo2.learner.get_weights()
                for a, b in zip(jax.tree.leaves(w), jax.tree.leaves(w2)):
                    np.testing.assert_array_equal(a, b)
            finally:
                algo2.stop()
        finally:
            algo.stop()


class TestOffline:
    def _expert_dataset(self, n_episodes=40):
        """CartPole 'expert': a hand-written stabilizing controller
        (push toward upright), good for ~150-350 reward — enough signal
        for BC to beat random (~20)."""
        import gymnasium as gym

        env = gym.make("CartPole-v1")
        episodes = []
        for ep in range(n_episodes):
            obs, _ = env.reset(seed=ep)
            rows = {"obs": [], "actions": [], "rewards": []}
            done = False
            while not done:
                a = 1 if (obs[2] + 0.3 * obs[3]) > 0 else 0
                rows["obs"].append(np.asarray(obs, np.float32))
                rows["actions"].append(a)
                nobs, r, term, trunc, _ = env.step(a)
                rows["rewards"].append(float(r))
                obs = nobs
                done = term or trunc
            rows["terminated"] = term
            episodes.append(rows)
        env.close()
        return episodes

    def test_bc_clones_expert(self, ray_start_regular):
        import gymnasium as gym

        from ray_tpu.rllib import BCConfig, episodes_to_dataset

        ds = episodes_to_dataset(self._expert_dataset())
        algo = BCConfig(
            dataset=ds, observation_dim=4, action_dim=2, discrete=True,
            hidden=(32, 32), updates_per_iteration=64, lr=3e-3, seed=0,
        ).build()
        l0 = algo.train()["loss"]
        for _ in range(7):
            res = algo.train()
        assert res["loss"] < l0 * 0.6, (l0, res["loss"])
        ev = algo.evaluate(lambda: gym.make("CartPole-v1"), num_episodes=5)
        assert ev["episode_return_mean"] >= 100.0, ev

    def test_marwil_beats_bc_on_mixed_data(self, ray_start_regular):
        """Mixed-quality corpus: MARWIL's advantage weighting should favor
        the good trajectories; with beta=0 (BC) the clone averages the
        policies. At minimum MARWIL must stay trainable and its evaluation
        must not collapse vs BC."""
        import gymnasium as gym

        from ray_tpu.rllib import BCConfig, MARWILConfig, episodes_to_dataset

        # Half expert, half random actions.
        expert = self._expert_dataset(20)
        env = gym.make("CartPole-v1")
        rng = np.random.default_rng(0)
        bad = []
        for ep in range(20):
            obs, _ = env.reset(seed=1000 + ep)
            rows = {"obs": [], "actions": [], "rewards": []}
            done = False
            while not done:
                a = int(rng.integers(0, 2))
                rows["obs"].append(np.asarray(obs, np.float32))
                rows["actions"].append(a)
                nobs, r, term, trunc, _ = env.step(a)
                rows["rewards"].append(float(r))
                obs = nobs
                done = term or trunc
            rows["terminated"] = term
            bad.append(rows)
        env.close()
        ds = episodes_to_dataset(expert + bad)

        def fit(cfg_cls, **kw):
            algo = cfg_cls(
                dataset=ds, observation_dim=4, action_dim=2, discrete=True,
                hidden=(32, 32), updates_per_iteration=64, lr=3e-3, seed=0,
                **kw).build()
            for _ in range(8):
                algo.train()
            return algo.evaluate(lambda: gym.make("CartPole-v1"),
                                 num_episodes=5)["episode_return_mean"]

        marwil_ret = fit(MARWILConfig, beta=2.0)
        bc_ret = fit(BCConfig)
        assert marwil_ret >= 60.0, (marwil_ret, bc_ret)
        assert marwil_ret >= bc_ret * 0.8, (marwil_ret, bc_ret)

    def test_bc_checkpoint_roundtrip(self, ray_start_regular, tmp_path):
        from ray_tpu.rllib import BCConfig, episodes_to_dataset

        ds = episodes_to_dataset(self._expert_dataset(4))
        algo = BCConfig(dataset=ds, observation_dim=4, action_dim=2,
                        hidden=(16,), updates_per_iteration=4, seed=0).build()
        algo.train()
        path = algo.save(str(tmp_path / "bc_ck"))
        algo2 = BCConfig(dataset=ds, observation_dim=4, action_dim=2,
                         hidden=(16,), updates_per_iteration=4, seed=7).build()
        algo2.restore(path)
        for a, b in zip(jax.tree.leaves(algo.learner.get_weights()),
                        jax.tree.leaves(algo2.learner.get_weights())):
            np.testing.assert_array_equal(a, b)

    def test_per_non_power_of_two_capacity(self):
        """Regression: the sum tree must round up internally — default
        configs use capacities like 50_000."""
        from ray_tpu.rllib.replay import PrioritizedReplayBuffer

        buf = PrioritizedReplayBuffer(10, seed=0)
        buf.add_batch({"obs": np.arange(7, dtype=np.float32).reshape(7, 1)})
        s = buf.sample(16)
        assert s["obs"].shape == (16, 1)
        assert set(np.unique(s["obs"])) <= set(np.arange(7.0))
        buf.update_priorities(s["indices"], np.abs(s["obs"][:, 0]) + 0.1)
        s2 = buf.sample(16)
        assert s2["obs"].shape == (16, 1)


class _TwoAgentBitEnv:
    """Cooperative test env on the MultiAgentEnv dict contract: each agent
    observes a 4-dim context encoding a target bit; reward 1 for matching
    it. Agent a1's bit is the NEGATION of a0's, so a shared policy cannot
    ace both — per-agent policies must specialize."""

    action_space_n = 2

    def __init__(self, episode_len=16, seed=0):
        self._len = episode_len
        self._rng = np.random.default_rng(seed)
        self._t = 0
        self._bit = 0

    def _obs(self):
        b0 = float(self._bit)
        return {
            "a0": np.array([b0, 1 - b0, 1.0, 0.0], np.float32),
            "a1": np.array([b0, 1 - b0, 0.0, 1.0], np.float32),
        }

    def reset(self, seed=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._t = 0
        self._bit = int(self._rng.integers(0, 2))
        return self._obs(), {}

    def step(self, actions):
        rewards = {
            "a0": float(actions["a0"] == self._bit),
            "a1": float(actions["a1"] == 1 - self._bit),
        }
        self._t += 1
        done = self._t >= self._len
        self._bit = int(self._rng.integers(0, 2))
        terms = {"a0": done, "a1": done, "__all__": done}
        truncs = {"a0": False, "a1": False, "__all__": False}
        return self._obs(), rewards, terms, truncs, {}

    def close(self):
        pass


class TestMultiAgent:
    def _policies(self):
        from ray_tpu.rllib import RLModuleSpec

        spec = RLModuleSpec(observation_dim=4, action_dim=2, hidden=(32,))
        return {"p0": spec, "p1": spec}

    def test_runner_groups_by_policy(self, ray_start_regular):
        from ray_tpu.rllib.multi_agent import MultiAgentEnvRunner

        runner = MultiAgentEnvRunner(
            lambda: _TwoAgentBitEnv(episode_len=8),
            policies=self._policies(),
            policy_mapping_fn=lambda a: "p0" if a == "a0" else "p1",
            seed=0)
        out = runner.sample(24)
        trajs = out["trajectories"]
        assert set(trajs) == {"p0", "p1"}
        assert trajs["p0"] and trajs["p1"]
        total = sum(len(t["rewards"]) for t in trajs["p0"])
        assert total == 24  # one agent per policy, one step per env step
        assert out["num_episodes"] >= 2  # 24 steps / 8-step episodes
        t = trajs["p0"][0]
        assert t["obs"].shape[1] == 4
        assert len(t["actions"]) == len(t["logp"]) == len(t["values"])

    def test_multi_agent_ppo_learns_both_policies(self, ray_start_regular):
        """Learning gate: per-agent policies must specialize (a1's target
        is the negation of a0's) and lift the joint return toward the
        32-per-episode max."""
        from ray_tpu.rllib import MultiAgentPPOConfig

        algo = (MultiAgentPPOConfig()
                .environment(lambda: _TwoAgentBitEnv(episode_len=16))
                .multi_agent(
                    policies=self._policies(),
                    policy_mapping_fn=lambda a: "p0" if a == "a0" else "p1")
                .training(rollout_fragment_length=256, num_sgd_iter=4,
                          minibatch_size=64, lr=3e-3, entropy_coeff=0.0,
                          seed=0)
                .build())
        try:
            first, best = None, -np.inf
            for _ in range(12):
                r = algo.train()
                ret = r["episode_return_mean"]
                if not np.isnan(ret):
                    first = ret if first is None else first
                    best = max(best, ret)
                if best >= 28.0:
                    break
            # Random play averages 16 (half right); learned play nears 32.
            assert best >= 26.0, (first, best)
        finally:
            algo.stop()

    def test_multi_agent_checkpoint_roundtrip(self, ray_start_regular, tmp_path):
        from ray_tpu.rllib import MultiAgentPPOConfig

        def build(seed):
            return (MultiAgentPPOConfig()
                    .environment(lambda: _TwoAgentBitEnv(episode_len=8))
                    .multi_agent(
                        policies=self._policies(),
                        policy_mapping_fn=lambda a: "p0" if a == "a0" else "p1")
                    .training(rollout_fragment_length=32, seed=seed)
                    .build())

        algo = build(0)
        try:
            algo.train()
            path = algo.save(str(tmp_path / "ma_ck"))
            algo2 = build(9)
            try:
                algo2.restore(path)
                for pid in ("p0", "p1"):
                    for a, b in zip(
                            jax.tree.leaves(algo.learners[pid].get_weights()),
                            jax.tree.leaves(algo2.learners[pid].get_weights())):
                        np.testing.assert_array_equal(a, b)
            finally:
                algo2.stop()
        finally:
            algo.stop()


class TestAPPO:
    def test_appo_clipped_surrogate_differs_from_impala(self):
        """APPOLearner = ImpalaLearner with the PPO clip: at large policy
        divergence the clipped loss must differ from (and be bounded vs)
        the raw pg loss."""
        from ray_tpu.rllib import APPOLearner, ImpalaLearner, RLModuleSpec

        spec = RLModuleSpec(observation_dim=4, action_dim=2, hidden=(16,))
        cfg = {"lr": 1e-3, "gamma": 0.99, "clip_param": 0.2,
               "vf_loss_coeff": 0.5, "entropy_coeff": 0.0, "grad_clip": 40.0}
        appo = APPOLearner(spec, cfg, seed=0)
        imp = ImpalaLearner(spec, cfg, seed=0)
        T, N = 8, 4
        rng = np.random.default_rng(0)
        batch = {
            "obs": rng.normal(size=(T, N, 4)).astype(np.float32),
            "actions": rng.integers(0, 2, (T, N)).astype(np.float32),
            # VERY off-policy behavior logp -> ratios far outside the clip
            "logp": np.full((T, N), -3.0, np.float32),
            "rewards": rng.normal(size=(T, N)).astype(np.float32),
            "terminateds": np.zeros((T, N), np.float32),
            "valids": np.ones((T, N), np.float32),
            "bootstrap_obs": rng.normal(size=(N, 4)).astype(np.float32),
        }
        la = float(appo.loss_fn(appo.params, {k: jnp.asarray(v)
                                              for k, v in batch.items()}))
        li = float(imp.loss_fn(imp.params, {k: jnp.asarray(v)
                                            for k, v in batch.items()}))
        assert np.isfinite(la) and np.isfinite(li)
        assert abs(la - li) > 1e-4  # the clip actually engaged

    def test_appo_learns_cartpole(self, ray_start_regular):
        import gymnasium as gym

        from ray_tpu.rllib import APPOConfig

        algo = (APPOConfig()
                .environment(lambda: gym.make("CartPole-v1"))
                .env_runners(num_env_runners=2, num_envs_per_env_runner=4)
                .training(rollout_fragment_length=64, lr=5e-3,
                          entropy_coeff=0.005, clip_param=0.3, seed=0)
                .build())
        try:
            first, best = None, -np.inf
            for _ in range(30):
                r = algo.train()
                ret = r["episode_return_mean"]
                if not np.isnan(ret):
                    first = ret if first is None else first
                    best = max(best, ret)
                if best >= 120.0:
                    break
            assert first is not None
            assert best >= max(first * 1.5, 60.0), (first, best)
        finally:
            algo.stop()


class TestCQL:
    def _pendulum_corpus(self, n=2000, seed=0):
        """Mediocre-policy Pendulum transitions (random + proportional
        controller mix) — enough signal for offline learning."""
        import gymnasium as gym

        env = gym.make("Pendulum-v1")
        rng = np.random.default_rng(seed)
        cols = {k: [] for k in ("obs", "actions", "rewards", "next_obs",
                                "terminateds")}
        obs, _ = env.reset(seed=seed)
        for i in range(n):
            if rng.random() < 0.5:
                a = rng.uniform(-2.0, 2.0, size=(1,)).astype(np.float32)
            else:
                # crude stabilizer: torque against angular velocity
                a = np.clip(-1.5 * obs[2:3], -2.0, 2.0).astype(np.float32)
            nobs, r, term, trunc, _ = env.step(a)
            cols["obs"].append(np.asarray(obs, np.float32))
            cols["actions"].append(a)
            cols["rewards"].append(np.float32(r / 10.0))  # scale rewards
            cols["next_obs"].append(np.asarray(nobs, np.float32))
            cols["terminateds"].append(np.float32(term))
            obs = nobs
            if term or trunc:
                obs, _ = env.reset(seed=seed + i)
        env.close()
        return {k: np.stack(v) for k, v in cols.items()}

    def test_cql_penalty_pushes_down_ood_q(self, ray_start_regular):
        """The conservative term must leave Q(s, a_random) BELOW
        Q(s, a_data) after training — the defining CQL property."""
        from ray_tpu.rllib import CQLConfig

        data = self._pendulum_corpus(1500, seed=0)
        algo = CQLConfig(
            dataset=data, observation_dim=3, action_dim=1,
            action_low=-2.0, action_high=2.0, hidden=(32, 32),
            train_batch_size=128, updates_per_iteration=40,
            cql_alpha=5.0, lr=1e-3, seed=0,
        ).build()
        for _ in range(6):
            r = algo.train()
        assert np.isfinite(r["loss"])

        m = algo.module
        qp = algo.learner.params["q1"]
        obs = jnp.asarray(data["obs"][:256])
        q_data = np.asarray(m.q_value(qp, obs,
                                      jnp.asarray(data["actions"][:256])))
        rng = np.random.default_rng(1)
        rand_a = jnp.asarray(rng.uniform(-2, 2, (256, 1)).astype(np.float32))
        q_rand = np.asarray(m.q_value(qp, obs, rand_a))
        assert q_rand.mean() < q_data.mean(), (q_rand.mean(), q_data.mean())

    def test_cql_checkpoint_roundtrip(self, ray_start_regular, tmp_path):
        from ray_tpu.rllib import CQLConfig

        data = self._pendulum_corpus(300, seed=2)
        cfg = dict(dataset=data, observation_dim=3, action_dim=1,
                   action_low=-2.0, action_high=2.0, hidden=(16,),
                   train_batch_size=64, updates_per_iteration=4)
        algo = CQLConfig(**cfg, seed=0).build()
        algo.train()
        path = algo.save(str(tmp_path / "cql_ck"))
        algo2 = CQLConfig(**cfg, seed=7).build()
        algo2.restore(path)
        for a, b in zip(jax.tree.leaves(algo.learner.params),
                        jax.tree.leaves(algo2.learner.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        ev = algo2.evaluate(lambda: __import__("gymnasium").make("Pendulum-v1"),
                            num_episodes=2)
        assert np.isfinite(ev["episode_return_mean"])
