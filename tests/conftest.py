"""Test fixtures.

Mirrors the reference's conftest strategy
(``python/ray/tests/conftest.py:419 ray_start_regular``, ``:500
ray_start_cluster``): a fresh runtime per test, plus a multi-virtual-node
cluster fixture with fake resources — the single-host trick that makes all
scheduler/fault-tolerance logic testable without real machines
(``python/ray/cluster_utils.py:135``).

JAX runs on a virtual 8-device CPU mesh so every sharding/collective test
exercises real multi-device SPMD without a TPU pod.
"""

import os

# Must be set before jax import anywhere in the test process. Tests always run
# on the virtual 8-device CPU mesh, even when a real TPU is attached.
if "--xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"
# Disable the axon TPU plugin preload outright for the whole test tree
# (drivers AND spawned cluster processes inherit this): tests never touch
# the real chip, the preload costs ~2s per spawned interpreter, and a
# wedged TPU tunnel must not be able to hang CPU-only tests at jax init.
os.environ["PALLAS_AXON_POOL_IPS"] = ""

import pytest  # noqa: E402

import jax  # noqa: E402

# The axon TPU plugin registers itself at INTERPRETER start (sitecustomize)
# and force-overrides the platform list — the JAX_PLATFORMS env var set
# above is too late to stop it. Re-pin the CONFIG to cpu-only before the
# first backends() call: tests never touch the real chip, and a wedged TPU
# tunnel must not be able to hang CPU-only tests at jax init.
jax.config.update("jax_platforms", "cpu")

# Pin all test computation to the virtual CPU devices and full matmul
# precision so numerical oracles are exact.
jax.config.update("jax_default_device", jax.devices("cpu")[0])
jax.config.update("jax_default_matmul_precision", "highest")

import signal  # noqa: E402
import threading  # noqa: E402

# Opt-in runtime lock-order validation (ray_tpu.devtools.lockcheck): with
# RAY_TPU_LOCK_ORDER_CHECK_ENABLED=1 every threading.Lock/RLock/Condition
# is instrumented — per-thread held-sets, a global acquisition-order graph,
# LockOrderError on inversion. ray_tpu/__init__ installs the wrappers at
# the TOP of the package import (so module-level locks like
# runtime._init_lock and collectives._groups_lock are covered too); this
# import triggers that, and the env var propagates to spawned cluster
# processes, which instrument the same way when they import ray_tpu.
from ray_tpu.devtools import lockcheck as _lockcheck  # noqa: E402

_LOCKCHECK_ON = _lockcheck.maybe_install()

# Opt-in runtime leak validation (ray_tpu.devtools.leakcheck): with
# RAY_TPU_LEAK_CHECK_ENABLED=1 threads/fds/sockets are stamped with their
# allocation site, and the autouse fixture below snapshots live
# threads/open fds/own shm segments per test and FAILS any test whose
# teardown leaves new ones behind, naming each survivor.
from ray_tpu.devtools import leakcheck as _leakcheck  # noqa: E402

_LEAKCHECK_ON = _leakcheck.maybe_install()

# Opt-in runtime JAX compile-churn validation (ray_tpu.devtools.jitcheck):
# with RAY_TPU_JIT_CHECK_ENABLED=1, jax.jit is wrapped to stamp and count
# compilations, and the autouse fixture below FAILS any test during which
# a steady-state contract violation (new XLA compile or implicit
# device->host read inside jitcheck.steady_state()) was recorded.
from ray_tpu.devtools import jitcheck as _jitcheck  # noqa: E402

_JITCHECK_ON = _jitcheck.maybe_install()

TEST_TIMEOUT_S = 180  # matches the reference's pytest.ini per-test timeout


def pytest_sessionstart(session):
    """With RAY_TPU_LINT_IN_CI=1, run raylint against its baseline before
    the suite: tier-1 fails on NEW static findings without a separate CI
    job (`python -m ray_tpu.devtools.lint --check-baseline`)."""
    if os.environ.get("RAY_TPU_LINT_IN_CI", "").lower() not in (
            "1", "true", "yes", "on"):
        return
    from ray_tpu.devtools import lint

    if lint.main(["--check-baseline"]) != 0:
        raise pytest.UsageError(
            "raylint found NEW findings (RAY_TPU_LINT_IN_CI=1) — fix them "
            "or accept deliberately with "
            "`python -m ray_tpu.devtools.lint --update-baseline`")


@pytest.fixture(autouse=True)
def _leak_guard(request):
    """With leakcheck installed, fail any test that leaks a thread, fd, or
    shm segment past teardown. Defined FIRST among the autouse fixtures so
    it wraps them all: the snapshot runs before ray_start_* setup and the
    diff after their teardown. `@pytest.mark.leaks("reason")` opts a test
    out (e.g. intentional-crash tests that orphan resources by design)."""
    if not _LEAKCHECK_ON:
        yield
        return
    before = _leakcheck.snapshot()
    yield
    if request.node.get_closest_marker("leaks") is not None:
        return
    leaked = _leakcheck.check(before)
    assert not leaked, (
        "resources leaked past test teardown:\n  " + "\n  ".join(leaked))


@pytest.fixture(autouse=True)
def _lock_order_guard():
    """With lockcheck installed, fail any test during which an inversion was
    recorded — even one raised (and swallowed) on a daemon thread."""
    if not _LOCKCHECK_ON:
        yield
        return
    before = len(_lockcheck.violations())
    yield
    new = _lockcheck.violations()[before:]
    assert not new, "lock-order violations during test:\n" + "\n".join(new)


@pytest.fixture(autouse=True)
def _steady_state_guard(request):
    """With jitcheck installed, fail any test during which a steady-state
    violation was recorded — a new XLA compile or an implicit device->host
    read inside jitcheck.steady_state(). `@pytest.mark.jit_violations`
    opts a test out (tests that provoke violations on purpose)."""
    if not _JITCHECK_ON:
        yield
        return
    before = len(_jitcheck.violations())
    yield
    if request.node.get_closest_marker("jit_violations") is not None:
        return
    new = _jitcheck.violations()[before:]
    assert not new, (
        "steady-state jit violations during test:\n  " + "\n  ".join(new))


@pytest.fixture(autouse=True)
def _per_test_timeout():
    """Hang protection for a condition-variable-heavy runtime: SIGALRM raises
    in the main thread if a test exceeds the budget (pytest-timeout is not in
    the image)."""
    if threading.current_thread() is not threading.main_thread():
        yield
        return

    def _on_alarm(signum, frame):
        raise TimeoutError(f"test exceeded {TEST_TIMEOUT_S}s (possible deadlock)")

    old = signal.signal(signal.SIGALRM, _on_alarm)
    signal.alarm(TEST_TIMEOUT_S)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


@pytest.fixture
def ray_start_regular():
    import ray_tpu

    ray_tpu.init(resources={"CPU": 4, "TPU": 8})
    yield ray_tpu
    ray_tpu.shutdown()


@pytest.fixture
def ray_start_cluster():
    """4 virtual nodes, 2 CPU + 4 TPU each."""
    import ray_tpu

    ray_tpu.init(resources={"CPU": 2, "TPU": 4}, num_nodes=4)
    yield ray_tpu
    ray_tpu.shutdown()


@pytest.fixture
def cpu_mesh_devices():
    import jax

    devices = jax.devices("cpu")
    assert len(devices) >= 8, "conftest must force 8 host-platform devices"
    return devices


def _sweep_stale_shm() -> None:
    """Remove shm arenas left by SIGKILLed test processes (crash tests kill
    whole interpreters, skipping store destructors). Names embed the owning
    pid — only arenas of DEAD processes are removed."""
    import re

    if not os.path.isdir("/dev/shm"):
        return
    for name in os.listdir("/dev/shm"):
        pid_m = re.match(r"rtpu_store_(\d+)_", name)
        if not pid_m:
            continue
        pid = int(pid_m.group(1))
        try:
            os.kill(pid, 0)
        except ProcessLookupError:
            try:
                os.unlink(os.path.join("/dev/shm", name))
            except OSError:
                pass
        except PermissionError:
            pass


_sweep_stale_shm()
