"""Test fixtures.

Mirrors the reference's conftest strategy
(``python/ray/tests/conftest.py:419 ray_start_regular``, ``:500
ray_start_cluster``): a fresh runtime per test, plus a multi-virtual-node
cluster fixture with fake resources — the single-host trick that makes all
scheduler/fault-tolerance logic testable without real machines
(``python/ray/cluster_utils.py:135``).

JAX runs on a virtual 8-device CPU mesh so every sharding/collective test
exercises real multi-device SPMD without a TPU pod.
"""

import os

# Must be set before jax import anywhere in the test process. Tests always run
# on the virtual 8-device CPU mesh, even when a real TPU is attached.
if "--xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import pytest  # noqa: E402


@pytest.fixture
def ray_start_regular():
    import ray_tpu

    ray_tpu.init(resources={"CPU": 4, "TPU": 8})
    yield ray_tpu
    ray_tpu.shutdown()


@pytest.fixture
def ray_start_cluster():
    """4 virtual nodes, 2 CPU + 4 TPU each."""
    import ray_tpu

    ray_tpu.init(resources={"CPU": 2, "TPU": 4}, num_nodes=4)
    yield ray_tpu
    ray_tpu.shutdown()


@pytest.fixture
def cpu_mesh_devices():
    import jax

    devices = jax.devices("cpu")
    assert len(devices) >= 8, "conftest must force 8 host-platform devices"
    return devices
