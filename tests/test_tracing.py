"""End-to-end request tracing tests (ISSUE 10).

A sampled serve LLM request must yield ONE connected trace — handle root,
router pick, replica queue wait, engine admission/prefill/decode spans —
retrievable by trace id through ``gcs.trace``, ``ray_tpu.timeline`` and the
CLI tree, with the TTFT span decomposition matching the engine's measured
TTFT. Head-based sampling is decided once at the root and inherited;
export is batched (spans ≫ RPCs); compiled-DAG ticks trace only under an
already-sampled caller.
"""

import jax
import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.core.config import Config, set_config
from ray_tpu.core.runtime import get_runtime
from ray_tpu.dag import InputNode
from ray_tpu.models import transformer
from ray_tpu.serve.llm import llm_deployment
from ray_tpu.util import tracing


def _span_events(events, name=None):
    spans = [e for e in events if e.get("kind") == "span"]
    if name is not None:
        spans = [e for e in spans if e.get("name") == name]
    return spans


def _ids(events):
    return {e.get("span_id") or e["task_id"] for e in events}


@pytest.fixture
def fresh_config():
    """Restore the default config after a test that overrides flags."""
    yield set_config
    set_config(Config())


class TestServeTraceE2E:
    def test_streamed_llm_request_yields_connected_trace(self,
                                                         ray_start_regular):
        """One streamed request through handle → router → replica → engine
        produces a single connected tree, retrievable by trace id, whose
        TTFT spans decompose the engine-measured TTFT."""
        cfg = transformer.tiny(max_seq_len=64)
        LM = llm_deployment(
            cfg, lambda: transformer.init_params(cfg, jax.random.key(0)),
            name="LM", slots=2, chunk=4)
        try:
            handle = serve.run(LM.bind())
            with tracing.span("client") as (trace_id, _client_span):
                gen = handle.options(stream=True).remote(
                    {"prompt_ids": [7, 3, 11], "max_new_tokens": 8})
                assert gen.trace_id == trace_id
                items = list(gen)
            assert items and items[-1]["finish_reason"] == "stop"
            ttft = items[-1]["ttft_s"]
            tracing.flush()

            events = get_runtime().gcs.trace(trace_id)
            names = {e["name"] for e in events}
            for expected in ("client", "serve.request", "serve.router_pick",
                            "serve.replica_queue", "llm.admission_wait",
                            "llm.prefill", "llm.decode_chunk"):
                assert expected in names, f"missing span {expected}: {names}"

            # Connected: every event's parent resolves inside the trace
            # (only the client root has no parent).
            ids = _ids(events)
            orphans = [e["name"] for e in events
                       if e.get("parent_span_id")
                       and e["parent_span_id"] not in ids]
            assert not orphans, f"disconnected spans: {orphans}"
            roots = [e for e in events if not e.get("parent_span_id")]
            assert [e["name"] for e in roots] == ["client"]

            # The router's pick recorded the occupancy snapshot it acted on.
            pick = _span_events(events, "serve.router_pick")[0]
            assert pick["attrs"]["deployment"] == "LM"
            assert "replica" in pick["attrs"]

            # TTFT decomposition: queue-side waits + prefill + first decode
            # chunk account for the engine's measured TTFT.
            first = lambda n: min(  # noqa: E731
                _span_events(events, n), key=lambda e: e["time"])
            parts = (first("llm.admission_wait")["duration"]
                     + first("llm.prefill")["duration"]
                     + first("llm.decode_chunk")["duration"])
            assert ttft > 0
            assert abs(parts - ttft) <= 0.10 * ttft + 0.015, \
                f"TTFT decomposition {parts:.4f}s vs measured {ttft:.4f}s"
        finally:
            serve.shutdown()

    def test_trace_reaches_timeline_and_cli_tree(self, ray_start_regular):
        """The same trace is retrievable through the timeline view (with
        flow events) and renders as the CLI span tree."""
        with tracing.span("request") as (trace_id, _sid):
            with tracing.span("inner"):
                pass
        tracing.flush()

        view = ray_tpu.timeline(trace_id=trace_id)
        assert {e["name"] for e in view if e["ph"] == "X"} == \
            {"request", "inner"}
        # Flow events pair up: one "s" (at the parent) and one "f" (at the
        # child) per resolved parent link.
        assert [e["ph"] for e in view if e["cat"] == "trace"] == ["s", "f"]

        from ray_tpu.scripts import format_trace_tree

        tree = format_trace_tree(get_runtime().gcs.trace(trace_id))
        assert "request" in tree
        assert "    inner" in tree  # nested under the root

    def test_timeline_feed_is_incremental(self, ray_start_regular):
        """Repeated timeline() polls reuse the per-caller cursor cache —
        entries accumulate, they are not rebuilt from a full-log copy."""
        with tracing.span("a"):
            pass
        tracing.flush()
        first = ray_tpu.timeline(client="t-incr")
        with tracing.span("b"):
            pass
        tracing.flush()
        second = ray_tpu.timeline(client="t-incr")
        assert len(second) == len(first) + 1
        assert second[-1]["name"] == "b"


class TestSampling:
    def test_rate_zero_propagates_but_emits_nothing(self, ray_start_regular,
                                                    fresh_config):
        set_config(Config({"trace_sample_rate": 0.0}))
        with tracing.span("root") as (trace_id, _sid):
            assert not tracing.is_sampled()
            with tracing.span("child"):
                # The child inherits the root's NEGATIVE decision — same
                # trace id, no fresh root, nothing emitted.
                assert tracing.current_context()[0] == trace_id
                assert not tracing.is_sampled()
        tracing.flush()
        assert _span_events(get_runtime().gcs.trace(trace_id)) == []

    def test_rate_one_emits_connected_spans(self, ray_start_regular):
        with tracing.span("root") as (trace_id, root_sid):
            assert tracing.is_sampled()
            with tracing.span("child"):
                pass
        tracing.flush()
        events = get_runtime().gcs.trace(trace_id)
        child = _span_events(events, "child")[0]
        assert child["parent_span_id"] == root_sid

    def test_unsampled_root_suppresses_actor_task_events(
            self, ray_start_regular, fresh_config):
        """Actor tasks submitted under an unsampled root emit no
        trace-linked task events (the untraced hot path)."""

        @ray_tpu.remote
        class A:
            def f(self):
                return 1

        a = A.remote()
        set_config(Config({"trace_sample_rate": 0.0}))
        with tracing.span("root") as (trace_id, _sid):
            assert ray_tpu.get(a.f.remote()) == 1
        tracing.flush()
        assert get_runtime().gcs.trace(trace_id) == []

    def test_gate_off_costs_no_context(self, ray_start_regular, fresh_config):
        set_config(Config({"trace_enabled": False}))
        assert tracing.new_root_context() is None
        with tracing.span("root") as (trace_id, _sid):
            assert not tracing.is_sampled()
        tracing.flush()
        assert get_runtime().gcs.trace(trace_id) == []


class TestDagTracing:
    def test_tick_spans_under_sampled_caller(self, ray_start_regular):
        @ray_tpu.remote
        class Doubler:
            def apply(self, x):
                return x * 2

        d = Doubler.remote()
        compiled = d.apply.bind(InputNode()).experimental_compile()
        try:
            # Untraced executes (no ambient context) emit nothing — the
            # µs-scale tick path stays span-free.
            assert compiled.execute(3).get(timeout=30) == 6
            tracing.flush()
            base = len(_span_events(
                get_runtime().gcs.task_events(), "dag.tick"))

            with tracing.span("driver") as (trace_id, _sid):
                assert compiled.execute(5).get(timeout=30) == 10
            tracing.flush()

            events = get_runtime().gcs.trace(trace_id)
            ticks = _span_events(events, "dag.tick")
            stages = _span_events(events, "dag.stage:apply")
            assert len(ticks) == 1 and len(stages) == 1
            # Stage spans parent to their tick; the tick to the caller.
            assert stages[0]["parent_span_id"] == ticks[0]["task_id"]
            all_ticks = _span_events(
                get_runtime().gcs.task_events(), "dag.tick")
            assert len(all_ticks) == base + 1
        finally:
            compiled.teardown()


class TestBatchedExport:
    def test_spans_ship_in_batches_not_per_rpc(self, ray_start_regular,
                                               monkeypatch):
        gcs = get_runtime().gcs
        calls = {"batches": 0, "events": 0}
        real = gcs.record_task_events

        def counting(events):
            calls["batches"] += 1
            calls["events"] += len(events)
            return real(events)

        monkeypatch.setattr(gcs, "record_task_events", counting)
        tracing.flush()  # start from an empty buffer
        n = 300
        ctx = tracing.new_root_context()
        assert ctx is not None and ctx[2]
        for _ in range(n):
            tracing.emit("bulk", ctx, duration=0.001)
        tracing.flush()
        assert calls["events"] >= n
        # 300 spans ride ~ n/FLUSH_MAX batched record_task_events calls —
        # far fewer RPCs than spans (time-triggered flushes add a handful).
        assert calls["batches"] <= n // 32
