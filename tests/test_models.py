"""Model-layer tests: transformer forward/loss, sharded-vs-single-device
parity (the oracle trick — same math under any mesh layout), training descent.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ray_tpu.models import mlp, transformer
from ray_tpu.models.training import make_train_step
from ray_tpu.parallel.mesh import MeshSpec, cpu_mesh
from ray_tpu.parallel.sharding import ShardingRules


def _tiny_cfg(**kw):
    return transformer.tiny(**kw)


def _batch(cfg, b=4, seed=0):
    rng = np.random.default_rng(seed)
    return {"tokens": jnp.asarray(rng.integers(0, cfg.vocab_size, (b, cfg.max_seq_len)), jnp.int32)}


class TestTransformer:
    def test_forward_shapes(self):
        cfg = _tiny_cfg()
        params = transformer.init_params(cfg, jax.random.key(0))
        logits = transformer.forward(params, _batch(cfg)["tokens"], cfg)
        assert logits.shape == (4, cfg.max_seq_len, cfg.padded_vocab)
        assert jnp.isfinite(logits.astype(jnp.float32)).all()

    def test_param_count_gpt2_small(self):
        # 124M-class: exact count depends on vocab padding; sanity band.
        cfg = transformer.gpt2_small()
        shapes = jax.eval_shape(lambda k: transformer.init_params(cfg, k), jax.random.key(0))
        n = sum(int(np.prod(s.shape)) for s in jax.tree.leaves(shapes))
        assert 120e6 < n < 135e6

    def test_logical_axes_match_params(self):
        cfg = _tiny_cfg()
        params = transformer.init_params(cfg, jax.random.key(0))
        axes = transformer.logical_axes(cfg)
        jax.tree.map(
            lambda p, a: None if a is None else pytest.approx(len(a)) == p.ndim,
            params, axes,
            is_leaf=lambda x: x is None or (isinstance(x, tuple) and not isinstance(x[0], dict)),
        )

    def test_loss_decreases(self):
        cfg = _tiny_cfg()
        params = transformer.init_params(cfg, jax.random.key(0))
        batch = _batch(cfg, b=8)
        opt = optax.adam(1e-3)
        state = opt.init(params)
        loss_fn = jax.jit(lambda p, b: transformer.lm_loss(p, b, cfg))
        grad_fn = jax.jit(jax.value_and_grad(lambda p, b: transformer.lm_loss(p, b, cfg)))
        l0 = float(loss_fn(params, batch))
        for _ in range(10):
            _, g = grad_fn(params, batch)
            upd, state = opt.update(g, state)
            params = optax.apply_updates(params, upd)
        l1 = float(loss_fn(params, batch))
        assert l1 < l0 - 0.1
        # initial loss ≈ ln(vocab) on random tokens
        assert abs(l0 - np.log(cfg.vocab_size)) < 1.0

    @pytest.mark.parametrize("spec,rules", [
        (MeshSpec(data=8), ShardingRules()),
        (MeshSpec(data=2, tensor=4), ShardingRules()),
        (MeshSpec(fsdp=4, tensor=2), ShardingRules()),
        (MeshSpec(data=2, seq=2, tensor=2), ShardingRules()),
    ])
    def test_sharded_forward_parity(self, spec, rules):
        """Any mesh layout computes the same logits as single-device."""
        cfg = _tiny_cfg(n_heads=4, d_ff=128)
        params = transformer.init_params(cfg, jax.random.key(1))
        tokens = _batch(cfg, b=8, seed=1)["tokens"]
        oracle = transformer.forward(params, tokens, cfg)

        mesh = cpu_mesh(spec)
        sharded = jax.jit(
            lambda p, t: transformer.forward(p, t, cfg, mesh=mesh, rules=rules)
        )(params, tokens)
        np.testing.assert_allclose(
            np.asarray(oracle, np.float32), np.asarray(sharded, np.float32),
            rtol=2e-4, atol=2e-4,
        )

    def test_ring_attention_model_parity(self):
        """attn_impl='ring' under a seq-sharded mesh matches dense."""
        cfg = _tiny_cfg(n_heads=4)
        params = transformer.init_params(cfg, jax.random.key(2))
        tokens = _batch(cfg, b=4, seed=2)["tokens"]
        oracle = transformer.forward(params, tokens, cfg)

        mesh = cpu_mesh(MeshSpec(data=2, seq=4))
        cfg_ring = cfg.replace(attn_impl="ring")
        out = jax.jit(
            lambda p, t: transformer.forward(p, t, cfg_ring, mesh=mesh, rules=ShardingRules())
        )(params, tokens)
        np.testing.assert_allclose(
            np.asarray(oracle, np.float32), np.asarray(out, np.float32),
            rtol=2e-4, atol=2e-4,
        )

    def test_rope_variant_runs(self):
        cfg = _tiny_cfg(pos="rope", tie_embeddings=False)
        params = transformer.init_params(cfg, jax.random.key(0))
        assert "pos_embed" not in params and "lm_head" in params
        logits = transformer.forward(params, _batch(cfg)["tokens"], cfg)
        assert jnp.isfinite(logits.astype(jnp.float32)).all()


class TestTrainStepFactory:
    def test_sharded_train_step_descends_and_matches_dp(self):
        cfg = _tiny_cfg()
        mesh = cpu_mesh(MeshSpec(data=2, tensor=4))
        rules = ShardingRules()
        bundle = make_train_step(
            loss_fn=lambda p, b: transformer.lm_loss(p, b, cfg, mesh=mesh, rules=rules),
            init_params_fn=lambda k: transformer.init_params(cfg, k),
            logical_params=transformer.logical_axes(cfg),
            mesh=mesh,
            rules=rules,
            optimizer=optax.adamw(1e-3),
            batch_logical=None,
        )
        params, opt_state = bundle.init(jax.random.key(0))
        batch = _batch(cfg, b=8)
        losses = []
        for _ in range(6):
            params, opt_state, metrics = bundle.step(params, opt_state, batch)
            losses.append(float(metrics["loss"]))
        assert losses[-1] < losses[0]

    def test_opt_state_sharded_like_params(self):
        cfg = _tiny_cfg()
        mesh = cpu_mesh(MeshSpec(data=2, tensor=4))
        rules = ShardingRules()
        bundle = make_train_step(
            loss_fn=lambda p, b: transformer.lm_loss(p, b, cfg, mesh=mesh, rules=rules),
            init_params_fn=lambda k: transformer.init_params(cfg, k),
            logical_params=transformer.logical_axes(cfg),
            mesh=mesh,
            rules=rules,
            batch_logical=None,
        )
        params, opt_state = bundle.init(jax.random.key(0))
        # adam mu for w_up must be tensor-sharded on the mlp dim like the param
        p_sh = params["blocks"]["w_up"].sharding
        mu_sh = opt_state[0].mu["blocks"]["w_up"].sharding
        assert p_sh.spec == mu_sh.spec


class TestMLP:
    def test_mlp_descends(self):
        cfg = mlp.MLPConfig(in_dim=16, hidden=(32,), n_classes=4)
        params = mlp.init_params(cfg, jax.random.key(0))
        rng = np.random.default_rng(0)
        batch = {
            "x": jnp.asarray(rng.normal(size=(64, 16)), jnp.float32),
            "y": jnp.asarray(rng.integers(0, 4, 64), jnp.int32),
        }
        opt = optax.adam(1e-2)
        state = opt.init(params)
        grad_fn = jax.jit(jax.value_and_grad(lambda p, b: mlp.classifier_loss(p, b, cfg)))
        l0, _ = grad_fn(params, batch)
        for _ in range(20):
            _, g = grad_fn(params, batch)
            upd, state = opt.update(g, state)
            params = optax.apply_updates(params, upd)
        l1, _ = grad_fn(params, batch)
        assert float(l1) < float(l0) - 0.3
