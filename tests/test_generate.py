"""KV-cache generation tests: incremental decode must match the full
forward (the numerical oracle), greedy determinism, streaming."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ray_tpu.models import transformer
from ray_tpu.models.generate import Generator, init_cache, _forward_cached


@pytest.fixture(scope="module")
def setup():
    cfg = transformer.tiny(max_seq_len=32, n_layers=2)
    params = transformer.init_params(cfg, jax.random.key(0))
    return cfg, params


class TestKVCache:
    def test_prefill_matches_full_forward(self, setup):
        cfg, params = setup
        tokens = jnp.asarray(
            np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 16)), jnp.int32
        )
        full = transformer.forward(params, tokens, cfg)
        cache = init_cache(cfg, 2)
        logits, cache = _forward_cached(params, tokens, cache, cfg, 0)
        np.testing.assert_allclose(
            np.asarray(full, np.float32), np.asarray(logits, np.float32),
            rtol=2e-4, atol=2e-4,
        )
        assert int(cache["length"]) == 16

    def test_incremental_decode_matches_full(self, setup):
        """Decoding token-by-token with the cache must give the same logits
        as running the growing sequence through the full forward."""
        cfg, params = setup
        rng = np.random.default_rng(1)
        seq = rng.integers(0, cfg.vocab_size, 12).astype(np.int32)

        cache = init_cache(cfg, 1)
        logits, cache = _forward_cached(
            params, jnp.asarray(seq[None, :4]), cache, cfg, 0
        )
        cached_logits = [np.asarray(logits[0, -1], np.float32)]
        for i in range(4, 12):
            logits, cache = _forward_cached(
                params, jnp.asarray(seq[None, i : i + 1]), cache, cfg, i
            )
            cached_logits.append(np.asarray(logits[0, -1], np.float32))

        for i in range(4, 13):
            full = transformer.forward(params, jnp.asarray(seq[None, :i]), cfg)
            np.testing.assert_allclose(
                np.asarray(full[0, -1], np.float32),
                cached_logits[i - 4],
                rtol=3e-4, atol=3e-4,
                err_msg=f"mismatch at position {i}",
            )

    def test_greedy_generation_deterministic(self, setup):
        cfg, params = setup
        g = Generator(params, cfg, batch=1)
        out1 = g.generate([1, 2, 3], max_new_tokens=8)
        out2 = g.generate([1, 2, 3], max_new_tokens=8)
        assert out1 == out2
        assert len(out1) == 8
        assert all(0 <= t < cfg.vocab_size for t in out1)

    def test_greedy_matches_full_forward_argmax(self, setup):
        """Each greedy token must equal argmax of the full-forward logits on
        the growing sequence — the e2e oracle for the whole decode path."""
        cfg, params = setup
        prompt = [5, 9, 2, 7]
        g = Generator(params, cfg, batch=1)
        generated = g.generate(prompt, max_new_tokens=6)

        seq = list(prompt)
        for expect in generated:
            logits = transformer.forward(params, jnp.asarray([seq], jnp.int32), cfg)
            nxt = int(jnp.argmax(logits[0, -1, : cfg.vocab_size]))
            assert nxt == expect, (seq, nxt, expect)
            seq.append(nxt)

    def test_streaming_and_sampling(self, setup):
        cfg, params = setup
        g = Generator(params, cfg, batch=1)
        stream = g.generate([1], max_new_tokens=5, stream=True)
        tokens = [next(stream) for _ in range(3)]
        assert len(tokens) == 3
        sampled = g.generate([1], max_new_tokens=5, temperature=1.0, seed=7)
        assert len(sampled) == 5

    def test_rope_model_decode_parity(self):
        cfg = transformer.tiny(max_seq_len=32, pos="rope", tie_embeddings=False)
        params = transformer.init_params(cfg, jax.random.key(3))
        seq = np.random.default_rng(2).integers(0, cfg.vocab_size, 10).astype(np.int32)
        cache = init_cache(cfg, 1)
        logits, cache = _forward_cached(params, jnp.asarray(seq[None, :6]), cache, cfg, 0)
        for i in range(6, 10):
            logits, cache = _forward_cached(
                params, jnp.asarray(seq[None, i : i + 1]), cache, cfg, i
            )
        full = transformer.forward(params, jnp.asarray(seq[None, :]), cfg)
        np.testing.assert_allclose(
            np.asarray(full[0, -1], np.float32),
            np.asarray(logits[0, -1], np.float32),
            rtol=3e-4, atol=3e-4,
        )


class TestChunkedDecode:
    """serve/llm.py fast path: fused prefill + lax.scan decode chunks."""

    @pytest.fixture()
    def setup(self):
        cfg = transformer.tiny(max_seq_len=64)
        params = transformer.init_params(cfg, jax.random.key(0))
        return cfg, params

    def test_chunked_matches_per_token_greedy(self, setup):
        cfg, params = setup
        from ray_tpu.serve.llm import LLMEngine

        prompt = [3, 1, 4, 1, 5]
        g = Generator(params, cfg, batch=1)
        oracle = g.generate(prompt, max_new_tokens=12)
        eng = LLMEngine(params, cfg, chunk=4)
        got = eng.generate(prompt, max_new_tokens=12)
        assert got == oracle

    def test_bucket_padding_is_invisible(self, setup):
        """Prompt of 5 pads to bucket 16; tokens must match the unpadded
        per-token oracle (pad K/V never attendable)."""
        cfg, params = setup
        from ray_tpu.serve.llm import LLMEngine

        eng = LLMEngine(params, cfg, chunk=4, prompt_buckets=(16, 64))
        prompt = [7, 2, 9]
        got = eng.generate(prompt, max_new_tokens=8)
        oracle = Generator(params, cfg, batch=1).generate(prompt, max_new_tokens=8)
        assert got == oracle

    def test_sampled_stream_runs(self, setup):
        cfg, params = setup
        from ray_tpu.serve.llm import LLMEngine

        eng = LLMEngine(params, cfg, chunk=4)
        toks = eng.generate([1, 2], max_new_tokens=6, temperature=0.8, seed=3)
        assert len(toks) == 6
        assert all(0 <= t < cfg.vocab_size for t in toks)

    def test_prompt_too_long_raises(self, setup):
        cfg, params = setup
        from ray_tpu.serve.llm import LLMEngine

        eng = LLMEngine(params, cfg, chunk=8)  # max_len 64
        with pytest.raises(ValueError, match="no room"):
            eng.generate(list(range(1, 60)), max_new_tokens=4)

    def test_length_cap_finish_reason(self, setup):
        cfg, params = setup
        from ray_tpu.serve.llm import LLMEngine

        eng = LLMEngine(params, cfg, chunk=8)  # max_len 64
        # 16-token prompt leaves 48 slots = 6 chunks; ask for more.
        toks = eng.generate([1] * 16, max_new_tokens=100)
        assert len(toks) == 48
        assert eng.finish_reason == "length_cap"
