"""Autoscaler tests, modeled on the reference's autoscaler-v2 tests against
fake instance providers (SURVEY §4.3): bin-packing, demand-driven upscale
unparking infeasible tasks, min-workers, idle downscale."""

import time

import pytest

import ray_tpu
from ray_tpu.autoscaler import (
    Autoscaler,
    AutoscalerConfig,
    FakeNodeProvider,
    NodeType,
    bin_pack,
)


class TestBinPack:
    def test_packs_multiple_demands_per_node(self):
        nt = NodeType("cpu4", {"CPU": 4}, max_workers=10)
        launches = bin_pack([{"CPU": 1}] * 4, [nt], {})
        assert launches == {"cpu4": 1}

    def test_spills_to_second_node(self):
        nt = NodeType("cpu4", {"CPU": 4}, max_workers=10)
        launches = bin_pack([{"CPU": 3}, {"CPU": 3}], [nt], {})
        assert launches == {"cpu4": 2}

    def test_respects_max_workers(self):
        nt = NodeType("cpu1", {"CPU": 1}, max_workers=2)
        launches = bin_pack([{"CPU": 1}] * 5, [nt], {"cpu1": 1})
        assert launches == {"cpu1": 1}

    def test_picks_matching_type(self):
        cpu = NodeType("cpu", {"CPU": 8}, max_workers=4)
        tpu = NodeType("tpu", {"CPU": 4, "TPU": 4}, max_workers=4)
        launches = bin_pack([{"TPU": 4}], [cpu, tpu], {})
        assert launches == {"tpu": 1}


class TestAutoscalerE2E:
    def test_upscale_unparks_infeasible_task(self, ray_start_regular):
        provider = FakeNodeProvider()
        asc = Autoscaler(
            provider,
            AutoscalerConfig(
                node_types=[NodeType("big", {"CPU": 2, "bignode": 1}, max_workers=2)],
                update_interval_s=0.05,
            ),
        )
        asc.start()
        try:
            @ray_tpu.remote(resources={"bignode": 0.5})
            def needs_big():
                return "ran-on-big"

            # infeasible on the base cluster; autoscaler must add a node
            result = ray_tpu.get(needs_big.remote(), timeout=30)
            assert result == "ran-on-big"
            assert len(provider.non_terminated_nodes()) >= 1
        finally:
            asc.stop()

    def test_min_workers_satisfied_at_start(self, ray_start_regular):
        provider = FakeNodeProvider()
        asc = Autoscaler(
            provider,
            AutoscalerConfig(
                node_types=[NodeType("warm", {"CPU": 1}, min_workers=2, max_workers=4)],
                update_interval_s=0.05,
            ),
        )
        asc.start()
        try:
            assert len(provider.non_terminated_nodes()) == 2
        finally:
            asc.stop()

    def test_idle_nodes_terminated(self, ray_start_regular):
        provider = FakeNodeProvider()
        asc = Autoscaler(
            provider,
            AutoscalerConfig(
                node_types=[NodeType("burst", {"CPU": 2, "burst": 2}, max_workers=2)],
                update_interval_s=0.05,
                idle_timeout_s=0.3,
            ),
        )
        asc.start()
        try:
            @ray_tpu.remote(resources={"burst": 1})
            def burst_work():
                return 1

            assert ray_tpu.get(burst_work.remote(), timeout=30) == 1
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if len(provider.non_terminated_nodes()) == 0:
                    break
                time.sleep(0.05)
            assert len(provider.non_terminated_nodes()) == 0, "idle node not reclaimed"
        finally:
            asc.stop()


class TestLiveClusterAutoscaling:
    """The autoscaler drives a LIVE multiprocess cluster: scale-up launches
    a real node-daemon process; scale-down SIGTERMs it (the in-repo
    fake_multi_node analog, reference:
    v2/instance_manager/instance_manager.py:29)."""

    def test_infeasible_task_triggers_daemon_launch_and_runs(self):
        import time

        import ray_tpu
        from ray_tpu.autoscaler import (Autoscaler, AutoscalerConfig,
                                        GcsAutoscalerView,
                                        LocalDaemonNodeProvider, NodeType)
        from ray_tpu.core.cluster import Cluster, connect
        from ray_tpu.core import runtime as runtime_mod

        cluster = Cluster(num_nodes=1, resources_per_node={"CPU": 1})
        provider = None
        try:
            core = connect(cluster.gcs_address)
            try:
                provider = LocalDaemonNodeProvider(cluster.gcs_address)
                scaler = Autoscaler(
                    provider,
                    AutoscalerConfig(
                        node_types=[NodeType("big", {"CPU": 4},
                                             max_workers=2)],
                        idle_timeout_s=8.0,
                        update_interval_s=0.25,
                    ),
                    runtime=GcsAutoscalerView(core),
                )
                scaler.start()
                try:
                    @ray_tpu.remote(num_cpus=4)
                    def needs_big():
                        import os

                        return os.getpid()

                    # Infeasible on the 1-CPU cluster until the autoscaler
                    # launches the 4-CPU daemon.
                    pid = ray_tpu.get(needs_big.remote(), timeout=240)
                    assert pid > 0
                    assert len(provider.non_terminated_nodes()) >= 1
                    # Scale-down: the added node idles past the timeout and
                    # is terminated (SIGTERM to the daemon process).
                    deadline = time.time() + 60
                    while time.time() < deadline:
                        if not provider.non_terminated_nodes():
                            break
                        time.sleep(0.5)
                    assert not provider.non_terminated_nodes(), \
                        "idle daemon never terminated"
                finally:
                    scaler.stop()
            finally:
                core.shutdown()
                runtime_mod._global_runtime = None
        finally:
            if provider is not None:
                provider.shutdown()
            cluster.shutdown()


class TestTPUPodProvider:
    def test_gcloud_lifecycle_via_mock_runner(self):
        import json

        from ray_tpu.autoscaler import NodeType, TPUPodNodeProvider

        calls = []

        def runner(argv):
            calls.append(argv)
            if "describe" in argv:
                return json.dumps({"state": "READY"})
            return "{}"

        p = TPUPodNodeProvider("proj", "us-central2-b", runner=runner)
        nt = NodeType("v5e", {"TPU": 4},
                      labels={"tpu-accelerator-type": "v5litepod-4"})
        inst = p.create_node(nt)
        assert inst.status == "RUNNING"  # describe said READY
        assert any("create" in c for c in calls)
        create_cmd = next(c for c in calls if "create" in c)
        assert "--accelerator-type=v5litepod-4" in create_cmd
        assert "--project=proj" in create_cmd
        assert [i.instance_id for i in p.non_terminated_nodes()] == \
            [inst.instance_id]
        p.terminate_node(inst)
        assert any("delete" in c for c in calls)
        assert p.non_terminated_nodes() == []
