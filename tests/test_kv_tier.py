"""Cluster-wide KV tier: prefix spill, directory, drain-by-migration
(ISSUE 17).

Directory-level: the ``ShardedPrefixDirectory`` is a bounded refcounted
cache — publisher refcounts gate removal, LRU capacity and TTL bound it,
every removal path reports through ``on_free`` exactly once, and
``dump``/``load`` round-trips entries across a shard-count change (GCS
restart). Tier-level: a chain spilled by one engine is fetched by another
(cluster-wide hit, token-identical to the single-sequence oracle), a cold
replica warms up from the store, and every publish drains to zero refs at
``close()`` (the suite's ``RAY_TPU_LEAK_CHECK_ENABLED=1`` teardown guard
covers the thread/fd half). Migration-level: a victim's chains travel a
``KVHandoffLane`` to a survivor and re-register as warm CACHED state with
``migrated`` hit attribution; the router REWRITES a drained replica's
affinity entries to the migration target. End-to-end: a mid-run scale-down
under active multi-turn sessions completes via drain-then-retire with zero
dropped streams and token-identical output.
"""

import threading
import time

import jax
import pytest

import ray_tpu
from ray_tpu.core.config import Config, set_config
from ray_tpu.core.gcs_shards import ShardedPrefixDirectory
from ray_tpu.models import generate, transformer
from ray_tpu.serve import kv_tier
from ray_tpu.serve.handle import Router
from ray_tpu.serve.llm import PagedLLMEngine
from ray_tpu.util import blockhash

BT = 8  # test block size: small enough to exercise multi-block prompts


@pytest.fixture(scope="module", autouse=True)
def tier_enabled():
    """Flip the tier on for this module only; engines read the flag at
    construction, so every engine below is built inside this scope."""
    from ray_tpu.core.config import config as get_config

    prev = get_config()
    set_config(Config({"kv_tier_enabled": True,
                       "kv_tier_drain_timeout_s": 5.0}))
    yield
    set_config(prev)


@pytest.fixture(autouse=True)
def fresh_local_tier():
    kv_tier.reset_local_backend()
    yield
    kv_tier.reset_local_backend()


@pytest.fixture(scope="module")
def tiny_model():
    cfg = transformer.tiny(max_seq_len=64)
    params = transformer.init_params(cfg, jax.random.key(0))
    return cfg, params


@pytest.fixture(scope="module")
def oracle(tiny_model):
    cfg, params = tiny_model
    gen = generate.Generator(params, cfg)
    memo = {}

    def run(prompt, n, temperature=0.0, seed=0):
        key = (tuple(prompt), n, temperature, seed)
        if key not in memo:
            memo[key] = gen.generate(
                list(prompt), max_new_tokens=n,
                temperature=temperature, seed=seed)
        return memo[key]

    return run


def _mk_engine(tiny_model, name):
    cfg, params = tiny_model
    eng = PagedLLMEngine(params, cfg, prompt_buckets=(16, 32), chunk=4,
                         slots=2, max_queue=0, name=name, block_tokens=BT,
                         pool_blocks=129)
    eng.warmup()
    return eng


def _d(i):
    return bytes([i]) * 16


# -- directory units ----------------------------------------------------------


class TestPrefixDirectory:
    def test_publisher_refcounts_gate_removal(self):
        freed = []
        d = ShardedPrefixDirectory(4, on_free=lambda dg, e: freed.append(dg))
        assert d.publish(_d(1), b"obj", 16, 2) is True
        assert d.publish(_d(1), b"obj", 16, 2) is False  # second publisher
        assert d.release(_d(1)) is False  # one publisher still holds it
        assert freed == []
        assert d.match([_d(1)]) is not None
        assert d.release(_d(1)) is True
        assert freed == [_d(1)]  # on_free exactly once, at zero refs
        assert d.match([_d(1)]) is None

    def test_match_longest_first_and_counters(self):
        d = ShardedPrefixDirectory(4)
        d.publish(_d(1), b"a", 8, 1)
        d.publish(_d(2), b"b", 16, 2)
        j, entry = d.match([_d(1), _d(2), _d(9)])
        assert j == 1 and entry["meta"] == b"b"  # longest wins
        assert d.match([_d(9)]) is None
        st = d.stats()
        assert st["prefix_dir_hits"] == 1 and st["prefix_dir_misses"] == 1

    def test_lru_capacity_eviction(self):
        freed = []
        d = ShardedPrefixDirectory(1, max_entries=3,
                                   on_free=lambda dg, e: freed.append(dg))
        for i in range(1, 6):
            d.publish(_d(i), b"x", 8, 1)
        assert d.stats()["prefix_dir_entries"] == 3
        assert freed == [_d(1), _d(2)]  # oldest out first
        # A match MRU-touches: the touched entry survives the next insert.
        assert d.match([_d(3)]) is not None
        d.publish(_d(6), b"x", 8, 1)
        assert d.match([_d(3)]) is not None
        assert d.match([_d(4)]) is None  # LRU victim instead

    def test_ttl_expiry(self):
        freed = []
        d = ShardedPrefixDirectory(2, ttl_s=0.05,
                                   on_free=lambda dg, e: freed.append(dg))
        d.publish(_d(1), b"x", 8, 1)
        assert d.match([_d(1)]) is not None
        time.sleep(0.08)
        assert d.match([_d(1)]) is None  # expired on the read path
        assert freed == [_d(1)]
        st = d.stats()
        assert st["prefix_dir_expired"] == 1

    def test_drop_is_unconditional(self):
        d = ShardedPrefixDirectory(2)
        d.publish(_d(1), b"x", 8, 1)
        d.publish(_d(1), b"x", 8, 1)  # refs = 2
        assert d.drop(_d(1)) is True  # fetch-miss self-heal ignores refs
        assert d.match([_d(1)]) is None

    def test_dump_load_across_shard_counts(self):
        d = ShardedPrefixDirectory(2, max_entries=8)
        for i in range(1, 5):
            d.publish(_d(i), b"m%d" % i, 8 * i, i)
        data = d.dump()
        d2 = ShardedPrefixDirectory(3, max_entries=8)  # GCS restart, resharded
        d2.load(data)
        assert d2.stats()["prefix_dir_entries"] == 4
        for i in range(1, 5):
            j, entry = d2.match([_d(i)])
            assert entry["meta"] == b"m%d" % i
            assert entry["tokens"] == 8 * i

    def test_load_preserves_lru_order(self):
        d = ShardedPrefixDirectory(1, max_entries=4)
        for i in range(1, 4):
            d.publish(_d(i), b"x", 8, 1)
            time.sleep(0.002)  # distinct wall-clock stamps
        d2 = ShardedPrefixDirectory(1, max_entries=4)
        d2.load(d.dump())
        d2.publish(_d(7), b"x", 8, 1)
        d2.publish(_d(8), b"x", 8, 1)  # over cap: evicts the OLDEST restored
        assert d2.match([_d(1)]) is None
        assert d2.match([_d(3)]) is not None


# -- tier client (local backend) ----------------------------------------------


class TestKVTierClient:
    def test_prefix_aliases_match_shorter_probe(self):
        t = kv_tier.KVTier("t")
        payload = {"k": None, "v": None, "tokens": list(range(24))}
        assert t.publish_chain([_d(1), _d(2), _d(3)], payload, 24, 3)
        # A probe covering only the first block still matches (alias entry).
        j, entry = t.match([_d(1)])
        assert j == 0 and entry["blocks"] == 1 and entry["tokens"] == 8
        j, entry = t.match([_d(1), _d(2)])
        assert j == 1 and entry["blocks"] == 2
        t.close()

    def test_fetch_miss_drops_entry(self):
        t = kv_tier.KVTier("t")
        t.publish_chain([_d(1)], {"k": None}, 8, 1)
        backend = t._resolve()
        with backend._lock:  # payload lost behind the directory's back
            backend._payloads.clear()
        m = t.match([_d(1)])
        assert m is not None
        assert t.fetch(_d(1), m[1]) is None
        assert t.match([_d(1)]) is None  # self-heal: entry dropped
        t.close()

    def test_close_drains_refs_to_zero(self):
        a = kv_tier.KVTier("a")
        b = kv_tier.KVTier("b")
        a.publish_chain([_d(1), _d(2)], {"k": None}, 16, 2)
        b.publish_chain([_d(1), _d(2)], {"k": None}, 16, 2)  # second pub
        a.close()
        st = a.stats()
        assert st["prefix_dir_entries"] == 2  # b still publishes them
        b.close()
        st = b.stats()
        assert st["prefix_dir_entries"] == 0
        assert st["prefix_dir_refs"] == 0
        assert st["prefix_dir_payloads"] == 0


# -- cluster-wide hits (bare engines, shared local tier) ----------------------


class TestClusterWideHit:
    def test_second_engine_fetches_from_store(self, tiny_model, oracle):
        """A computes and spills; B — which never saw the prompt — pulls
        the prefix from the store instead of recomputing, token-identical."""
        a = _mk_engine(tiny_model, "tier-a")
        b = _mk_engine(tiny_model, "tier-b")
        try:
            prompt = [5, 9] * 8  # 2 full blocks
            out_a = a.generate(list(prompt), max_new_tokens=8)
            assert a.stats()["kv_tier_spilled_blocks"] >= 2
            out_b = b.generate(list(prompt), max_new_tokens=8)
            assert out_a == out_b == oracle(prompt, 8)
            st = b.stats()
            assert st["kv_tier_hits_store"] >= BT  # >= one fetched block
            assert st["kv_tier_hits_local"] == 0
        finally:
            a.close()
            b.close()
        assert kv_tier._local_backend().stats()["prefix_dir_refs"] == 0

    def test_multi_turn_extension_hits_full_chain(self, tiny_model, oracle):
        """Turn 2 (= turn-1 prompt + output + new text) on a DIFFERENT
        engine covers A's whole spilled chain — the cluster-wide multi-turn
        path that makes replica death lossless."""
        a = _mk_engine(tiny_model, "tier-a2")
        b = _mk_engine(tiny_model, "tier-b2")
        try:
            p1 = [5, 9] * 8
            out1 = a.generate(list(p1), max_new_tokens=8)
            p2 = list(p1) + out1 + [3, 3]  # 26 tokens: 3 full blocks spilled
            out2 = b.generate(list(p2), max_new_tokens=4)
            assert out2 == oracle(p2, 4)
            assert b.stats()["kv_tier_hits_store"] >= 3 * BT
        finally:
            a.close()
            b.close()

    def test_cold_replica_warmup_vs_fresh_prefill(self, tiny_model, oracle):
        """A cold engine's first request over a spilled chain prefills ONLY
        the uncovered suffix — its engine-reported hit length equals the
        store hit, where a fresh engine with no tier hits nothing."""
        a = _mk_engine(tiny_model, "tier-a3")
        prompt = [7, 2] * 10  # 20 tokens: 2 full blocks
        out = a.generate(list(prompt), max_new_tokens=8)
        cold = _mk_engine(tiny_model, "tier-cold")
        try:
            out_cold = cold.generate(list(prompt), max_new_tokens=8)
            assert out_cold == out == oracle(prompt, 8)
            st = cold.stats()
            # Both probe-able full blocks came from the store — the cold
            # engine prefilled ONLY the uncovered suffix (its LOCAL lookup
            # saw nothing: kv.hit_tokens counts local hits only).
            assert st["kv_tier_hits_store"] == 2 * BT
            assert cold.kv.stats()["kv_hit_tokens"] == 0
        finally:
            a.close()
            cold.close()

    def test_flag_off_restores_private_kv(self, tiny_model):
        """kv_tier_enabled=0: no tier object, no directory traffic — the
        engine is byte-identical to the pre-tier PagedLLMEngine."""
        from ray_tpu.core.config import config as get_config

        prev = get_config()
        set_config(Config({"kv_tier_enabled": False}))
        try:
            a = _mk_engine(tiny_model, "off-a")
            b = _mk_engine(tiny_model, "off-b")
            assert a._tier is None and b._tier is None
            prompt = [5, 9] * 8
            a.generate(list(prompt), max_new_tokens=8)
            b.generate(list(prompt), max_new_tokens=8)
            assert "kv_tier_spilled_blocks" not in a.stats()
            st = kv_tier._local_backend().stats()
            assert st["prefix_dir_published"] == 0
            a.close()
            b.close()
        finally:
            set_config(prev)


# -- drain migration ----------------------------------------------------------


class TestDrainMigration:
    def test_chains_migrate_over_lane(self, tiny_model, oracle):
        """Victim's tracked chains travel the handoff lane to the survivor,
        re-register as CACHED state, and attribute follow-up hits to
        ``migrated``; streams stay token-identical."""
        victim = _mk_engine(tiny_model, "mig-victim")
        survivor = _mk_engine(tiny_model, "mig-survivor")
        try:
            p1 = [5, 9] * 8
            out1 = victim.generate(list(p1), max_new_tokens=8)
            got = {}
            th = threading.Thread(
                target=lambda: got.setdefault(
                    "n", survivor.kv_migrate_in("kvtest-mig-1")))
            th.start()
            sent = victim.kv_migrate_out("kvtest-mig-1")
            th.join()
            assert sent >= 1 and got["n"] >= 1
            # Imported chains are pure cache (no pinned blocks).
            assert survivor.kv.stats()["kv_blocks_active"] == 0
            p2 = list(p1) + out1 + [3, 3]
            out2 = survivor.generate(list(p2), max_new_tokens=4)
            assert out2 == oracle(p2, 4)
            st = survivor.stats()
            assert st["kv_tier_hits_migrated"] >= 3 * BT
            assert st["kv_tier_hits_store"] == 0  # lane beat the store
        finally:
            victim.close()
            survivor.close()

    def test_migrate_out_without_survivor_lane_times_out(self, tiny_model):
        from ray_tpu.core.config import config as get_config

        prev = get_config()
        set_config(Config({"kv_tier_enabled": True,
                           "kv_tier_drain_timeout_s": 0.2}))
        try:
            victim = _mk_engine(tiny_model, "mig-lonely")
            victim.generate([5, 9] * 8, max_new_tokens=8)
            assert victim.kv_migrate_out("kvtest-nobody-home") == 0
            victim.close()
        finally:
            set_config(prev)


# -- router affinity rewrite --------------------------------------------------


class TestAffinityRewrite:
    def _router(self, aff):
        r = Router.__new__(Router)
        r.__dict__["_affinity"] = dict(aff)
        return r

    def test_drained_replica_entries_rewritten_to_target(self):
        r = self._router({b"h1": "victim", b"h2": "live-b", b"h3": "gone"})
        r._sweep_affinity_locked(
            live={"live-a", "live-b"},
            migrations={"victim": "live-a"})
        assert r._affinity_map() == {b"h1": "live-a", b"h2": "live-b"}

    def test_chain_following_and_cycle_safety(self):
        r = self._router({b"h1": "v1", b"h2": "v3"})
        r._sweep_affinity_locked(
            live={"live"},
            migrations={"v1": "v2", "v2": "live", "v3": "v4", "v4": "v3"})
        # v1 -> v2 -> live resolves; the v3 <-> v4 cycle sweeps.
        assert r._affinity_map() == {b"h1": "live"}


# -- GCS-backed directory (runtime backend) -----------------------------------


class TestRuntimeBackend:
    def test_snapshot_roundtrip_and_stale_self_heal(self, ray_start_regular):
        """Directory state rides kv_dump/kv_load; a restored entry whose
        payload is gone drops on first fetch — no dangling object ids."""
        import numpy as np

        from ray_tpu.core.runtime import get_runtime

        t = kv_tier.KVTier("rt")
        payload = {"k": np.ones((2, 1, BT, 4, 16), np.float32),
                   "v": np.ones((2, 1, BT, 4, 16), np.float32),
                   "tokens": list(range(BT))}
        assert t.publish_chain([_d(1)], payload, BT, 1)
        rt = get_runtime()
        assert not isinstance(t._resolve(), kv_tier._LocalBackend)
        m = rt.gcs.prefix_match([_d(1)])
        assert m is not None
        assert t.fetch(_d(1), m[1])["k"].shape[1] == 1
        dump = rt.gcs.kv_dump()
        # Restart-over-snapshot: the publisher dies (pin + entry go), THEN
        # the directory restores from the stale snapshot — its locator now
        # points at a freed object.
        t.close()
        assert rt.gcs.prefix_stats()["prefix_dir_entries"] == 0
        rt.gcs.kv_load(dump)
        assert rt.gcs.prefix_stats()["prefix_dir_entries"] == 1
        m = rt.gcs.prefix_match([_d(1)])
        assert m is not None
        assert t.fetch(_d(1), m[1]) is None  # object gone
        # The failed fetch dropped the entry (self-heal): no dangling
        # object ids survive a GCS restart over a stale snapshot.
        assert rt.gcs.prefix_stats()["prefix_dir_entries"] == 0


# -- end-to-end: scale-down under active sessions -----------------------------


@pytest.fixture
def serve_instance(ray_start_regular):
    from ray_tpu import serve

    yield serve
    serve.shutdown()


class TestScaleDownE2E:
    def test_multi_turn_sessions_survive_forced_scale_down(
            self, serve_instance, tiny_model, oracle):
        """2 replicas -> 1 mid-run: the victim drains (in-flight streams
        finish), migrates its chains to the survivor, and retires; every
        session's turn 2 is token-identical to the no-drain tokens, zero
        streams drop, and the controller publishes the migration rewrite."""
        from ray_tpu.serve.controller import get_or_create_controller
        from ray_tpu.serve.llm import llm_deployment

        serve = serve_instance
        cfg, _params = tiny_model
        # ray_tpu.init (the ray_start_regular fixture) RESET the global
        # config from its system_config — re-apply the tier knobs before
        # any replica or controller reads them.
        set_config(Config({"kv_tier_enabled": True,
                           "kv_tier_drain_timeout_s": 5.0}))
        LM = llm_deployment(
            cfg, lambda: transformer.init_params(cfg, jax.random.key(0)),
            name="LM", slots=4, chunk=4, num_replicas=2)
        handle = serve.run(LM.bind())
        controller = get_or_create_controller()

        sessions = [[11 + i, 3 + i] * 9 for i in range(6)]  # 18 tokens
        turn1 = [None] * len(sessions)
        errs = []

        def run_turn(i, prompt, out):
            try:
                toks = []
                for item in handle.options(stream=True).remote(
                        {"prompt_ids": prompt, "max_new_tokens": 8}):
                    toks.append(item["token"])
                    if "finish_reason" in item:
                        assert item["finish_reason"] == "stop"
                out[i] = toks
            except BaseException as e:  # noqa: BLE001
                errs.append(e)

        threads = [threading.Thread(target=run_turn,
                                    args=(i, sessions[i], turn1))
                   for i in range(len(sessions))]
        for t in threads:
            t.start()
        # Mid-run scale-down: streams are in flight RIGHT NOW.
        time.sleep(0.3)
        assert ray_tpu.get(
            controller.set_target_replicas.remote("LM", 1), timeout=10)
        for t in threads:
            t.join()
        assert not errs, errs
        for i, prompt in enumerate(sessions):
            assert turn1[i] == oracle(prompt, 8), \
                f"turn-1 stream {i} diverged across the scale-down"

        # The drain must resolve: one routed replica + a migration
        # rewrite in the snapshot.
        deadline = time.monotonic() + 30
        migrations, reps = {}, []
        while time.monotonic() < deadline:
            _v, table = ray_tpu.get(
                controller.get_snapshot.remote(-1, 0.0))
            entry = table.get("LM", {})
            migrations = entry.get("migrations", {})
            reps = entry.get("replicas", [])
            if len(reps) == 1 and migrations:
                break
            time.sleep(0.2)
        assert len(reps) == 1, "scale-down never converged"
        assert migrations, "drain-then-retire published no migration"

        # Turn 2 extends every session's chain — served by the
        # survivor, token-identical to a run that never scaled.
        turn2 = [None] * len(sessions)
        threads = []
        for i, prompt in enumerate(sessions):
            p2 = list(prompt) + turn1[i] + [2, 4]
            threads.append(threading.Thread(
                target=run_turn, args=(i, p2, turn2)))
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs, errs
        hits_migrated = 0.0
        for i, prompt in enumerate(sessions):
            p2 = list(prompt) + turn1[i] + [2, 4]
            assert turn2[i] == oracle(p2, 8), \
                f"turn-2 stream {i} diverged after drain"
        # The victim's sessions now hit as `migrated` on the survivor.
        _v, table = ray_tpu.get(controller.get_snapshot.remote(-1, 0.0))
        for m in table["LM"]["replica_load"].values():
            hits_migrated += float(m.get("kv_tier_hits_migrated") or 0)
        deadline = time.monotonic() + 10
        while hits_migrated == 0 and time.monotonic() < deadline:
            time.sleep(0.3)  # load poll lags by a poll period
            _v, table = ray_tpu.get(
                controller.get_snapshot.remote(-1, 0.0))
            for m in table["LM"]["replica_load"].values():
                hits_migrated += float(
                    m.get("kv_tier_hits_migrated") or 0)
        assert hits_migrated > 0, \
            "no migrated-source hits: drain shipped no usable chains"
