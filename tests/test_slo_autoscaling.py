"""SLO-driven serve autoscaling + open-loop load harness (ISSUE 13).

Unit layers are pure (seeded traces, SLOPolicy with injected time, the
tenant-quota ledger, the delta-window TTFT rollup reader); the e2e layer
drives the real data plane — handle → router → replica actors — under the
sim-LLM deployment from ``benches/loadgen.py`` and watches the controller
scale on queue pressure, hold through hysteresis, fall back to min on
idle, and converge through a replica death.
"""

from __future__ import annotations

import threading
import time

import pytest

from benches.loadgen import (TraceConfig, sim_llm_deployment, synth_trace)
from ray_tpu.serve.admission import TenantAdmission
from ray_tpu.serve.autoscaling import (DeploymentSignals, SLOPolicy,
                                       TTFTRollup)
from ray_tpu.serve.config import AutoscalingConfig, DeploymentConfig
from ray_tpu.serve.errors import Saturated

# ---------------------------------------------------------------- loadgen --


class TestLoadgenDeterminism:
    def _snap(self, cfg):
        return [(round(r.t, 9), tuple(r.prompt_ids), r.max_new_tokens,
                 r.tenant, r.session, r.turn) for r in synth_trace(cfg)]

    def test_same_seed_same_trace(self):
        cfg = TraceConfig(seed=42, duration_s=4.0, rate_rps=10.0,
                          arrival="bursty", tenants={"A": 1.0, "B": 3.0})
        assert self._snap(cfg) == self._snap(cfg)

    def test_seed_changes_trace(self):
        a = TraceConfig(seed=1, duration_s=4.0, rate_rps=10.0)
        b = TraceConfig(seed=2, duration_s=4.0, rate_rps=10.0)
        assert self._snap(a) != self._snap(b)

    def test_trace_shape(self):
        cfg = TraceConfig(seed=3, duration_s=6.0, rate_rps=20.0,
                          multi_turn_frac=0.5, shared_prefix_frac=0.5,
                          tenants={"A": 1.0, "B": 1.0})
        trace = synth_trace(cfg)
        assert trace, "empty trace"
        ts = [r.t for r in trace]
        assert ts == sorted(ts) and all(0 <= t < 6.0 for t in ts)
        assert {r.tenant for r in trace} == {"A", "B"}
        # multi-turn follow-ups exist and carry longer (history) prompts
        followups = [r for r in trace if r.turn > 0]
        assert followups
        by_session = {r.session: r for r in trace if r.turn == 0}
        assert any(len(f.prompt_ids) > len(by_session[f.session].prompt_ids)
                   for f in followups if f.session in by_session)


# -------------------------------------------------------------- SLOPolicy --


def _asc(**kw):
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 8)
    kw.setdefault("target_ongoing_requests", 2.0)
    kw.setdefault("upscale_delay_s", 0.0)
    kw.setdefault("downscale_delay_s", 2.0)
    kw.setdefault("idle_timeout_s", 10.0)
    return AutoscalingConfig(**kw)


class TestSLOPolicy:
    def test_scale_up_on_ongoing(self):
        p = SLOPolicy(_asc())
        sig = DeploymentSignals(replicas=1, ongoing=8.0)
        assert p.desired(1, sig, now=0.0) == 4  # ceil(1 * 8/2)

    def test_scale_up_on_queue_pressure(self):
        p = SLOPolicy(_asc(target_queue_depth=4.0))
        sig = DeploymentSignals(replicas=2, ongoing=0.0, queue_depth=24.0)
        assert p.desired(2, sig, now=0.0) == 6  # ceil(2 * 24/(2*4))

    def test_scale_up_on_kv_pressure(self):
        p = SLOPolicy(_asc(target_kv_utilization=0.5))
        sig = DeploymentSignals(replicas=2, kv_active=90.0, kv_total=100.0)
        assert p.desired(2, sig, now=0.0) == 4  # ceil(2 * 0.9/0.5)

    def test_hysteresis_dead_band_holds(self):
        p = SLOPolicy(_asc(hysteresis=0.25))
        # pressure 1.2 < 1.25 -> inside the band, hold
        sig = DeploymentSignals(replicas=2, ongoing=4.8)
        assert p.desired(2, sig, now=0.0) == 2
        # pressure 0.8 > 0.75 -> still inside, hold
        sig = DeploymentSignals(replicas=2, ongoing=3.2)
        assert p.desired(2, sig, now=10.0) == 2

    def test_ttft_violation_overrides(self):
        p = SLOPolicy(_asc(ttft_p99_slo_s=0.2))
        # utilization at target (pressure == 1.0) but latency breached
        sig = DeploymentSignals(replicas=2, ongoing=4.0, ttft_p99_s=0.5)
        assert p.desired(2, sig, now=0.0) == 3

    def test_no_flap_within_cooldown(self):
        p = SLOPolicy(_asc(downscale_delay_s=3.0))
        up = DeploymentSignals(replicas=1, ongoing=8.0)
        assert p.desired(1, up, now=0.0) == 4
        # quiet immediately after the resize: must NOT step down until the
        # low condition has held for downscale_delay_s
        low = DeploymentSignals(replicas=4, ongoing=1.0)
        assert p.desired(4, low, now=0.1) == 4
        assert p.desired(4, low, now=2.0) == 4
        assert p.desired(4, low, now=3.5) < 4  # held >= 3s -> downscale

    def test_downscale_hold_resets_on_pressure(self):
        p = SLOPolicy(_asc(downscale_delay_s=2.0, idle_timeout_s=60.0))
        low = DeploymentSignals(replicas=4, ongoing=1.0)
        mid = DeploymentSignals(replicas=4, ongoing=8.5)  # in dead band
        assert p.desired(4, low, now=0.0) == 4
        assert p.desired(4, mid, now=1.0) == 4  # interrupts the hold
        assert p.desired(4, low, now=2.5) == 4  # hold restarted at 2.5
        assert p.desired(4, low, now=4.6) < 4

    def test_idle_scales_to_min(self):
        p = SLOPolicy(_asc(idle_timeout_s=5.0))
        idle = DeploymentSignals(replicas=6, ongoing=0.0)
        assert p.desired(6, idle, now=0.0) == 6
        assert p.desired(6, idle, now=5.5) == 1  # straight to min

    def test_clamps_to_max(self):
        p = SLOPolicy(_asc(max_replicas=3))
        sig = DeploymentSignals(replicas=1, ongoing=100.0)
        assert p.desired(1, sig, now=0.0) == 3

    def test_upscale_delay_gates(self):
        p = SLOPolicy(_asc(upscale_delay_s=2.0))
        up = DeploymentSignals(replicas=1, ongoing=8.0)
        assert p.desired(1, up, now=0.0) == 4
        more = DeploymentSignals(replicas=4, ongoing=32.0)
        assert p.desired(4, more, now=0.5) == 4  # inside upscale cooldown
        assert p.desired(4, more, now=2.5) == 8


# -------------------------------------------------------------- admission --


class TestTenantAdmission:
    def test_quota_enforced_with_wildcard_default(self):
        adm = TenantAdmission({"A": 2.0, "*": 1.0})
        r1, r2 = adm.acquire("A"), adm.acquire("A")
        with pytest.raises(Saturated) as ei:
            adm.acquire("A", deployment="d")
        assert ei.value.reason == "quota"
        assert ei.value.retry_after_s and ei.value.retry_after_s > 0
        adm.acquire("B")  # wildcard: 1 in flight ok
        with pytest.raises(Saturated):
            adm.acquire("B")
        r1()
        assert adm.acquire("A") is not None
        r2()

    def test_release_idempotent(self):
        adm = TenantAdmission({"A": 1.0})
        rel = adm.acquire("A")
        rel()
        rel()  # double release must not free a phantom slot
        assert adm.in_flight("A") == 0
        rel2 = adm.acquire("A")
        with pytest.raises(Saturated):
            adm.acquire("A")
        rel2()

    def test_no_quota_table_admits_everything(self):
        adm = TenantAdmission(None)
        assert adm.acquire("anyone") is None
        adm2 = TenantAdmission({"A": 1.0})
        # tenant not listed and no wildcard -> unlimited
        assert adm2.acquire("B") is None

    def test_update_applies_live(self):
        adm = TenantAdmission({"A": 1.0})
        rel = adm.acquire("A")
        adm.update({"A": 2.0})
        rel2 = adm.acquire("A")  # limit raised while in flight
        rel()
        rel2()

    def test_saturated_survives_pickle(self):
        import pickle

        e = Saturated("over", reason="quota", retry_after_s=0.25)
        e2 = pickle.loads(pickle.dumps(e))
        assert (str(e2), e2.reason, e2.retry_after_s) == \
            ("over", "quota", 0.25)

    def test_config_validates_quotas(self):
        with pytest.raises(ValueError):
            DeploymentConfig(tenant_quotas={"A": -1.0})


# ------------------------------------------------------------ TTFT rollup --


class TestTTFTRollup:
    def test_delta_window_quantile(self, monkeypatch):
        import ray_tpu.core.metrics_export as me

        snaps = [
            {"bounds": [0.1, 1.0], "buckets": [100, 0, 0],
             "sum": 5.0, "count": 100},
            # window adds 100 slow observations: cumulative p99 would stay
            # polluted forever; the DELTA p99 must see only the new ones
            {"bounds": [0.1, 1.0], "buckets": [100, 100, 0],
             "sum": 60.0, "count": 200},
        ]
        it = iter(snaps)
        monkeypatch.setattr(me, "cluster_histogram",
                            lambda name, tags: next(it))
        roll = TTFTRollup(min_interval_s=1.0)
        first = roll.p99("d", now=0.0)
        assert first is not None and first <= 0.1
        # rate limit: inside min_interval the cached value is returned
        assert roll.p99("d", now=0.5) == first
        second = roll.p99("d", now=2.0)
        assert second is not None and second > 0.5

    def test_no_data_returns_none(self, monkeypatch):
        import ray_tpu.core.metrics_export as me

        monkeypatch.setattr(me, "cluster_histogram", lambda n, t: None)
        assert TTFTRollup(0.0).p99("d", now=0.0) is None


# ------------------------------------------------------------------- e2e --


@pytest.fixture
def serve_cluster(ray_start_regular):
    from ray_tpu import serve

    yield serve
    serve.shutdown()


def _drive_open_loop(handle, stop, tenant="default", gap_s=0.05,
                     tokens=8):
    """Background offered load: fire-and-forget streams until ``stop``."""
    threads = []

    def one():
        try:
            for _ in handle.options(stream=True).remote(
                    {"prompt_ids": [1] * 8, "max_new_tokens": tokens,
                     "tenant": tenant}):
                pass
        except Exception:  # noqa: BLE001 — sheds are expected under burst
            pass

    def pump():
        while not stop.is_set():
            t = threading.Thread(target=one, daemon=True)
            t.start()
            threads.append(t)
            time.sleep(gap_s)

    pumper = threading.Thread(target=pump, daemon=True)
    pumper.start()
    return pumper, threads


def _replica_count(name):
    import ray_tpu
    from ray_tpu.serve.controller import get_or_create_controller

    info = ray_tpu.get(get_or_create_controller().list_deployments.remote())
    return info[name]["num_replicas"]


class TestServeSLOEndToEnd:
    def test_quota_tenant_isolated_e2e(self, serve_cluster):
        serve = serve_cluster
        sim = sim_llm_deployment("sim-quota", slots=2,
                                 decode_s_per_token=0.05)
        handle = serve.run(
            sim.options(num_replicas=1,
                        tenant_quotas={"A": 1.0, "*": 100.0}).bind())
        stop = threading.Event()
        # tenant A holds its single quota slot with a long stream
        pumper, workers = _drive_open_loop(handle, stop, tenant="A",
                                           gap_s=0.02, tokens=24)
        try:
            time.sleep(0.3)
            # A is over quota: a second A request sheds with reason=quota
            shed = None
            for _ in range(50):
                try:
                    for _ in handle.options(stream=True).remote(
                            {"prompt_ids": [1] * 4, "max_new_tokens": 1,
                             "tenant": "A"}):
                        pass
                except Saturated as e:
                    shed = e
                    break
                time.sleep(0.05)
            assert shed is not None and shed.reason == "quota"
            assert shed.retry_after_s and shed.retry_after_s > 0
            # ...while tenant B still gets served
            got = 0
            for item in handle.options(stream=True).remote(
                    {"prompt_ids": [1] * 4, "max_new_tokens": 4,
                     "tenant": "B"}):
                got += 1
            assert got == 4
        finally:
            stop.set()
            pumper.join(timeout=5)
            # Drain every in-flight stream BEFORE serve/runtime teardown:
            # a worker mid-stream during shutdown wedges cleanup and trips
            # the leak guard.
            for w in workers:
                w.join(timeout=10)

    def test_scale_up_then_idle_scale_down_no_flap(self, serve_cluster):
        serve = serve_cluster
        sim = sim_llm_deployment("sim-scale", slots=2,
                                 decode_s_per_token=0.04)
        handle = serve.run(sim.options(
            num_replicas=1,
            autoscaling_config={
                "min_replicas": 1, "max_replicas": 3,
                "target_ongoing_requests": 2.0, "target_queue_depth": 2.0,
                "upscale_delay_s": 0.0, "downscale_delay_s": 0.5,
                "idle_timeout_s": 1.0, "hysteresis": 0.1,
            }).bind())
        stop = threading.Event()
        pumper, workers = _drive_open_loop(handle, stop, gap_s=0.03,
                                           tokens=10)
        counts = []
        try:
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                counts.append(_replica_count("sim-scale"))
                if counts[-1] >= 2:
                    break
                time.sleep(0.1)
            assert max(counts) >= 2, f"never scaled up: {counts}"
        finally:
            stop.set()
            pumper.join(timeout=5)
            for w in workers:
                w.join(timeout=5)
        # idle: must fall back to min within idle_timeout + signal latency
        deadline = time.monotonic() + 8.0
        while time.monotonic() < deadline:
            if _replica_count("sim-scale") == 1:
                break
            time.sleep(0.1)
        assert _replica_count("sim-scale") == 1, "did not scale to min"
        # hysteresis/no-flap: once at min with zero load it STAYS there
        # for longer than the downscale cooldown (0.5s)
        for _ in range(6):
            assert _replica_count("sim-scale") == 1
            time.sleep(0.1)

    def test_replica_death_converges_to_target(self, serve_cluster):
        import ray_tpu
        from ray_tpu.serve.controller import get_or_create_controller

        serve = serve_cluster
        sim = sim_llm_deployment("sim-death", slots=2,
                                 decode_s_per_token=0.01)
        handle = serve.run(sim.options(num_replicas=2).bind())
        ctrl = get_or_create_controller()

        def live_replicas():
            _v, table = ray_tpu.get(ctrl.get_snapshot.remote(-1, 0.0))
            return table["sim-death"]["replicas"]

        deadline = time.monotonic() + 10.0
        while len(live_replicas()) < 2 and time.monotonic() < deadline:
            time.sleep(0.1)
        reps = live_replicas()
        assert len(reps) == 2
        victim = reps[0]
        ray_tpu.kill(victim)
        # the controller must notice the death and respawn to target
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            if _replica_count("sim-death") == 2:
                alive = live_replicas()
                if len(alive) == 2 and all(
                        r.actor_id.hex() != victim.actor_id.hex()
                        for r in alive):
                    break
            time.sleep(0.1)
        alive = live_replicas()
        assert len(alive) == 2
        assert all(r.actor_id.hex() != victim.actor_id.hex()
                   for r in alive)
        # and the fleet still serves — the handle's router snapshot may
        # stay up to SNAPSHOT_MAX_AGE_S stale and route one more request
        # at the dead replica (streams can't resubmit mid-flight), so a
        # real client retries on ActorError
        from ray_tpu.core.exceptions import ActorError

        got = 0
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            try:
                got = sum(1 for _ in handle.options(stream=True).remote(
                    {"prompt_ids": [1] * 4, "max_new_tokens": 3}))
                break
            except ActorError:
                time.sleep(0.3)
        assert got == 3


@pytest.mark.slow
class TestLoadHarnessSweep:
    def test_loadgen_quick_acceptance(self, tmp_path):
        """Full --quick harness in a child interpreter: curve schema, zero
        unexplained errors, autoscaled >= 1.5x fixed-1, quota sheds."""
        import json
        import os
        import subprocess
        import sys

        out = tmp_path / "BENCH_slo_test.json"
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        r = subprocess.run(
            [sys.executable, os.path.join(repo, "benches", "loadgen.py"),
             "--quick", "--out", str(out)],
            capture_output=True, text=True, timeout=900,
            env={**os.environ, "JAX_PLATFORMS": "cpu",
                 "RAY_TPU_METRICS_EXPORT_INTERVAL_S": "0.5"})
        assert r.returncode == 0, r.stderr[-2000:]
        acc = json.loads(out.read_text())["results"]["acceptance"]
        assert acc["unexplained_errors"] == 0
        assert acc["autoscaled_ge_1p5x_fixed1"]
        assert acc["quota_sheds"] > 0
        assert acc["scaled_back_to_min"]
