"""Tests for util (ActorPool, Queue, metrics), accelerators, state API, CLI —
modeled on the reference's ``python/ray/tests/test_actor_pool.py``,
``test_queue.py``, ``test_metrics.py``, and state-API tests.
"""

import json
import subprocess
import sys

import pytest

import ray_tpu
from ray_tpu.util.actor_pool import ActorPool
from ray_tpu.util.queue import Empty, Full, Queue
from ray_tpu.util import metrics as rt_metrics


class TestActorPool:
    def test_map_ordered(self, ray_start_regular):
        @ray_tpu.remote
        class Worker:
            def double(self, x):
                return x * 2

        pool = ActorPool([Worker.remote() for _ in range(2)])
        out = list(pool.map(lambda a, v: a.double.remote(v), range(8)))
        assert out == [x * 2 for x in range(8)]

    def test_map_unordered_complete(self, ray_start_regular):
        import time as _t

        @ray_tpu.remote
        class Worker:
            def work(self, x):
                _t.sleep(0.01 * (x % 3))
                return x

        pool = ActorPool([Worker.remote() for _ in range(3)])
        out = list(pool.map_unordered(lambda a, v: a.work.remote(v), range(9)))
        assert sorted(out) == list(range(9))

    def test_submit_more_than_actors(self, ray_start_regular):
        @ray_tpu.remote
        class Worker:
            def f(self, x):
                return x + 1

        pool = ActorPool([Worker.remote()])
        for i in range(5):
            pool.submit(lambda a, v: a.f.remote(v), i)
        results = [pool.get_next() for _ in range(5)]
        assert results == [1, 2, 3, 4, 5]


class TestQueue:
    def test_fifo_and_batch(self, ray_start_regular):
        q = Queue()
        for i in range(5):
            q.put(i)
        assert q.qsize() == 5
        assert [q.get() for _ in range(5)] == list(range(5))
        q.put_nowait_batch([10, 11, 12])
        assert q.get_nowait_batch(3) == [10, 11, 12]
        q.shutdown()

    def test_empty_and_full(self, ray_start_regular):
        q = Queue(maxsize=2)
        with pytest.raises(Empty):
            q.get_nowait()
        q.put(1)
        q.put(2)
        with pytest.raises(Full):
            q.put_nowait(3)
        assert q.full()
        q.shutdown()

    def test_cross_actor_queue(self, ray_start_regular):
        q = Queue()

        @ray_tpu.remote
        def producer(q, n):
            for i in range(n):
                q.put(i)
            return True

        assert ray_tpu.get(producer.remote(q, 4))
        assert [q.get(timeout=5) for _ in range(4)] == [0, 1, 2, 3]
        q.shutdown()


class TestMetrics:
    def test_counter_gauge_histogram(self):
        c = rt_metrics.Counter("test_requests", tag_keys=("route",))
        c.inc(1.0, {"route": "/a"})
        c.inc(2.0, {"route": "/a"})
        assert c.get({"route": "/a"}) == 3.0
        with pytest.raises(ValueError):
            c.inc(0)

        g = rt_metrics.Gauge("test_inflight")
        g.set(7)
        assert g.get() == 7.0

        h = rt_metrics.Histogram("test_latency", boundaries=[0.1, 1.0])
        h.observe(0.05)
        h.observe(0.5)
        h.observe(5.0)
        text = rt_metrics.prometheus_text()
        assert 'test_requests{route="/a"} 3.0' in text
        assert "test_latency_bucket" in text
        assert 'le="+Inf"} 3' in text

    def test_invalid_tags_rejected(self):
        g = rt_metrics.Gauge("test_tagged", tag_keys=("k",))
        with pytest.raises(ValueError):
            g.set(1.0, {"other": "x"})


class TestAccelerators:
    def test_resources_from_env(self, monkeypatch):
        from ray_tpu.accelerators import tpu as acc

        monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5litepod-16")
        monkeypatch.setenv("TPU_VISIBLE_CHIPS", "0,1,2,3")
        monkeypatch.setenv("TPU_WORKER_ID", "0")
        info = acc.detect_tpu()
        assert info is not None
        # jax may report the real attached chip count; env fallback says 4
        assert info.chips_on_host >= 1
        res = acc.tpu_resources(
            acc.TpuInfo(
                chips_on_host=4, accelerator_type="v5litepod-16", generation="V5E",
                pod_name=None, worker_id=0, hosts_in_slice=4,
            )
        )
        assert res["TPU"] == 4.0
        assert res["TPU-V5E"] == 4.0
        assert res["TPU-v5litepod-16-head"] == 1.0

    def test_non_head_worker_has_no_head_resource(self):
        from ray_tpu.accelerators import tpu as acc

        res = acc.tpu_resources(
            acc.TpuInfo(
                chips_on_host=4, accelerator_type="v5litepod-16", generation="V5E",
                pod_name=None, worker_id=2, hosts_in_slice=4,
            )
        )
        assert "TPU-v5litepod-16-head" not in res

    def test_generation_parsing(self):
        from ray_tpu.accelerators.tpu import _generation_from_type

        assert _generation_from_type("v5litepod-16") == "V5E"
        assert _generation_from_type("v4-8") == "V4"
        assert _generation_from_type("v5p-128") == "V5P"


class TestStateApi:
    def test_lists_and_summaries(self, ray_start_cluster):
        from ray_tpu.util import state

        @ray_tpu.remote
        def f(x):
            return x

        @ray_tpu.remote
        class A:
            def ping(self):
                return 1

        a = A.remote()
        ray_tpu.get([f.remote(i) for i in range(3)] + [a.ping.remote()])

        nodes = state.list_nodes()
        assert len(nodes) == 4 and all(n["state"] == "ALIVE" for n in nodes)
        actors = state.list_actors()
        assert any(x["class_name"] == "A" for x in actors)
        tasks = state.list_tasks()
        assert any(t["name"].endswith("f") for t in tasks)
        assert state.summarize_tasks().get("FINISHED", 0) >= 3
        summary = state.cluster_summary()
        assert summary["alive_nodes"] == 4


class TestCli:
    def test_status_and_list(self):
        import os

        env = {**__import__("os").environ, "JAX_PLATFORMS": "cpu"}
        out = subprocess.run(
            [sys.executable, "-m", "ray_tpu.scripts", "--num-cpus", "2", "status"],
            capture_output=True, text=True, timeout=120, cwd="/root/repo", env=env,
        )
        assert out.returncode == 0, out.stderr
        data = json.loads(out.stdout[out.stdout.index("{"):])
        assert data["alive_nodes"] >= 1


def test_cross_process_trace_propagation():
    """Spans propagate submit -> execute across PROCESS boundaries: a task
    tree submitted under a driver span shares one trace_id, parent links
    form the chain, and worker-side events reach the timeline through the
    batched task-event pipeline (tracing_helper.py + task_event_buffer.cc
    analogs)."""
    import time

    import ray_tpu
    from ray_tpu.core import runtime as runtime_mod
    from ray_tpu.core.cluster import Cluster, connect
    from ray_tpu.util import tracing

    cluster = Cluster(num_nodes=1, resources_per_node={"CPU": 2})
    try:
        core = connect(cluster.gcs_address)
        try:
            @ray_tpu.remote
            def child():
                return "leaf"

            @ray_tpu.remote
            def parent_task():
                return ray_tpu.get(child.remote(), timeout=120)

            with tracing.span("root", runtime=core) as (trace_id, root_span):
                assert ray_tpu.get(parent_task.remote(),
                                   timeout=240) == "leaf"
            # worker event buffers flush once a second
            def by_suffix():
                out = {}
                for e in ray_tpu.timeline():
                    for want in ("root", "parent_task", "child"):
                        if e["name"] == want or e["name"].endswith(want):
                            out[want] = e
                return out

            deadline = time.time() + 15
            while time.time() < deadline:
                named = by_suffix()
                if {"root", "parent_task", "child"} <= set(named):
                    break
                time.sleep(0.5)
            named = by_suffix()
            assert {"root", "parent_task", "child"} <= set(named), named.keys()
            p = named["parent_task"]["args"]
            c = named["child"]["args"]
            assert p["trace_id"] == trace_id
            assert c["trace_id"] == trace_id
            assert p["parent_span_id"] == root_span
            # child's parent is parent_task's span (the task id prefix)
            assert c["parent_span_id"] is not None
            assert c["parent_span_id"] != root_span
        finally:
            core.shutdown()
            runtime_mod._global_runtime = None
    finally:
        cluster.shutdown()
