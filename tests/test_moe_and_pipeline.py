"""Expert-parallel MoE + pipeline-parallel integration tests (SURVEY §7 P10:
"mesh-sharding configs for TP/PP/EP" — the strategies the reference delegates
to DeepSpeed, first-class here)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from ray_tpu.ops import moe
from ray_tpu.parallel.mesh import MeshSpec, cpu_mesh
from ray_tpu.parallel.pipeline import make_pipeline
from ray_tpu.parallel.sharding import ShardingRules, pytree_shardings


class TestMoE:
    def _setup(self, E=4, D=16, F=32, B=2, S=8, seed=0):
        cfg = moe.MoEConfig(d_model=D, d_ff=F, num_experts=E, capacity_factor=2.0)
        params = moe.init_params(cfg, jax.random.key(seed))
        x = jax.random.normal(jax.random.key(seed + 1), (B, S, D))
        return cfg, params, x

    def test_forward_shapes_and_finite(self):
        cfg, params, x = self._setup()
        y, metrics = moe.moe_ffn(params, x, cfg)
        assert y.shape == x.shape
        assert jnp.isfinite(y).all()
        assert float(metrics["dropped_fraction"]) == 0.0  # ample capacity

    def test_single_expert_equals_dense_ffn(self):
        """E=1 routes every token to the one expert with gate ≈ 1 → must equal
        a plain FFN with those weights."""
        cfg, params, x = self._setup(E=1, B=1, S=4)
        y, _ = moe.moe_ffn(params, x, cfg)
        h = jnp.einsum("bsd,df->bsf", x, params["w_up"][0])
        from ray_tpu.ops.layers import gelu

        expected = jnp.einsum("bsf,fd->bsd", gelu(h), params["w_down"][0])
        np.testing.assert_allclose(np.asarray(y), np.asarray(expected), rtol=1e-4, atol=1e-5)

    def test_capacity_drops_overflow(self):
        cfg = moe.MoEConfig(d_model=8, d_ff=16, num_experts=4, capacity_factor=0.1)
        params = moe.init_params(cfg, jax.random.key(0))
        x = jax.random.normal(jax.random.key(1), (4, 32, 8))
        y, metrics = moe.moe_ffn(params, x, cfg)
        assert float(metrics["dropped_fraction"]) > 0

    def test_expert_parallel_parity(self):
        """Sharding experts on the ``expert`` mesh axis must not change the
        math (XLA inserts the all_to_alls)."""
        cfg, params, x = self._setup(E=4, B=2, S=16)
        oracle, _ = moe.moe_ffn(params, x, cfg)

        mesh = cpu_mesh(MeshSpec(data=2, expert=4))
        rules = ShardingRules()
        shardings = pytree_shardings(moe.logical_axes(cfg), mesh, rules)
        sharded_params = jax.tree.map(jax.device_put, params, shardings)

        y, _ = jax.jit(lambda p, x: moe.moe_ffn(p, x, cfg))(sharded_params, x)
        np.testing.assert_allclose(np.asarray(oracle), np.asarray(y), rtol=1e-4, atol=1e-5)

    def test_trainable_end_to_end(self):
        """Router + experts learn: reconstruct targets through the MoE."""
        cfg, params, x = self._setup(E=2, D=8, F=16, B=4, S=8)
        target = jax.random.normal(jax.random.key(9), x.shape)
        opt = optax.adam(1e-2)
        state = opt.init(params)

        def loss_fn(p):
            y, m = moe.moe_ffn(p, x, cfg)
            return jnp.mean((y - target) ** 2) + 0.01 * m["aux_loss"]

        grad_fn = jax.jit(jax.value_and_grad(loss_fn))
        l0, _ = grad_fn(params)
        for _ in range(30):
            _, g = grad_fn(params)
            upd, state = opt.update(g, state)
            params = optax.apply_updates(params, upd)
        l1, _ = grad_fn(params)
        assert float(l1) < float(l0) * 0.9


class TestPipelineIntegration:
    def test_pipeline_matches_sequential(self):
        """GPipe schedule over the pipe axis == sequential stage application."""
        n_stages, n_micro, D = 4, 8, 16

        def stage_fn(p, x):
            return jnp.tanh(x @ p["w"] + p["b"])

        key = jax.random.key(0)
        ks = jax.random.split(key, n_stages)
        stage_params = {
            "w": jnp.stack([jax.random.normal(k, (D, D)) * 0.5 for k in ks]),
            "b": jnp.zeros((n_stages, D)),
        }
        # Layout contract: [microbatch, num_microbatches, ...] (pipeline
        # docstring — microbatch index trails the batch-sharded dim).
        x = jax.random.normal(jax.random.key(1), (4, n_micro, D))

        # sequential oracle (stage_fn broadcasts over leading dims)
        h = x
        for i in range(n_stages):
            p = {"w": stage_params["w"][i], "b": stage_params["b"][i]}
            h = stage_fn(p, h)

        mesh = cpu_mesh(MeshSpec(pipe=4, data=2))
        pipeline = make_pipeline(stage_fn, mesh, num_microbatches=n_micro)
        out = pipeline(stage_params, x)
        np.testing.assert_allclose(np.asarray(h), np.asarray(out), rtol=1e-4, atol=1e-5)


class TestPipelineTransformerTraining:
    """Differentiate THROUGH the GPipe schedule on the real model: a pipe=2
    (x data=2 x fsdp=2) train step whose losses must track the non-PP
    oracle step-for-step (gradients crossed ppermute correctly — step 2's
    loss depends on step 1's update)."""

    def test_pp_train_step_matches_oracle(self):
        from ray_tpu.models import transformer as tf
        from ray_tpu.models.training import make_train_step

        cfg = tf.tiny(n_layers=4)
        rules = ShardingRules()
        # B=16, M=4 -> microbatch 4, shardable over data*fsdp = 4.
        tokens = np.asarray(
            jax.random.randint(jax.random.key(0), (16, cfg.max_seq_len), 0,
                               cfg.vocab_size, jnp.int32))
        batch = {"tokens": jnp.asarray(tokens)}

        def run(mesh, loss_fn):
            bundle = make_train_step(
                loss_fn=loss_fn,
                init_params_fn=lambda k: tf.init_params(cfg, k),
                logical_params=tf.logical_axes(cfg),
                mesh=mesh,
                rules=rules,
                optimizer=optax.adamw(1e-3),
            )
            params, opt = bundle.init(jax.random.key(42))
            losses = []
            for _ in range(2):
                params, opt, m = bundle.step(params, opt, batch)
                losses.append(float(m["loss"]))
            return losses

        pp_mesh = cpu_mesh(MeshSpec(pipe=2, data=2, fsdp=2))
        pp_losses = run(
            pp_mesh,
            lambda p, b: tf.pp_lm_loss(p, b, cfg, mesh=pp_mesh, rules=rules,
                                       num_microbatches=4),
        )

        oracle_mesh = cpu_mesh(MeshSpec(data=2))
        oracle_losses = run(
            oracle_mesh,
            lambda p, b: tf.lm_loss(p, b, cfg, mesh=oracle_mesh, rules=rules),
        )
        np.testing.assert_allclose(pp_losses, oracle_losses, rtol=2e-4)
        # Training actually progressed.
        assert pp_losses[1] < pp_losses[0]

    def test_pp_sp_tp_composed_matches_oracle(self):
        """The full 3D composition in ONE jitted train step: blocks
        pipelined over ``pipe``, ring attention over ``seq`` and megatron
        psums over ``tensor`` INSIDE the pipeline shard_map. Losses must
        track the plain data-parallel oracle."""
        from ray_tpu.models import transformer as tf
        from ray_tpu.models.training import make_train_step

        cfg = tf.tiny(n_layers=2)
        rules = ShardingRules()
        tokens = np.asarray(
            jax.random.randint(jax.random.key(3), (8, cfg.max_seq_len), 0,
                               cfg.vocab_size, jnp.int32))
        batch = {"tokens": jnp.asarray(tokens)}

        def run(mesh, loss_fn):
            bundle = make_train_step(
                loss_fn=loss_fn,
                init_params_fn=lambda k: tf.init_params(cfg, k),
                logical_params=tf.logical_axes(cfg),
                mesh=mesh,
                rules=rules,
                optimizer=optax.adamw(1e-3),
            )
            params, opt = bundle.init(jax.random.key(7))
            losses = []
            for _ in range(2):
                params, opt, m = bundle.step(params, opt, batch)
                losses.append(float(m["loss"]))
            return losses

        mesh3d = cpu_mesh(MeshSpec(pipe=2, seq=2, tensor=2))
        l3d = run(
            mesh3d,
            lambda p, b: tf.pp_lm_loss(p, b, cfg, mesh=mesh3d, rules=rules,
                                       num_microbatches=2),
        )
        oracle_mesh = cpu_mesh(MeshSpec(data=2))
        lo = run(
            oracle_mesh,
            lambda p, b: tf.lm_loss(p, b, cfg, mesh=oracle_mesh, rules=rules),
        )
        np.testing.assert_allclose(l3d, lo, rtol=1e-3)
        assert l3d[1] < l3d[0]
