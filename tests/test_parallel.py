"""Parallel layer tests on the virtual 8-device CPU mesh: mesh construction,
logical shardings, ring/Ulysses attention vs oracle, pipeline vs serial."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ray_tpu.parallel.mesh import MeshSpec, cpu_mesh, make_mesh, mesh_shape
from ray_tpu.parallel.pipeline import make_pipeline
from ray_tpu.parallel.ring_attention import (
    make_ring_attention,
    make_ulysses_attention,
    reference_attention,
)
from ray_tpu.parallel.sharding import ShardingRules, logical_sharding, shard_pytree


def test_mesh_construction(cpu_mesh_devices):
    mesh = make_mesh(MeshSpec(data=2, tensor=4), cpu_mesh_devices)
    shape = mesh_shape(mesh)
    assert shape["data"] == 2 and shape["tensor"] == 4
    assert int(np.prod(list(shape.values()))) == 8


def test_mesh_wildcard(cpu_mesh_devices):
    mesh = make_mesh(MeshSpec(data=-1, tensor=2), cpu_mesh_devices)
    assert mesh_shape(mesh)["data"] == 4


def test_mesh_mismatch_raises(cpu_mesh_devices):
    with pytest.raises(ValueError, match="devices"):
        make_mesh(MeshSpec(data=3, tensor=5), cpu_mesh_devices)


def test_logical_sharding_rules():
    mesh = cpu_mesh(MeshSpec(data=2, tensor=4))
    rules = ShardingRules()
    s = logical_sharding(mesh, rules, ("embed", "mlp"))
    assert s.spec == P("fsdp", "tensor")
    s2 = logical_sharding(mesh, rules, ("batch", None, "heads"))
    assert s2.spec == P(("data", "fsdp"), None, "tensor")
    with pytest.raises(ValueError, match="unknown logical axis"):
        logical_sharding(mesh, rules, ("bogus",))


def test_shard_pytree_places_arrays():
    mesh = cpu_mesh(MeshSpec(data=2, tensor=4))
    rules = ShardingRules()
    params = {"w": jnp.ones((16, 32)), "b": jnp.ones((32,))}
    logical = {"w": ("embed", "mlp"), "b": ("mlp",)}
    placed = shard_pytree(params, logical, mesh, rules)
    assert placed["w"].sharding.spec == P("fsdp", "tensor")
    np.testing.assert_array_equal(np.asarray(placed["w"]), np.ones((16, 32)))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_oracle(causal):
    mesh = cpu_mesh(MeshSpec(seq=8))
    rng = np.random.default_rng(0)
    b, l, h, d = 2, 32, 4, 16
    q = jnp.asarray(rng.normal(size=(b, l, h, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, l, h, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, l, h, d)), jnp.float32)
    ring = make_ring_attention(mesh, causal=causal)
    out = jax.jit(ring)(q, k, v)
    expect = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expect), atol=2e-5)


def test_ring_attention_with_tensor_heads():
    """Ring over seq composes with head sharding on the tensor axis."""
    mesh = cpu_mesh(MeshSpec(seq=4, tensor=2))
    rng = np.random.default_rng(1)
    b, l, h, d = 2, 16, 4, 8
    q, k, v = (
        jnp.asarray(rng.normal(size=(b, l, h, d)), jnp.float32) for _ in range(3)
    )
    out = jax.jit(make_ring_attention(mesh, causal=True))(q, k, v)
    expect = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expect), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_oracle(causal):
    mesh = cpu_mesh(MeshSpec(seq=4))
    rng = np.random.default_rng(2)
    b, l, h, d = 2, 16, 4, 8  # heads divisible by seq axis
    q, k, v = (
        jnp.asarray(rng.normal(size=(b, l, h, d)), jnp.float32) for _ in range(3)
    )
    out = jax.jit(make_ulysses_attention(mesh, causal=causal))(q, k, v)
    expect = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expect), atol=2e-5)


def test_pipeline_matches_serial():
    mesh = cpu_mesh(MeshSpec(pipe=4, data=2))
    n_stages, n_mb, mb, dim = 4, 6, 4, 8
    rng = np.random.default_rng(3)
    weights = jnp.asarray(rng.normal(size=(n_stages, dim, dim)) * 0.3, jnp.float32)
    biases = jnp.asarray(rng.normal(size=(n_stages, dim)) * 0.1, jnp.float32)
    # Layout contract: [microbatch, num_microbatches, ...] — the microbatch
    # INDEX trails the batch-sharded dim (parallel.pipeline docstring).
    x = jnp.asarray(rng.normal(size=(mb, n_mb, dim)), jnp.float32)

    def stage_fn(params, h):
        w, b = params
        return jnp.tanh(h @ w + b)

    pipeline = make_pipeline(stage_fn, mesh, num_microbatches=n_mb)
    out = jax.jit(pipeline)((weights, biases), x)

    expect = x
    for s in range(n_stages):
        expect = jnp.tanh(expect @ weights[s] + biases[s])
    np.testing.assert_allclose(np.asarray(out), np.asarray(expect), atol=1e-5)


def test_collectives_between_actors(ray_start_regular):
    """The §5.8 eager collective contract, exercised from real actors."""
    rt = ray_start_regular
    from ray_tpu.parallel import collectives as col

    world = 4

    @rt.remote(max_concurrency=1)
    class Rank:
        def __init__(self, rank):
            self.rank = rank
            col.init_collective_group(world, rank, backend="local", group_name="g1")

        def do_allreduce(self):
            return col.allreduce(np.full(4, self.rank + 1.0), op="sum", group_name="g1")

        def do_broadcast(self):
            return col.broadcast(np.arange(3.0) if self.rank == 0 else None, 0, "g1")

        def do_allgather(self):
            return col.allgather(np.full(2, float(self.rank)), "g1")

        def do_reducescatter(self):
            return col.reducescatter(np.arange(8.0), op="sum", group_name="g1")

        def do_alltoall(self):
            return col.alltoall(np.full(4, float(self.rank)), "g1")

    ranks = [Rank.remote(i) for i in range(world)]
    out = rt.get([r.do_allreduce.remote() for r in ranks])
    for o in out:
        np.testing.assert_array_equal(o, np.full(4, 1.0 + 2 + 3 + 4))
    out = rt.get([r.do_broadcast.remote() for r in ranks])
    for o in out:
        np.testing.assert_array_equal(o, np.arange(3.0))
    out = rt.get([r.do_allgather.remote() for r in ranks])
    for o in out:
        assert len(o) == world
        np.testing.assert_array_equal(o[2], np.full(2, 2.0))
    out = rt.get([r.do_reducescatter.remote() for r in ranks])
    np.testing.assert_array_equal(out[1], np.array([2.0 * world * 1, 3.0 * world]))
    out = rt.get([r.do_alltoall.remote() for r in ranks])
    np.testing.assert_array_equal(out[3], np.array([0.0, 1.0, 2.0, 3.0]))


def test_collectives_send_recv(ray_start_regular):
    rt = ray_start_regular
    from ray_tpu.parallel import collectives as col

    @rt.remote
    class Peer:
        def __init__(self, rank):
            col.init_collective_group(2, rank, group_name="p2p")
            self.rank = rank

        def send_it(self):
            col.send(np.array([7.0, 8.0]), dst_rank=1, group_name="p2p")
            return True

        def recv_it(self):
            return col.recv(src_rank=0, group_name="p2p", timeout=10)

    a, b = Peer.remote(0), Peer.remote(1)
    r = b.recv_it.remote()
    rt.get(a.send_it.remote())
    np.testing.assert_array_equal(rt.get(r), np.array([7.0, 8.0]))


class TestCollectiveRoundStress:
    def test_back_to_back_allreduce_rounds(self, ray_start_regular):
        """Regression: a fast rank re-entering round k+1 while a straggler
        withdraws from round k must not corrupt slots (mixed-epoch race)."""
        import numpy as np

        import ray_tpu
        from ray_tpu.parallel import collectives

        @ray_tpu.remote
        class Member:
            def __init__(self, rank, world):
                from ray_tpu.parallel import collectives as c

                c.init_collective_group(world, rank, group_name="stress")
                self.rank = rank

            def run_rounds(self, n):
                from ray_tpu.parallel import collectives as c

                out = []
                for i in range(n):
                    # different shape per round: mixing rounds would blow up
                    shape = (2 + i % 3, 4)
                    val = np.full(shape, float(self.rank + 1))
                    out.append(float(c.allreduce(val, group_name="stress").sum()))
                return out

        world = 3
        members = [Member.remote(r, world) for r in range(world)]
        results = ray_tpu.get([m.run_rounds.remote(40) for m in members])
        assert results[0] == results[1] == results[2]
        # sum of (1+2+3) over each round's element count
        expected = [6.0 * ((2 + i % 3) * 4) for i in range(40)]
        assert results[0] == expected
        collectives.destroy_collective_group("stress")


@pytest.mark.skipif(not hasattr(jax, "shard_map"),
                    reason="backend='device' compiles a jax.shard_map psum; "
                           "without it one rank dies at compile and the rest "
                           "burn the full collective timeout")
def test_device_backend_allreduce_stays_on_device():
    """backend="device": the eager NCCL-tier analog (§5.8) — actor-held
    DEVICE arrays are reduced by a COMPILED psum over the devices they
    already live on; each rank's result lands on its own device, no host
    round trip. Exercised over 4 of the virtual CPU devices."""
    import threading

    from ray_tpu.parallel import collectives as col

    world = 4
    devices = jax.devices()[:world]
    results = {}
    errors = []

    def member(rank):
        try:
            col.init_collective_group(world, rank, backend="device",
                                      group_name="dev-g")
            x = jax.device_put(
                jnp.full((8,), float(rank + 1)), devices[rank])
            out = col.allreduce(x, op="sum", group_name="dev-g")
            results[rank] = out
        except Exception as e:  # noqa: BLE001
            errors.append((rank, e))

    threads = [threading.Thread(target=member, args=(r,), daemon=True)
               for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    # daemon=True: a wedged member must FAIL here, not hang interpreter
    # exit at threading._shutdown.
    assert not any(t.is_alive() for t in threads), "member thread hung"
    assert not errors, errors
    expect = sum(range(1, world + 1))  # 1+2+3+4
    for rank in range(world):
        out = results[rank]
        assert isinstance(out, jax.Array)
        np.testing.assert_allclose(np.asarray(out), np.full((8,), expect))
        # The result shard lives on the rank's OWN device.
        assert list(out.devices())[0] == devices[rank], (
            rank, out.devices())
    col.destroy_collective_group("dev-g")


def test_device_backend_mean_and_colocated_fallback():
    import threading

    from ray_tpu.parallel import collectives as col

    world = 2
    dev = jax.devices()[0]  # BOTH ranks on one device: compiled fallback
    results = {}

    def member(rank):
        col.init_collective_group(world, rank, backend="device",
                                  group_name="dev-co")
        x = jax.device_put(jnp.full((4,), float(rank)), dev)
        results[rank] = col.allreduce(x, op="mean", group_name="dev-co")

    threads = [threading.Thread(target=member, args=(r,), daemon=True)
               for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not any(t.is_alive() for t in threads), "member thread hung"
    for rank in range(world):
        np.testing.assert_allclose(np.asarray(results[rank]),
                                   np.full((4,), 0.5))
    col.destroy_collective_group("dev-co")


@pytest.mark.skipif(not hasattr(jax, "shard_map"),
                    reason="backend='device' compiles a jax.shard_map psum; "
                           "without it one rank dies at compile and the rest "
                           "burn the full collective timeout")
def test_device_backend_from_actors(ray_start_regular):
    """backend="device" through REAL actors (in-process runtime: actors
    share the process, each pins its array to a different virtual
    device) — the eager §5.8 device-tier contract end-to-end."""
    rt = ray_start_regular
    from ray_tpu.parallel import collectives as col

    world = 4

    @rt.remote(max_concurrency=1)
    class DeviceRank:
        def __init__(self, rank):
            self.rank = rank
            self.dev = jax.devices()[rank]
            col.init_collective_group(world, rank, backend="device",
                                      group_name="adev")

        def reduce(self):
            x = jax.device_put(jnp.full((16,), float(self.rank + 1)),
                               self.dev)
            out = col.allreduce(x, op="sum", group_name="adev")
            return (np.asarray(out),
                    list(out.devices())[0] == self.dev)

    ranks = [DeviceRank.remote(i) for i in range(world)]
    results = rt.get([r.reduce.remote() for r in ranks], timeout=300)
    expect = np.full((16,), float(sum(range(1, world + 1))))
    for arr, on_own_device in results:
        np.testing.assert_allclose(arr, expect)
        assert on_own_device
    col.destroy_collective_group("adev")


class TestBroadcastSubtreeAcks:
    """_broadcast republisher ack accounting (ADVICE r5): a non-root rank
    that publishes the payload to shm must expect acks from its binomial
    SUBTREE only — publishing with consumers=n-1 would leave shm_done
    forever short of zero and leak the backing object."""

    def test_subtree_consumer_counts(self):
        from ray_tpu.parallel.collectives import _DistributedGroup

        f = _DistributedGroup._bc_subtree_consumers

        def children(rel, n):
            out, k = [], 1
            while k < n:  # mirrors _broadcast's child enumeration
                if rel < k and rel + k < n:
                    out.append(rel + k)
                k *= 2
            return out

        for n in range(1, 33):
            # Root's subtree covers the whole tree: n-1 descendants.
            assert f(0, n) == n - 1
            for r in range(n):
                # Recursive consistency: my acks = each child's delivery
                # plus everything that child forwards.
                assert f(r, n) == sum(1 + f(c, n) for c in children(r, n))
        # Spot checks in the n=8 binomial tree: 1 -> {3, 5}, 3 -> {7}.
        assert f(1, 8) == 3
        assert f(2, 8) == 1  # 2 -> {6}
        assert f(4, 8) == 0  # leaf

    def test_republisher_publishes_with_subtree_count(self):
        """Rank 1 of 4 (src=0) receives by socket (root's publish failed),
        republishes to shm for its children: consumers must equal its
        subtree size (1 = rank 3), not n-1 = 3."""
        from ray_tpu.parallel.collectives import _DistributedGroup

        g = object.__new__(_DistributedGroup)
        g.world_size = 4
        g.rank = 1
        g._addrs = {i: f"addr{i}" for i in range(4)}
        g._stores = {i: "storeA" for i in range(4)}
        g._all_same_store = True
        g._shm = object()  # only truthiness is checked on this path

        published = {}

        def publish(arr, consumers):
            published["consumers"] = consumers
            return b"k" * 16

        g._publish_shm = publish

        class _Fut:
            def result(self, timeout=None):
                return True

        sent = []

        class _Peer:
            def call_async(self, method, *args):
                sent.append((method, args))
                return _Fut()

        class _Peers:
            def get(self, addr):
                return _Peer()

        g._peers = _Peers()
        payload = np.ones(_DistributedGroup.SHM_MIN_BYTES // 8 + 16,
                          dtype=np.float64)
        g._service = None  # not used on this path
        g._recv = lambda tag, timeout=120.0: payload  # socket delivery
        out = g._broadcast(seq=9, value=None, src=0)
        assert np.array_equal(out, payload)
        assert published["consumers"] == \
            _DistributedGroup._bc_subtree_consumers(1, 4) == 1
        # The forward to the child went by shm key.
        assert sent and sent[0][0] == "deliver_shm"
