"""Sharded control plane — capacity blocks, sharded tables, ingest plane.

Covers the round-8 control-plane split: batched daemon-local scheduling
leases (one GCS hop grants a revocable capacity BLOCK; per-task leases are
carved at the node daemon), hash-sharded GCS tables (object directory /
pubsub / KV in independent lock domains), and the non-blocking
observability ingest queue (a slow aggregator may lag telemetry but can
never stall a lease grant).
"""

import contextlib
import os
import threading
import time

import pytest

from ray_tpu.core.config import Config, config, set_config
from ray_tpu.core.ids import NodeID
from ray_tpu.core.lease_table import (LocalLeaseTable, block_of,
                                      is_block_lease)
from ray_tpu.core.rpc import RpcClient, RpcServer


@contextlib.contextmanager
def _cfg(**flags):
    """Env-backed config override, restored on exit (the same resolution
    path a real process uses: RAY_TPU_<NAME> before defaults)."""
    old = {}
    for k, v in flags.items():
        key = f"RAY_TPU_{k.upper()}"
        old[key] = os.environ.get(key)
        os.environ[key] = str(v)
    set_config(Config())
    try:
        yield
    finally:
        for key, v in old.items():
            if v is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = v
        set_config(Config())


def _wait_for(predicate, timeout=60.0, interval=0.1):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


# ====================== local lease table (daemon side) ======================


def test_local_lease_table_carve_release_sweep():
    t = LocalLeaseTable()
    t.adopt("cap-1", {"CPU": 1}, 3)
    ids = [t.carve("cap-1") for _ in range(3)]
    assert all(ids) and len(set(ids)) == 3
    assert all(is_block_lease(i) and block_of(i) == "cap-1" for i in ids)
    assert t.carve("cap-1") is None  # exhausted
    assert t.release(ids[0]) is True
    assert t.free_units("cap-1") == 1
    # Idle sweep only reaps blocks past the TTL; fresh activity protects it.
    assert t.sweep_idle(10.0) == []
    time.sleep(0.05)
    swept = t.sweep_idle(0.01)
    assert swept == [("cap-1", 1)]
    assert t.free_units("cap-1") == 0
    # GCS rejected the return (e.g. restart): unsweep puts the unit back.
    t.unsweep("cap-1", 1)
    assert t.free_units("cap-1") == 1


def test_local_lease_table_revoke_vs_release_no_double_free():
    """GCS revocation racing a lease release: the released unit is
    DISCARDED, never re-carved — the GCS already re-granted that capacity
    elsewhere, so re-carving it here would double-spend the resources."""
    t = LocalLeaseTable()
    t.adopt("cap-7", {"CPU": 1}, 2)
    a = t.carve("cap-7")
    b = t.carve("cap-7")
    t.revoke("cap-7")
    assert t.carve("cap-7") is None  # revoked blocks grant nothing
    assert t.release(a) is True  # lease known; its unit is DISCARDED
    assert t.free_units("cap-7") == 0  # ...not freed for re-carving
    assert t.carve("cap-7") is None
    assert t.release(b) is True
    # Fully drained revoked block is forgotten entirely.
    assert t.stats() == {}
    assert t.release(a) is False  # double release of a dead lease: no-op


def test_local_lease_table_adopt_on_first_touch():
    """The carve-side adopt hint: a daemon that never saw the GCS's adopt
    push (lost notify) still serves carves — the first carve carries the
    block's shape and size inline."""
    t = LocalLeaseTable()
    lease = t.carve("cap-9", shape={"CPU": 1}, total=2)
    assert lease == "cap-9#1" or lease.startswith("cap-9#")
    assert t.carve("cap-9") is not None
    assert t.carve("cap-9") is None
    assert t.carve("cap-404") is None  # unknown block, no hint: refused


# ====================== batched grants (GCS side) ======================


def _fresh_service(**flags):
    """In-process GcsService under a config override; no real daemons run
    at the fake node addresses, so grant pushes are silently swallowed
    (the carve-side adopt hint covers real clusters)."""
    from ray_tpu.core.gcs_server import GcsService

    ctx = _cfg(**flags) if flags else contextlib.nullcontext()
    return ctx, GcsService


def test_lease_batch_grant_and_partial_return():
    ctx, GcsService = _fresh_service()
    with ctx:
        svc = GcsService()
        try:
            svc.register_node(NodeID.from_random(), "127.0.0.1:1",
                              {"CPU": 4}, {})
            block_id, node_id, addr, granted = svc.request_lease_batch(
                {"CPU": 1}, None, count=10, timeout=5.0, _client_id="c1")
            # Partial grant: the node holds 4 units, not 10.
            assert granted == 4 and block_id.startswith("cap-")
            assert svc.available_resources().get("CPU", 0) == 0
            # Daemon ships back 2 idle units.
            assert svc.return_block_capacity(block_id, 2) is True
            assert svc.available_resources().get("CPU", 0) == 2
            # Over-return clamps to what's still out.
            assert svc.return_block_capacity(block_id, 99) is True
            assert svc.available_resources().get("CPU", 0) == 4
            # Fully-returned block is gone; further returns say so.
            assert svc.return_block_capacity(block_id, 1) is False
        finally:
            svc.shutdown()


def test_lease_batch_rejects_placement_group_strategy():
    from ray_tpu.core.task_spec import PlacementGroupSchedulingStrategy

    ctx, GcsService = _fresh_service()
    with ctx:
        svc = GcsService()
        try:
            svc.register_node(NodeID.from_random(), "127.0.0.1:1",
                              {"CPU": 4}, {})
            with pytest.raises(ValueError):
                svc.request_lease_batch(
                    {"CPU": 1},
                    PlacementGroupSchedulingStrategy("pg", None), count=2)
        finally:
            svc.shutdown()


def test_block_reclaim_on_client_death_no_double_free():
    """Client dies holding a capacity block the daemon partially returned:
    the GCS reclaims exactly total-returned units — both orderings of
    (daemon return x client-death reclaim) end at full availability."""
    ctx, GcsService = _fresh_service()
    with ctx:
        svc = GcsService()
        try:
            svc.register_node(NodeID.from_random(), "127.0.0.1:1",
                              {"CPU": 4}, {})
            block_id, _n, _a, granted = svc.request_lease_batch(
                {"CPU": 1}, None, count=4, timeout=5.0, _client_id="dead-1")
            assert granted == 4
            svc.return_block_capacity(block_id, 1)  # daemon sweep first
            svc.on_client_closed("dead-1")  # then the client dies
            assert svc.available_resources().get("CPU", 0) == 4
            # The reclaim consumed the block: a late daemon return is
            # refused (the daemon then revokes its local record).
            assert svc.return_block_capacity(block_id, 1) is False
        finally:
            svc.shutdown()


def test_pending_demands_visible_while_batch_waits():
    """The incrementally-maintained demand list (autoscaler feed) shows a
    waiting batch request's shape, and clears when the wait ends."""
    ctx, GcsService = _fresh_service()
    with ctx:
        svc = GcsService()  # no nodes: everything waits
        try:
            done = threading.Event()

            def ask():
                with contextlib.suppress(TimeoutError):
                    svc.request_lease_batch({"TPU": 8}, None, count=4,
                                            timeout=1.5)
                done.set()

            threading.Thread(target=ask, daemon=True).start()
            assert _wait_for(
                lambda: {"TPU": 8.0} in svc.pending_resource_demands()
                or {"TPU": 8} in svc.pending_resource_demands(), timeout=5)
            assert done.wait(timeout=10)
            assert svc.pending_resource_demands() == []
        finally:
            svc.shutdown()


def test_shape_indexed_wakeups_skip_unfit_shapes():
    """S1: releases of one resource shape must not wake waiters parked on
    a shape no node can fit — the old notify_all() thundering herd."""
    ctx, GcsService = _fresh_service()
    with ctx:
        svc = GcsService()
        try:
            cpu_node = NodeID.from_random()
            svc.register_node(cpu_node, "127.0.0.1:1", {"CPU": 4}, {})
            got = {}

            def want_tpu():
                with contextlib.suppress(TimeoutError):
                    got["r"] = svc.request_lease({"TPU": 8}, None,
                                                 timeout=30.0)

            t = threading.Thread(target=want_tpu, daemon=True)
            t.start()
            assert _wait_for(lambda: svc.wake_stats() is not None
                             and bool(svc._shape_waiters), timeout=5)
            # CPU lease churn: grants + releases while the TPU waiter parks.
            for _ in range(5):
                lease_id, _n, _a = svc.request_lease({"CPU": 1}, None,
                                                     timeout=5.0)
                svc.release_lease(lease_id)
            stats = svc.wake_stats()
            assert stats["skips"] >= 5, stats  # TPU shape never notified
            assert "r" not in got
            # A TPU node registering wakes everyone (membership events use
            # the wake-all path) and the waiter completes.
            svc.register_node(NodeID.from_random(), "127.0.0.1:2",
                              {"TPU": 8}, {})
            t.join(timeout=10)
            assert not t.is_alive() and "r" in got
        finally:
            svc.shutdown()


# ====================== sharded tables ======================


def test_shard_routing_stable_and_single_shard_compat():
    from ray_tpu.core.gcs_shards import shard_index

    assert shard_index("chan", 1) == 0
    assert shard_index(b"\x00" * 28, 1) == 0
    # crc32 routing is process-independent: pin a few known routes so a
    # refactor to seeded hash() (restart-unstable) fails loudly.
    assert shard_index("chan", 8) == shard_index("chan", 8)
    for key in (b"a" * 28, b"b" * 28, "node", "object_locations"):
        assert 0 <= shard_index(key, 8) < 8


def test_sharded_directory_and_pubsub_round_trip():
    """Locations, lineage GC, filtered subscribes and channel polls behave
    identically at gcs_shards=4 — sharding moves lock domains, not
    semantics."""
    ctx, GcsService = _fresh_service(gcs_shards=4)
    with ctx:
        assert config().gcs_shards == 4
        svc = GcsService()
        try:
            node = NodeID.from_random()
            svc.register_node(node, "127.0.0.1:1", {"CPU": 4}, {})
            oids = [bytes([i]) * 24 + b"\x00" * 4 for i in range(16)]
            for oid in oids:
                svc.add_object_location(oid, node, 100 + oid[0])
            for oid in oids:
                locs = svc.locate_object(oid)
                assert [(n, a, s) for n, a, s in locs] == [
                    (node, "127.0.0.1:1", 100 + oid[0])]
            batch = svc.locate_object_batch(oids)
            assert len(batch) == 16 and all(len(b) == 1 for b in batch)
            svc.remove_object_location(oids[0], node)
            assert svc.locate_object(oids[0]) == []
            # Filtered subscribe wakes only on its oid, across shards.
            target = oids[5]
            cur, _ = svc.subscribe_object_locations(None, 0.1, [target])
            done = {}

            def park():
                done["r"] = svc.subscribe_object_locations(cur, 10.0,
                                                           [target])

            t = threading.Thread(target=park, daemon=True)
            t.start()
            time.sleep(0.2)
            svc._publish("object_locations", (oids[7], node, "a", 1))
            time.sleep(0.2)
            assert "r" not in done
            svc._publish("object_locations", (target, node, "a", 1))
            t.join(timeout=5)
            assert [m[0] for m in done["r"][1]] == [target]
        finally:
            svc.shutdown()


def test_kv_sharding_and_snapshot_across_shard_counts():
    """KV routes to gcs_shards independent lock domains; a snapshot taken
    at one shard count restores at another (restart with a new config)."""
    from ray_tpu.core.gcs import GlobalControlStore

    with _cfg(gcs_shards=4):
        store = GlobalControlStore()
        assert store.kv_shard_count() == 4
        for i in range(32):
            store.kv_put(f"k{i}", f"v{i}".encode(), namespace="ns")
        store.kv_put("k0", b"x", namespace="other")
        assert store.kv_get("k7", namespace="ns") == b"v7"
        assert sorted(store.kv_keys(namespace="ns")) == sorted(
            f"k{i}" for i in range(32))
        store.kv_del("k0", namespace="ns")
        assert store.kv_get("k0", namespace="ns") is None
        dump = store.kv_dump()
    with _cfg(gcs_shards=2):
        store2 = GlobalControlStore()
        assert store2.kv_shard_count() == 2
        store2.kv_load(dump)
        assert store2.kv_get("k7", namespace="ns") == b"v7"
        assert store2.kv_get("k0", namespace="other") == b"x"
        assert store2.kv_get("k0", namespace="ns") is None


# ====================== observability ingest plane ======================


def test_slow_aggregator_cannot_stall_lease_grants():
    """THE regression this plane exists for: a slow metrics apply used to
    park GCS handler threads until the pool starved and request_lease
    queued behind telemetry. With the ingest queue, reports land in the
    staging deque and the handler returns; a lease grant through the SAME
    4-thread server stays fast while the aggregator crawls."""
    ctx, GcsService = _fresh_service()
    with ctx:
        svc = GcsService()
        server = RpcServer(svc, max_workers=4, name="gcs-lag")
        try:
            svc.register_node(NodeID.from_random(), "127.0.0.1:1",
                              {"CPU": 4}, {})
            orig = svc.store.report_metrics
            svc.store.report_metrics = (
                lambda *a, **k: (time.sleep(0.5), orig(*a, **k)))
            flood = RpcClient(server.address)
            lease = RpcClient(server.address)
            try:
                for i in range(12):  # 6s of serialized apply work staged
                    flood.notify("report_metrics", "n", "comp", i, [])
                t0 = time.monotonic()
                lease_id, _n, _a = lease.call(
                    "request_lease", {"CPU": 1}, None, 10.0, timeout=10.0)
                elapsed = time.monotonic() - t0
                assert elapsed < 2.0, (
                    f"lease grant took {elapsed:.2f}s behind telemetry")
                lease.notify("release_lease", lease_id)
                # notify() is fire-and-forget: on a loaded box the flood
                # frames may still be in the conn loop when the grant
                # returns — poll until the staging deque has seen them.
                deadline = time.monotonic() + 20.0
                while time.monotonic() < deadline:
                    stats = lease.call("ingest_stats")
                    if stats["submitted"] >= 12:
                        break
                    time.sleep(0.1)
                assert stats["submitted"] >= 12
            finally:
                flood.close()
                lease.close()
        finally:
            server.stop()
            svc.shutdown()


def test_ingest_queue_bounded_drops_counted():
    ctx, GcsService = _fresh_service(gcs_ingest_queue_max=4)
    with ctx:
        svc = GcsService()
        try:
            orig = svc.store.report_metrics
            svc.store.report_metrics = (
                lambda *a, **k: (time.sleep(0.2), orig(*a, **k)))
            for i in range(64):
                svc.report_metrics("n", "comp", i, [])
            stats = svc.ingest_stats()
            assert stats["dropped"] > 0
            assert stats["submitted"] + stats["dropped"] == 64
        finally:
            svc.shutdown()


def test_ingest_read_your_writes_and_inline_fallback():
    """Readers see staged events (flush barrier), and disabling the plane
    reproduces the old inline-apply behavior exactly."""
    ctx, GcsService = _fresh_service()
    with ctx:
        svc = GcsService()
        try:
            svc.record_task_event({"task_id": "t1", "state": "RUNNING",
                                   "ts": 1.0})
            events = svc.task_events()
            assert any(e.get("task_id") == "t1" for e in events)
        finally:
            svc.shutdown()
    ctx, GcsService = _fresh_service(gcs_ingest_async_enabled=0)
    with ctx:
        svc = GcsService()
        try:
            assert svc._ingest is None
            svc.record_task_event({"task_id": "t2", "state": "RUNNING",
                                   "ts": 1.0})
            assert any(e.get("task_id") == "t2" for e in svc.task_events())
            assert svc.ingest_stats() == {"queued": 0, "dropped": 0,
                                          "submitted": 0, "drained": 0}
        finally:
            svc.shutdown()


# ====================== multiprocess: blocks across real daemons ======================


@pytest.fixture(scope="module")
def block_cluster():
    from ray_tpu.core.cluster import Cluster

    cluster = Cluster(num_nodes=2, resources_per_node={"CPU": 2})
    yield cluster
    cluster.shutdown()


def test_capacity_block_protocol_end_to_end(block_cluster):
    """Raw protocol drive: GCS batch grant -> daemon carve -> worker runs
    -> return -> daemon idle sweep ships capacity back to the GCS."""
    gcs = RpcClient(block_cluster.gcs_address)
    daemon = None
    try:
        block_id, node_id, addr, granted = gcs.call(
            "request_lease_batch", {"CPU": 1}, None, 2, 30.0, timeout=35.0)
        assert granted == 2
        daemon = RpcClient(addr)
        got1 = daemon.call("lease_worker_block", block_id, {"CPU": 1}, 2,
                           timeout=60.0)
        got2 = daemon.call("lease_worker_block", block_id, {"CPU": 1}, 2,
                           timeout=60.0)
        assert got1 and got2
        assert is_block_lease(got1[0]) and block_of(got1[0]) == block_id
        # Block exhausted: a third carve is refused locally.
        assert daemon.call("lease_worker_block", block_id, {"CPU": 1}, 2,
                           timeout=10.0) is None
        for got in (got1, got2):
            daemon.notify("return_leased_worker", got[1])
        # Freed units idle past the TTL; the daemon sweep returns them and
        # the GCS sees full availability with the block retired.
        assert _wait_for(
            lambda: gcs.call("available_resources").get("CPU", 0) == 4.0,
            timeout=30)
        assert gcs.call("return_block_capacity", block_id, 1) is False
    finally:
        if daemon is not None:
            daemon.close()
        gcs.close()


def test_lease_worker_block_n_carves_batch_in_one_hop(block_cluster):
    """The n-carve RPC returns up to n (lease, worker) pairs in ONE daemon
    round trip, short-returns under pool pressure instead of stalling, and
    reports exhaustion as an empty list."""
    gcs = RpcClient(block_cluster.gcs_address)
    daemon = None
    try:
        block_id, _nid, addr, granted = gcs.call(
            "request_lease_batch", {"CPU": 1}, None, 2, 30.0, timeout=35.0)
        assert granted == 2
        daemon = RpcClient(addr)
        grants = []
        deadline = time.time() + 60.0
        while len(grants) < 2 and time.time() < deadline:
            # Short batches are legal (slow worker spawn): keep asking for
            # the remainder, as the client's carve loop does.
            grants += daemon.call("lease_worker_block_n", block_id,
                                  {"CPU": 1}, 2, 4, timeout=70.0)
        assert len(grants) == 2
        leases = {g[0] for g in grants}
        assert len(leases) == 2
        assert all(is_block_lease(lid) and block_of(lid) == block_id
                   for lid in leases)
        # Exhausted block: the n-carve reports it as an empty batch.
        assert daemon.call("lease_worker_block_n", block_id, {"CPU": 1},
                           2, 4, timeout=10.0) == []
        for g in grants:
            daemon.notify("return_leased_worker", g[1])
        assert _wait_for(
            lambda: gcs.call("available_resources").get("CPU", 0) == 4.0,
            timeout=30)
    finally:
        if daemon is not None:
            daemon.close()
        gcs.close()


def test_lease_requester_pool_bounded_under_burst(block_cluster):
    """S2: a burst far wider than the cluster spawns at most
    lease_requester_threads concurrent lease-req pool threads (the old
    transport spun one thread per queued task, up to 64 per key)."""
    import ray_tpu
    from ray_tpu.core import runtime as runtime_mod
    from ray_tpu.core.cluster import connect

    core = connect(block_cluster.gcs_address)
    try:
        @ray_tpu.remote
        def nap():
            time.sleep(0.2)
            return os.getpid()

        refs = [nap.remote() for _ in range(40)]
        peak = 0
        deadline = time.time() + 5.0
        while time.time() < deadline:
            n = sum(1 for t in threading.enumerate()
                    if t.name.startswith("lease-req"))
            peak = max(peak, n)
            time.sleep(0.02)
        assert peak <= config().lease_requester_threads, peak
        assert peak >= 1  # the pool did engage
        pids = ray_tpu.get(refs, timeout=120)
        assert len(pids) == 40
    finally:
        core.shutdown()
        runtime_mod._global_runtime = None


def test_daemon_sigkill_holding_block_reclaims_capacity(block_cluster):
    """kill -9 the daemon holding a live capacity block: node-death
    handling drops the node AND its blocks in one motion — no resources
    leak, and a late return for the dead block is refused. (Defined last:
    it removes a node from the module-scoped cluster.)"""
    gcs = RpcClient(block_cluster.gcs_address)
    try:
        block_id, node_id, addr, granted = gcs.call(
            "request_lease_batch", {"CPU": 1}, None, 2, 30.0, timeout=35.0)
        assert granted == 2
        idx = next(i for i, h in enumerate(block_cluster.nodes)
                   if h.address == addr)
        block_cluster.kill_node(idx)
        # Death detection drops the node's 2 CPUs and its block; the
        # survivor's 2 CPUs are all that remain — and all of them free.
        assert _wait_for(
            lambda: gcs.call("available_resources").get("CPU", 0) == 2.0
            and gcs.call("cluster_resources").get("CPU", 0) == 2.0,
            timeout=60)
        assert gcs.call("return_block_capacity", block_id, 1) is False
    finally:
        gcs.close()
