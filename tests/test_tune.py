"""Tune tests, modeled on the reference's ``python/ray/tune/tests/``:
variant generation (grid × random), controller end-to-end, ASHA early
stopping, PBT exploit/explore, trainer-through-tune integration.
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.tune import (
    ASHAScheduler,
    BasicVariantGenerator,
    PopulationBasedTraining,
    TuneConfig,
    Tuner,
    TrialStatus,
)


class TestSearchSpaces:
    def test_grid_cross_product_and_samples(self):
        space = {
            "a": tune.grid_search([1, 2, 3]),
            "b": tune.grid_search(["x", "y"]),
            "c": tune.uniform(0.0, 1.0),
        }
        gen = BasicVariantGenerator(space, num_samples=2, seed=0)
        assert gen.total_variants == 12  # 3*2 grid × 2 samples
        cfgs = [gen.suggest(str(i)) for i in range(12)]
        assert all(c is not None for c in cfgs)
        assert gen.suggest("13") is None
        assert {c["a"] for c in cfgs} == {1, 2, 3}
        assert all(0.0 <= c["c"] <= 1.0 for c in cfgs)

    def test_random_only_space(self):
        gen = BasicVariantGenerator(
            {"lr": tune.loguniform(1e-5, 1e-1), "n": tune.randint(1, 5)},
            num_samples=8,
            seed=1,
        )
        assert gen.total_variants == 8
        for i in range(8):
            c = gen.suggest(str(i))
            assert 1e-5 <= c["lr"] <= 1e-1
            assert 1 <= c["n"] < 5

    def test_nested_space(self):
        gen = BasicVariantGenerator(
            {"opt": {"lr": tune.choice([1, 2]), "wd": 0.1}}, num_samples=3, seed=0
        )
        c = gen.suggest("0")
        assert c["opt"]["lr"] in (1, 2) and c["opt"]["wd"] == 0.1


class TestTunerE2E:
    def test_fifo_runs_all_trials(self, ray_start_regular):
        def trainable(config):
            tune.report({"score": config["x"] * 2})

        grid = Tuner(
            trainable,
            param_space={"x": tune.grid_search([1, 2, 3, 4])},
            tune_config=TuneConfig(metric="score", mode="max"),
        ).fit()
        assert len(grid) == 4
        assert grid.num_errors == 0
        assert grid.get_best_result().metrics["score"] == 8

    def test_final_return_dict_counts_as_report(self, ray_start_regular):
        def trainable(config):
            return {"score": config["x"]}

        grid = tune.run(trainable, config={"x": tune.grid_search([5, 7])},
                        metric="score", mode="max")
        assert grid.get_best_result().metrics["score"] == 7

    def test_trial_error_isolated(self, ray_start_regular):
        def trainable(config):
            if config["x"] == 2:
                raise RuntimeError("bad trial")
            tune.report({"score": config["x"]})

        grid = tune.run(trainable, config={"x": tune.grid_search([1, 2, 3])},
                        metric="score", mode="max")
        assert grid.num_errors == 1
        assert grid.get_best_result().metrics["score"] == 3

    def test_asha_stops_bad_trials_early(self, ray_start_regular):
        iters_run = {}

        def trainable(config):
            n = 0
            for i in range(1, 17):
                n = i
                tune.report({"loss": config["quality"] + i * 0.001})
            # record via metric (can't touch driver state from actor)
            tune.report({"loss": config["quality"], "final_iters": n})

        grid = tune.run(
            trainable,
            config={"quality": tune.grid_search([0.1, 0.2, 5.0, 6.0])},
            metric="loss",
            mode="min",
            scheduler=ASHAScheduler(max_t=32, grace_period=2, reduction_factor=2, mode="min"),
        )
        statuses = [t.status for t in grid._trials]
        assert TrialStatus.STOPPED in statuses  # bad trials cut early
        # the best (lowest quality value) trial survived to completion
        best = grid.get_best_result()
        assert best.metrics["loss"] <= 0.2

    def test_pbt_exploits_and_restores(self, ray_start_regular, tmp_path):
        """Bad PBT trials must pick up the good trial's checkpointed step &
        mutated lr."""
        from ray_tpu.train import save_pytree, load_pytree

        def trainable(config):
            ctx = tune.get_context()
            start, inherited_lr = 0, None
            ck = tune.get_checkpoint()
            if ck is not None:
                state = load_pytree(ck.path)
                start = state["step"]
                inherited_lr = state["lr"]
            score = config["lr"]  # higher lr == better, to make exploit deterministic
            import tempfile as tf

            for i in range(start, start + 12):
                d = tf.mkdtemp()
                save_pytree({"step": i + 1, "lr": config["lr"]}, d)
                tune.report(
                    {"score": score, "step": i + 1, "inherited": inherited_lr or 0.0},
                    checkpoint=tune.Checkpoint(d),
                )

        grid = tune.run(
            trainable,
            config={"lr": tune.grid_search([0.01, 1.0])},
            metric="score",
            mode="max",
            scheduler=PopulationBasedTraining(
                metric="score",
                mode="max",
                perturbation_interval=3,
                quantile_fraction=0.5,
                hyperparam_mutations={"lr": tune.uniform(0.5, 2.0)},
                seed=0,
            ),
        )
        restarted = [t for t in grid._trials if t.restarts > 0]
        assert restarted, "PBT should have restarted the weak trial"
        # after exploit, the restarted trial inherits the strong lr lineage
        assert any(
            t.last_result.get("inherited", 0) >= 0.5 for t in restarted
        ), [t.last_result for t in grid._trials]

    def test_trainer_through_tuner(self, ray_start_regular, tmp_path):
        """Reference layering: Train's fit runs through Tune
        (``base_trainer.py:580``) — here via as_trainable()."""
        from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig
        import ray_tpu.train as rtt

        def loop(config):
            rtt.report({"loss": 1.0 / config.get("lr", 1.0)})

        trainer = JaxTrainer(
            loop,
            scaling_config=ScalingConfig(num_workers=1),
            run_config=RunConfig(storage_path=str(tmp_path), name="tt"),
        )
        grid = Tuner(
            trainer,
            param_space={"lr": tune.grid_search([1.0, 2.0, 4.0])},
            tune_config=TuneConfig(metric="loss", mode="min"),
        ).fit()
        assert len(grid) == 3
        assert grid.get_best_result().metrics["loss"] == 0.25


class TestReviewRegressions:
    def test_scheduler_own_metric_respected(self):
        from ray_tpu.tune.tune_controller import TuneController

        sched = ASHAScheduler(metric="loss", mode="min", max_t=8)
        c = TuneController(lambda cfg: None, [], scheduler=sched)
        assert sched.metric == "loss" and sched.mode == "min"

    def test_asha_uneven_time_attr(self):
        from ray_tpu.tune.experiment import Trial

        sched = ASHAScheduler(metric="s", mode="max", time_attr="step",
                              max_t=100, grace_period=2, reduction_factor=2)
        good, bad = Trial({}), Trial({})
        # reports at step 5 (crosses milestones 2 and 4 at once)
        assert sched.on_trial_result(good, {"step": 5, "s": 10.0}) == "CONTINUE"
        assert sched.on_trial_result(bad, {"step": 5, "s": 0.1}) == "STOP"

    def test_sample_from_sees_siblings(self):
        gen = BasicVariantGenerator(
            {"a": tune.choice([3]), "b": tune.sample_from(lambda c: c["a"] * 2)},
            num_samples=2, seed=0,
        )
        cfg = gen.suggest("0")
        assert cfg == {"a": 3, "b": 6}

    def test_sample_from_sees_grid_values(self):
        gen = BasicVariantGenerator(
            {"a": tune.grid_search([1, 5]), "b": tune.sample_from(lambda c: c["a"] + 1)},
            num_samples=1,
        )
        cfgs = [gen.suggest(str(i)) for i in range(2)]
        assert sorted((c["a"], c["b"]) for c in cfgs) == [(1, 2), (5, 6)]

    def test_pbt_rejects_bad_quantile(self):
        with pytest.raises(ValueError):
            PopulationBasedTraining(metric="s", quantile_fraction=0.7)


class TestExperimentPersistence:
    """Tuner.restore after a hard crash (tune/execution/experiment_state.py
    analog): completed trials keep results, the interrupted trial resumes
    from its checkpoint, pending trials run — nothing completed reruns."""

    def test_crash_and_restore(self, tmp_path):
        import os
        import subprocess
        import sys

        storage = tmp_path / "exp_store"
        marks = tmp_path / "marks"
        marks.mkdir()
        script = tmp_path / "crash_run.py"
        script.write_text(f"""
import os, sys
sys.path.insert(0, {str(os.getcwd())!r})
import ray_tpu
from ray_tpu import tune
from ray_tpu.train.config import RunConfig
from ray_tpu.train.checkpoint import Checkpoint

MARKS = {str(marks)!r}

def trainable(config):
    trial = config["idx"]
    ckpt = tune.get_checkpoint()
    start = 0
    if ckpt is not None:
        with open(os.path.join(ckpt.path, "it.txt")) as f:
            start = int(f.read())
    import uuid
    open(os.path.join(MARKS, f"start-{{trial}}-{{start}}-{{uuid.uuid4().hex[:6]}}"), "w").close()
    # The third trial crashes the whole controller process mid-flight
    # after writing one checkpoint.
    for i in range(start, 3):
        cdir = os.path.join(MARKS, f"ckpt-{{trial}}-{{i}}")
        os.makedirs(cdir, exist_ok=True)
        with open(os.path.join(cdir, "it.txt"), "w") as f:
            f.write(str(i + 1))
        tune.report({{"score": trial * 10 + i, "training_iteration": i + 1}},
                    checkpoint=Checkpoint(cdir))
        crash_marker = os.path.join(MARKS, "crashed-once")
        if trial == 2 and i == 1 and not os.path.exists(crash_marker):
            open(crash_marker, "w").close()
            os.kill(os.getpid(), 9)  # one-shot: resumes must survive

ray_tpu.init(resources={{"CPU": 2}})
tuner = tune.Tuner(
    trainable,
    param_space={{"idx": tune.grid_search([0, 1, 2, 3])}},
    tune_config=tune.TuneConfig(metric="score", mode="max",
                                max_concurrent_trials=1),
    run_config=RunConfig(name="crashy", storage_path={str(storage)!r}),
)
tuner.fit()
""")
        proc = subprocess.run([sys.executable, str(script)],
                              capture_output=True, timeout=300)
        assert proc.returncode != 0, "expected the run to crash"
        exp_path = str(storage / "crashy")

        import ray_tpu
        from ray_tpu import tune as rtune

        assert rtune.Tuner.can_restore(exp_path)
        ray_tpu.init(resources={"CPU": 2})
        try:
            tuner = rtune.Tuner.restore(exp_path)
            grid = tuner.fit()
        finally:
            ray_tpu.shutdown()
        assert len(grid) == 4
        scores = sorted(r.metrics["score"] for r in grid.results)
        assert scores == [2, 12, 22, 32]  # every trial reached iteration 3

        starts = sorted(p.name for p in marks.iterdir()
                        if p.name.startswith("start-"))
        # Trials 0,1 ran once (before crash; never rerun). Trial 2 ran
        # fresh then resumed from its iteration-2 checkpoint. Trial 3 ran
        # only after restore.
        def count(prefix):
            return len([s for s in starts if s.startswith(prefix)])

        # Trials 0,1 completed before the crash and never rerun.
        assert count("start-0-") == 1 and count("start-1-") == 1
        # Trial 2 ran in both processes (resumed from whichever point the
        # snapshot caught — possibly from scratch; only COMPLETION
        # persistence is guaranteed).
        assert count("start-2-") == 2
        # Trial 3 only ran after restore.
        assert count("start-3-") == 1


class TestModelBasedSearch:
    """TPE + ConcurrencyLimiter + HyperBand (VERDICT r4 missing #5)."""

    @staticmethod
    def _surface(x, y):
        # Deterministic 2-D objective, minimum 0 at (0.2, -0.6); multi-scale
        # enough that random search wastes samples far from the bowl.
        return (x - 0.2) ** 2 + (y + 0.6) ** 2

    def _best_offline(self, searcher_factory, budget: int, seed: int) -> float:
        """Drive a searcher through the suggest/complete protocol without a
        cluster; returns the best (lowest) objective found."""
        s = searcher_factory(seed)
        s.metric, s.mode = "obj", "min"
        best = float("inf")
        for i in range(budget):
            cfg = s.suggest(f"t{i}")
            assert cfg is not None
            v = self._surface(cfg["x"], cfg["y"])
            best = min(best, v)
            s.on_trial_complete(f"t{i}", result={"obj": v})
        return best

    def test_tpe_beats_random_on_2d_surface(self):
        from ray_tpu.tune.search import TPESearcher

        space = {"x": tune.uniform(-2.0, 2.0), "y": tune.uniform(-2.0, 2.0)}
        budget = 40
        seeds = range(6)
        tpe = [self._best_offline(
            lambda s: TPESearcher(space, n_initial=8, seed=s), budget, s)
            for s in seeds]
        rnd = [self._best_offline(
            lambda s: BasicVariantGenerator(space, num_samples=budget, seed=s),
            budget, s) for s in seeds]
        # Same budget, averaged over seeds: the model must focus samples
        # into the bowl and land measurably closer to the optimum.
        assert np.mean(tpe) < np.mean(rnd), (tpe, rnd)
        assert np.median(tpe) < 0.05, tpe

    def test_tpe_nested_and_categorical(self):
        from ray_tpu.tune.search import TPESearcher

        space = {"opt": {"lr": tune.loguniform(1e-4, 1e0),
                         "kind": tune.choice(["sgd", "adam"])},
                 "n": tune.randint(1, 8)}
        s = TPESearcher(space, n_initial=4, seed=0)
        s.metric, s.mode = "obj", "min"
        for i in range(12):
            cfg = s.suggest(f"t{i}")
            assert 1e-4 <= cfg["opt"]["lr"] <= 1.0
            assert cfg["opt"]["kind"] in ("sgd", "adam")
            assert 1 <= cfg["n"] < 8
            # "adam" with small lr is better: TPE should learn this.
            v = (0.0 if cfg["opt"]["kind"] == "adam" else 1.0) + cfg["opt"]["lr"]
            s.on_trial_complete(f"t{i}", result={"obj": v})
        late = [s.suggest(f"late{i}") for i in range(6)]
        assert sum(1 for c in late if c["opt"]["kind"] == "adam") >= 4

    def test_concurrency_limiter_defers(self):
        from ray_tpu.tune.search import ConcurrencyLimiter, Searcher, TPESearcher

        space = {"x": tune.uniform(0.0, 1.0)}
        lim = ConcurrencyLimiter(TPESearcher(space, seed=0), max_concurrent=2)
        lim.metric, lim.mode = "obj", "min"
        a, b = lim.suggest("a"), lim.suggest("b")
        assert a is not None and b is not None
        assert lim.suggest("c") is Searcher.DEFER
        lim.on_trial_complete("a", result={"obj": 0.5})
        assert lim.suggest("c") is not Searcher.DEFER

    def test_tpe_through_tuner_lazy(self, ray_start_regular):
        """End-to-end: a sequential searcher under a ConcurrencyLimiter
        through the real controller — trials are created lazily and the
        searcher sees completions between suggestions."""
        from ray_tpu.tune.search import ConcurrencyLimiter, TPESearcher

        def trainable(config):
            tune.report({"obj": (config["x"] - 0.3) ** 2})

        space = {"x": tune.uniform(-1.0, 1.0)}
        result = Tuner(
            trainable,
            param_space=space,
            tune_config=TuneConfig(
                metric="obj", mode="min", num_samples=10,
                search_alg=ConcurrencyLimiter(
                    TPESearcher(space, n_initial=4, seed=0), max_concurrent=2),
            ),
        ).fit()
        assert len(result) == 10
        best = result.get_best_result()
        assert best.metrics["obj"] < 0.2

    def test_hyperband_brackets_and_stops(self):
        from ray_tpu.tune.experiment import Trial
        from ray_tpu.tune.schedulers import HyperBandScheduler, TrialScheduler

        hb = HyperBandScheduler(metric="score", mode="max", max_t=9,
                                reduction_factor=3)
        # Brackets exist with distinct initial budgets.
        assert len(hb._bracket_milestones) == 3
        trials = [Trial(config={"i": i}) for i in range(9)]
        # Feed results: trial quality equals its index (higher = better).
        stopped = set()
        for t_iter in range(1, 10):
            for i, tr in enumerate(trials):
                if tr.trial_id in stopped:
                    continue
                d = hb.on_trial_result(tr, {"training_iteration": t_iter,
                                            "score": float(i)})
                if d == TrialScheduler.STOP:
                    stopped.add(tr.trial_id)
        # Some early stopping happened, and the best trial was never culled
        # before max_t (it can only stop by exhausting the budget).
        assert stopped
        best = trials[-1]
        # best trial stops only via t >= max_t, which counts as budget end
        d = hb.on_trial_result(best, {"training_iteration": 9, "score": 8.0})
        assert d == TrialScheduler.STOP  # budget exhausted, not culled early


class TestBOHBStyleComposition:
    def test_tpe_searcher_with_hyperband_scheduler(self, ray_start_regular):
        """BOHB's shape: a model-based searcher PROPOSES configs while
        HyperBand's bracketed successive halving CULLS them early — the
        two compose through the standard TuneConfig surface (reference:
        tune/schedulers/hb_bohb.py + search/bohb)."""
        from ray_tpu.tune.schedulers import HyperBandScheduler
        from ray_tpu.tune.search import ConcurrencyLimiter, TPESearcher

        def trainable(cfg):
            # Converges toward a config-dependent plateau; bad x plateaus
            # low and should be culled at early rungs.
            for i in range(1, 10):
                score = (1.0 - (cfg["x"] - 0.6) ** 2) * (i / 9.0)
                tune.report({"score": score})

        space = {"x": tune.uniform(0.0, 1.0)}
        res = Tuner(
            trainable,
            param_space=space,
            tune_config=TuneConfig(
                metric="score", mode="max", num_samples=12,
                search_alg=ConcurrencyLimiter(
                    TPESearcher(space, n_initial=4, seed=3),
                    max_concurrent=3),
                scheduler=HyperBandScheduler(
                    metric="score", mode="max", max_t=9,
                    reduction_factor=3),
            ),
        ).fit()
        assert len(res) == 12
        best = res.get_best_result()
        assert best.metrics["score"] > 0.8, best.metrics
        # HyperBand actually culled: some trials stopped before max_t.
        iters = [r.metrics.get("training_iteration", 0) for r in res.results]
        assert min(iters) < 9, iters


class TestTPECategoricalExploration:
    """_categorical_axis must SAMPLE candidates ∝ the smoothed good-set
    frequencies and argmax the density ratio over that candidate set — the
    old deterministic argmax over all categories emitted the identical
    value on every back-to-back suggest (ADVICE r5), killing exploration
    under ConcurrencyLimiter(max_concurrent>1)."""

    def _searcher(self, seed=0):
        from ray_tpu.tune.search import Choice, TPESearcher

        space = {"c": Choice(["a", "b", "c"])}
        # Small candidate pool so the draw visibly subsets the categories.
        return TPESearcher(space, n_initial=2, n_candidates=4, seed=seed)

    def test_back_to_back_draws_explore(self):
        s = self._searcher(seed=3)
        # good favors "a" heavily; "c" is rare-but-good (highest l/g ratio);
        # bad concentrates on "a"/"b".
        good = ["a"] * 8 + ["c"]
        bad = ["a"] * 6 + ["b"] * 6
        draws = [s._categorical_axis(["a", "b", "c"], good, bad)
                 for _ in range(100)]
        # No new observations between calls — the old code returned one
        # category 100 times; the fix must explore.
        assert len(set(draws)) > 1, "categorical axis collapsed to argmax"
        # ...while still favoring categories that look good.
        counts = {v: draws.count(v) for v in set(draws)}
        assert counts.get("b", 0) < counts.get("a", 0) + counts.get("c", 0)

    def test_candidates_follow_good_frequencies(self):
        s = self._searcher(seed=11)
        # Everything good is "b": the draw should essentially always pick it.
        draws = [s._categorical_axis(["a", "b", "c"], ["b"] * 12, ["a"] * 6)
                 for _ in range(50)]
        assert draws.count("b") >= 45


class TestTuneControllerLazySuggestGuard:
    """TuneController must not silently complete with zero trials when a
    sequential searcher is given but num_samples was left at 0 (ADVICE r5)."""

    def _sequential_searcher(self):
        from ray_tpu.tune.search import Searcher

        class Seq(Searcher):
            sequential = True

            def suggest(self, trial_id):
                return {"x": 1}

        return Seq()

    def test_zero_samples_no_trials_raises(self):
        from ray_tpu.tune.tune_controller import TuneController

        with pytest.raises(ValueError, match="num_samples"):
            TuneController(lambda cfg: None, [],
                           searcher=self._sequential_searcher())

    def test_samples_below_pregenerated_warns(self, caplog):
        import logging

        from ray_tpu.tune.experiment import Trial
        from ray_tpu.tune.tune_controller import TuneController

        trials = [Trial(config={"x": 0}), Trial(config={"x": 1})]
        # The ray_tpu root logger is propagate=False; caplog captures at the
        # python root, so re-enable propagation for the assertion.
        logging.getLogger("ray_tpu").propagate = True
        try:
            with caplog.at_level(logging.WARNING, logger="ray_tpu.tune"):
                TuneController(lambda cfg: None, trials,
                               searcher=self._sequential_searcher(),
                               num_samples=2)
        finally:
            logging.getLogger("ray_tpu").propagate = False
        assert any("never be consulted" in r.message for r in caplog.records)

    def test_adequate_budget_is_silent(self, caplog):
        import logging

        from ray_tpu.tune.tune_controller import TuneController

        logging.getLogger("ray_tpu").propagate = True
        try:
            with caplog.at_level(logging.WARNING, logger="ray_tpu.tune"):
                TuneController(lambda cfg: None, [],
                               searcher=self._sequential_searcher(),
                               num_samples=4)
        finally:
            logging.getLogger("ray_tpu").propagate = False
        assert not any("never be consulted" in r.message
                       for r in caplog.records)
