"""Native shm object store tests (the plasma analog), modeled on the
reference's ``src/ray/object_manager/test/``: create/seal/get lifecycle,
pinning, allocator reuse/coalescing, cross-process zero-copy access.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

from ray_tpu.core.native_store import NativeObjectStore, NativeStoreUnavailable


@pytest.fixture
def store():
    name = f"rtpu_test_{os.getpid()}"
    try:
        s = NativeObjectStore(name, capacity=8 * 1024 * 1024, max_entries=128)
    except NativeStoreUnavailable as e:
        pytest.skip(f"native store unavailable: {e}")
    yield s
    s.destroy()


class TestNativeStore:
    def test_put_get_roundtrip(self, store):
        data = np.arange(1000, dtype=np.float64).tobytes()
        store.put(b"obj1", data)
        view = store.get(b"obj1")
        assert view is not None
        assert bytes(view) == data
        store.release(b"obj1")

    def test_zero_copy_numpy(self, store):
        arr = np.random.default_rng(0).normal(size=(100, 100))
        store.put(b"arr", arr.tobytes())
        view = store.get(b"arr")
        back = np.frombuffer(view, np.float64).reshape(100, 100)
        np.testing.assert_array_equal(back, arr)
        store.release(b"arr")

    def test_contains_and_missing(self, store):
        assert not store.contains(b"nope")
        assert store.get(b"nope") is None
        store.put(b"yes", b"x")
        assert store.contains(b"yes")

    def test_duplicate_put_fails(self, store):
        store.put(b"dup", b"a")
        with pytest.raises(MemoryError):
            store.put(b"dup", b"b")

    def test_delete_respects_pins(self, store):
        store.put(b"pinned", b"data")
        view = store.get(b"pinned")  # pin
        assert not store.delete(b"pinned")  # refused: pinned
        store.release(b"pinned")
        assert store.delete(b"pinned")
        assert not store.contains(b"pinned")

    def test_allocator_reuses_freed_space(self, store):
        cap = store.capacity()
        chunk = cap // 4
        # fill-free cycles exceed capacity in total => space must be reused
        for cycle in range(8):
            oid = f"c{cycle}".encode()
            store.put(oid, b"\x07" * chunk)
            assert store.delete(oid)
        assert store.bytes_in_use() == 0

    def test_out_of_memory_raises(self, store):
        with pytest.raises(MemoryError):
            store.put(b"huge", b"x" * (store.capacity() + 1))

    def test_stats(self, store):
        assert store.num_objects() == 0
        store.put(b"a", b"12345678")
        assert store.num_objects() == 1
        assert store.bytes_in_use() >= 8

    def test_cross_process_zero_copy(self, store):
        """A second PROCESS opens the segment and reads the object —
        the multi-worker zero-copy path (reference: plasma clients)."""
        payload = np.arange(4096, dtype=np.int32)
        store.put(b"shared", payload.tobytes())
        code = f"""
import sys
sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})
import numpy as np
from ray_tpu.core.native_store import NativeObjectStore
s = NativeObjectStore.open({store.name!r})
view = s.get(b"shared")
arr = np.frombuffer(view, np.int32)
assert arr.sum() == {int(payload.sum())}, arr.sum()
s.release(b"shared")
s.put(b"reply", b"from-child")
s.close()
print("CHILD-OK")
"""
        out = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True, timeout=60,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert "CHILD-OK" in out.stdout, out.stderr
        view = store.get(b"reply")
        assert bytes(view) == b"from-child"
        store.release(b"reply")

    def test_views_are_readonly(self, store):
        """Sealed objects are immutable: zero-copy views must be read-only so
        a consumer can't corrupt the object for other readers (plasma
        returns read-only buffers for sealed objects)."""
        arr = np.arange(16, dtype=np.int64)
        store.put(b"ro", arr.tobytes())
        view = store.get(b"ro")
        assert view.readonly
        back = np.frombuffer(view, np.int64)
        assert not back.flags.writeable
        with pytest.raises((TypeError, ValueError)):
            view[0] = 0xFF
        store.release(b"ro")
        view2 = store.get_view(b"ro")
        assert view2.readonly

    def test_long_id_rejected(self, store):
        """Ids longer than ID_SIZE must raise, not silently truncate (two
        ids sharing a 20-byte prefix would alias the same shm slot)."""
        with pytest.raises(ValueError):
            store.put(b"x" * 21, b"data")
        with pytest.raises(ValueError):
            store.get(b"y" * 40)

    def test_eownerdead_rebuilds_allocator(self, store):
        """A peer that dies holding the robust mutex with half-spliced
        allocator metadata: the next locker must rebuild the free list from
        the entry table (the source of truth), not just mark the mutex
        consistent."""
        import ctypes

        # The corrupt-and-hold hook is only exported from the test build.
        native_dir = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "ray_tpu", "_native",
        )
        subprocess.run(
            ["make", "-C", native_dir, "test"],
            check=True, capture_output=True, timeout=120,
        )
        test_lib = os.path.join(native_dir, "libray_tpu_store_test.so")

        payload = np.arange(2048, dtype=np.int64)
        # zero-size object: must occupy a distinct arena range (min alloc)
        # so recovery's offset walk can never conflate it with a neighbor
        store.put(b"empty", b"")
        store.put(b"survivor", payload.tobytes())
        in_use_before = store.bytes_in_use()
        num_before = store.num_objects()

        code = f"""
import sys, ctypes, os
sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})
# Open the segment entirely through the TEST build of the library (same
# source, plus the crash-injection hook; Store struct layout is identical).
lib = ctypes.CDLL({test_lib!r})
lib.rt_store_open.restype = ctypes.c_void_p
lib.rt_store_open.argtypes = [ctypes.c_char_p]
lib.rt_store_test_corrupt_and_hold.restype = ctypes.c_int
lib.rt_store_test_corrupt_and_hold.argtypes = [ctypes.c_void_p]
h = lib.rt_store_open({store.name!r}.encode())
assert h, "open failed"
lib.rt_store_test_corrupt_and_hold(h)
print("CORRUPTED", flush=True)
os._exit(1)  # die holding the lock -> EOWNERDEAD for the next locker
"""
        out = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True, timeout=60,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert "CORRUPTED" in out.stdout, out.stderr

        # Next op takes the EOWNERDEAD path and rebuilds; invariants restored.
        assert store.contains(b"survivor")
        assert store.bytes_in_use() == in_use_before
        assert store.num_objects() == num_before
        view = store.get(b"survivor")
        np.testing.assert_array_equal(np.frombuffer(view, np.int64), payload)
        store.release(b"survivor")
        # allocator still functional: can fill a fresh object without
        # overwriting survivors (the zero-size entry kept its own range)
        store.put(b"after", b"z" * 4096)
        assert store.contains(b"after")
        assert store.contains(b"empty")
        view2 = store.get(b"survivor")
        np.testing.assert_array_equal(np.frombuffer(view2, np.int64), payload)
        store.release(b"survivor")


def test_asan_stress_clean():
    """The multi-threaded arena stress harness under AddressSanitizer: no
    races/UAF/leaks in create/seal/get/delete cycles incl. tombstone reuse
    and the crash-rebuild path (the reference's asan CI job for plasma,
    ci/ray_ci/tester.py:137-144)."""
    import os
    import shutil
    import subprocess

    if shutil.which("g++") is None:
        pytest.skip("no g++")
    native = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "ray_tpu", "_native")
    subprocess.run(["make", "-C", native, "asan"], check=True,
                   capture_output=True, timeout=180)
    out = subprocess.run([os.path.join(native, "stress_store_asan"), "2"],
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr[-2000:]
    assert "leftover_objects=0" in out.stdout
