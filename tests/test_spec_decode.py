"""Speculative decoding in the paged serve engine (ISSUE 16).

Correctness bar: speculation changes THROUGHPUT, never tokens. Greedy
spec output must be token-for-token identical to the non-speculative paged
engine for any draft (aligned, misaligned, partially aligned — including
mid-request EWMA demotion of a hopeless draft); sampled output must follow
the target distribution (rejection sampling guarantees it for any draft —
checked empirically over fixed seeds); and the block-table advance on
partial acceptance must leave zero pinned blocks behind
(``active_blocks() == 0``), including when draft and target share a pool
under prefix-reuse COW forks. Runs under ``RAY_TPU_LEAK_CHECK_ENABLED=1``.
"""

import collections
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import generate, transformer
from ray_tpu.serve.llm import PagedLLMEngine

BT = 8


@pytest.fixture(scope="module")
def models():
    """Target + three drafts. The 3x parameter scale pushes the random
    init out of its fixed-point attractor so greedy output is VARIED —
    a constant-token stream would vacuously pass identity checks."""
    cfg = transformer.tiny(max_seq_len=64)
    scale = lambda t: jax.tree.map(lambda p: p * 3.0, t)
    params = scale(transformer.init_params(cfg, jax.random.key(2)))
    miss = scale(transformer.init_params(cfg, jax.random.key(7)))
    near = jax.tree.map(
        lambda p, n: p + 0.05 * n, params,
        scale(transformer.init_params(cfg, jax.random.key(11))))
    return cfg, params, {"aligned": params, "near": near, "miss": miss}


ENG_KW = dict(prompt_buckets=(16, 32), chunk=4, slots=2, max_queue=4,
              block_tokens=BT, pool_blocks=80)
PROMPTS = [[5, 9, 3, 77, 21], [1, 2, 3], [9, 8, 7, 6, 5, 4, 3, 2, 1],
           [42] * 12]


def _spec_engine(models, draft, k=3, **kw):
    cfg, params, drafts = models
    merged = {**ENG_KW, **kw}
    return PagedLLMEngine(params, cfg, draft_params=drafts[draft],
                          draft_config=cfg, spec_tokens=k,
                          name=f"spec-{draft}", **merged)


@pytest.fixture(scope="module")
def plain(models):
    cfg, params, _ = models
    return PagedLLMEngine(params, cfg, name="spec-base", **ENG_KW)


class TestGreedyTokenIdentity:
    @pytest.mark.parametrize("draft", ["aligned", "near", "miss"])
    def test_matches_plain_engine(self, models, plain, draft):
        """Identical greedy tokens whatever the draft quality. The 'miss'
        draft's acceptance EWMA collapses below the floor mid-request —
        the demotion handoff (pending-carry consumption, last-logits
        refresh) must not skew a single token."""
        eng = _spec_engine(models, draft)
        for p in PROMPTS:
            assert eng.generate(p, max_new_tokens=20) == plain.generate(
                p, max_new_tokens=20)
        assert eng.kv.active_blocks() == 0

    def test_acceptance_rates_span_regimes(self, models):
        """The three drafts genuinely exercise different acceptance
        regimes: aligned ~1, near in between, miss ~0 (whereupon the gate
        stops proposing — proposed stays finite)."""
        ratios = {}
        for draft in ("aligned", "near", "miss"):
            eng = _spec_engine(models, draft)
            eng.generate(PROMPTS[0], max_new_tokens=20)
            st = eng.stats()
            assert st["spec_proposed_total"] > 0
            ratios[draft] = st["spec_accept_ratio"]
        assert ratios["aligned"] > 0.9
        assert ratios["miss"] < 0.2
        assert ratios["miss"] <= ratios["near"] <= ratios["aligned"]

    def test_concurrent_slots(self, models, plain):
        """Staggered concurrent requests share spec decode dispatches;
        per-slot acceptance state must not bleed across slots."""
        eng = _spec_engine(models, "near")
        outs = [None] * len(PROMPTS)
        errs = []

        def client(i):
            try:
                outs[i] = eng.generate(PROMPTS[i], max_new_tokens=16)
            except BaseException as e:  # noqa: BLE001
                errs.append(e)

        ts = [threading.Thread(target=client, args=(i,))
              for i in range(len(PROMPTS))]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not errs
        for i, p in enumerate(PROMPTS):
            assert outs[i] == plain.generate(p, max_new_tokens=16)
        assert eng.kv.active_blocks() == 0


class TestSampledDistribution:
    def test_aligned_draft_accepts_everything(self, models):
        """With draft == target, p(d)/q(d) == 1 — rejection sampling must
        accept every proposal regardless of temperature."""
        eng = _spec_engine(models, "aligned")
        eng.generate(PROMPTS[0], max_new_tokens=16, temperature=0.9, seed=3)
        assert eng.stats()["spec_accept_ratio"] == pytest.approx(1.0)
        assert eng.kv.active_blocks() == 0

    def test_fixed_seed_deterministic(self, models):
        """Same seed, fresh engines → identical sampled output (the spec
        RNG chain is a pure function of the slot key)."""
        a = _spec_engine(models, "near").generate(
            PROMPTS[0], max_new_tokens=12, temperature=0.8, seed=42)
        b = _spec_engine(models, "near").generate(
            PROMPTS[0], max_new_tokens=12, temperature=0.8, seed=42)
        assert a == b

    def test_distribution_preserved(self, models, plain):
        """Rejection sampling must leave the MARGINAL distribution of
        emitted tokens equal to the target's even under a mismatched
        draft: the empirical distribution of the first three sampled
        tokens over many fixed seeds stays close to the plain engine's
        (deterministic — the seed sweep is fixed)."""
        eng = _spec_engine(models, "miss")
        n, new = 150, 3

        def sweep(e, base_seed):
            cs = [collections.Counter() for _ in range(new)]
            for seed in range(n):
                out = e.generate(PROMPTS[1], max_new_tokens=new,
                                 temperature=1.0, seed=base_seed + seed)
                for i in range(new):
                    cs[i][out[i]] += 1
            return cs

        def l1(a, b):
            return sum(abs(a[t] - b[t]) for t in set(a) | set(b)) / n

        cs_spec = sweep(eng, 0)
        cs_b1 = sweep(plain, 10_000)
        cs_b2 = sweep(plain, 20_000)  # plain-vs-plain null calibrates L1
        for i in range(new):
            # The target distribution here is nearly flat over ~120 tokens,
            # so even two same-distribution 150-draw samples sit at L1 ~ 1.
            # Spec must stay within the null's neighborhood; residual-
            # sampling bugs (mass collapsing onto the draft's argmax) push
            # the divergence toward 2.
            null = l1(cs_b1[i], cs_b2[i])
            assert l1(cs_spec[i], cs_b1[i]) <= 1.3 * null + 0.1, (i, null)
        assert eng.kv.active_blocks() == 0


class TestBlockAccounting:
    def test_partial_acceptance_refcounts_drain(self, models):
        """Variable per-step advances (partial acceptance) must not skew
        the host block accounting: every refcount drains at retire."""
        eng = _spec_engine(models, "near")
        for p in PROMPTS:
            eng.generate(p, max_new_tokens=20)
            eng.generate(p, max_new_tokens=20, temperature=0.7, seed=1)
        assert eng.kv.active_blocks() == 0

    def test_cow_fork_shared_pool(self, models, plain):
        """Draft and target share the block tables under prefix reuse: a
        follow-up turn hits the retired chain, COW-forks the tail in BOTH
        pools, and still decodes token-identically."""
        eng = _spec_engine(models, "near")
        first = [3, 1, 4, 1, 5, 9, 2, 6]
        out1 = eng.generate(first, max_new_tokens=12)
        assert out1 == plain.generate(first, max_new_tokens=12)
        follow = first + out1[:5] + [7, 7]
        before = eng.kv.stats()["kv_hit_tokens"]
        out2 = eng.generate(follow, max_new_tokens=12)
        assert eng.kv.stats()["kv_hit_tokens"] > before  # the fork hit
        assert out2 == plain.generate(follow, max_new_tokens=12)
        assert eng.kv.active_blocks() == 0

    def test_draft_requires_config(self, models):
        cfg, params, drafts = models
        with pytest.raises(ValueError):
            PagedLLMEngine(params, cfg, spec_tokens=2, **ENG_KW)
        with pytest.raises(ValueError):
            generate.PagedGenerator(params, cfg, slots=2, num_blocks=17,
                                    block_tokens=BT,
                                    draft_params=drafts["aligned"])


class TestLengthCapRegression:
    """Satellite: a slot at table capacity must finish as length_cap at
    the ENGINE layer before dispatch — and the forward itself may never
    silently overwrite the last cell when handed an at-capacity length."""

    def test_engine_finishes_length_cap(self, models, plain):
        eng = _spec_engine(models, "near")
        outs = {}
        for e in (eng, plain):
            outcome = {}
            toks = list(e.stream([5, 9, 3, 77, 21], max_new_tokens=500,
                                 result=outcome))
            assert outcome["finish_reason"] == "length_cap"
            # emitted never exceeds the table capacity minus the prompt
            assert len(toks) <= e.max_len - 5
            outs[e] = toks
        # Plain quantizes emission to chunk multiples while spec advances
        # by variable 1+accepted per scan step, so the exact stop point
        # near the cap differs — but the streams must agree token-for-token
        # on their common prefix, and spec may only ever get FURTHER.
        np, ns = len(outs[plain]), len(outs[eng])
        assert ns >= np
        assert outs[eng][:np] == outs[plain]
        assert eng.kv.active_blocks() == 0

    def test_at_capacity_write_redirects_to_trash(self, models):
        """Direct forward unit: lengths == table capacity redirects the
        scatter to trash block 0 instead of clamping onto the last cell
        (the pre-fix behavior corrupted position cap-1)."""
        cfg, params, _ = models
        nb_seq = 3
        pool = 8
        k_pool, v_pool = generate.init_block_pool(cfg, pool, BT)
        k_pool = k_pool + 1.5  # sentinel content
        v_pool = v_pool + 2.5
        tables = jnp.asarray(
            np.array([[1, 2, 3]], np.int32))          # fully live table
        cap = nb_seq * BT
        lengths = jnp.asarray(np.array([cap], np.int32))
        toks = jnp.asarray(np.array([[4]], np.int32))
        logits, k2, v2 = generate._forward_decode_paged(
            params, toks, k_pool, v_pool, tables, lengths, cfg, BT)
        assert np.isfinite(np.asarray(logits)).all()
        # Every live block — in particular the last cell of block 3 —
        # keeps its sentinel; only trash block 0 absorbed the write.
        np.testing.assert_array_equal(np.asarray(k2[:, 1:]),
                                      np.asarray(k_pool[:, 1:]))
        np.testing.assert_array_equal(np.asarray(v2[:, 1:]),
                                      np.asarray(v_pool[:, 1:]))
        assert not np.array_equal(np.asarray(k2[:, 0]),
                                  np.asarray(k_pool[:, 0]))
