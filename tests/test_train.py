"""Train-library tests, modeled on the reference's
``python/ray/train/tests/test_backend.py`` / ``test_data_parallel_trainer.py``:
rank mapping, report rounds as barriers, checkpoint persistence + top-k,
failure→restart-from-checkpoint, and the MLP e2e gate (SURVEY §7 P4 gate #1).
"""

import os
import tempfile

import numpy as np
import pytest

import ray_tpu
from ray_tpu import train as rt_train
from ray_tpu.train import (
    Checkpoint,
    CheckpointConfig,
    FailureConfig,
    JaxTrainer,
    RunConfig,
    ScalingConfig,
)


@pytest.fixture
def storage(tmp_path):
    return str(tmp_path / "results")


class TestSessionAndExecutor:
    def test_rank_mapping_and_rounds(self, ray_start_regular, storage):
        def loop(config):
            ctx = rt_train.get_context()
            for i in range(3):
                rt_train.report(
                    {
                        "round": i,
                        "rank": ctx.get_world_rank(),
                        "world": ctx.get_world_size(),
                        "local_rank": ctx.get_local_rank(),
                    }
                )

        trainer = JaxTrainer(
            loop,
            scaling_config=ScalingConfig(num_workers=4),
            run_config=RunConfig(storage_path=storage, name="ranks"),
        )
        result = trainer.fit()
        assert result.error is None
        assert len(result.metrics_history) == 3
        assert result.metrics["round"] == 2
        assert result.metrics["world"] == 4

    def test_all_ranks_report_each_round(self, ray_start_regular, storage):
        from ray_tpu.train.backend_executor import BackendExecutor

        def loop(config):
            ctx = rt_train.get_context()
            rt_train.report({"rank": ctx.get_world_rank()})
            rt_train.report({"rank2": ctx.get_world_rank()})

        ex = BackendExecutor(scaling_config=ScalingConfig(num_workers=3))
        ex.start()
        ex.start_training(loop, {})
        r0 = ex.get_next_results(timeout=60)
        assert sorted(r.metrics["rank"] for r in r0) == [0, 1, 2]
        r1 = ex.get_next_results(timeout=60)
        assert sorted(r.metrics["rank2"] for r in r1) == [0, 1, 2]
        assert ex.get_next_results(timeout=60) is None
        ex.shutdown()

    def test_worker_exception_surfaces(self, ray_start_regular, storage):
        def loop(config):
            ctx = rt_train.get_context()
            if ctx.get_world_rank() == 1:
                raise ValueError("boom on rank 1")
            rt_train.report({"ok": 1})

        trainer = JaxTrainer(
            loop,
            scaling_config=ScalingConfig(num_workers=2),
            run_config=RunConfig(storage_path=storage, name="fail"),
        )
        result = trainer.fit()
        assert result.error is not None
        assert "boom" in str(result.error)


class TestCheckpointing:
    def test_checkpoint_pytree_roundtrip(self, tmp_path):
        import jax.numpy as jnp

        tree = {"w": jnp.arange(6.0).reshape(2, 3), "meta": {"step": 7, "name": "x"}}
        d = str(tmp_path / "ck")
        rt_train.save_pytree(tree, d)
        back = rt_train.load_pytree(d)
        np.testing.assert_array_equal(back["w"], np.arange(6.0).reshape(2, 3))
        assert back["meta"] == {"step": 7, "name": "x"}

    def test_restore_preserves_container_types(self, tmp_path):
        import jax
        import jax.numpy as jnp
        import optax

        params = {"w": jnp.ones((3,))}
        opt = optax.adam(1e-3)
        state = opt.init(params)
        d = str(tmp_path / "opt")
        rt_train.save_pytree(state, d)
        restored = rt_train.restore_pytree(jax.tree.map(np.zeros_like, state), d)
        assert type(restored[0]).__name__ == type(state[0]).__name__
        np.testing.assert_array_equal(restored[0].mu["w"], state[0].mu["w"])

    def test_report_checkpoint_and_topk(self, ray_start_regular, storage):
        def loop(config):
            import tempfile as tf

            ctx = rt_train.get_context()
            for i in range(5):
                ckpt = None
                if ctx.get_world_rank() == 0:
                    d = tf.mkdtemp()
                    rt_train.save_pytree({"step": i}, d)
                    ckpt = Checkpoint(d)
                rt_train.report({"score": float(i)}, checkpoint=ckpt)

        trainer = JaxTrainer(
            loop,
            scaling_config=ScalingConfig(num_workers=2),
            run_config=RunConfig(
                storage_path=storage,
                name="topk",
                checkpoint_config=CheckpointConfig(
                    num_to_keep=2, checkpoint_score_attribute="score"
                ),
            ),
        )
        result = trainer.fit()
        assert result.error is None
        kept = result.best_checkpoints
        assert len(kept) == 2
        assert rt_train.load_pytree(result.checkpoint.path)["step"] == 4

    def test_failure_restarts_from_checkpoint(self, ray_start_regular, storage):
        marker = os.path.join(storage, "crashed_once")
        os.makedirs(storage, exist_ok=True)

        def loop(config):
            import tempfile as tf

            ctx = rt_train.get_context()
            start = 0
            ck = rt_train.get_checkpoint()
            if ck is not None:
                start = rt_train.load_pytree(ck.path)["step"] + 1
            for i in range(start, 4):
                if i == 2 and not os.path.exists(config["marker"]):
                    open(config["marker"], "w").close()
                    raise RuntimeError("injected failure at step 2")
                ckpt = None
                if ctx.get_world_rank() == 0:
                    d = tf.mkdtemp()
                    rt_train.save_pytree({"step": i}, d)
                    ckpt = Checkpoint(d)
                rt_train.report({"step": i, "resumed_from": start}, checkpoint=ckpt)

        trainer = JaxTrainer(
            loop,
            train_loop_config={"marker": marker},
            scaling_config=ScalingConfig(num_workers=2),
            run_config=RunConfig(
                storage_path=storage,
                name="restart",
                failure_config=FailureConfig(max_failures=1),
            ),
        )
        result = trainer.fit()
        assert result.error is None
        assert result.metrics["step"] == 3
        assert result.metrics["resumed_from"] == 2  # resumed, not from scratch


class TestMLPGate:
    def test_mlp_e2e_converges(self, ray_start_regular, storage):
        """SURVEY §7 P4 e2e gate #1: MLP classification through the trainer."""

        def loop(config):
            import jax
            import jax.numpy as jnp
            import optax

            from ray_tpu.models import mlp

            cfg = mlp.MLPConfig(in_dim=8, hidden=(32,), n_classes=2)
            params = mlp.init_params(cfg, jax.random.key(0))
            opt = optax.adam(1e-2)
            state = opt.init(params)
            rng = np.random.default_rng(0)
            x = rng.normal(size=(256, 8)).astype(np.float32)
            y = (x[:, 0] > 0).astype(np.int32)
            batch = {"x": jnp.asarray(x), "y": jnp.asarray(y)}
            grad_fn = jax.jit(jax.value_and_grad(lambda p, b: mlp.classifier_loss(p, b, cfg)))
            for epoch in range(30):
                loss, g = grad_fn(params, batch)
                upd, state = opt.update(g, state)
                params = optax.apply_updates(params, upd)
                if epoch % 10 == 9:
                    rt_train.report({"loss": float(loss)})

        trainer = JaxTrainer(
            loop,
            scaling_config=ScalingConfig(num_workers=2),
            run_config=RunConfig(storage_path=storage, name="mlp"),
        )
        result = trainer.fit()
        assert result.error is None
        assert result.metrics["loss"] < 0.2


class TestCheckpointRegressions:
    def test_none_leaf_roundtrip(self, tmp_path):
        d = str(tmp_path / "nck")
        rt_train.save_pytree({"a": None, "b": 1.0, "c": np.arange(3)}, d)
        back = rt_train.load_pytree(d)
        assert back["a"] is None and back["b"] == 1.0
        np.testing.assert_array_equal(back["c"], np.arange(3))

    def test_non_string_dict_keys_rejected(self, tmp_path):
        with pytest.raises(TypeError, match="keys must be str"):
            rt_train.save_pytree({0: np.zeros(2)}, str(tmp_path / "bad"))

    def test_async_checkpointer_surfaces_errors(self, tmp_path):
        ck = rt_train.AsyncCheckpointer()
        ck.save({"x": object()}, str(tmp_path / "a"))  # unsupported leaf
        with pytest.raises(TypeError):
            ck.wait()


class TestGpt2Gate:
    def test_gpt2_through_jax_trainer_with_data(self, ray_start_regular, storage):
        """SURVEY §7 P4 gate #2 (scaled down): tiny GPT-2, sharded train step
        over the virtual mesh, Data-library ingest, checkpointed via report."""

        def loop(config):
            import tempfile as tf

            import jax
            import numpy as np
            import optax

            from ray_tpu import data as rt_data
            from ray_tpu.models import transformer
            from ray_tpu.models.training import make_train_step
            from ray_tpu.parallel.mesh import MeshSpec, cpu_mesh
            from ray_tpu.parallel.sharding import ShardingRules

            cfg = transformer.tiny(max_seq_len=32, n_layers=2)
            mesh = cpu_mesh(MeshSpec(data=2, tensor=4))
            rules = ShardingRules()
            bundle = make_train_step(
                loss_fn=lambda p, b: transformer.lm_loss(p, b, cfg, mesh=mesh, rules=rules),
                init_params_fn=lambda k: transformer.init_params(cfg, k),
                logical_params=transformer.logical_axes(cfg),
                mesh=mesh,
                rules=rules,
                optimizer=optax.adamw(1e-3),
                batch_logical=None,
            )
            params, opt = bundle.init(jax.random.key(0))

            # token stream through the Data library
            rng = np.random.default_rng(0)
            docs = [{"tokens": rng.integers(0, cfg.vocab_size, 32).tolist()} for _ in range(64)]
            ds = rt_data.from_items(docs)
            it = ds.iterator()

            losses = []
            for epoch in range(4):
                for batch in it.iter_batches(batch_size=8, drop_last=True):
                    jb = {"tokens": np.stack([np.asarray(t, np.int32) for t in batch["tokens"]])}
                    params, opt, metrics = bundle.step(params, opt, jb)
                    losses.append(float(metrics["loss"]))
                d = tf.mkdtemp()
                rt_train.save_pytree({"epoch": epoch}, d)
                rt_train.report(
                    {"loss": losses[-1], "first_loss": losses[0], "epoch": epoch},
                    checkpoint=Checkpoint(d),
                )

        trainer = JaxTrainer(
            loop,
            scaling_config=ScalingConfig(num_workers=1),
            run_config=RunConfig(storage_path=storage, name="gpt2gate"),
        )
        result = trainer.fit()
        assert result.error is None
        assert result.metrics["loss"] < result.metrics["first_loss"]
        assert rt_train.load_pytree(result.checkpoint.path)["epoch"] == 3
