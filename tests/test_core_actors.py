"""Actor tests: lifecycle, ordering, named actors, async actors, kill/restart —
the reference's ``python/ray/tests/test_actor.py`` surface."""

import asyncio
import time

import pytest


def test_actor_basic(ray_start_regular):
    rt = ray_start_regular

    @rt.remote
    class Counter:
        def __init__(self, start=0):
            self.n = start

        def incr(self, by=1):
            self.n += by
            return self.n

        def value(self):
            return self.n

    c = Counter.remote(10)
    assert rt.get(c.incr.remote()) == 11
    assert rt.get(c.incr.remote(5)) == 16
    assert rt.get(c.value.remote()) == 16


def test_actor_method_ordering(ray_start_regular):
    rt = ray_start_regular

    @rt.remote
    class Log:
        def __init__(self):
            self.items = []

        def append(self, x):
            self.items.append(x)

        def get_items(self):
            return self.items

    log = Log.remote()
    for i in range(50):
        log.append.remote(i)
    assert rt.get(log.get_items.remote()) == list(range(50))


def test_actor_method_error_does_not_kill(ray_start_regular):
    rt = ray_start_regular

    @rt.remote
    class A:
        def bad(self):
            raise ValueError("nope")

        def good(self):
            return "ok"

    a = A.remote()
    with pytest.raises(ValueError):
        rt.get(a.bad.remote())
    assert rt.get(a.good.remote()) == "ok"


def test_actor_creation_failure(ray_start_regular):
    rt = ray_start_regular

    @rt.remote
    class Broken:
        def __init__(self):
            raise RuntimeError("ctor boom")

        def m(self):
            return 1

    b = Broken.remote()
    with pytest.raises(rt.ActorError):
        rt.get(b.m.remote(), timeout=10)


def test_named_actor(ray_start_regular):
    rt = ray_start_regular

    @rt.remote
    class Svc:
        def ping(self):
            return "pong"

    Svc.options(name="svc1").remote()
    h = rt.get_actor("svc1")
    assert rt.get(h.ping.remote()) == "pong"


def test_named_actor_duplicate_rejected(ray_start_regular):
    rt = ray_start_regular

    @rt.remote
    class Svc:
        def ping(self):
            return 1

    Svc.options(name="dup").remote()
    time.sleep(0.2)
    with pytest.raises(ValueError, match="already taken"):
        Svc.options(name="dup").remote()


def test_get_if_exists(ray_start_regular):
    rt = ray_start_regular

    @rt.remote
    class Singleton:
        def __init__(self):
            self.token = time.time()

        def get_token(self):
            return self.token

    a = Singleton.options(name="s", get_if_exists=True).remote()
    t1 = rt.get(a.get_token.remote())
    b = Singleton.options(name="s", get_if_exists=True).remote()
    t2 = rt.get(b.get_token.remote())
    assert t1 == t2


def test_kill_actor(ray_start_regular):
    rt = ray_start_regular

    @rt.remote
    class A:
        def m(self):
            return 1

    a = A.remote()
    assert rt.get(a.m.remote()) == 1
    rt.kill(a)
    with pytest.raises(rt.ActorError):
        rt.get(a.m.remote(), timeout=10)


def test_actor_restart(ray_start_regular):
    rt = ray_start_regular

    @rt.remote(max_restarts=1)
    class Phoenix:
        def __init__(self):
            self.state = "alive"

        def get_state(self):
            return self.state

    p = Phoenix.remote()
    assert rt.get(p.get_state.remote()) == "alive"
    rt.kill(p, no_restart=False)
    time.sleep(0.5)
    # After restart the actor serves calls again (state reset).
    assert rt.get(p.get_state.remote(), timeout=10) == "alive"


def test_async_actor(ray_start_regular):
    rt = ray_start_regular

    @rt.remote(max_concurrency=4)
    class AsyncWorker:
        async def work(self, i):
            await asyncio.sleep(0.1)
            return i * 2

    w = AsyncWorker.remote()
    start = time.time()
    refs = [w.work.remote(i) for i in range(4)]
    assert rt.get(refs) == [0, 2, 4, 6]
    # 4 concurrent 0.1s sleeps should take well under 0.4s total.
    assert time.time() - start < 2.0


def test_threaded_actor_concurrency(ray_start_regular):
    rt = ray_start_regular

    @rt.remote(max_concurrency=4)
    class Sleeper:
        def nap(self):
            time.sleep(0.2)
            return 1

    s = Sleeper.remote()
    start = time.time()
    assert sum(rt.get([s.nap.remote() for _ in range(4)])) == 4
    assert time.time() - start < 0.79  # serial would be 0.8s


def test_actor_handle_in_task(ray_start_regular):
    rt = ray_start_regular

    @rt.remote
    class Store:
        def __init__(self):
            self.v = 0

        def set(self, v):
            self.v = v

        def get_v(self):
            return self.v

    @rt.remote
    def writer(store, v):
        rt.get(store.set.remote(v))
        return True

    s = Store.remote()
    rt.get(writer.remote(s, 42))
    assert rt.get(s.get_v.remote()) == 42


def test_actor_resources_held(ray_start_regular):
    rt = ray_start_regular

    @rt.remote(num_tpus=4)
    class MeshHolder:
        def ping(self):
            return 1

    m = MeshHolder.remote()
    assert rt.get(m.ping.remote()) == 1
    assert rt.available_resources().get("TPU", 0) == 4
    rt.kill(m)
    time.sleep(0.3)
    assert rt.available_resources().get("TPU", 0) == 8
