"""Job submission + runtime-env tests, modeled on the reference's
``dashboard/modules/job/tests`` and ``python/ray/tests/test_runtime_env*``.
"""

import os
import sys
import time

import pytest

import ray_tpu
from ray_tpu.job_submission import JobStatus, JobSubmissionClient
from ray_tpu.runtime_env import RuntimeEnv, applied


class TestRuntimeEnv:
    def test_validation(self, tmp_path):
        env = RuntimeEnv(env_vars={"A": "1"}, working_dir=str(tmp_path))
        assert env["env_vars"] == {"A": "1"}
        with pytest.raises(ValueError):
            RuntimeEnv(bogus_field=1)
        with pytest.raises(ValueError):
            RuntimeEnv(working_dir="/nonexistent/dir")
        with pytest.raises(TypeError):
            RuntimeEnv(env_vars={"A": 1})

    def test_deferred_plugins_flagged(self):
        env = RuntimeEnv(pip=["requests"])
        assert env.deferred_plugins() == ["pip"]

    def test_applied_env_vars_restored(self):
        os.environ.pop("RT_TEST_VAR", None)
        with applied({"env_vars": {"RT_TEST_VAR": "inner"}}):
            assert os.environ["RT_TEST_VAR"] == "inner"
        assert "RT_TEST_VAR" not in os.environ

    def test_task_runtime_env(self, ray_start_regular):
        @ray_tpu.remote(runtime_env={"env_vars": {"MY_TASK_VAR": "hello"}})
        def read_env():
            return os.environ.get("MY_TASK_VAR")

        assert ray_tpu.get(read_env.remote()) == "hello"
        assert "MY_TASK_VAR" not in os.environ

    def test_working_dir_on_sys_path(self, ray_start_regular, tmp_path):
        mod = tmp_path / "my_renv_module.py"
        mod.write_text("VALUE = 42\n")

        @ray_tpu.remote(runtime_env={"working_dir": str(tmp_path)})
        def use_module():
            import my_renv_module

            return my_renv_module.VALUE

        assert ray_tpu.get(use_module.remote()) == 42


class TestJobSubmission:
    def test_submit_and_succeed(self, ray_start_regular):
        client = JobSubmissionClient()
        job_id = client.submit_job(
            entrypoint=f"{sys.executable} -c \"print('job ran fine')\""
        )
        status = client.wait_until_finish(job_id, timeout_s=60)
        assert status == JobStatus.SUCCEEDED
        assert "job ran fine" in client.get_job_logs(job_id)

    def test_failed_job_status(self, ray_start_regular):
        client = JobSubmissionClient()
        job_id = client.submit_job(
            entrypoint=f"{sys.executable} -c \"import sys; print('boom'); sys.exit(3)\""
        )
        status = client.wait_until_finish(job_id, timeout_s=60)
        assert status == JobStatus.FAILED
        info = client.get_job_info(job_id)
        assert info["returncode"] == 3

    def test_env_vars_reach_job(self, ray_start_regular):
        client = JobSubmissionClient()
        job_id = client.submit_job(
            entrypoint=f"{sys.executable} -c \"import os; print('VAR=' + os.environ['JOBVAR'])\"",
            runtime_env={"env_vars": {"JOBVAR": "xyz"}},
        )
        client.wait_until_finish(job_id, timeout_s=60)
        assert "VAR=xyz" in client.get_job_logs(job_id)

    def test_stop_job(self, ray_start_regular):
        client = JobSubmissionClient()
        job_id = client.submit_job(
            entrypoint=f"{sys.executable} -c \"import time; time.sleep(300)\""
        )
        deadline = time.monotonic() + 10
        while client.get_job_status(job_id) != JobStatus.RUNNING:
            assert time.monotonic() < deadline
            time.sleep(0.05)
        assert client.stop_job(job_id)
        status = client.wait_until_finish(job_id, timeout_s=30)
        assert status == JobStatus.STOPPED

    def test_list_jobs(self, ray_start_regular):
        client = JobSubmissionClient()
        a = client.submit_job(entrypoint="true")
        b = client.submit_job(entrypoint="true")
        client.wait_until_finish(a)
        client.wait_until_finish(b)
        ids = {j["job_id"] for j in client.list_jobs()}
        assert {a, b} <= ids


def test_pip_runtime_env_builds_isolated_venv(tmp_path):
    """runtime_env={"pip": [...]} builds a cached venv on the node daemon
    (the runtime-env agent's pip plugin) and runs the task inside it:
    the package imports there and ONLY there. Zero-egress image: the
    requirement is a local source tree."""
    import ray_tpu
    from ray_tpu.core import runtime as runtime_mod
    from ray_tpu.core.cluster import Cluster, connect

    # a minimal installable package
    pkg = tmp_path / "rtpu_demo_pkg"
    (pkg / "rtpu_demo_pkg").mkdir(parents=True)
    (pkg / "rtpu_demo_pkg" / "__init__.py").write_text("MAGIC = 1337\n")
    (pkg / "pyproject.toml").write_text(
        '[project]\nname = "rtpu-demo-pkg"\nversion = "0.1"\n'
        '[build-system]\nrequires = ["setuptools"]\n'
        'build-backend = "setuptools.build_meta"\n'
        '[tool.setuptools]\npackages = ["rtpu_demo_pkg"]\n')

    cluster = Cluster(num_nodes=1, resources_per_node={"CPU": 2})
    try:
        core = connect(cluster.gcs_address)
        try:
            @ray_tpu.remote(runtime_env={"pip": {
                "packages": [f"{pkg}"],
                # zero-egress image: no index, no isolated build env
                "pip_install_options": ["--no-index",
                                        "--no-build-isolation"],
            }})
            def with_pkg():
                import rtpu_demo_pkg

                return rtpu_demo_pkg.MAGIC

            @ray_tpu.remote
            def without_pkg():
                try:
                    import rtpu_demo_pkg  # noqa: F401

                    return "leaked"
                except ImportError:
                    return "isolated"

            assert ray_tpu.get(with_pkg.remote(), timeout=600) == 1337
            assert ray_tpu.get(without_pkg.remote(), timeout=120) == "isolated"
            # second task with the same spec reuses the cached env (fast)
            import time as _t

            t0 = _t.time()
            assert ray_tpu.get(with_pkg.remote(), timeout=120) == 1337
            assert _t.time() - t0 < 60
        finally:
            core.shutdown()
            runtime_mod._global_runtime = None
    finally:
        cluster.shutdown()


def test_conda_prefix_runtime_env(tmp_path):
    """runtime_env={"conda": <prefix path>}: the task runs under that
    environment's interpreter (the conda plugin's existing-env path — a
    venv prefix exercises it without the conda binary)."""
    import subprocess
    import sys

    import ray_tpu
    from ray_tpu.core import runtime as runtime_mod
    from ray_tpu.core.cluster import Cluster, connect

    prefix = tmp_path / "condaish"
    subprocess.run([sys.executable, "-m", "venv", "--system-site-packages",
                    str(prefix)], check=True, timeout=300)
    # Parent-env visibility (the daemon's pip builder writes the same .pth).
    import sysconfig

    site = subprocess.run(
        [str(prefix / "bin" / "python"), "-c",
         "import sysconfig; print(sysconfig.get_paths()['purelib'])"],
        capture_output=True, text=True, timeout=60).stdout.strip()
    with open(f"{site}/_rtpu_parent.pth", "w") as f:
        f.write(sysconfig.get_paths()["purelib"] + "\n")

    cluster = Cluster(num_nodes=1, resources_per_node={"CPU": 2})
    try:
        core = connect(cluster.gcs_address)
        try:
            @ray_tpu.remote(runtime_env={"conda": str(prefix)})
            def which_python():
                import sys as _s

                return _s.executable

            exe = ray_tpu.get(which_python.remote(), timeout=300)
            assert exe.startswith(str(prefix)), exe
        finally:
            core.shutdown()
            runtime_mod._global_runtime = None
    finally:
        cluster.shutdown()


def test_container_runtime_env_wraps_worker(tmp_path):
    """runtime_env={"container": {...}}: the worker command is wrapped in
    the container runtime with host networking and env passthrough. A fake
    runtime (shim that records its argv, applies -e vars, and execs the
    inner command) proves the wrapping end-to-end without docker."""
    import os
    import stat
    import sys

    import ray_tpu
    from ray_tpu.core import runtime as runtime_mod
    from ray_tpu.core.cluster import Cluster, connect

    record = tmp_path / "invocations.log"
    shim = tmp_path / "fake-docker"
    shim.write_text(f"""#!{sys.executable}
import os, sys
args = sys.argv[1:]
with open({str(record)!r}, "a") as f:
    f.write(" ".join(args) + "\\n")
env = dict(os.environ)
i = 1  # skip "run"
cmd = None
while i < len(args):
    a = args[i]
    if a == "-e":
        k, _, v = args[i + 1].partition("="); env[k] = v; i += 2
    elif a == "-v":
        i += 2
    elif a.startswith("-"):
        i += 1
    else:
        cmd = args[i + 1:]  # args[i] is the image
        break
        i += 1
cmd[0] = {sys.executable!r}  # the "image python" is this interpreter
os.execvpe(cmd[0], cmd, env)
""")
    shim.chmod(shim.stat().st_mode | stat.S_IEXEC)

    os.environ["RAY_TPU_CONTAINER_RUNTIME"] = str(shim)
    cluster = Cluster(num_nodes=1, resources_per_node={"CPU": 2})
    try:
        core = connect(cluster.gcs_address)
        try:
            @ray_tpu.remote(runtime_env={"container": {
                "image": "example.com/rtpu:latest",
                "run_options": ["--read-only"],
            }})
            def inside():
                return "containerized-ok"

            assert ray_tpu.get(inside.remote(), timeout=300) == "containerized-ok"
            logged = record.read_text()
            assert "example.com/rtpu:latest" in logged
            assert "--network=host" in logged
            assert "--read-only" in logged
            assert "-e RAY_TPU_GCS_ADDRESS=" in logged
        finally:
            core.shutdown()
            runtime_mod._global_runtime = None
    finally:
        cluster.shutdown()
        os.environ.pop("RAY_TPU_CONTAINER_RUNTIME", None)


class TestCondaEnvBuildRace:
    """_ensure_conda_env's dict branch uses the pip path's claim protocol:
    an atomic mkdir claim + a .building staleness marker, so two concurrent
    spawns can never rmtree each other's in-progress build (ADVICE r5)."""

    def _daemon(self, monkeypatch, tmp_path):
        from ray_tpu.core.node_daemon import NodeDaemon

        daemon = object.__new__(NodeDaemon)  # only env methods are used
        monkeypatch.setattr(NodeDaemon, "_pip_env_root",
                            staticmethod(lambda: str(tmp_path)))
        return daemon

    def _fake_conda(self, monkeypatch, build_log, build_delay=0.0):
        import shutil
        import subprocess

        monkeypatch.setattr(shutil, "which",
                            lambda name: "/usr/bin/conda"
                            if name == "conda" else None)
        real_run = subprocess.run

        def fake_run(cmd, **kw):
            if len(cmd) >= 3 and cmd[1:3] == ["env", "create"]:
                prefix = cmd[cmd.index("-p") + 1]
                build_log.append(prefix)
                if build_delay:
                    time.sleep(build_delay)
                os.makedirs(os.path.join(prefix, "bin"), exist_ok=True)
                with open(os.path.join(prefix, "bin", "python"), "w") as f:
                    f.write("#!/bin/true\n")

                class R:
                    returncode = 0
                    stderr = ""
                return R()
            return real_run(cmd, **kw)

        monkeypatch.setattr(subprocess, "run", fake_run)

    def test_concurrent_builders_single_build(self, monkeypatch, tmp_path):
        """Two threads racing on the same spec: exactly one conda build
        runs; the loser waits for .ready instead of deleting the winner's
        in-progress env."""
        import threading

        daemon = self._daemon(monkeypatch, tmp_path)
        build_log = []
        self._fake_conda(monkeypatch, build_log, build_delay=0.6)
        spec = {"dependencies": ["python=3.11"]}
        results, errors = [], []

        def build():
            try:
                results.append(daemon._ensure_conda_env(dict(spec)))
            except BaseException as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=build, daemon=True)
                   for _ in range(2)]
        threads[0].start()
        time.sleep(0.15)  # let A claim and enter the slow build
        threads[1].start()
        for t in threads:
            t.join(30)
        assert not errors, errors
        assert len(build_log) == 1, "both racers built (claim not honored)"
        assert len(set(results)) == 1 and len(results) == 2
        python = results[0]
        assert os.path.exists(python), "winner's env was deleted by loser"
        prefix = os.path.dirname(os.path.dirname(python))
        assert os.path.exists(os.path.join(prefix, ".ready"))
        assert not os.path.exists(prefix + ".claim"), "claim must be released"

    def test_stale_claim_is_reclaimed(self, monkeypatch, tmp_path):
        """A claim whose .building marker is ancient (builder died) is
        reclaimed instead of wedging the spec forever."""
        import hashlib
        import json

        daemon = self._daemon(monkeypatch, tmp_path)
        build_log = []
        self._fake_conda(monkeypatch, build_log)
        spec = {"dependencies": ["python=3.11"]}
        key = hashlib.sha1(json.dumps(
            spec, sort_keys=True).encode()).hexdigest()[:16]
        prefix = os.path.join(str(tmp_path), f"conda-{key}")
        claim = prefix + ".claim"
        os.makedirs(claim)
        marker = os.path.join(claim, ".building")
        open(marker, "w").close()
        ancient = time.time() - 10_000
        os.utime(marker, (ancient, ancient))
        os.makedirs(prefix)  # dead builder's half-written debris
        python = daemon._ensure_conda_env(spec)
        assert os.path.exists(python)
        assert len(build_log) == 1
        assert os.path.exists(os.path.join(prefix, ".ready"))

    def test_ready_env_reused_without_build(self, monkeypatch, tmp_path):
        daemon = self._daemon(monkeypatch, tmp_path)
        build_log = []
        self._fake_conda(monkeypatch, build_log)
        spec = {"dependencies": ["numpy"]}
        p1 = daemon._ensure_conda_env(spec)
        p2 = daemon._ensure_conda_env(spec)
        assert p1 == p2
        assert len(build_log) == 1
