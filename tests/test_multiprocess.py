"""Multiprocess distributed runtime tests — real process boundaries.

The distributed analog of the reference's cluster tests: a head GCS process +
N node-daemon processes + worker processes, driven through the public API
(reference test strategy: ``python/ray/cluster_utils.py:135 Cluster`` +
``python/ray/tests/test_*`` with kill-based fault injection from
``python/ray/_private/test_utils.py:1429,1560,1907``).

Everything here crosses real process boundaries: RPC control plane, shm
object plane, kill -9 fault injection, GCS-restart recovery.
"""

import os
import signal
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.core.cluster import Cluster, connect
from ray_tpu.core import runtime as runtime_mod


@pytest.fixture(scope="module")
def mp_cluster():
    cluster = Cluster(num_nodes=2, resources_per_node={"CPU": 2})
    yield cluster
    cluster.shutdown()


@pytest.fixture
def driver(mp_cluster):
    core = connect(mp_cluster.gcs_address)
    yield core
    core.shutdown()
    runtime_mod._global_runtime = None


def _wait_for(predicate, timeout=60.0, interval=0.2):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


# ====================== tasks / objects ======================


def test_task_roundtrip_and_chaining(driver):
    @ray_tpu.remote
    def add(a, b=0):
        return a + b

    ref = add.remote(1, b=2)
    assert ray_tpu.get(ref, timeout=60) == 3
    # Chained: the ref flows to another process as a dependency.
    assert ray_tpu.get(add.remote(ref, b=10), timeout=60) == 13


def test_multiple_returns_and_wait(driver):
    @ray_tpu.remote(num_returns=2)
    def two():
        return 1, 2

    r1, r2 = two.remote()
    ready, not_ready = ray_tpu.wait([r1, r2], num_returns=2, timeout=60)
    assert len(ready) == 2 and not not_ready
    assert ray_tpu.get([r1, r2]) == [1, 2]


def test_error_propagation_across_processes(driver):
    @ray_tpu.remote(max_retries=0)
    def boom():
        raise ValueError("remote kaboom")

    ref = boom.remote()
    with pytest.raises(ValueError, match="remote kaboom"):
        ray_tpu.get(ref, timeout=60)

    # Dependency failure propagates to downstream tasks.
    @ray_tpu.remote(max_retries=0)
    def use(x):
        return x

    with pytest.raises(ValueError, match="remote kaboom"):
        ray_tpu.get(use.remote(ref), timeout=60)


def test_large_object_shm_plane(driver, mp_cluster):
    """Large puts ride the C++ shm arena and cross process boundaries."""
    arr = np.arange(500_000, dtype=np.float64)  # ~4 MB
    ref = ray_tpu.put(arr)
    out = ray_tpu.get(ref, timeout=60)
    np.testing.assert_array_equal(out, arr)

    @ray_tpu.remote
    def total(a):
        return float(a.sum())

    assert ray_tpu.get(total.remote(ref), timeout=60) == float(arr.sum())
    # The arena actually holds bytes (zero-copy plane, not the heap shelf).
    stats = [driver._daemons.get(h.address).call("stats", timeout=10)
             for h in mp_cluster.nodes]
    assert any(s["shm_bytes"] > 0 for s in stats)


def test_parallel_execution_across_processes(driver):
    """Distinct worker processes with overlapping execution windows — the
    multiprocess runtime escapes the GIL (>1 task truly concurrent)."""

    @ray_tpu.remote
    def window(sec):
        t0 = time.time()
        time.sleep(sec)
        return os.getpid(), t0, time.time()

    # Prewarm until 4 DISTINCT workers answer one batch: 1s windows force 4
    # concurrent leases (lease reuse would let fewer warm workers serve
    # trivial tasks back-to-back), and on a loaded 1-core box interpreter
    # boots take many seconds, so keep batching until the pool is actually
    # 4 wide.
    deadline = time.time() + 120
    while True:
        warm = ray_tpu.get([window.remote(1.0) for _ in range(4)], timeout=120)
        if len({pid for pid, _, _ in warm}) >= 4 or time.time() > deadline:
            break
    # 4s windows: wide enough that submission stagger on a loaded one-core
    # CI box cannot break the all-overlap assertion.
    rs = ray_tpu.get([window.remote(4.0) for _ in range(4)], timeout=120)
    assert len({pid for pid, _, _ in rs}) >= 2
    latest_start = max(t0 for _, t0, _ in rs)
    earliest_end = min(t1 for _, _, t1 in rs)
    assert latest_start < earliest_end, "executions did not overlap"


def test_nested_tasks(driver):
    @ray_tpu.remote
    def inner(x):
        return x * 2

    @ray_tpu.remote
    def outer(x):
        return ray_tpu.get(inner.remote(x), timeout=60) + 1

    assert ray_tpu.get(outer.remote(10), timeout=120) == 21


# ====================== actors ======================


def test_actor_ordering_and_state(driver):
    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def incr(self, k=1):
            self.n += k
            return self.n

    c = Counter.remote()
    assert ray_tpu.get([c.incr.remote() for _ in range(10)], timeout=120) == \
        list(range(1, 11))


def test_serial_actor_strict_order_under_burst(driver):
    """A serial actor EXECUTES per-caller calls strictly in sequence order
    even when a deep pipelined burst lands coalesced (many requests in one
    socket read, all racing the admission cv). Regression for the
    admitted-but-overtaken race: next_seq used to advance before the
    method ran, so an admitted handler could lose the actor lock to its
    successor — ~10-call bursts rarely tripped it; coalesced 300-call
    bursts did constantly."""

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def incr(self):
            self.n += 1
            return self.n

    c = Counter.remote()
    out = ray_tpu.get([c.incr.remote() for _ in range(300)], timeout=300)
    assert out == list(range(1, 301)), f"out-of-order prefix: {out[:8]}"


def test_named_actor_lookup(driver):
    @ray_tpu.remote
    class KV:
        def __init__(self):
            self.d = {}

        def put(self, k, v):
            self.d[k] = v
            return True

        def get(self, k):
            return self.d.get(k)

    a = KV.options(name="kv-store").remote()
    assert ray_tpu.get(a.put.remote("x", 42), timeout=60)
    b = ray_tpu.get_actor("kv-store")
    assert ray_tpu.get(b.get.remote("x"), timeout=60) == 42


def test_actor_task_error(driver):
    @ray_tpu.remote
    class Fragile:
        def ok(self):
            return "fine"

        def bad(self):
            raise RuntimeError("actor method failed")

    a = Fragile.remote()
    assert ray_tpu.get(a.ok.remote(), timeout=60) == "fine"
    with pytest.raises(RuntimeError, match="actor method failed"):
        ray_tpu.get(a.bad.remote(), timeout=60)
    # Actor survives a method exception.
    assert ray_tpu.get(a.ok.remote(), timeout=60) == "fine"


def test_kill_actor(driver):
    @ray_tpu.remote
    class Victim:
        def ping(self):
            return "pong"

    a = Victim.remote()
    assert ray_tpu.get(a.ping.remote(), timeout=60) == "pong"
    ray_tpu.kill(a)
    assert _wait_for(
        lambda: driver.gcs.get_actor(a.actor_id).state == "DEAD", timeout=30
    )


def test_kill_actor_with_restart(driver):
    """kill(no_restart=False) runs the restart ladder on the multiprocess
    runtime: the daemon keeps the actor binding so its reaper reports the
    death and the GCS reschedules (and releases the old lifetime lease)."""
    @ray_tpu.remote(max_restarts=2)
    class Phoenix0:
        def pid(self):
            return os.getpid()

    a = Phoenix0.remote()
    p1 = ray_tpu.get(a.pid.remote(), timeout=60)
    ray_tpu.kill(a, no_restart=False)
    p2 = ray_tpu.get(a.pid.remote(), timeout=120)
    assert p2 != p1


def test_cancel_sticks_after_task_completes(driver):
    """cancel() marks the pending task; a late real result must not race
    the cancellation error back to a value (get stays deterministic)."""
    @ray_tpu.remote
    def slowish():
        time.sleep(2.0)
        return 42

    ref = slowish.remote()
    time.sleep(0.3)  # in flight
    ray_tpu.cancel(ref)
    with pytest.raises(ray_tpu.TaskCancelledError):
        ray_tpu.get(ref, timeout=30)
    time.sleep(2.5)  # the worker finishes the task anyway
    with pytest.raises(ray_tpu.TaskCancelledError):
        ray_tpu.get(ref, timeout=30)


def test_lease_for_removed_pg_fails_fast(driver):
    """A lease against a removed placement group raises promptly instead of
    spinning out the full scheduling timeout."""
    from ray_tpu.core.placement_group import (
        placement_group,
        remove_placement_group,
    )
    from ray_tpu.core.task_spec import PlacementGroupSchedulingStrategy

    pg = placement_group([{"CPU": 1}], strategy="PACK")
    assert pg.ready(timeout=60)
    remove_placement_group(pg)

    @ray_tpu.remote(scheduling_strategy=PlacementGroupSchedulingStrategy(
        placement_group=pg))
    def where():
        return "ran"

    start = time.time()
    with pytest.raises(Exception, match="does not exist"):
        ray_tpu.get(where.remote(), timeout=60)
    assert time.time() - start < 30.0


# ====================== fault tolerance (kill -9) ======================


def test_task_retry_on_worker_kill(driver, mp_cluster):
    @ray_tpu.remote(max_retries=3)
    def slow():
        time.sleep(3.0)
        return os.getpid()

    ref = slow.remote()
    time.sleep(1.0)
    killed = 0
    for i in range(len(mp_cluster.nodes)):
        for pid in mp_cluster.worker_pids(i):
            try:
                os.kill(pid, signal.SIGKILL)
                killed += 1
            except ProcessLookupError:
                pass
    assert killed > 0
    # The task is retried on a fresh worker and completes.
    assert isinstance(ray_tpu.get(ref, timeout=150), int)


def test_actor_restart_on_worker_kill(driver, mp_cluster):
    @ray_tpu.remote(max_restarts=2)
    class Phoenix:
        def pid(self):
            return os.getpid()

    a = Phoenix.remote()
    p1 = ray_tpu.get(a.pid.remote(), timeout=60)
    os.kill(p1, signal.SIGKILL)
    p2 = ray_tpu.get(a.pid.remote(), timeout=120)
    assert p2 != p1


def test_actor_no_restart_budget_dies(driver):
    @ray_tpu.remote(max_restarts=0)
    class OneShot:
        def pid(self):
            return os.getpid()

    a = OneShot.remote()
    p1 = ray_tpu.get(a.pid.remote(), timeout=60)
    os.kill(p1, signal.SIGKILL)
    with pytest.raises(ray_tpu.ActorError):
        ray_tpu.get(a.pid.remote(), timeout=120)


# ====================== placement groups ======================


def test_placement_group_strict_spread(driver, mp_cluster):
    from ray_tpu.core.placement_group import (
        placement_group,
        remove_placement_group,
    )
    from ray_tpu.core.task_spec import PlacementGroupSchedulingStrategy

    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="STRICT_SPREAD")
    assert pg.ready(timeout=30)
    nodes = pg.bundle_node_ids()
    assert len(set(nodes)) == 2  # bundles on distinct node daemons

    @ray_tpu.remote(num_cpus=1)
    def where():
        return os.getpid()

    refs = [
        where.options(scheduling_strategy=PlacementGroupSchedulingStrategy(
            placement_group=pg, placement_group_bundle_index=i)).remote()
        for i in range(2)
    ]
    pids = ray_tpu.get(refs, timeout=120)
    assert len(set(pids)) == 2
    remove_placement_group(pg)


# ====================== GCS restart / persistence ======================


def test_gcs_restart_preserves_state(tmp_path):
    """Head restart: KV + detached actor survive via snapshot + re-adoption
    (gcs_server.cc:523-524 Redis persistence analog)."""
    snapshot = str(tmp_path / "gcs.snap")
    cluster = Cluster(num_nodes=1, resources_per_node={"CPU": 2},
                      snapshot_path=snapshot)
    try:
        core = connect(cluster.gcs_address)
        try:
            core.gcs.kv_put("persisted-key", b"persisted-value")

            @ray_tpu.remote(lifetime="detached", name="durable", max_restarts=1)
            class Durable:
                def __init__(self):
                    self.pid = os.getpid()

                def ping(self):
                    return os.getpid()

            a = Durable.remote()
            p1 = ray_tpu.get(a.ping.remote(), timeout=60)
            # Force a snapshot before the kill.
            core._gcs_rpc.call("snapshot_now")

            cluster.kill_gcs()
            time.sleep(0.5)
            cluster.restart_gcs()
        finally:
            core.shutdown()
            runtime_mod._global_runtime = None

        core2 = connect(cluster.gcs_address)
        try:
            # KV survived the head restart.
            assert core2.gcs.kv_get("persisted-key") == b"persisted-value"
            # The daemon re-registered and the GCS re-adopted the LIVE
            # detached actor (same process, no restart).
            assert _wait_for(
                lambda: core2._gcs_rpc.call("get_named_actor", "durable")
                is not None,
                timeout=30,
            )
            b = ray_tpu.get_actor("durable")
            p2 = ray_tpu.get(b.ping.remote(), timeout=60)
            assert p2 == p1
        finally:
            core2.shutdown()
            runtime_mod._global_runtime = None
    finally:
        cluster.shutdown()


def test_node_death_actor_restart_elsewhere():
    """kill -9 a node daemon: health check marks the node dead and the actor
    restarts on a surviving node (gcs_health_check_manager.h:39 +
    gcs_actor_manager restart ladder)."""
    cluster = Cluster(num_nodes=2, resources_per_node={"CPU": 2})
    try:
        core = connect(cluster.gcs_address)
        try:
            @ray_tpu.remote(max_restarts=1)
            class Survivor:
                def pid(self):
                    return os.getpid()

            a = Survivor.remote()
            p1 = ray_tpu.get(a.pid.remote(), timeout=60)
            info = core._gcs_rpc.call("get_actor_info", a.actor_id)
            idx = next(i for i, h in enumerate(cluster.nodes)
                       if h.node_id == info["node_id"])
            cluster.kill_node(idx)
            p2 = ray_tpu.get(a.pid.remote(), timeout=150)
            assert p2 != p1
            info2 = core._gcs_rpc.call("get_actor_info", a.actor_id)
            assert info2["node_id"] != info["node_id"]
        finally:
            core.shutdown()
            runtime_mod._global_runtime = None
    finally:
        cluster.shutdown()


def test_dynamic_generator_error_surfaces(driver):
    """A failed dynamic-generator task must raise at iteration, not yield an
    empty stream."""

    @ray_tpu.remote(num_returns="dynamic", max_retries=0)
    def bad_gen():
        yield 1
        raise ValueError("gen kaboom")

    gen = bad_gen.remote()
    with pytest.raises(ValueError, match="gen kaboom"):
        for ref in gen:
            ray_tpu.get(ref, timeout=60)


def test_dynamic_generator_success(driver):
    @ray_tpu.remote(num_returns="dynamic")
    def gen(n):
        for i in range(n):
            yield i * i

    vals = [ray_tpu.get(r, timeout=60) for r in gen.remote(4)]
    assert vals == [0, 1, 4, 9]


def test_placement_group_rescheduled_after_node_death():
    """A PG bundle whose node dies is re-placed on a surviving node
    (gcs_placement_group_manager re-queue analog)."""
    from ray_tpu.core.placement_group import placement_group

    cluster = Cluster(num_nodes=3, resources_per_node={"CPU": 1})
    try:
        core = connect(cluster.gcs_address)
        try:
            pg = placement_group([{"CPU": 1}, {"CPU": 1}],
                                 strategy="STRICT_SPREAD")
            assert pg.ready(timeout=30)
            victim_node = pg.bundle_node_ids()[0]
            idx = next(i for i, h in enumerate(cluster.nodes)
                       if h.node_id == victim_node)
            cluster.kill_node(idx)
            # Health check marks the node dead, then the bundle re-places on
            # the spare node.
            assert _wait_for(
                lambda: not core.gcs.nodes[victim_node].alive, timeout=30
            ), "node death not detected"
            assert _wait_for(
                lambda: victim_node not in pg.bundle_node_ids()
                and pg.ready(timeout=1), timeout=60
            ), "PG was not re-placed"
        finally:
            core.shutdown()
            runtime_mod._global_runtime = None
    finally:
        cluster.shutdown()


def test_lineage_object_recovery():
    """Kill the node holding a task's output: get() transparently resubmits
    the creating task (object_recovery_manager.h:41 analog)."""
    cluster = Cluster(num_nodes=2, resources_per_node={"CPU": 2})
    try:
        core = connect(cluster.gcs_address)
        try:
            @ray_tpu.remote
            def make_blob(tag):
                # Big enough to never ride inline in the reply (so the
                # driver holds no copy — only the shm replica exists).
                return np.full(200_000, tag, np.float64)

            ref = make_blob.remote(7.0)
            # Wait until sealed, find which node holds it.
            assert _wait_for(
                lambda: core._gcs_rpc.call("locate_object", ref.id.binary()),
                timeout=60)
            locs = core._gcs_rpc.call("locate_object", ref.id.binary())
            holder = locs[0][0]
            # Drop any driver-local cached value so get() must fetch.
            with core._cache_lock:
                core._cache.pop(ref.id, None)
            idx = next(i for i, h in enumerate(cluster.nodes)
                       if h.node_id == holder)
            cluster.kill_node(idx)
            # Wait for the control plane to drop the dead node's locations.
            assert _wait_for(
                lambda: not core._gcs_rpc.call(
                    "locate_object", ref.id.binary()),
                timeout=30)
            out = ray_tpu.get(ref, timeout=120)
            assert float(out[0]) == 7.0 and out.shape == (200_000,)
        finally:
            core.shutdown()
            runtime_mod._global_runtime = None
    finally:
        cluster.shutdown()


def test_refcount_owner_free_protocol():
    """Property test of the local refcounter: frees fire exactly when an
    OWNED id's local+submitted counts both reach zero; borrowed ids never
    free (reference_count.h:61 simplification)."""
    import random

    from ray_tpu.core.core_worker import _LocalRefCounter
    from ray_tpu.core.ids import ObjectID

    class FakeCore:
        def __init__(self):
            self.freed = []

        def _free_object(self, oid):
            self.freed.append(oid)

    rng = random.Random(0)
    for trial in range(50):
        core = FakeCore()
        rc = _LocalRefCounter(core)
        ids = [ObjectID.for_put() for _ in range(4)]
        owned = set(rng.sample(ids, 2))
        for oid in owned:
            rc.set_owned(oid)
        counts = {oid: [0, 0] for oid in ids}  # [local, submitted]
        ops = []
        for _ in range(60):
            oid = rng.choice(ids)
            kind = rng.randrange(4)
            if kind == 0:
                rc.add_local_reference(oid)
                counts[oid][0] += 1
            elif kind == 1 and counts[oid][0] > 0:
                rc.remove_local_reference(oid)
                counts[oid][0] -= 1
            elif kind == 2:
                rc.add_submitted_task_reference(oid)
                counts[oid][1] += 1
            elif kind == 3 and counts[oid][1] > 0:
                rc.remove_submitted_task_reference(oid)
                counts[oid][1] -= 1
            ops.append((oid, kind))
        # Drain all remaining refs.
        for oid in ids:
            for _ in range(counts[oid][0]):
                rc.remove_local_reference(oid)
            for _ in range(counts[oid][1]):
                rc.remove_submitted_task_reference(oid)
        freed = set(core.freed)
        assert freed == owned, (trial, freed, owned)
        # Never double-freed.
        assert len(core.freed) == len(freed)


def test_runtime_env_env_vars(driver):
    """Per-task/actor env_vars apply at worker process SPAWN (fresh process,
    never returned to the vanilla pool)."""

    @ray_tpu.remote(runtime_env={"env_vars": {"MY_FLAG": "hello-env"}})
    def read_env():
        import os

        return os.environ.get("MY_FLAG"), os.getpid()

    @ray_tpu.remote
    def read_plain():
        import os

        return os.environ.get("MY_FLAG"), os.getpid()

    val, env_pid = ray_tpu.get(read_env.remote(), timeout=120)
    assert val == "hello-env"
    val2, plain_pid = ray_tpu.get(read_plain.remote(), timeout=120)
    assert val2 is None  # vanilla pool never contaminated
    assert env_pid != plain_pid


def test_worker_log_aggregation():
    """Worker prints land in per-worker session logs, stream through the
    GCS "logs" channel, and mirror to the driver (log_monitor.py analog)."""
    cluster = Cluster(num_nodes=1, resources_per_node={"CPU": 2})
    try:
        core = connect(cluster.gcs_address)
        try:
            captured = []
            core.start_log_mirroring(
                sink=lambda entry, line: captured.append((entry["worker"], line)))

            @ray_tpu.remote
            def chatty():
                print("hello-from-worker-log")
                return 1

            assert ray_tpu.get(chatty.remote(), timeout=120) == 1
            assert _wait_for(
                lambda: any("hello-from-worker-log" in line
                            for _, line in captured),
                timeout=30,
            ), captured
            # Raw tail RPC (state API path) sees it too.
            tails = core._daemons.get(cluster.nodes[0].address).call(
                "tail_worker_logs", timeout=10)
            assert any("hello-from-worker-log" in text
                       for text in tails.values())
        finally:
            core.shutdown()
            runtime_mod._global_runtime = None
    finally:
        cluster.shutdown()


def test_memory_monitor_kills_newest_task_worker():
    """OOM policy: above the usage threshold the daemon kills the newest
    busy TASK worker (retriable-FIFO analog); parked actors survive."""
    cluster = Cluster(num_nodes=1, resources_per_node={"CPU": 2},
                      system_config={"memory_monitor_threshold": 0.0001,
                                     "memory_monitor_period_s": 0.2})
    try:
        core = connect(cluster.gcs_address)
        try:
            @ray_tpu.remote
            class Bystander:
                def ping(self):
                    return "alive"

            b = Bystander.remote()
            assert ray_tpu.get(b.ping.remote(), timeout=120) == "alive"

            @ray_tpu.remote(max_retries=0)
            def hog():
                time.sleep(10.0)
                return "survived"

            ref = hog.remote()
            with pytest.raises(Exception, match="worker died|WorkerDied"):
                ray_tpu.get(ref, timeout=120)
            # The actor was never a kill candidate.
            assert ray_tpu.get(b.ping.remote(), timeout=60) == "alive"
        finally:
            core.shutdown()
            runtime_mod._global_runtime = None
    finally:
        cluster.shutdown()


def test_gloo_collectives_across_processes():
    """Eager collectives with the cross-process ("gloo") backend: 3 actor
    PROCESSES rendezvous through the GCS KV and exchange via rank 0's hub
    (the ray.util.collective gloo-group analog)."""
    cluster = Cluster(num_nodes=1, resources_per_node={"CPU": 3})
    try:
        core = connect(cluster.gcs_address)
        try:
            @ray_tpu.remote
            class Member:
                def __init__(self, rank, world):
                    from ray_tpu.parallel import collectives as c

                    c.init_collective_group(world, rank, backend="gloo",
                                            group_name="xp")
                    self.rank = rank
                    self.world = world

                def round_trip(self):
                    import numpy as np

                    from ray_tpu.parallel import collectives as c

                    total = c.allreduce(np.array([self.rank + 1.0]),
                                        group_name="xp")
                    gathered = c.allgather(np.array([self.rank]),
                                           group_name="xp")
                    root = c.broadcast(
                        np.array([42.0]) if self.rank == 0 else None,
                        src_rank=0, group_name="xp")
                    return (float(total[0]),
                            [int(g[0]) for g in gathered],
                            float(root[0]),
                            os.getpid())

                def p2p(self):
                    import numpy as np

                    from ray_tpu.parallel import collectives as c

                    if self.rank == 0:
                        c.send(np.array([7.0]), dst_rank=2, group_name="xp")
                        return None
                    if self.rank == 2:
                        got = c.recv(0, group_name="xp", timeout=60)
                        return float(got[0])
                    return None

            world = 3
            members = [Member.options(num_cpus=1).remote(r, world)
                       for r in range(world)]
            # All ranks must run the collective concurrently.
            results = ray_tpu.get(
                [m.round_trip.remote() for m in members], timeout=180)
            pids = {r[3] for r in results}
            assert len(pids) == world, "members must be distinct processes"
            for total, gathered, root, _pid in results:
                assert total == 6.0          # 1 + 2 + 3
                assert gathered == [0, 1, 2]
                assert root == 42.0
            p2p = ray_tpu.get([m.p2p.remote() for m in members], timeout=120)
            assert p2p[2] == 7.0
        finally:
            core.shutdown()
            runtime_mod._global_runtime = None
    finally:
        cluster.shutdown()


def test_nested_tasks_no_deadlock_when_fully_leased():
    """Blocked-worker release: outer tasks saturate every CPU lease, then
    each spawns an inner task and blocks in get() — without releasing the
    outer leases this deadlocks; with the protocol it completes."""
    cluster = Cluster(num_nodes=1, resources_per_node={"CPU": 2})
    try:
        core = connect(cluster.gcs_address)
        try:
            @ray_tpu.remote
            def inner(x):
                return x * 10

            @ray_tpu.remote
            def outer(x):
                return ray_tpu.get(inner.remote(x), timeout=150) + 1

            # 2 CPUs, 2 outer tasks -> both leases taken before either
            # inner can schedule.
            refs = [outer.remote(i) for i in range(2)]
            assert sorted(ray_tpu.get(refs, timeout=240)) == [1, 11]
        finally:
            core.shutdown()
            runtime_mod._global_runtime = None
    finally:
        cluster.shutdown()


def test_ring_collectives_full_surface():
    """The hubless ring backend across 4 actor processes: allreduce
    (sum/mean/scalar), reducescatter, alltoall, broadcast from a nonzero
    root, barrier, and back-to-back rounds (tag isolation between ops)."""
    cluster = Cluster(num_nodes=1, resources_per_node={"CPU": 4})
    try:
        core = connect(cluster.gcs_address)
        try:
            @ray_tpu.remote
            class Member:
                def __init__(self, rank, world):
                    from ray_tpu.parallel import collectives as c

                    c.init_collective_group(world, rank, backend="gloo",
                                            group_name="ring4")
                    self.rank = rank
                    self.world = world

                def rounds(self):
                    import numpy as np

                    from ray_tpu.parallel import collectives as c

                    out = {}
                    base = np.arange(8.0) + self.rank
                    out["sum"] = c.allreduce(base, group_name="ring4")
                    out["mean"] = c.allreduce(base, op="mean",
                                              group_name="ring4")
                    out["scalar"] = c.allreduce(np.float64(self.rank + 1),
                                                group_name="ring4")
                    rs = c.reducescatter(np.arange(8.0) + self.rank,
                                         group_name="ring4")
                    out["rs"] = rs
                    a2a = c.alltoall(np.arange(8.0) * (self.rank + 1),
                                     group_name="ring4")
                    out["a2a"] = a2a
                    out["bcast"] = c.broadcast(
                        np.array([9.0, 9.5]) if self.rank == 2 else None,
                        src_rank=2, group_name="ring4")
                    c.barrier(group_name="ring4")
                    # second back-to-back allreduce: tags must not collide
                    out["sum2"] = c.allreduce(np.ones(3) * self.rank,
                                              group_name="ring4")
                    return out

            world = 4
            members = [Member.options(num_cpus=1).remote(r, world)
                       for r in range(world)]
            results = ray_tpu.get([m.rounds.remote() for m in members],
                                  timeout=240)
            import numpy as np

            expect_sum = np.sum([np.arange(8.0) + r for r in range(world)],
                                axis=0)
            for rank, out in enumerate(results):
                np.testing.assert_allclose(out["sum"], expect_sum)
                np.testing.assert_allclose(out["mean"], expect_sum / world)
                assert out["scalar"] == sum(range(1, world + 1))
                np.testing.assert_allclose(
                    out["rs"], np.array_split(expect_sum, world)[rank])
                expect_a2a = np.concatenate(
                    [np.array_split(np.arange(8.0) * (s + 1), world)[rank]
                     for s in range(world)])
                np.testing.assert_allclose(out["a2a"], expect_a2a)
                np.testing.assert_allclose(out["bcast"], [9.0, 9.5])
                np.testing.assert_allclose(out["sum2"],
                                           np.ones(3) * sum(range(world)))
        finally:
            core.shutdown()
            runtime_mod._global_runtime = None
    finally:
        cluster.shutdown()


def test_gcs_head_disk_loss_restores_from_mirror(tmp_path):
    """Head-DISK-loss recovery: snapshots are MIRRORED to node daemons
    each tick; a fresh GCS whose local snapshot is gone restores from any
    surviving daemon (the external-store role Redis plays in the
    reference, gcs_server.cc:523-524)."""
    snapshot = str(tmp_path / "gcs.snap")
    cluster = Cluster(num_nodes=2, resources_per_node={"CPU": 2},
                      snapshot_path=snapshot)
    try:
        core = connect(cluster.gcs_address)
        try:
            core.gcs.kv_put("mirrored-key", b"mirrored-value")
            core.gcs.kv_put("mirrored-key-2", b"v2")
            core._gcs_rpc.call("snapshot_now")  # writes local + mirrors
            # A daemon holds the mirror.
            assert _wait_for(
                lambda: any(
                    core._daemons.get(n.address).call("fetch_gcs_snapshot",
                                                      timeout=10)
                    for n in cluster.nodes),
                timeout=30)
            mirror_node = next(
                n for n in cluster.nodes
                if core._daemons.get(n.address).call("fetch_gcs_snapshot",
                                                     timeout=10))

            cluster.kill_gcs()
            os.remove(snapshot)  # the head's DISK is gone
            time.sleep(0.5)
            cluster.restart_gcs(restore_from=mirror_node.address)
        finally:
            core.shutdown()
            runtime_mod._global_runtime = None

        core2 = connect(cluster.gcs_address)
        try:
            assert core2.gcs.kv_get("mirrored-key") == b"mirrored-value"
            assert core2.gcs.kv_get("mirrored-key-2") == b"v2"
        finally:
            core2.shutdown()
            runtime_mod._global_runtime = None
    finally:
        cluster.shutdown()
