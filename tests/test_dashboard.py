"""Dashboard HTTP endpoint tests (reference: dashboard module tests)."""

import json

import httpx
import pytest

import ray_tpu
from ray_tpu.dashboard import start_dashboard


@pytest.fixture
def dashboard(ray_start_regular):
    d = start_dashboard(port=18265)
    yield d
    d.stop()


class TestDashboard:
    def test_endpoints(self, dashboard):
        @ray_tpu.remote
        def work(x):
            return x

        ray_tpu.get([work.remote(i) for i in range(3)])

        base = dashboard.url
        summary = httpx.get(f"{base}/api/cluster_summary", timeout=10).json()
        assert summary["alive_nodes"] >= 1
        nodes = httpx.get(f"{base}/api/nodes", timeout=10).json()
        assert nodes and nodes[0]["state"] == "ALIVE"
        tasks = httpx.get(f"{base}/api/tasks", timeout=10).json()
        assert len(tasks) >= 3
        metrics = httpx.get(f"{base}/metrics", timeout=10)
        assert metrics.status_code == 200
        index = httpx.get(base, timeout=10)
        assert "ray_tpu cluster" in index.text
        timeline = httpx.get(f"{base}/timeline", timeout=10).json()
        assert isinstance(timeline, list)
