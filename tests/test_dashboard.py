"""Dashboard HTTP endpoint tests (reference: dashboard module tests)."""

import json

import httpx
import pytest

import ray_tpu
from ray_tpu.dashboard import start_dashboard


@pytest.fixture
def dashboard(ray_start_regular):
    d = start_dashboard(port=18265)
    yield d
    d.stop()


class TestDashboard:
    def test_endpoints(self, dashboard):
        @ray_tpu.remote
        def work(x):
            return x

        ray_tpu.get([work.remote(i) for i in range(3)])

        base = dashboard.url
        summary = httpx.get(f"{base}/api/cluster_summary", timeout=10).json()
        assert summary["alive_nodes"] >= 1
        nodes = httpx.get(f"{base}/api/nodes", timeout=10).json()
        assert nodes and nodes[0]["state"] == "ALIVE"
        tasks = httpx.get(f"{base}/api/tasks", timeout=10).json()
        assert len(tasks) >= 3
        metrics = httpx.get(f"{base}/metrics", timeout=10)
        assert metrics.status_code == 200
        index = httpx.get(base, timeout=10)
        assert "ray_tpu cluster" in index.text
        timeline = httpx.get(f"{base}/timeline", timeout=10).json()
        assert isinstance(timeline, list)


def test_node_stats_and_ui_on_multiprocess_cluster():
    """The per-node agent role: /api/node_stats fans out to every daemon's
    psutil+store reporter; / serves the SPA."""
    import httpx

    import ray_tpu
    from ray_tpu.core import runtime as runtime_mod
    from ray_tpu.core.cluster import Cluster, connect
    from ray_tpu.dashboard import start_dashboard

    cluster = Cluster(num_nodes=2, resources_per_node={"CPU": 1})
    try:
        core = connect(cluster.gcs_address)
        try:
            dash = start_dashboard(port=0) if False else start_dashboard(
                port=18799)
            try:
                stats = httpx.get(f"{dash.url}/api/node_stats",
                                  timeout=30).json()
                assert len(stats) == 2
                for n in stats:
                    assert n.get("workers") is not None, n
                    assert n.get("store_capacity", 0) > 0, n
                    assert "cpu_percent" in n, n
                page = httpx.get(f"{dash.url}/", timeout=30).text
                assert "ray_tpu cluster" in page and "renderNodes" in page
            finally:
                dash.stop()
        finally:
            core.shutdown()
            runtime_mod._global_runtime = None
    finally:
        cluster.shutdown()


def test_log_viewer_and_event_feed():
    """Log pane + event feed (reference: dashboard/modules/log/,
    modules/event/): /api/logs serves the aggregated worker log stream
    with a resumable cursor; /api/events serves the GCS task-event feed."""
    import time

    import httpx

    import ray_tpu
    from ray_tpu.core import runtime as runtime_mod
    from ray_tpu.core.cluster import Cluster, connect
    from ray_tpu.dashboard import start_dashboard

    cluster = Cluster(num_nodes=1, resources_per_node={"CPU": 2})
    try:
        core = connect(cluster.gcs_address)
        try:
            dash = start_dashboard(port=18897)
            try:
                @ray_tpu.remote
                def chatty(i):
                    print(f"dashboard-log-probe-{i}")
                    return i

                ray_tpu.get([chatty.remote(i) for i in range(3)], timeout=120)

                # Logs reach the channel via the daemon's 0.5s tailer tick.
                deadline = time.time() + 30
                seen, cursor = [], 0
                while time.time() < deadline:
                    d = httpx.get(f"{dash.url}/api/logs?cursor={cursor}",
                                  timeout=30).json()
                    cursor = d["cursor"]
                    for b in d["batches"]:
                        seen.extend(b.get("lines", []))
                    if any("dashboard-log-probe-" in ln for ln in seen):
                        break
                    time.sleep(0.5)
                assert any("dashboard-log-probe-" in ln for ln in seen), seen[-5:]
                # Cursor is resumable: a follow-up poll returns nothing new.
                d2 = httpx.get(f"{dash.url}/api/logs?cursor={cursor}",
                               timeout=30).json()
                assert d2["cursor"] >= cursor

                # Worker event buffers flush on a ~1s cadence; poll.
                deadline = time.time() + 30
                events = []
                while time.time() < deadline:
                    events = httpx.get(f"{dash.url}/api/events",
                                       timeout=30).json()
                    if events:
                        break
                    time.sleep(0.5)
                assert isinstance(events, list) and events, "no task events"
                assert any("chatty" in (e.get("name") or "")
                           for e in events), events[:3]
                assert all(e.get("kind") in ("FINISHED", "FAILED", "event")
                           for e in events[:5]), events[:3]

                page = httpx.get(f"{dash.url}/", timeout=30).text
                assert "renderLogs" in page and "renderEvents" in page
            finally:
                dash.stop()
        finally:
            core.shutdown()
            runtime_mod._global_runtime = None
    finally:
        cluster.shutdown()
