"""jaxlint + jitcheck: the four JAX-aware static checks trip on seeded
violations and stay quiet on their clean twins, the pragma/baseline
machinery covers them, and the runtime compile-churn guard counts
compilations per (site, signature) and enforces the steady-state
contract — including end-to-end on a warmed paged engine, whose
mixed-bucket burst must trigger ZERO new XLA compilations and zero
implicit device→host reads.
"""

import textwrap
import threading

import jax
import numpy as np
import pytest

from ray_tpu.devtools import jaxlint, jitcheck, lint


def _write(tmp_path, rel, src):
    p = tmp_path / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(src))
    return p


def _jax_findings(tmp_path, check=None):
    found = [f for f in lint.lint_tree(str(tmp_path))
             if f.check in jaxlint.JAX_CHECKS]
    if check is not None:
        found = [f for f in found if f.check == check]
    return found


# ---------------------------------------------------------------------------
# jit-churn
# ---------------------------------------------------------------------------


class TestJitChurn:
    def test_local_jit_flagged(self, tmp_path):
        _write(tmp_path, "mod.py", """
            import jax

            class Model:
                def evaluate(self, xs):
                    fwd = jax.jit(self.forward)   # rebuilt per evaluate()
                    return [fwd(x) for x in xs]
            """)
        found = _jax_findings(tmp_path, "jit-churn")
        assert len(found) == 1 and "fwd" in found[0].message
        assert found[0].scope == "Model.evaluate"

    def test_immediate_call_flagged(self, tmp_path):
        _write(tmp_path, "mod.py", """
            import jax

            def step(f, x):
                return jax.jit(f)(x)   # compile-and-discard every call
            """)
        assert len(_jax_findings(tmp_path, "jit-churn")) == 1

    def test_partial_form_flagged(self, tmp_path):
        _write(tmp_path, "mod.py", """
            import functools
            import jax

            def run(f, x):
                g = functools.partial(jax.jit, donate_argnums=(0,))(f)
                return g(x)
            """)
        assert len(_jax_findings(tmp_path, "jit-churn")) == 1

    def test_cached_builder_and_module_scope_clean(self, tmp_path):
        _write(tmp_path, "mod.py", """
            import jax

            top = jax.jit(lambda x: x)      # module scope: compiled once

            class Model:
                def __init__(self):
                    self._fwd = jax.jit(self.forward)   # cached on self

                def lazy(self):
                    if self._fwd is None:
                        self._fwd = jax.jit(self.forward)
                    return self._fwd

                def build(self):
                    return jax.jit(self.forward)  # one-shot builder

                def build2(self):
                    f = jax.jit(self.forward)     # escapes via return
                    return f

                def register(self, table):
                    f = jax.jit(self.forward)     # escapes into a call
                    table.add(f)

                def cache_slot(self, table, k):
                    f = jax.jit(self.forward)     # escapes via subscript
                    table[k] = f
            """)
        assert _jax_findings(tmp_path, "jit-churn") == []

    def test_static_argnums_data_derived(self, tmp_path):
        _write(tmp_path, "mod.py", """
            import functools
            import jax

            @functools.partial(jax.jit, static_argnums=(1,))
            def pad_to(x, n):
                return x[:n]

            @functools.partial(jax.jit, static_argnames=("width",))
            def pad_named(x, width=8):
                return x[:width]

            BUCKET = 128

            def hot(batch, x):
                pad_to(x, len(batch))          # one compile per batch size
                pad_named(x, width=x.shape[0])  # same, by name
                pad_to(x, BUCKET)              # constant: fine
                pad_named(x, width=BUCKET)     # constant: fine
            """)
        found = _jax_findings(tmp_path, "jit-churn")
        assert len(found) == 2
        assert {f.line for f in found} == {16, 17}


# ---------------------------------------------------------------------------
# host-sync
# ---------------------------------------------------------------------------

_HOT_HEADER = """
    import jax
    import numpy as np

    class Engine:
        def _run_decode(self, active):
            return self._decode_fn(self.params, active)

"""


class TestHostSync:
    def test_sinks_flagged_in_hot_scope(self, tmp_path):
        _write(tmp_path, "serve/llm.py", _HOT_HEADER + """
        def _step_inner(self):
            toks = self._run_decode(self._active)
            host = np.asarray(toks)         # implicit sync
            first = float(toks[0])          # coercion sync
            n = toks.sum().item()           # .item() sync
            if toks.any():                  # truthiness sync
                pass
            return host, first, n
        """)
        found = _jax_findings(tmp_path, "host-sync")
        kinds = {f.detail.split(":")[0] for f in found}
        assert kinds == {"np-sync", "coerce", "item", "truthiness"}

    def test_device_get_twin_clean(self, tmp_path):
        _write(tmp_path, "serve/llm.py", _HOT_HEADER + """
        def _step_inner(self):
            toks = self._run_decode(self._active)
            host = jax.device_get(toks)     # the sanctioned batched fetch
            first = float(host[0])
            n = host.sum().item()
            if host.any():
                pass
            return host, first, n
        """)
        assert _jax_findings(tmp_path, "host-sync") == []

    def test_cold_files_not_patrolled(self, tmp_path):
        _write(tmp_path, "util/cold.py", """
            import jax.numpy as jnp
            import numpy as np

            def checkpoint(params):
                return np.asarray(jnp.stack(params))  # cold path: fine
            """)
        assert _jax_findings(tmp_path, "host-sync") == []

    def test_coverage_guard_fires_on_missing_scope(self, tmp_path):
        _write(tmp_path, "serve/llm.py", """
            class Engine:
                def _step_inner(self):
                    return None
            """)
        found = _jax_findings(tmp_path, "host-sync")
        assert len(found) == 1
        assert "_run_decode" in found[0].message
        assert found[0].detail == "hot-scope-missing:_run_decode"

    def test_nested_generator_is_walked(self, tmp_path):
        _write(tmp_path, "models/generate.py", _HOT_HEADER + """
        def generate(self, prompt):
            last = self._prefill_fn(self.params, prompt)

            def run():
                nxt = last
                while True:
                    yield int(nxt[0])       # per-token sync in the closure
            return run()
        """)
        found = _jax_findings(tmp_path, "host-sync")
        assert any(f.detail == "coerce:int" for f in found)


# ---------------------------------------------------------------------------
# key-reuse
# ---------------------------------------------------------------------------


class TestKeyReuse:
    def test_reuse_flagged(self, tmp_path):
        _write(tmp_path, "mod.py", """
            import jax

            def sample(shape):
                key = jax.random.PRNGKey(0)
                a = jax.random.normal(key, shape)
                b = jax.random.uniform(key, shape)   # reuse!
                return a + b
            """)
        found = _jax_findings(tmp_path, "key-reuse")
        assert len(found) == 1 and "'key'" in found[0].message

    def test_loop_reuse_flagged(self, tmp_path):
        _write(tmp_path, "mod.py", """
            import jax

            def rollout(key, n):
                outs = []
                for _ in range(n):
                    outs.append(jax.random.normal(key, (4,)))  # every iter
                return outs
            """)
        assert len(_jax_findings(tmp_path, "key-reuse")) == 1

    def test_split_then_use_clean(self, tmp_path):
        _write(tmp_path, "mod.py", """
            import jax

            def sample(key, shape):
                key, sub = jax.random.split(key)
                a = jax.random.normal(sub, shape)
                key, sub = jax.random.split(key)
                b = jax.random.uniform(sub, shape)
                return a + b

            def loop(self, n):
                for _ in range(n):
                    self._key, sub = jax.random.split(self._key)
                    yield jax.random.normal(sub, (4,))
            """)
        assert _jax_findings(tmp_path, "key-reuse") == []

    def test_branches_fold_in_and_shadowing_clean(self, tmp_path):
        _write(tmp_path, "mod.py", """
            import jax

            def branchy(key, logits, discrete):
                if discrete:
                    return jax.random.categorical(key, logits)
                else:
                    return jax.random.normal(key, logits.shape)

            def folded(key, n):
                return [jax.random.normal(jax.random.fold_in(key, i), (2,))
                        for i in range(n)]

            def outer(key):
                k = iter(jax.random.split(key, 4))

                def nrm(key, shape):
                    # param shadows the outer key — fresh key per call
                    return jax.random.normal(key, shape)

                return nrm(next(k), (2,)), nrm(next(k), (3,))
            """)
        assert _jax_findings(tmp_path, "key-reuse") == []


# ---------------------------------------------------------------------------
# donate-uaf
# ---------------------------------------------------------------------------


class TestDonateUaf:
    def test_read_after_donate_flagged(self, tmp_path):
        _write(tmp_path, "mod.py", """
            import jax

            update = jax.jit(lambda p, g: p, donate_argnums=(0,))

            def train_step(params, grads):
                new = update(params, grads)
                stale = params["w"]          # donated buffer: dead!
                return new, stale
            """)
        found = _jax_findings(tmp_path, "donate-uaf")
        assert len(found) == 1 and "'params'" in found[0].message

    def test_rebind_through_clean(self, tmp_path):
        _write(tmp_path, "mod.py", """
            import functools
            import jax

            @functools.partial(jax.jit, donate_argnums=(0, 1))
            def set_last(last, keys, row):
                return last, keys

            def attach(last, keys, row):
                last, keys = set_last(last, keys, row)  # rebind-through
                return last.sum() + keys.sum()

            def swap(params, grads, update):
                params = update(params, grads)
                return params
            """)
        assert _jax_findings(tmp_path, "donate-uaf") == []


# ---------------------------------------------------------------------------
# pragmas + baseline round-trip
# ---------------------------------------------------------------------------


class TestSuppression:
    def test_pragma_suppresses_jax_checks(self, tmp_path):
        _write(tmp_path, "mod.py", """
            import jax

            def churn(f, x):
                # raylint: ignore[jit-churn]
                g = jax.jit(f)
                return g(x)
            """)
        assert _jax_findings(tmp_path) == []

    def test_baseline_round_trip(self, tmp_path):
        _write(tmp_path, "mod.py", """
            import jax

            def sample(key, shape):
                a = jax.random.normal(key, shape)
                return a + jax.random.uniform(key, shape)
            """)
        baseline = tmp_path / "baseline.txt"
        assert lint.main([str(tmp_path), "--baseline", str(baseline),
                          "-q"]) == 1
        assert lint.main([str(tmp_path), "--baseline", str(baseline),
                          "--update-baseline"]) == 0
        assert lint.main([str(tmp_path), "--baseline", str(baseline),
                          "-q"]) == 0
        # fingerprints are line-free: shifting the finding keeps it accepted
        src = (tmp_path / "mod.py").read_text()
        (tmp_path / "mod.py").write_text("# moved\n" + src)
        assert lint.main([str(tmp_path), "--baseline", str(baseline),
                          "-q"]) == 0

    def test_profile_reports_jax_phases(self, tmp_path):
        _write(tmp_path, "mod.py", "x = 1\n")
        linter = lint.Linter(str(tmp_path))
        linter.run()
        for phase in jaxlint.JAX_CHECKS:
            assert phase in linter.timings


# ---------------------------------------------------------------------------
# jitcheck (runtime)
# ---------------------------------------------------------------------------


@pytest.fixture
def jc():
    """jitcheck installed for the test; leaves a suite-level install
    (RAY_TPU_JIT_CHECK_ENABLED=1 runs) untouched."""
    was = jitcheck.installed()
    if not was:
        jitcheck.install()
    yield jitcheck
    if not was:
        jitcheck.uninstall()


class TestJitcheck:
    def test_compile_counting_per_site_and_signature(self, jc):
        f = jax.jit(lambda x: x * 3)
        n0 = jc.total_compiles()
        f(np.ones(3, np.float32))
        f(np.ones(3, np.float32))   # cached: no new compile
        assert jc.total_compiles() == n0 + 1
        f(np.ones(5, np.float32))   # new shape: one more
        assert jc.total_compiles() == n0 + 2
        sites = {site for site, _sig in jc.compile_counts()}
        assert any("test_devtools_jax.py" in s for s in sites)
        sigs = {sig for _s, sig in jc.compile_counts()
                if "test_devtools_jax.py" in _s}
        assert "(float32[3])" in sigs and "(float32[5])" in sigs
        secs = jc.compile_seconds_by_site()
        assert any("test_devtools_jax.py" in s and v > 0
                   for s, v in secs.items())

    def test_steady_state_allows_warm_calls_and_device_get(self, jc):
        f = jax.jit(lambda x: x + 1)
        f(np.ones(4, np.float32))   # warm
        v0 = len(jc.violations())
        with jc.steady_state():
            y = f(np.ones(4, np.float32))
            host = jax.device_get(y)
        assert host.sum() == 8.0
        assert len(jc.violations()) == v0

    @pytest.mark.jit_violations("provokes an implicit read on purpose")
    def test_implicit_read_recorded(self, jc):
        f = jax.jit(lambda x: x * 2)
        y = f(np.ones(2, np.float32))
        v0 = len(jc.violations())
        with jc.steady_state():
            float(y.sum())          # implicit device->host read
        new = jc.violations()[v0:]
        assert any("implicit device->host read" in v for v in new)

    @pytest.mark.jit_violations("provokes a steady-state compile on purpose")
    def test_shape_churn_fails_strict_guard(self, jc):
        f = jax.jit(lambda x: x - 1)
        f(np.ones(4, np.float32))   # warm one bucket only
        with pytest.raises(jitcheck.SteadyStateViolation):
            with jc.steady_state(strict=True):
                f(np.ones(7, np.float32))   # unwarmed shape: compiles

    def test_steady_state_noop_when_not_installed(self):
        if jitcheck.installed():
            pytest.skip("suite runs with jitcheck installed")
        with jitcheck.steady_state(strict=True):
            jax.jit(lambda x: x)(np.ones(2))  # fine: guard inert

    def test_uninstall_restores_jax(self):
        was = jitcheck.installed()
        if not was:
            jitcheck.install()
            jitcheck.uninstall()
            assert not jitcheck.installed()
        f = jax.jit(lambda x: x)
        assert f(np.ones(1, np.float32)).shape == (1,)


# ---------------------------------------------------------------------------
# e2e: the steady-state decode invariant
# ---------------------------------------------------------------------------


class TestEngineSteadyState:
    def test_warmed_paged_engine_burst_zero_compiles(self, jc):
        """After warmup, a mixed-bucket greedy+sampled burst (the whole
        request path: admission, prefill, batched decode, distribution)
        triggers ZERO new XLA compilations and zero implicit host reads —
        the invariant every serve perf number rests on."""
        from ray_tpu.models import transformer
        from ray_tpu.serve.llm import PagedLLMEngine

        cfg = transformer.tiny(max_seq_len=64)
        params = transformer.init_params(cfg, jax.random.key(0))
        eng = PagedLLMEngine(params, cfg, prompt_buckets=(16, 32), chunk=4,
                             slots=2, max_queue=4, name="jitcheck-e2e",
                             block_tokens=8, pool_blocks=65)
        eng.warmup()
        assert eng._steady
        warm_compiles = jc.total_compiles()
        assert warm_compiles > 0  # warmup really did compile the programs

        prompts = [[7, 3, 11], [2, 4, 6, 8, 10], [1] * 9,
                   list(range(100, 125))]  # last spans the 32 bucket
        v0 = len(jc.violations())
        outs = [None] * len(prompts)

        def run(i):
            temp = 0.0 if i % 2 == 0 else 0.8
            outs[i] = eng.generate(list(prompts[i]), max_new_tokens=6,
                                   temperature=temp, seed=i)

        threads = [threading.Thread(target=run, args=(i,))
                   for i in range(len(prompts))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

        assert all(o is not None and len(o) > 0 for o in outs)
        assert jc.total_compiles() == warm_compiles, (
            "steady-state burst compiled:",
            jc.compile_counts())
        assert jc.violations()[v0:] == []
