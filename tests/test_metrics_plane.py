"""Cluster metrics plane tests — exporter → GCS aggregation → dashboard.

Covers the per-process export pipeline (reference: ``_private/
metrics_agent.py`` → Prometheus scrape), the built-in task lifecycle phase
histograms, the bisect histogram + label escaping, and the cursor'd
task-event reads.
"""

import time
from unittest import mock

import pytest

import ray_tpu
from ray_tpu.util import metrics as um


# ====================== metrics module units ======================


def test_histogram_bisect_bucketing():
    h = um.Histogram("t_hist_bisect", boundaries=[1.0, 5.0, 10.0])
    for v in (0.5, 1.0, 1.5, 5.0, 7.0, 11.0, 1e9):
        h.observe(v)
    snap = h._snapshot()
    assert snap["type"] == "histogram" and snap["bounds"] == [1.0, 5.0, 10.0]
    [(tags, (buckets, total_sum, count))] = snap["samples"]
    # value <= bound semantics: 0.5,1.0 | 1.5,5.0 | 7.0 | 11.0,1e9 (+Inf)
    assert buckets == [2, 2, 1, 2]
    assert count == 7
    lines = h._prom_lines()
    # cumulative counts in the exposition
    assert any(line.endswith(" 2") and 'le="1.0"' in line for line in lines)
    assert any(line.endswith(" 5") and 'le="10.0"' in line for line in lines)
    assert any(line.endswith(" 7") and 'le="+Inf"' in line for line in lines)


def test_histogram_rejects_unsorted_bounds():
    with pytest.raises(ValueError):
        um.Histogram("t_hist_bad", boundaries=[5.0, 1.0])


def test_label_value_escaping():
    g = um.Gauge("t_gauge_escape", tag_keys=("path",))
    g.set(1.0, {"path": 'a\\b"c\nd'})
    [line] = [ln for ln in g._prom_lines() if not ln.startswith("#")]
    assert 'path="a\\\\b\\"c\\nd"' in line
    # and the escaped form survives the aggregator's merged rendering
    agg = um.MetricsAggregator()
    agg.report("n1", "driver", 1, [g._snapshot()])
    assert 'path="a\\\\b\\"c\\nd"' in agg.prometheus_text()


def test_aggregator_merges_processes_with_identity_labels():
    c = um.Counter("t_agg_counter", tag_keys=("op",))
    c.inc(3, {"op": "x"})
    snap = [c._snapshot()]
    agg = um.MetricsAggregator()
    agg.report("node-a", "worker", 11, snap)
    agg.report("node-b", "node_daemon", 22, snap)
    text = agg.prometheus_text()
    assert text.count("# TYPE t_agg_counter counter") == 1
    assert 'component="worker"' in text and 'component="node_daemon"' in text
    assert 'node_id="node-a"' in text and 'pid="22"' in text
    summ = agg.summary()
    assert len(summ["processes"]) == 2
    [row] = [m for m in summ["metrics"] if m["name"] == "t_agg_counter"]
    assert row["series"] == 2 and row["total"] == 6.0


def test_aggregator_staleness_eviction():
    g = um.Gauge("t_agg_stale")
    g.set(1.0)
    agg = um.MetricsAggregator()
    now = time.time()
    agg.report("dead-node", "worker", 1, [g._snapshot()], now=now - 3600)
    agg.report("live-node", "worker", 2, [g._snapshot()], now=now)
    text = agg.prometheus_text(now=now)
    assert "live-node" in text and "dead-node" not in text
    assert len(agg.summary(now=now)["processes"]) == 1


def test_collector_hooks_run_before_snapshot():
    g = um.Gauge("t_collected")
    unregister = um.register_collector(lambda: g.set(42.0))
    try:
        snap = um.snapshot_registry()
        [m] = [m for m in snap if m["name"] == "t_collected"]
        assert m["samples"] == [((), 42.0)]
    finally:
        unregister()


# ====================== exporter units ======================


def test_exporter_survives_gcs_outage():
    """Reports raising (GCS down/restarting) are swallowed and the next
    tick re-registers the full snapshot — no crash, no thread death."""
    from ray_tpu.core.config import Config, set_config
    from ray_tpu.core.metrics_export import MetricsExporter
    from ray_tpu.core.rpc import RpcConnectionError

    set_config(Config({"metrics_export_interval_s": 0.05}))
    try:
        got = []
        calls = {"n": 0}

        def report(node_id, component, pid, snapshot):
            calls["n"] += 1
            if calls["n"] <= 2:
                raise RpcConnectionError("gcs restarting")
            got.append((node_id, component, pid, snapshot))

        exp = MetricsExporter(report, node_id="n1",
                              component="worker").start()
        try:
            deadline = time.time() + 10
            while not got and time.time() < deadline:
                time.sleep(0.02)
            assert got, "exporter never recovered after failed reports"
            node_id, component, pid, snapshot = got[0]
            assert (node_id, component) == ("n1", "worker")
            assert isinstance(snapshot, list)
        finally:
            exp.stop()
    finally:
        set_config(Config())


def test_exporter_disabled_by_knob():
    from ray_tpu.core.config import Config, set_config
    from ray_tpu.core.metrics_export import MetricsExporter, metrics_enabled

    set_config(Config({"metrics_export_enabled": False}))
    try:
        assert not metrics_enabled()
        exp = MetricsExporter(lambda *a: (_ for _ in ()).throw(
            AssertionError("must not report")), "n", "driver").start()
        assert exp._thread is None
        exp.stop()
    finally:
        set_config(Config())


# ====================== cursor'd task events ======================


def test_task_events_since_cursor():
    from ray_tpu.core.gcs import GlobalControlStore

    store = GlobalControlStore()
    for i in range(10):
        store.record_task_event({"task_id": f"t{i}"})
    cur, evs = store.task_events_since(0, limit=4)
    assert [e["task_id"] for e in evs] == ["t0", "t1", "t2", "t3"]
    assert cur == 4
    cur, evs = store.task_events_since(cur, limit=100)
    assert len(evs) == 6 and cur == 10
    # caught up: nothing new
    cur2, evs2 = store.task_events_since(cur)
    assert evs2 == [] and cur2 == 10
    # None tails from the end
    cur3, tail = store.task_events_since(None, limit=3)
    assert [e["task_id"] for e in tail] == ["t7", "t8", "t9"] and cur3 == 10
    # a cursor past the end (GCS restarted with a fresh log) clamps
    cur4, evs4 = store.task_events_since(99999)
    assert evs4 == [] and cur4 == 10
    # legacy full read unchanged
    assert len(store.task_events()) == 10


def test_task_events_since_survives_truncation():
    from ray_tpu.core.gcs import GlobalControlStore

    store = GlobalControlStore()
    store._task_events = [{"task_id": f"t{i}"} for i in range(100)]
    store._task_event_base = 0
    # force the 100k truncation path with a small synthetic log
    with store._lock:
        drop = 50
        del store._task_events[:drop]
        store._task_event_base += drop
    cur, evs = store.task_events_since(10, limit=5)
    # events below the base were truncated away; read resumes at the base
    assert [e["task_id"] for e in evs] == ["t50", "t51", "t52", "t53", "t54"]
    assert cur == 55


# ====================== tracing satellite ======================


def test_span_duration_uses_monotonic_clock():
    from ray_tpu.util import tracing

    class _GcsSink:
        def __init__(self):
            self.events = []

        def record_task_event(self, e):
            self.events.append(e)

    class _Rt:
        gcs = _GcsSink()

    rt = _Rt()
    # Freeze the WALL clock: with time.time pinned, only a monotonic-based
    # duration can come out positive.
    with mock.patch.object(tracing.time, "time", return_value=1234.0):
        with tracing.span("probe", runtime=rt):
            time.sleep(0.05)
    [event] = rt.gcs.events
    assert event["time"] == 1234.0
    assert event["duration"] >= 0.04


# ====================== in-process pipeline ======================


def test_phase_histograms_and_summary_in_process(ray_start_regular):
    @ray_tpu.remote
    def work(x):
        return x + 1

    assert ray_tpu.get([work.remote(i) for i in range(4)]) == [1, 2, 3, 4]
    from ray_tpu.core.runtime import get_runtime

    rt = get_runtime()
    rt._metrics_exporter.flush()
    text = rt.gcs.metrics_text()
    assert "ray_tpu_task_phase_s_bucket" in text
    for phase in ("queued", "args_fetch", "execute", "total"):
        assert f'phase="{phase}"' in text
    assert 'component="driver"' in text
    # task events carry the phase stamps too
    evs = [e for e in rt.gcs.task_events() if e.get("phases")]
    assert evs and "execute" in evs[-1]["phases"]
    summ = rt.gcs.metrics_summary()
    assert summ["processes"] and summ["metrics"]


# ====================== multiprocess cluster pipeline ======================


def test_cluster_metrics_merged_exposition_and_dashboard():
    """Acceptance: dashboard /metrics returns the merged exposition with
    ≥2 distinct components and populated task phase histograms after a
    multi-process workload; the exporter pipeline survives a GCS restart."""
    import os

    import httpx

    from ray_tpu.core import runtime as runtime_mod
    from ray_tpu.core.cluster import Cluster, connect
    from ray_tpu.core.config import Config, set_config
    from ray_tpu.dashboard import start_dashboard

    os.environ["RAY_TPU_METRICS_EXPORT_INTERVAL_S"] = "0.3"
    set_config(Config())  # driver adopts the fast cadence too
    cluster = Cluster(num_nodes=2, resources_per_node={"CPU": 1})
    try:
        core = connect(cluster.gcs_address)
        try:
            @ray_tpu.remote
            def work(x):
                return x * 2

            assert ray_tpu.get([work.remote(i) for i in range(6)],
                               timeout=120) == [0, 2, 4, 6, 8, 10]
            dash = start_dashboard(port=18931)
            try:
                deadline = time.time() + 60
                text = ""
                while time.time() < deadline:
                    text = httpx.get(f"{dash.url}/metrics", timeout=30).text
                    comps = {seg.split('"')[0]
                             for seg in text.split('component="')[1:]}
                    if ({"worker", "node_daemon"} <= comps
                            and "ray_tpu_task_phase_s_bucket" in text):
                        break
                    time.sleep(0.5)
                assert {"worker", "node_daemon"} <= comps, text[:2000]
                assert "ray_tpu_task_phase_s_bucket" in text
                assert 'phase="execute"' in text
                # one TYPE header per metric despite many reporting processes
                assert text.count("# TYPE ray_tpu_task_phase_s ") == 1

                summ = httpx.get(f"{dash.url}/api/metrics_summary",
                                 timeout=30).json()
                comps = {p["component"] for p in summ["processes"]}
                assert {"worker", "node_daemon", "gcs"} <= comps
                daemon_nodes = {p["node_id"] for p in summ["processes"]
                                if p["component"] == "node_daemon"}
                assert len(daemon_nodes) == 2
                page = httpx.get(f"{dash.url}/", timeout=30).text
                assert "renderMetrics" in page

                # GCS restart: exporters keep notifying and re-register on
                # the fresh aggregator — series reappear, nothing crashes.
                cluster.kill_gcs()
                cluster.restart_gcs()
                deadline = time.time() + 60
                comps = set()
                while time.time() < deadline:
                    try:
                        summ = core.gcs.metrics_summary()
                    except Exception:  # noqa: BLE001 — GCS still rebinding
                        time.sleep(0.5)
                        continue
                    comps = {p["component"] for p in summ["processes"]}
                    if "node_daemon" in comps:
                        break
                    time.sleep(0.5)
                assert "node_daemon" in comps, comps
            finally:
                dash.stop()
        finally:
            core.shutdown()
            runtime_mod._global_runtime = None
    finally:
        cluster.shutdown()
        os.environ.pop("RAY_TPU_METRICS_EXPORT_INTERVAL_S", None)
        set_config(Config())
