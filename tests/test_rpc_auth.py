"""RPC auth-token handshake (control-plane hardening).

The pickle RPC plane grants code execution to anyone who can connect; with
``RAY_TPU_AUTH_TOKEN`` set (or an explicit ``auth_token``), every connection
must open with a raw token frame the server verifies BEFORE unpickling
anything from the peer.
"""

import pytest

from ray_tpu.core.rpc import RpcClient, RpcConnectionError, RpcServer


class _Handler:
    def ping(self):
        return "pong"


def test_auth_token_roundtrip_and_rejection():
    server = RpcServer(_Handler(), name="auth-test", auth_token=b"s3cret")
    try:
        good = RpcClient(server.address, auth_token=b"s3cret")
        assert good.call("ping", timeout=10) == "pong"
        good.close()

        bad = RpcClient(server.address, auth_token=b"wrong")
        with pytest.raises(RpcConnectionError):
            bad.call("ping", timeout=10)
        bad.close()

        # No token at all: the server must also reject (first frame is a
        # pickled request, not the expected raw auth blob).
        naked = RpcClient(server.address, auth_token=b"")
        with pytest.raises(RpcConnectionError):
            naked.call("ping", timeout=10)
        naked.close()
    finally:
        server.stop()


def test_no_token_plain_roundtrip():
    server = RpcServer(_Handler(), name="plain-test", auth_token=b"")
    try:
        client = RpcClient(server.address, auth_token=b"")
        assert client.call("ping", timeout=10) == "pong"
        client.close()
    finally:
        server.stop()


def test_env_token_propagates(monkeypatch):
    """Default token comes from RAY_TPU_AUTH_TOKEN, matching how cluster
    processes inherit it through spawn env."""
    monkeypatch.setenv("RAY_TPU_AUTH_TOKEN", "cluster-secret")
    server = RpcServer(_Handler(), name="env-auth")
    try:
        client = RpcClient(server.address)
        assert client.call("ping", timeout=10) == "pong"
        client.close()
    finally:
        server.stop()
