"""Scheduler + placement group tests — the reference's
``cluster_task_manager_test.cc`` / ``scheduling_policy_test.cc`` concerns
exercised through the Python surface on a multi-virtual-node cluster."""

import time

import pytest


def test_spread_strategy(ray_start_cluster):
    rt = ray_start_cluster

    @rt.remote(scheduling_strategy="SPREAD", num_cpus=1)
    def where():
        return rt.get_runtime_context().node_id.hex()

    nodes = set(rt.get([where.remote() for _ in range(8)]))
    assert len(nodes) >= 3  # 4 nodes; spread should hit most of them


def test_node_affinity(ray_start_cluster):
    rt = ray_start_cluster
    target = rt.nodes()[2]["NodeID"]
    from ray_tpu.core.ids import NodeID

    @rt.remote(scheduling_strategy=rt.NodeAffinitySchedulingStrategy(node_id=NodeID.from_hex(target)))
    def where():
        return rt.get_runtime_context().node_id.hex()

    assert rt.get(where.remote()) == target


def test_placement_group_strict_pack(ray_start_cluster):
    rt = ray_start_cluster
    pg = rt.placement_group([{"CPU": 1}, {"CPU": 1}], strategy="STRICT_PACK")
    assert pg.ready(timeout=5)
    node_ids = pg.bundle_node_ids()
    assert node_ids[0] == node_ids[1]


def test_placement_group_strict_spread(ray_start_cluster):
    rt = ray_start_cluster
    pg = rt.placement_group([{"CPU": 1}] * 4, strategy="STRICT_SPREAD")
    assert pg.ready(timeout=5)
    node_ids = pg.bundle_node_ids()
    assert len(set(node_ids)) == 4


def test_placement_group_task_lands_on_bundle(ray_start_cluster):
    rt = ray_start_cluster
    pg = rt.placement_group([{"CPU": 1, "TPU": 2}], strategy="PACK")
    assert pg.ready(timeout=5)

    @rt.remote(
        scheduling_strategy=rt.PlacementGroupSchedulingStrategy(
            placement_group=pg, placement_group_bundle_index=0
        )
    )
    def where():
        return rt.get_runtime_context().node_id.hex()

    assert rt.get(where.remote()) == pg.bundle_node_ids()[0].hex()


def test_placement_group_reserves_resources(ray_start_cluster):
    rt = ray_start_cluster
    before = rt.available_resources().get("TPU", 0)
    pg = rt.placement_group([{"TPU": 4}], strategy="PACK")
    assert pg.ready(timeout=5)
    assert rt.available_resources().get("TPU", 0) == before - 4
    rt.remove_placement_group(pg)
    assert rt.available_resources().get("TPU", 0) == before


def test_tpu_slice_gang_reservation(ray_start_cluster):
    """A STRICT_PACK TPU bundle gang = the atomic ICI-slice claim."""
    rt = ray_start_cluster
    pg = rt.placement_group([{"TPU": 4}], strategy="STRICT_PACK")
    assert pg.ready(timeout=5)
    # A second whole-slice claim must land on a different node.
    pg2 = rt.placement_group([{"TPU": 4}], strategy="STRICT_PACK")
    assert pg2.ready(timeout=5)
    assert pg.bundle_node_ids()[0] != pg2.bundle_node_ids()[0]


def test_node_death_fails_actors(ray_start_cluster):
    rt = ray_start_cluster
    from ray_tpu.core.runtime import get_runtime

    @rt.remote(num_cpus=1)
    class Pinned:
        def node(self):
            return rt.get_runtime_context().node_id

    actors = [Pinned.remote() for _ in range(4)]
    nodes_of = [rt.get(a.node.remote()) for a in actors]
    victim = nodes_of[0]
    get_runtime().remove_node(victim)
    time.sleep(0.3)
    dead = alive = 0
    for a, n in zip(actors, nodes_of):
        try:
            rt.get(a.node.remote(), timeout=5)
            alive += 1
        except rt.ActorError:
            dead += 1
    assert dead >= 1
    assert dead + alive == 4


def test_cluster_resources_sum(ray_start_cluster):
    rt = ray_start_cluster
    total = rt.cluster_resources()
    assert total["CPU"] == 8  # 4 nodes x 2
    assert total["TPU"] == 16  # 4 nodes x 4
