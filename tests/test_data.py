"""Data-library tests, modeled on the reference's
``python/ray/data/tests/``: transforms, fusion, shuffle/sort/groupby,
streaming (no full materialization), file IO round-trips, Train ingest.
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rt_data


@pytest.fixture(autouse=True)
def _rt(ray_start_regular):
    yield


class TestBasics:
    def test_range_count_schema(self):
        ds = rt_data.range(1000, override_num_blocks=8)
        assert ds.count() == 1000
        assert ds.columns() == ["id"]
        assert ds.num_blocks() == 8

    def test_from_items_take(self):
        ds = rt_data.from_items([{"a": i, "b": str(i)} for i in range(10)])
        rows = ds.take(3)
        assert rows == [{"a": 0, "b": "0"}, {"a": 1, "b": "1"}, {"a": 2, "b": "2"}]

    def test_map_batches_numpy(self):
        ds = rt_data.range(100).map_batches(lambda b: {"x": b["id"] * 2})
        assert ds.sum("x") == 2 * sum(range(100))

    def test_map_filter_flatmap(self):
        ds = (
            rt_data.range(20)
            .map(lambda r: {"v": r["id"] + 1})
            .filter(lambda r: r["v"] % 2 == 0)
            .flat_map(lambda r: [{"v": r["v"]}, {"v": -r["v"]}])
        )
        vals = [r["v"] for r in ds.take_all()]
        assert len(vals) == 20 and sum(vals) == 0

    def test_add_select_drop_columns(self):
        ds = rt_data.range(10).add_column("sq", lambda b: b["id"] ** 2)
        assert ds.columns() == ["id", "sq"]
        assert ds.select_columns(["sq"]).columns() == ["sq"]
        assert ds.drop_columns(["sq"]).columns() == ["id"]

    def test_limit_union_zip(self):
        a = rt_data.range(10).limit(5)
        assert a.count() == 5
        u = a.union(rt_data.range(3))
        assert u.count() == 8
        z = rt_data.range(4).zip(
            rt_data.from_items([{"y": i * 10} for i in range(4)])
        )
        rows = z.take_all()
        assert rows[2] == {"id": 2, "y": 20}

    def test_aggregates(self):
        ds = rt_data.from_items([{"x": float(i)} for i in range(1, 6)])
        assert ds.sum("x") == 15.0
        assert ds.min("x") == 1.0
        assert ds.max("x") == 5.0
        assert ds.mean("x") == 3.0


class TestFusionAndStreaming:
    def test_map_chain_fuses(self):
        ds = rt_data.range(10).map_batches(lambda b: b).map_batches(lambda b: b)
        plan = ds._plan.optimized()
        # Read -> single fused MapBlocks
        from ray_tpu.data.plan import MapBlocks, Read

        assert isinstance(plan.dag, MapBlocks)
        assert "->" in plan.dag.label
        assert isinstance(plan.dag.inputs[0], Read)

    def test_streaming_does_not_materialize_all(self):
        """Consuming the first batch must not execute every read task."""
        executed = []

        def slow_batch(b):
            return {"id": b["id"]}

        ds = rt_data.range(10_000, override_num_blocks=50).map_batches(slow_batch)
        it = ds.iter_batches(batch_size=10)
        first = next(iter(it))
        assert len(first["id"]) == 10
        # cannot observe task counts directly; assert the executor yields
        # lazily by checking a fresh iterator is cheap (subsecond)

    def test_actor_compute_map(self):
        ds = rt_data.range(100).map_batches(
            lambda b: {"x": b["id"] + 1}, compute="actors", concurrency=2
        )
        assert ds.sum("x") == sum(range(1, 101))


class TestAllToAll:
    def test_repartition(self):
        ds = rt_data.range(100, override_num_blocks=7).repartition(3)
        assert ds.num_blocks() == 3
        assert ds.count() == 100

    def test_random_shuffle_preserves_multiset(self):
        ds = rt_data.range(500, override_num_blocks=5).random_shuffle(seed=7)
        vals = [r["id"] for r in ds.take_all()]
        assert sorted(vals) == list(range(500))
        assert vals != list(range(500))  # actually shuffled

    def test_sort(self):
        rng = np.random.default_rng(0)
        items = [{"k": int(v)} for v in rng.permutation(200)]
        ds = rt_data.from_items(items).sort("k")
        vals = [r["k"] for r in ds.take_all()]
        assert vals == sorted(vals)
        desc = rt_data.from_items(items).sort("k", descending=True).take(3)
        assert [r["k"] for r in desc] == [199, 198, 197]

    def test_groupby(self):
        ds = rt_data.from_items(
            [{"g": i % 3, "v": float(i)} for i in range(30)]
        )
        counts = {r["g"]: r["count()"] for r in ds.groupby("g").count().take_all()}
        assert counts == {0: 10, 1: 10, 2: 10}
        sums = {r["g"]: r["sum(v)"] for r in ds.groupby("g").sum("v").take_all()}
        assert sums[0] == sum(float(i) for i in range(0, 30, 3))

    def test_split(self):
        shards = rt_data.range(100).split(4, equal=True)
        counts = [s.count() for s in shards]
        assert counts == [25, 25, 25, 25]


class TestIO:
    def test_parquet_roundtrip(self, tmp_path):
        ds = rt_data.range(100, override_num_blocks=3)
        ds.write_parquet(str(tmp_path / "p"))
        back = rt_data.read_parquet(str(tmp_path / "p"))
        assert back.count() == 100
        assert sorted(r["id"] for r in back.take_all()) == list(range(100))

    def test_csv_roundtrip(self, tmp_path):
        rt_data.from_items([{"a": i, "b": i * 0.5} for i in range(20)]).write_csv(
            str(tmp_path / "c")
        )
        back = rt_data.read_csv(str(tmp_path / "c"))
        assert back.count() == 20
        assert back.sum("a") == sum(range(20))

    def test_json_roundtrip(self, tmp_path):
        rt_data.from_items([{"a": i} for i in range(10)]).write_json(str(tmp_path / "j"))
        back = rt_data.read_json(str(tmp_path / "j"))
        assert back.sum("a") == 45

    def test_pandas_numpy_conversion(self):
        import pandas as pd

        df = pd.DataFrame({"x": [1, 2, 3]})
        assert rt_data.from_pandas(df).to_pandas()["x"].tolist() == [1, 2, 3]
        ds = rt_data.from_numpy(np.arange(5))
        assert ds.count() == 5


class TestTrainIngest:
    def test_iter_batches_sizes(self):
        ds = rt_data.range(105, override_num_blocks=4)
        sizes = [len(b["id"]) for b in ds.iter_batches(batch_size=25)]
        assert sizes == [25, 25, 25, 25, 5]
        sizes = [len(b["id"]) for b in ds.iter_batches(batch_size=25, drop_last=True)]
        assert sizes == [25, 25, 25, 25]

    def test_iter_jax_batches_sharded(self, cpu_mesh_devices):
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec

        mesh = Mesh(np.array(cpu_mesh_devices[:4]), ("data",))
        sharding = NamedSharding(mesh, PartitionSpec("data"))
        it = rt_data.range(64).iterator()
        batches = list(
            it.iter_jax_batches(batch_size=16, sharding=sharding, drop_last=True)
        )
        assert len(batches) == 4
        assert batches[0]["id"].sharding == sharding

    def test_streaming_split_for_ranks(self):
        its = rt_data.range(80).streaming_split(4)
        totals = [sum(r["id"] for r in it.iter_rows()) for it in its]
        assert sum(totals) == sum(range(80))


class TestNewDatasources:
    def test_read_text(self, ray_start_regular, tmp_path):
        from ray_tpu import data

        p = tmp_path / "a.txt"
        p.write_text("hello\nworld\nray tpu\n")
        ds = data.read_text(str(p))
        assert [r["text"] for r in ds.take_all()] == ["hello", "world", "ray tpu"]

    def test_read_binary_files(self, ray_start_regular, tmp_path):
        from ray_tpu import data

        (tmp_path / "x.bin").write_bytes(b"\x01\x02\x03")
        (tmp_path / "y.bin").write_bytes(b"\xff" * 10)
        ds = data.read_binary_files([str(tmp_path / "x.bin"),
                                     str(tmp_path / "y.bin")],
                                    include_paths=True)
        rows = sorted(ds.take_all(), key=lambda r: r["path"])
        assert rows[0]["bytes"] == b"\x01\x02\x03"
        assert len(rows[1]["bytes"]) == 10

    def test_read_images(self, ray_start_regular, tmp_path):
        from PIL import Image
        from ray_tpu import data

        rng = np.random.default_rng(0)
        for i in range(3):
            arr = rng.integers(0, 255, (16, 16, 3)).astype(np.uint8)
            Image.fromarray(arr).save(tmp_path / f"img{i}.png")
        ds = data.read_images(str(tmp_path))
        rows = ds.take_all()
        assert len(rows) == 3
        assert np.asarray(rows[0]["image"]).shape == (16, 16, 3)

    def test_tfrecords_round_trip(self, ray_start_regular, tmp_path):
        from ray_tpu import data

        payloads = [f"record-{i}".encode() for i in range(25)]
        ds = data.from_items([{"data": p} for p in payloads])
        out = tmp_path / "tfr"
        data.write_tfrecords(ds, str(out))
        back = data.read_tfrecords(str(out))
        got = sorted(r["data"] for r in back.take_all())
        assert got == sorted(payloads)

    def test_tfrecord_crc_detects_corruption(self, ray_start_regular, tmp_path):
        from ray_tpu import data
        from ray_tpu.data.datasources import _read_tfrecord_file

        ds = data.from_items([{"data": b"x" * 100}])
        out = tmp_path / "tfr"
        data.write_tfrecords(ds, str(out))
        f = next(out.iterdir())
        raw = bytearray(f.read_bytes())
        raw[20] ^= 0xFF  # flip a data byte
        f.write_bytes(bytes(raw))
        with pytest.raises(ValueError, match="corrupt TFRecord"):
            _read_tfrecord_file(str(f))

    def test_crc32c_known_vectors(self):
        from ray_tpu.data.datasources import crc32c

        # RFC 3720 test vectors for CRC32C (Castagnoli).
        assert crc32c(b"123456789") == 0xE3069283
        assert crc32c(b"") == 0x0
        assert crc32c(bytes(32)) == 0x8A9136AA


class TestPushBasedShuffle:
    def test_random_shuffle_preserves_multiset(self, ray_start_regular):
        from ray_tpu import data

        ds = data.range(2000, override_num_blocks=8)
        out = ds.random_shuffle(seed=5)
        vals = [r["id"] for r in out.take_all()]
        assert sorted(vals) == list(builtins_range(2000))
        assert vals != list(builtins_range(2000))  # actually shuffled

    def test_shuffle_rounds_merge_incrementally(self, ray_start_regular):
        """More input blocks than one round: outputs still exact."""
        from ray_tpu import data
        from ray_tpu.data import shuffle as sh

        ds = data.range(600, override_num_blocks=12)
        refs = list(__import__("ray_tpu.data.executor", fromlist=["execute_streaming"])
                    .execute_streaming(ds._plan))
        out_refs = sh.push_based_shuffle(
            refs, num_partitions=3, map_fn=sh.shuffle_map_split,
            final_fn=sh._merge_and_permute, maps_per_round=4, seed=1)
        assert len(out_refs) == 3
        import ray_tpu

        rows = []
        for r in out_refs:
            block = ray_tpu.get(r)
            rows.extend(v["id"] for v in data.BlockAccessor(block).iter_rows())
        assert sorted(rows) == list(builtins_range(600))

    def test_repartition_push(self, ray_start_regular):
        from ray_tpu import data

        ds = data.range(1000, override_num_blocks=7).repartition(3)
        assert ds.num_blocks() == 3
        # Repartition preserves GLOBAL row order (reference semantics).
        assert [r["id"] for r in ds.take_all()] == list(builtins_range(1000))


from builtins import range as builtins_range  # noqa: E402


class TestAutoscalingActorPool:
    def test_tuple_concurrency_scales_and_completes(self, ray_start_regular):
        from ray_tpu import data

        ds = data.range(400, override_num_blocks=8).map_batches(
            lambda b: {"id": b["id"] * 2},
            compute="actors", concurrency=(1, 3), batch_format="numpy",
        )
        out = sorted(r["id"] for r in ds.take_all())
        assert out == [i * 2 for i in builtins_range(400)]

    def test_int_concurrency_fixed_pool(self, ray_start_regular):
        from ray_tpu import data

        ds = data.range(100, override_num_blocks=4).map_batches(
            lambda b: {"id": b["id"] + 1},
            compute="actors", concurrency=2, batch_format="numpy",
        )
        assert sorted(r["id"] for r in ds.take_all()) == list(builtins_range(1, 101))


class TestStreamingSplit:
    """Coordinated streaming_split (reference:
    _internal/iterator/stream_split_iterator.py)."""

    def test_dynamic_assignment_disjoint_and_complete(self, ray_start_regular):
        import ray_tpu.data as rd

        ds = rd.range(200, override_num_blocks=10).map_batches(lambda b: b)
        its = ds.streaming_split(2)
        seen = [[], []]
        done = [False, False]
        # interleave pulls so both consumers draw from ONE execution
        iters = [it.iter_rows() for it in its]
        while not all(done):
            for i in range(2):
                if done[i]:
                    continue
                row = next(iters[i], None)
                if row is None:
                    done[i] = True
                else:
                    seen[i].append(row["id"])
        all_ids = sorted(seen[0] + seen[1])
        assert all_ids == list(range(200))
        assert not (set(seen[0]) & set(seen[1]))  # disjoint
        assert seen[0] and seen[1]  # both actually consumed

    def test_work_stealing_favors_fast_consumer(self, ray_start_regular):
        """A slow consumer must not strand blocks: the fast consumer picks
        up the slack (dynamic assignment, NOT a static split)."""
        import ray_tpu.data as rd

        ds = rd.range(400, override_num_blocks=16)
        fast, slow = ds.streaming_split(2)
        fast_rows = sum(1 for _ in fast.iter_rows())  # drains nearly all
        slow.finish()
        slow_rows = sum(1 for _ in slow.iter_rows())
        assert fast_rows + slow_rows == 400
        assert fast_rows > slow_rows

    def test_equal_split_keeps_consumers_close(self, ray_start_regular):
        import ray_tpu.data as rd

        ds = rd.range(320, override_num_blocks=8)
        a, b = ds.streaming_split(2, equal=True)
        rows_a = []
        rows_b = []
        ia, ib = a.iter_rows(), b.iter_rows()
        done_a = done_b = False
        while not (done_a and done_b):
            if not done_a:
                r = next(ia, None)
                done_a = r is None
                if r is not None:
                    rows_a.append(r["id"])
            if not done_b:
                r = next(ib, None)
                done_b = r is None
                if r is not None:
                    rows_b.append(r["id"])
        assert sorted(rows_a + rows_b) == list(range(320))
        # equal: within one block (40 rows) of each other
        assert abs(len(rows_a) - len(rows_b)) <= 40, (len(rows_a), len(rows_b))


class TestMemoryBudget:
    """Budgeted backpressure (reference:
    streaming_executor_state.py:494 resource-budgeted scheduling)."""

    def test_window_adapts_to_block_size(self):
        from ray_tpu.data.executor import _MemoryBudget

        b = _MemoryBudget(64 * 1024 * 1024, max_in_flight=8)
        assert b.window() == 8  # 1MB prior, plenty of budget
        # learn that blocks are huge -> window shrinks to the floor
        class FakeRef:
            pass
        b._avg = 48 * 1024 * 1024
        assert b.window() == 1
        b._avg = 8 * 1024 * 1024
        assert b.window() == 8  # 64/8
        b.stages = 4
        assert b.window() == 2  # budget shared across stages

    def test_small_budget_bounds_in_flight(self, ray_start_regular):
        """A pipeline of ~1MB blocks under a 2MB budget holds at most ~2
        tasks in flight; a big budget opens the window."""
        import numpy as np

        import ray_tpu.data as rd
        from ray_tpu.data.executor import execute_streaming

        def make(n_rows):
            import pyarrow as pa

            return rd.range(64, override_num_blocks=16).map_batches(
                lambda b: {"x": np.zeros((len(b["id"]), 32_000),
                                         np.float32)})

        stats_small: dict = {}
        ds = make(64)
        refs = list(execute_streaming(ds._plan,
                                      memory_budget=2 * 1024 * 1024,
                                      _stats=stats_small))
        # consume so sizes register, then re-run: the learned window stays
        for r in refs:
            ray_tpu.get(r)
        stats2: dict = {}
        refs = list(execute_streaming(ds._plan,
                                      memory_budget=512 * 1024 * 1024,
                                      _stats=stats2))
        for r in refs:
            ray_tpu.get(r)
        assert stats2["max_pending"] >= stats_small["max_pending"]


class TestConnectors:
    """WebDataset / SQL / partitioned-parquet / Mongo (VERDICT r4 #8)."""

    def test_webdataset_roundtrip(self, ray_start_regular, tmp_path):
        from ray_tpu import data as rt_data
        from ray_tpu.data.connectors import read_webdataset, write_webdataset

        rows = [{"__key__": f"{i:04d}",
                 "txt": f"caption {i}",
                 "cls": i % 3,
                 "json": {"idx": i}}
                for i in range(25)]
        ds = rt_data.from_items(rows)
        write_webdataset(ds, str(tmp_path / "wds"), rows_per_shard=10)
        import os

        shards = sorted(os.listdir(tmp_path / "wds"))
        assert len(shards) == 3, shards  # 10 + 10 + 5

        back = read_webdataset(str(tmp_path / "wds")).take_all()
        assert len(back) == 25
        back.sort(key=lambda r: r["__key__"])
        assert back[7]["txt"] == "caption 7"
        assert back[7]["cls"] == 7 % 3
        assert back[7]["json"] == {"idx": 7}

    def test_webdataset_suffix_filter_and_images(self, ray_start_regular, tmp_path):
        import io
        import tarfile

        import numpy as np
        from PIL import Image

        from ray_tpu.data.connectors import read_webdataset

        p = tmp_path / "shard-0.tar"
        with tarfile.open(p, "w") as tar:
            for i in range(3):
                img = Image.fromarray(
                    np.full((4, 4, 3), i * 10, np.uint8))
                buf = io.BytesIO()
                img.save(buf, format="PNG")
                data = buf.getvalue()
                info = tarfile.TarInfo(f"{i:03d}.png")
                info.size = len(data)
                tar.addfile(info, io.BytesIO(data))
                lbl = str(i).encode()
                info = tarfile.TarInfo(f"{i:03d}.cls")
                info.size = len(lbl)
                tar.addfile(info, io.BytesIO(lbl))

        rows = read_webdataset(str(p), decode_images=True).take_all()
        assert len(rows) == 3
        rows.sort(key=lambda r: r["__key__"])
        assert rows[1]["png"].shape == (4, 4, 3)
        assert int(rows[1]["png"][0, 0, 0]) == 10
        assert rows[1]["cls"] == 1

        only_cls = read_webdataset(str(p), suffixes=[".cls"]).take_all()
        assert all("png" not in r for r in only_cls)

    def test_sql_read_and_sharded(self, ray_start_regular, tmp_path):
        import sqlite3

        from ray_tpu.data.connectors import read_sql

        db = str(tmp_path / "t.db")
        conn = sqlite3.connect(db)
        conn.execute("CREATE TABLE metrics (id INTEGER, name TEXT, value REAL)")
        conn.executemany("INSERT INTO metrics VALUES (?, ?, ?)",
                         [(i, f"m{i}", i * 0.5) for i in range(40)])
        conn.commit()
        conn.close()

        factory = lambda: __import__("sqlite3").connect(db)
        ds = read_sql("SELECT * FROM metrics", factory)
        rows = ds.take_all()
        assert len(rows) == 40
        assert {r["name"] for r in rows} == {f"m{i}" for i in range(40)}

        sharded = read_sql("SELECT * FROM metrics WHERE value >= 5.0",
                           factory, shard_key="id", parallelism=4)
        assert sharded.num_blocks() == 4
        srows = sharded.take_all()
        assert len(srows) == 30  # ids 10..39
        assert {r["id"] for r in srows} == set(range(10, 40))

    def test_parquet_partition_pruning(self, ray_start_regular, tmp_path):
        from ray_tpu import data as rt_data
        from ray_tpu.data.connectors import (
            read_parquet_partitioned,
            write_parquet_partitioned,
        )

        rows = [{"day": f"2026-07-{d:02d}", "shard": s % 2, "x": d * 10 + s}
                for d in (1, 2, 3) for s in range(4)]
        write_parquet_partitioned(rt_data.from_items(rows),
                                  str(tmp_path / "pq"),
                                  partition_cols=["day"])
        import os

        assert sorted(os.listdir(tmp_path / "pq")) == [
            "day=2026-07-01", "day=2026-07-02", "day=2026-07-03"]

        # Pruned read: only day 2 files are opened; partition col attached.
        ds = read_parquet_partitioned(
            str(tmp_path / "pq"),
            partition_filter=lambda p: p["day"] == "2026-07-02")
        got = ds.take_all()
        assert len(got) == 4
        assert all(r["day"] == "2026-07-02" for r in got)
        assert {r["x"] for r in got} == {20, 21, 22, 23}

        full = read_parquet_partitioned(str(tmp_path / "pq")).take_all()
        assert len(full) == 12

    def test_mongo_with_injected_client(self, ray_start_regular):
        from ray_tpu.data.connectors import read_mongo

        class FakeCollection:
            def __init__(self, docs): self._docs = docs
            def find(self): return list(self._docs)
            def aggregate(self, stages):
                docs = list(self._docs)
                for st in stages:
                    if "$match" in st:
                        docs = [d for d in docs
                                if all(d.get(k) == v
                                       for k, v in st["$match"].items())]
                return docs

        class FakeDB(dict):
            pass

        class FakeClient:
            def __init__(self, docs):
                self._db = FakeDB(events=FakeCollection(docs))
            def __getitem__(self, name): return self._db
            def close(self): pass

        docs = [{"_id": i, "kind": "a" if i % 2 else "b", "v": i}
                for i in range(10)]
        ds = read_mongo("mongodb://unused", "db", "events",
                        _client_factory=lambda: FakeClient(docs))
        assert len(ds.take_all()) == 10

        filtered = read_mongo(
            "mongodb://unused", "db", "events",
            pipeline=[{"$match": {"kind": "a"}}],
            _client_factory=lambda: FakeClient(docs)).take_all()
        assert len(filtered) == 5 and all(r["kind"] == "a" for r in filtered)

    def test_webdataset_arbitrary_columns_roundtrip(self, ray_start_regular, tmp_path):
        import numpy as np

        from ray_tpu import data as rt_data
        from ray_tpu.data.connectors import read_webdataset, write_webdataset

        rows = [{"__key__": f"{i:03d}",
                 "caption": f"a photo #{i}",
                 "label": i,
                 "meta": {"w": i * 2},
                 "emb": np.arange(4, dtype=np.float32) + i}
                for i in range(6)]
        write_webdataset(rt_data.from_items(rows), str(tmp_path / "w2"))
        back = read_webdataset(str(tmp_path / "w2")).take_all()
        back.sort(key=lambda r: r["__key__"])
        assert back[3]["caption"] == "a photo #3"      # str round-trips
        assert back[3]["label"] == 3                   # int round-trips
        assert back[3]["meta"] == {"w": 6}             # dict round-trips
        np.testing.assert_allclose(np.asarray(back[3]["emb"]),
                                   [3.0, 4.0, 5.0, 6.0])

    def test_mixed_shape_tensor_blocks_concat(self, ray_start_regular):
        import numpy as np
        import pyarrow as pa

        from ray_tpu.data.block import BlockAccessor

        a = BlockAccessor.from_items(
            [{"img": np.zeros((2, 2), np.uint8)} for _ in range(3)])
        b = BlockAccessor.from_items(
            [{"img": np.zeros((4, 4), np.uint8)} for _ in range(2)])
        out = BlockAccessor.concat([a, b])
        assert out.num_rows == 5  # schema clash demoted, not raised

    def test_sql_shard_null_and_negative_keys(self, ray_start_regular, tmp_path):
        import sqlite3

        from ray_tpu.data.connectors import read_sql

        db = str(tmp_path / "neg.db")
        conn = sqlite3.connect(db)
        conn.execute("CREATE TABLE t (id INTEGER, v TEXT)")
        conn.executemany("INSERT INTO t VALUES (?, ?)",
                         [(-3, "neg"), (None, "null"), (5, "pos"),
                          (0, "zero")])
        conn.commit()
        conn.close()
        factory = lambda: __import__("sqlite3").connect(db)
        rows = read_sql("SELECT * FROM t", factory,
                        shard_key="id", parallelism=3).take_all()
        assert len(rows) == 4, rows  # no silent drops
        assert {r["v"] for r in rows} == {"neg", "null", "pos", "zero"}
