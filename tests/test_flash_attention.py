"""Flash-attention kernel vs dense oracle (interpret mode on CPU; the same
kernel compiles for real TPU — exercised by bench.py)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ray_tpu.ops.flash_attention import flash_attention
from ray_tpu.parallel.ring_attention import reference_attention


def _qkv(b=2, l=128, h=4, d=32, seed=0):
    ks = jax.random.split(jax.random.key(seed), 3)
    shape = (b, l, h, d)
    return tuple(jax.random.normal(k, shape, jnp.float32) for k in ks)


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_dense(self, causal):
        q, k, v = _qkv()
        out = flash_attention(q, k, v, causal, None, 64, 64, True)
        oracle = reference_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(oracle), rtol=2e-4, atol=2e-4)

    def test_uneven_blocks(self):
        q, k, v = _qkv(l=256)
        out = flash_attention(q, k, v, True, None, 128, 64, True)
        oracle = reference_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(oracle), rtol=2e-4, atol=2e-4)

    def test_gradients_match_dense(self):
        q, k, v = _qkv(b=1, l=64, h=2, d=16)

        def loss_flash(q, k, v):
            return jnp.sum(flash_attention(q, k, v, True, None, 32, 32, True) ** 2)

        def loss_dense(q, k, v):
            return jnp.sum(reference_attention(q, k, v, causal=True).astype(jnp.float32) ** 2)

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gd):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-3)

    def test_bf16_inputs(self):
        q, k, v = _qkv(l=64)
        q, k, v = (x.astype(jnp.bfloat16) for x in (q, k, v))
        out = flash_attention(q, k, v, True, None, 32, 32, True)
        assert out.dtype == jnp.bfloat16
        oracle = reference_attention(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(oracle, np.float32), rtol=3e-2, atol=3e-2
        )

    @pytest.mark.parametrize("causal", [True, False])
    def test_backward_kernel_matches_dense(self, causal):
        """The Pallas dq/dk/dv kernels (not dense recompute) against the
        dense-path VJP, multi-block grid both axes."""
        q, k, v = _qkv(b=2, l=128, h=2, d=32)
        g_key = jax.random.key(9)
        g = jax.random.normal(g_key, q.shape, jnp.float32)

        def flash_out(q, k, v):
            return flash_attention(q, k, v, causal, None, 32, 32, True)

        def dense_out(q, k, v):
            return reference_attention(q, k, v, causal=causal).astype(jnp.float32)

        _, vjp_f = jax.vjp(flash_out, q, k, v)
        _, vjp_d = jax.vjp(dense_out, q, k, v)
        for a, b in zip(vjp_f(g), vjp_d(g)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-3
            )

    def test_backward_uneven_blocks(self):
        q, k, v = _qkv(b=1, l=128, h=2, d=16)
        g = jax.random.normal(jax.random.key(3), q.shape, jnp.float32)
        _, vjp_f = jax.vjp(
            lambda q, k, v: flash_attention(q, k, v, True, None, 64, 32, True),
            q, k, v)
        _, vjp_d = jax.vjp(
            lambda q, k, v: reference_attention(q, k, v, causal=True).astype(jnp.float32),
            q, k, v)
        for a, b in zip(vjp_f(g), vjp_d(g)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-3
            )

    def test_ragged_seq_falls_back_dense(self):
        """L=192 with block 128 → dense fallback, gradients still correct."""
        q, k, v = _qkv(b=1, l=192, h=2, d=16)

        def loss_flash(q, k, v):
            return jnp.sum(flash_attention(q, k, v, True, None, 128, 128, True) ** 2)

        def loss_dense(q, k, v):
            return jnp.sum(reference_attention(q, k, v, causal=True).astype(jnp.float32) ** 2)

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gd):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-3)
