"""Per-node Serve proxy actors: placement, drain, zero-drop redeploy,
driver-exit survival (reference: serve/_private/proxy.py proxy actors +
proxy_state.py drain protocol)."""

import json
import os
import subprocess
import sys
import threading
import time

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.core.cluster import Cluster, connect
from ray_tpu.core import runtime as runtime_mod


@pytest.fixture()
def mp_serve():
    cluster = Cluster(num_nodes=2, resources_per_node={"CPU": 3})
    core = connect(cluster.gcs_address)
    yield cluster, core
    try:
        serve.shutdown()
    except Exception:
        pass
    core.shutdown()
    runtime_mod._global_runtime = None
    cluster.shutdown()


def _get(url, timeout=30.0, **kw):
    import httpx

    return httpx.post(url, timeout=timeout, **kw)


def test_per_node_proxies_and_drain_under_load(mp_serve):
    cluster, core = mp_serve

    @serve.deployment(num_replicas=2)
    def slowish(payload):
        time.sleep(0.3)
        return {"v": payload["v"]}

    serve.run(slowish.bind(), route_prefix="/m")
    addrs = serve.start_proxies()
    assert len(addrs) == 2, addrs  # one proxy per node

    # Both proxies serve.
    for addr in addrs.values():
        r = _get(f"http://{addr}/m", json={"v": 1})
        assert r.status_code == 200 and r.json() == {"v": 1}

    # Drain one node while requests are in flight THROUGH it: accepted
    # requests complete; post-drain requests are refused; the other proxy
    # keeps serving.
    victim_node, victim_addr = next(iter(addrs.items()))
    other_addr = next(a for n, a in addrs.items() if n != victim_node)
    results = []

    def fire(i):
        try:
            r = _get(f"http://{victim_addr}/m", json={"v": i})
            results.append((i, r.status_code))
        except Exception as e:  # noqa: BLE001 — refused post-drain
            results.append((i, f"refused:{type(e).__name__}"))

    threads = [threading.Thread(target=fire, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    # Deterministic: drain only once the victim proxy has ACCEPTED at least
    # one request (replica holds it for 0.3s), so the drain provably
    # overlaps in-flight work.
    from ray_tpu.serve import api as serve_api

    victim_handle = serve_api._proxy_manager._proxies[victim_node]
    deadline = time.time() + 30
    while time.time() < deadline:
        if ray_tpu.get(victim_handle.num_in_flight.remote(), timeout=10) > 0:
            break
        time.sleep(0.01)
    else:
        raise AssertionError("no request ever went in flight")
    drained = serve.drain_proxy(victim_node, timeout_s=30)
    for t in threads:
        t.join(timeout=60)
    assert drained is True
    in_flight_ok = [s for _i, s in results if s == 200]
    assert len(in_flight_ok) >= 1, results  # accepted ones completed
    assert all(s in (200, 503) or str(s).startswith("refused")
               for _i, s in results), results

    # Post-drain: victim refuses, the other node still serves.
    with pytest.raises(Exception):
        _get(f"http://{victim_addr}/m", json={"v": 9}, timeout=3)
    r = _get(f"http://{other_addr}/m", json={"v": 2})
    assert r.status_code == 200 and r.json() == {"v": 2}


def test_rolling_redeploy_drops_zero_requests(mp_serve):
    cluster, core = mp_serve

    @serve.deployment(num_replicas=2)
    def versioned(payload):
        return {"version": 1}

    serve.run(versioned.bind(), route_prefix="/v")
    addrs = serve.start_proxies()
    addr = next(iter(addrs.values()))

    stop = threading.Event()
    outcomes = []

    def hammer():
        while not stop.is_set():
            try:
                r = _get(f"http://{addr}/v", json={}, timeout=30)
                outcomes.append(r.status_code)
            except Exception as e:  # noqa: BLE001
                outcomes.append(f"error:{e}")
            time.sleep(0.02)

    t = threading.Thread(target=hammer)
    t.start()
    try:
        time.sleep(0.5)

        @serve.deployment(num_replicas=2)
        def versioned(payload):  # noqa: F811 — the new version
            return {"version": 2}

        serve.run(versioned.bind(), route_prefix="/v")
        time.sleep(1.0)
    finally:
        stop.set()
        t.join(timeout=60)
    assert outcomes, "no requests made"
    bad = [o for o in outcomes if o != 200]
    assert not bad, f"dropped {len(bad)}/{len(outcomes)}: {bad[:5]}"
    # and the new version actually took over
    r = _get(f"http://{addr}/v", json={})
    assert r.json() == {"version": 2}


def test_ingress_survives_driver_exit():
    cluster = Cluster(num_nodes=2, resources_per_node={"CPU": 3})
    try:
        script = f"""
import os, json
os.environ["JAX_PLATFORMS"] = "cpu"
import ray_tpu
from ray_tpu import serve
from ray_tpu.core.cluster import connect

core = connect({cluster.gcs_address!r})

@serve.deployment(num_replicas=2)
def app(payload):
    return {{"pong": payload.get("n", 0)}}

serve.run(app.bind(), route_prefix="/app")
addrs = serve.start_proxies()
print("ADDRS=" + json.dumps(addrs), flush=True)
core.shutdown()
"""
        proc = subprocess.run(
            [sys.executable, "-c", script], capture_output=True, text=True,
            timeout=180,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        assert proc.returncode == 0, proc.stderr[-2000:]
        line = next(l for l in proc.stdout.splitlines()
                    if l.startswith("ADDRS="))
        addrs = json.loads(line[len("ADDRS="):])
        assert len(addrs) == 2
        # The driver is GONE; the detached controller + proxy actors +
        # replicas must still serve HTTP.
        time.sleep(1.0)
        for addr in addrs.values():
            r = _get(f"http://{addr}/app", json={"n": 7}, timeout=60)
            assert r.status_code == 200 and r.json() == {"pong": 7}
    finally:
        cluster.shutdown()
