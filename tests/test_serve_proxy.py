"""Per-node Serve proxy actors: placement, drain, zero-drop redeploy,
driver-exit survival (reference: serve/_private/proxy.py proxy actors +
proxy_state.py drain protocol)."""

import json
import os
import subprocess
import sys
import threading
import time

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.core.cluster import Cluster, connect
from ray_tpu.core import runtime as runtime_mod


@pytest.fixture()
def mp_serve():
    cluster = Cluster(num_nodes=2, resources_per_node={"CPU": 3})
    core = connect(cluster.gcs_address)
    yield cluster, core
    try:
        serve.shutdown()
    except Exception:
        pass
    core.shutdown()
    runtime_mod._global_runtime = None
    cluster.shutdown()


def _get(url, timeout=30.0, **kw):
    import httpx

    return httpx.post(url, timeout=timeout, **kw)


def test_per_node_proxies_and_drain_under_load(mp_serve):
    cluster, core = mp_serve

    @serve.deployment(num_replicas=2)
    def slowish(payload):
        time.sleep(0.3)
        return {"v": payload["v"]}

    serve.run(slowish.bind(), route_prefix="/m")
    addrs = serve.start_proxies()
    assert len(addrs) == 2, addrs  # one proxy per node

    # Both proxies serve.
    for addr in addrs.values():
        r = _get(f"http://{addr}/m", json={"v": 1})
        assert r.status_code == 200 and r.json() == {"v": 1}

    # Drain one node while requests are in flight THROUGH it: accepted
    # requests complete; post-drain requests are refused; the other proxy
    # keeps serving.
    victim_node, victim_addr = next(iter(addrs.items()))
    other_addr = next(a for n, a in addrs.items() if n != victim_node)
    results = []

    def fire(i):
        try:
            r = _get(f"http://{victim_addr}/m", json={"v": i})
            results.append((i, r.status_code))
        except Exception as e:  # noqa: BLE001 — refused post-drain
            results.append((i, f"refused:{type(e).__name__}"))

    threads = [threading.Thread(target=fire, args=(i,), daemon=True)
               for i in range(6)]
    for t in threads:
        t.start()
    # Deterministic: drain only once the victim proxy has ACCEPTED at least
    # one request (replica holds it for 0.3s), so the drain provably
    # overlaps in-flight work.
    from ray_tpu.serve import api as serve_api

    victim_handle = serve_api._proxy_manager._proxies[victim_node]
    deadline = time.time() + 30
    while time.time() < deadline:
        if ray_tpu.get(victim_handle.num_in_flight.remote(), timeout=10) > 0:
            break
        time.sleep(0.01)
    else:
        raise AssertionError("no request ever went in flight")
    drained = serve.drain_proxy(victim_node, timeout_s=30)
    for t in threads:
        t.join(timeout=60)
    assert drained is True
    in_flight_ok = [s for _i, s in results if s == 200]
    assert len(in_flight_ok) >= 1, results  # accepted ones completed
    assert all(s in (200, 503) or str(s).startswith("refused")
               for _i, s in results), results

    # Post-drain: victim refuses, the other node still serves.
    with pytest.raises(Exception):
        _get(f"http://{victim_addr}/m", json={"v": 9}, timeout=3)
    r = _get(f"http://{other_addr}/m", json={"v": 2})
    assert r.status_code == 200 and r.json() == {"v": 2}


def test_rolling_redeploy_drops_zero_requests(mp_serve):
    cluster, core = mp_serve

    @serve.deployment(num_replicas=2)
    def versioned(payload):
        return {"version": 1}

    serve.run(versioned.bind(), route_prefix="/v")
    addrs = serve.start_proxies()
    addr = next(iter(addrs.values()))

    stop = threading.Event()
    outcomes = []

    def hammer():
        while not stop.is_set():
            try:
                r = _get(f"http://{addr}/v", json={}, timeout=30)
                outcomes.append(r.status_code)
            except Exception as e:  # noqa: BLE001
                outcomes.append(f"error:{e}")
            time.sleep(0.02)

    t = threading.Thread(target=hammer, daemon=True)
    t.start()
    try:
        time.sleep(0.5)

        @serve.deployment(num_replicas=2)
        def versioned(payload):  # noqa: F811 — the new version
            return {"version": 2}

        serve.run(versioned.bind(), route_prefix="/v")
        # Routing switches only once new-version replicas pass READINESS
        # (the reference's rollout gate — requests never land on a replica
        # still in __init__), so takeover is not instantaneous: poll for it
        # while the hammer thread keeps proving zero drops.
        deadline = time.time() + 30.0
        took_over = False
        while time.time() < deadline:
            r = _get(f"http://{addr}/v", json={})
            if r.status_code == 200 and r.json() == {"version": 2}:
                took_over = True
                break
            time.sleep(0.2)
    finally:
        stop.set()
        t.join(timeout=60)
    assert outcomes, "no requests made"
    bad = [o for o in outcomes if o != 200]
    assert not bad, f"dropped {len(bad)}/{len(outcomes)}: {bad[:5]}"
    assert took_over, "new version never took over within 30s"


def test_ingress_survives_driver_exit():
    cluster = Cluster(num_nodes=2, resources_per_node={"CPU": 3})
    try:
        script = f"""
import os, json
os.environ["JAX_PLATFORMS"] = "cpu"
import ray_tpu
from ray_tpu import serve
from ray_tpu.core.cluster import connect

core = connect({cluster.gcs_address!r})

@serve.deployment(num_replicas=2)
def app(payload):
    return {{"pong": payload.get("n", 0)}}

serve.run(app.bind(), route_prefix="/app")
addrs = serve.start_proxies()
print("ADDRS=" + json.dumps(addrs), flush=True)
core.shutdown()
"""
        proc = subprocess.run(
            [sys.executable, "-c", script], capture_output=True, text=True,
            timeout=180,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        assert proc.returncode == 0, proc.stderr[-2000:]
        line = next(l for l in proc.stdout.splitlines()
                    if l.startswith("ADDRS="))
        addrs = json.loads(line[len("ADDRS="):])
        assert len(addrs) == 2
        # The driver is GONE; the detached controller + proxy actors +
        # replicas must still serve HTTP.
        time.sleep(1.0)
        for addr in addrs.values():
            r = _get(f"http://{addr}/app", json={"n": 7}, timeout=60)
            assert r.status_code == 200 and r.json() == {"pong": 7}
    finally:
        cluster.shutdown()


# ---------------------------------------------------------------------------
# gRPC half of the per-node ingress (reference: serve/_private/proxy.py:533
# gRPCProxy runs beside the HTTP half in the same proxy actors).
# ---------------------------------------------------------------------------

def _grpc_caller(addr):
    import grpc

    from ray_tpu.serve.grpc_proxy import (
        _decode_payload_field,
        _encode_payload_field,
    )

    channel = grpc.insecure_channel(addr)
    unary = channel.unary_unary(
        "/ray_tpu.serve.RayTpuServe/Call",
        request_serializer=_encode_payload_field,
        response_deserializer=_decode_payload_field,
    )
    return channel, unary


def test_grpc_per_node_proxies_and_drain_under_load(mp_serve):
    import grpc

    cluster, core = mp_serve

    @serve.deployment(num_replicas=2)
    def slowg(payload):
        time.sleep(0.3)
        return {"v": payload["v"]}

    serve.run(slowg.bind(), route_prefix="/g")
    serve.start_proxies(grpc=True)
    gaddrs = serve.proxy_grpc_addresses()
    assert len(gaddrs) == 2, gaddrs  # one gRPC ingress per node

    for addr in gaddrs.values():
        _ch, unary = _grpc_caller(addr)
        reply = unary(json.dumps({"v": 1}).encode(),
                      metadata=(("application", "slowg"),), timeout=60)
        assert json.loads(reply.decode()) == {"v": 1}
        _ch.close()

    victim_node, victim_addr = next(iter(gaddrs.items()))
    other_addr = next(a for n, a in gaddrs.items() if n != victim_node)
    results = []
    vch, vunary = _grpc_caller(victim_addr)

    def fire(i):
        try:
            r = vunary(json.dumps({"v": i}).encode(),
                       metadata=(("application", "slowg"),), timeout=60)
            results.append((i, json.loads(r.decode())["v"]))
        except grpc.RpcError as e:
            results.append((i, f"rpc:{e.code().name}"))

    threads = [threading.Thread(target=fire, args=(i,), daemon=True)
               for i in range(6)]
    for t in threads:
        t.start()
    from ray_tpu.serve import api as serve_api

    victim_handle = serve_api._proxy_manager._proxies[victim_node]
    deadline = time.time() + 30
    while time.time() < deadline:
        if ray_tpu.get(victim_handle.num_in_flight.remote(), timeout=10) > 0:
            break
        time.sleep(0.01)
    else:
        raise AssertionError("no gRPC request ever went in flight")
    drained = serve.drain_proxy(victim_node, timeout_s=30)
    for t in threads:
        t.join(timeout=60)
    assert drained is True
    ok = [v for _i, v in results if isinstance(v, int)]
    assert len(ok) >= 1, results  # accepted calls completed during drain
    assert all(isinstance(v, int) or v in ("rpc:UNAVAILABLE",)
               for _i, v in results), results

    # Post-drain: victim's port is gone; the other node still serves.
    with pytest.raises(grpc.RpcError):
        vunary(b"{}", metadata=(("application", "slowg"),), timeout=3)
    vch.close()
    _ch2, ounary = _grpc_caller(other_addr)
    r = ounary(json.dumps({"v": 2}).encode(),
               metadata=(("application", "slowg"),), timeout=60)
    assert json.loads(r.decode()) == {"v": 2}
    _ch2.close()


def test_grpc_rolling_redeploy_drops_zero_requests(mp_serve):
    import grpc

    cluster, core = mp_serve

    @serve.deployment(num_replicas=2)
    def gversioned(payload):
        return {"version": 1}

    serve.run(gversioned.bind(), route_prefix="/gv")
    serve.start_proxies(grpc=True)
    gaddrs = serve.proxy_grpc_addresses()
    addr = next(iter(gaddrs.values()))
    ch, unary = _grpc_caller(addr)

    stop = threading.Event()
    outcomes = []

    def hammer():
        while not stop.is_set():
            try:
                r = unary(b"{}", metadata=(("application", "gversioned"),),
                          timeout=30)
                outcomes.append(json.loads(r.decode())["version"])
            except grpc.RpcError as e:
                outcomes.append(f"rpc:{e.code().name}")
            time.sleep(0.02)

    t = threading.Thread(target=hammer, daemon=True)
    t.start()
    took_over = False
    try:
        time.sleep(0.5)

        @serve.deployment(num_replicas=2)
        def gversioned(payload):  # noqa: F811 — the new version
            return {"version": 2}

        serve.run(gversioned.bind(), route_prefix="/gv")
        deadline = time.time() + 30.0
        while time.time() < deadline:
            try:
                r = unary(b"{}", metadata=(("application", "gversioned"),),
                          timeout=30)
                if json.loads(r.decode()) == {"version": 2}:
                    took_over = True
                    break
            except grpc.RpcError:
                pass
            time.sleep(0.2)
    finally:
        stop.set()
        t.join(timeout=60)
        ch.close()
    assert outcomes, "no requests made"
    bad = [o for o in outcomes if not isinstance(o, int)]
    assert not bad, f"dropped {len(bad)}/{len(outcomes)}: {bad[:5]}"
    assert took_over, "new version never took over on the gRPC ingress"


def test_grpc_ingress_survives_driver_exit():
    cluster = Cluster(num_nodes=2, resources_per_node={"CPU": 3})
    try:
        script = f"""
import os, json
os.environ["JAX_PLATFORMS"] = "cpu"
import ray_tpu
from ray_tpu import serve
from ray_tpu.core.cluster import connect

core = connect({cluster.gcs_address!r})

@serve.deployment(num_replicas=2)
def gapp(payload):
    return {{"pong": payload.get("n", 0)}}

serve.run(gapp.bind(), route_prefix="/gapp")
serve.start_proxies(grpc=True)
print("GADDRS=" + json.dumps(serve.proxy_grpc_addresses()), flush=True)
core.shutdown()
"""
        proc = subprocess.run(
            [sys.executable, "-c", script], capture_output=True, text=True,
            timeout=180,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        assert proc.returncode == 0, proc.stderr[-2000:]
        line = next(l for l in proc.stdout.splitlines()
                    if l.startswith("GADDRS="))
        gaddrs = json.loads(line[len("GADDRS="):])
        assert len(gaddrs) == 2
        # Driver gone; detached proxy actors must still answer gRPC.
        time.sleep(1.0)
        for addr in gaddrs.values():
            _ch, unary = _grpc_caller(addr)
            r = unary(json.dumps({"n": 7}).encode(),
                      metadata=(("application", "gapp"),), timeout=60)
            assert json.loads(r.decode()) == {"pong": 7}
            _ch.close()
    finally:
        cluster.shutdown()
