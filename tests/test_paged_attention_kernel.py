"""Pallas paged-attention kernel vs the gather-path oracle (ISSUE 16).

The kernel (``ray_tpu/ops/paged_attention.py``) must be numerically
equivalent to ``paged_attention_reference`` — the table-gather + dense-mask
formulation the decode path used before — across per-slot lengths sitting
ON block boundaries and ±1 around them, for single-token decode and
multi-token (speculative verify / prefill) queries alike. The reserved
trash block 0 and dead table entries must be unable to influence any live
slot's output, and the kernel path must never materialize the
``[S, max_len, H, D]`` gather the roofline forbids. Tier-1 runs the kernel
in Pallas interpret mode (CPU); the compiled-TPU twin is marked ``slow``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import generate, transformer
from ray_tpu.ops.paged_attention import (paged_attention,
                                         paged_attention_reference)
from ray_tpu.serve.llm import PagedLLMEngine

BT = 8   # block_tokens
NB = 6   # blocks per sequence (table width)
H, D = 4, 16


def _setup(lengths, t_tokens, *, seed=0, pool_blocks=24):
    """Random pool + one live block chain per slot; returns operands."""
    rng = np.random.default_rng(seed)
    S = len(lengths)
    q = rng.standard_normal((S, t_tokens, H, D)).astype(np.float32)
    k_pool = rng.standard_normal((pool_blocks, BT, H, D)).astype(np.float32)
    v_pool = rng.standard_normal((pool_blocks, BT, H, D)).astype(np.float32)
    tables = np.zeros((S, NB), np.int32)
    nxt = 1  # block 0 stays trash
    for s, ln in enumerate(lengths):
        live = -(-max(ln + t_tokens, 1) // BT)
        for j in range(min(live, NB)):
            tables[s, j] = nxt
            nxt += 1
    return (jnp.asarray(q), jnp.asarray(k_pool), jnp.asarray(v_pool),
            jnp.asarray(tables), jnp.asarray(np.asarray(lengths, np.int32)))


def _assert_close(a, b, tol=2e-5):
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=tol,
                               rtol=tol)


class TestKernelOracleEquivalence:
    @pytest.mark.parametrize("lengths", [
        [0], [1], [5], [BT - 1], [BT], [BT + 1],      # block boundary +-1
        [2 * BT - 1], [2 * BT], [2 * BT + 1],
        [NB * BT - 1],                                 # table-capacity edge
        [0, 3, BT, 2 * BT + 1, NB * BT - 2],           # ragged batch
    ])
    def test_decode_lengths(self, lengths):
        ops = _setup(lengths, 1)
        out = paged_attention(*ops, interpret=True)
        ref = paged_attention_reference(*ops)
        _assert_close(out, ref)

    @pytest.mark.parametrize("t_tokens", [2, 4, 7])
    def test_multi_token_verify(self, t_tokens):
        """The speculative verify's T>1 queries: query t attends
        kv <= lengths + t, straddling block boundaries mid-chunk."""
        lengths = [0, BT - 1, BT, 13]
        ops = _setup(lengths, t_tokens, seed=3)
        out = paged_attention(*ops, interpret=True)
        ref = paged_attention_reference(*ops)
        _assert_close(out, ref)

    def test_scale_override(self):
        ops = _setup([11], 1, seed=5)
        out = paged_attention(*ops, scale=0.25, interpret=True)
        ref = paged_attention_reference(*ops, scale=0.25)
        _assert_close(out, ref)

    def test_trash_block_cannot_leak(self):
        """Poisoning the reserved trash block (and the dead tail of every
        table) must not move any live output by a single ULP."""
        lengths = [5, BT + 2]
        q, k_pool, v_pool, tables, lens = _setup(lengths, 1, seed=7)
        out = paged_attention(q, k_pool, v_pool, tables, lens,
                              interpret=True)
        k_bad = k_pool.at[0].set(1e9)
        v_bad = v_pool.at[0].set(-1e9)
        out_bad = paged_attention(q, k_bad, v_bad, tables, lens,
                                  interpret=True)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(out_bad))

    def test_inactive_slot_is_finite(self):
        """An all-trash table at length 0 (a parked slot) must produce
        finite output — the online softmax may not divide by zero."""
        q, k_pool, v_pool, tables, lens = _setup([0, 9], 1, seed=9)
        tables = tables.at[0].set(0)
        out = paged_attention(q, k_pool, v_pool, tables, lens,
                              interpret=True)
        assert np.isfinite(np.asarray(out)).all()


class TestNoGatherMaterialization:
    def test_kernel_path_has_no_full_gather(self):
        """The acceptance bar of the roofline work: no intermediate of
        shape [S, NB*BT, H, D] exists anywhere in the kernel path's jaxpr
        (the reference path exists precisely to materialize it)."""
        ops = _setup([5, 9], 1)
        gathered = (2, NB * BT, H, D)

        def shapes(fn):
            jaxpr = jax.make_jaxpr(fn)(*ops)
            seen = set()

            def walk(jx):
                for eqn in jx.eqns:
                    for v in list(eqn.invars) + list(eqn.outvars):
                        aval = getattr(v, "aval", None)
                        if aval is not None and hasattr(aval, "shape"):
                            seen.add(tuple(aval.shape))
                    for sub in eqn.params.values():
                        if hasattr(sub, "jaxpr"):
                            walk(sub.jaxpr)
            walk(jaxpr.jaxpr)
            return seen

        kernel_fn = lambda *a: paged_attention(*a, interpret=True)
        assert gathered not in shapes(kernel_fn)
        assert gathered in shapes(paged_attention_reference)


class TestEngineKernelModes:
    def test_resolve_modes(self):
        assert generate.resolve_attention_kernel("gather") == "gather"
        assert generate.resolve_attention_kernel("interpret") == "interpret"
        assert generate.resolve_attention_kernel("pallas") == "pallas"
        # auto on this CPU suite resolves to the gather path
        assert generate.resolve_attention_kernel("auto") in (
            "gather", "pallas")
        with pytest.raises(ValueError):
            generate.resolve_attention_kernel("nope")

    def test_interpret_engine_token_identical_to_gather(self):
        """The interpret-mode Pallas kernel driving the full paged engine
        (prefill AND decode forwards) emits exactly the gather path's
        tokens — the CPU twin of the TPU deployment configuration."""
        cfg = transformer.tiny(max_seq_len=64)
        params = transformer.init_params(cfg, jax.random.key(0))
        kw = dict(prompt_buckets=(16,), chunk=4, slots=2, max_queue=0,
                  block_tokens=BT, pool_blocks=40)
        eng_g = PagedLLMEngine(params, cfg, attention_kernel="gather",
                               name="kern-g", **kw)
        eng_i = PagedLLMEngine(params, cfg, attention_kernel="interpret",
                               name="kern-i", **kw)
        for prompt in ([7, 3, 11], [2, 4, 6, 8, 10, 12, 14]):
            a = eng_g.generate(prompt, max_new_tokens=10)
            b = eng_i.generate(prompt, max_new_tokens=10)
            assert a == b
        assert eng_g.kv.active_blocks() == 0
        assert eng_i.kv.active_blocks() == 0


@pytest.mark.slow
@pytest.mark.skipif(jax.default_backend() != "tpu",
                    reason="compiled Pallas kernel needs a TPU")
class TestCompiledKernelTPU:
    """The compiled twin of TestKernelOracleEquivalence — identical cases,
    interpret=False, run only where a TPU backend is attached."""

    @pytest.mark.parametrize("lengths", [[0, 3, BT, 2 * BT + 1,
                                          NB * BT - 2]])
    @pytest.mark.parametrize("t_tokens", [1, 4])
    def test_compiled_matches_reference(self, lengths, t_tokens):
        ops = _setup(lengths, t_tokens)
        out = paged_attention(*ops, interpret=False)
        ref = paged_attention_reference(*ops)
        _assert_close(out, ref, tol=5e-3)  # bf16-ish TPU accumulate slack
