"""Core API tests: tasks, objects, options — the reference's
``python/ray/tests/test_basic.py`` surface."""

import time

import numpy as np
import pytest


def test_put_get(ray_start_regular):
    rt = ray_start_regular
    ref = rt.put({"a": 1, "b": [1, 2, 3]})
    assert rt.get(ref) == {"a": 1, "b": [1, 2, 3]}


def test_put_get_numpy_zero_copy(ray_start_regular):
    rt = ray_start_regular
    arr = np.arange(1_000_000, dtype=np.float32)
    ref = rt.put(arr)
    out = rt.get(ref)
    np.testing.assert_array_equal(arr, out)


def test_simple_task(ray_start_regular):
    rt = ray_start_regular

    @rt.remote
    def add(a, b):
        return a + b

    assert rt.get(add.remote(1, 2)) == 3


def test_task_with_ref_args(ray_start_regular):
    rt = ray_start_regular

    @rt.remote
    def add(a, b):
        return a + b

    x = rt.put(10)
    y = add.remote(x, 5)
    z = add.remote(y, y)
    assert rt.get(z) == 30


def test_task_kwargs(ray_start_regular):
    rt = ray_start_regular

    @rt.remote
    def f(a, b=0, c=0):
        return a + b + c

    assert rt.get(f.remote(1, c=3)) == 4


def test_multiple_returns(ray_start_regular):
    rt = ray_start_regular

    @rt.remote(num_returns=3)
    def three():
        return 1, 2, 3

    a, b, c = three.remote()
    assert rt.get([a, b, c]) == [1, 2, 3]


def test_num_returns_zero(ray_start_regular):
    rt = ray_start_regular

    @rt.remote(num_returns=0)
    def fire_and_forget():
        return None

    assert fire_and_forget.remote() is None


def test_task_error_propagation(ray_start_regular):
    rt = ray_start_regular

    @rt.remote(max_retries=0)
    def boom():
        raise ValueError("bad value")

    with pytest.raises(ValueError, match="bad value"):
        rt.get(boom.remote())


def test_error_propagates_through_dependents(ray_start_regular):
    rt = ray_start_regular

    @rt.remote(max_retries=0)
    def boom():
        raise KeyError("k")

    @rt.remote
    def consume(x):
        return x

    with pytest.raises(KeyError):
        rt.get(consume.remote(boom.remote()))


def test_retries(ray_start_regular):
    rt = ray_start_regular
    counter = {"n": 0}

    @rt.remote(max_retries=3, retry_exceptions=True)
    def flaky():
        counter["n"] += 1
        if counter["n"] < 3:
            raise RuntimeError("transient")
        return counter["n"]

    assert rt.get(flaky.remote()) == 3


def test_nested_tasks_no_deadlock(ray_start_regular):
    rt = ray_start_regular

    @rt.remote
    def inner(x):
        return x * 2

    @rt.remote
    def outer(x):
        return rt.get(inner.remote(x)) + 1

    # More nested calls than CPU resources — blocked-worker release must kick in.
    results = rt.get([outer.remote(i) for i in range(10)])
    assert results == [i * 2 + 1 for i in range(10)]


@pytest.mark.leaks("abandons an in-flight sleeping task: the in-process runtime cannot interrupt user code mid-sleep")
def test_wait(ray_start_regular):
    rt = ray_start_regular

    @rt.remote
    def fast():
        return "fast"

    @rt.remote
    def slow():
        time.sleep(5)
        return "slow"

    f, s = fast.remote(), slow.remote()
    ready, not_ready = rt.wait([f, s], num_returns=1, timeout=3)
    assert ready == [f]
    assert not_ready == [s]


@pytest.mark.leaks("abandons an in-flight sleeping task: the in-process runtime cannot interrupt user code mid-sleep")
def test_wait_timeout_empty(ray_start_regular):
    rt = ray_start_regular

    @rt.remote
    def slow():
        time.sleep(10)

    ready, not_ready = rt.wait([slow.remote()], num_returns=1, timeout=0.1)
    assert ready == []
    assert len(not_ready) == 1


@pytest.mark.leaks("abandons an in-flight sleeping task: the in-process runtime cannot interrupt user code mid-sleep")
def test_get_timeout(ray_start_regular):
    rt = ray_start_regular

    @rt.remote
    def slow():
        time.sleep(10)

    with pytest.raises(rt.GetTimeoutError):
        rt.get(slow.remote(), timeout=0.1)


def test_generator_streaming(ray_start_regular):
    rt = ray_start_regular

    @rt.remote(num_returns="streaming")
    def gen(n):
        for i in range(n):
            yield i * i

    items = [rt.get(ref) for ref in gen.remote(5)]
    assert items == [0, 1, 4, 9, 16]


@pytest.mark.leaks("abandons an in-flight sleeping task: the in-process runtime cannot interrupt user code mid-sleep")
def test_cancel_pending(ray_start_regular):
    rt = ray_start_regular

    @rt.remote
    def blocker():
        time.sleep(30)

    @rt.remote
    def target():
        return 1

    # Saturate CPUs so target stays queued, then cancel it.
    blockers = [blocker.remote() for _ in range(4)]
    t = target.remote()
    time.sleep(0.2)
    rt.cancel(t)
    with pytest.raises(rt.TaskCancelledError):
        rt.get(t, timeout=5)
    del blockers


def test_options_override(ray_start_regular):
    rt = ray_start_regular

    @rt.remote
    def whoami():
        return 1

    ref = whoami.options(num_cpus=2).remote()
    assert rt.get(ref) == 1


def test_resources_respected(ray_start_regular):
    rt = ray_start_regular
    total = rt.cluster_resources()
    assert total["CPU"] == 4
    assert total["TPU"] == 8

    @rt.remote(num_tpus=8)
    def use_all_tpus():
        return rt.available_resources().get("TPU", 0)

    assert rt.get(use_all_tpus.remote()) == 0


def test_infeasible_task_errors(ray_start_regular):
    rt = ray_start_regular

    @rt.remote(num_cpus=1000, max_retries=0)
    def huge():
        return 1

    with pytest.raises(RuntimeError, match="no feasible node"):
        rt.get(huge.remote(), timeout=5)


def test_remote_function_direct_call_rejected(ray_start_regular):
    rt = ray_start_regular

    @rt.remote
    def f():
        return 1

    with pytest.raises(TypeError, match="remote"):
        f()
