"""Regression tests for review findings on the core runtime."""

import time

import pytest


def test_retry_does_not_leak_resources(ray_start_regular):
    """Failed attempts must release their CPU allocation."""
    rt = ray_start_regular
    counter = {"n": 0}

    @rt.remote(num_cpus=4, max_retries=3, retry_exceptions=True)
    def flaky():
        counter["n"] += 1
        if counter["n"] < 4:
            raise RuntimeError("transient")
        return "done"

    assert rt.get(flaky.remote()) == "done"
    # All 4 CPUs must be free again after the retries.
    deadline = time.time() + 5
    while time.time() < deadline:
        if rt.available_resources().get("CPU", 0) == 4:
            break
        time.sleep(0.05)
    assert rt.available_resources().get("CPU", 0) == 4


def test_actor_call_before_creation_completes(ray_start_regular):
    """Method calls during slow creation buffer, not error."""
    rt = ray_start_regular

    @rt.remote
    class Slow:
        def __init__(self):
            time.sleep(0.5)
            self.ready = True

        def check(self):
            return self.ready

    s = Slow.remote()
    # Submit immediately — creation still running.
    assert rt.get(s.check.remote(), timeout=10) is True


def test_actor_ordering_with_pending_deps(ray_start_regular):
    """A later no-dep call must not overtake an earlier call blocked on deps."""
    rt = ray_start_regular

    @rt.remote
    def slow_value():
        time.sleep(0.5)
        return 42

    @rt.remote
    class Box:
        def __init__(self):
            self.v = None

        def set(self, v):
            self.v = v

        def get_v(self):
            return self.v

    b = Box.remote()
    b.set.remote(slow_value.remote())  # dep resolves in ~0.5s
    # Submitted after set(): must observe the set value.
    assert rt.get(b.get_v.remote(), timeout=10) == 42


def test_pending_placement_group_eventually_places(ray_start_regular):
    """A PG created while resources are busy places once they free."""
    rt = ray_start_regular

    @rt.remote(num_tpus=8)
    def hog():
        time.sleep(1.0)
        return 1

    h = hog.remote()
    time.sleep(0.2)
    pg = rt.placement_group([{"TPU": 8}], strategy="STRICT_PACK")
    assert not pg.ready(timeout=0.1)  # resources still held
    assert rt.get(h) == 1
    assert pg.ready(timeout=5)


def test_actor_in_placement_group_bundle(ray_start_regular):
    """An actor using a PG bundle must not double-allocate chip resources."""
    rt = ray_start_regular
    pg = rt.placement_group([{"TPU": 8, "CPU": 1}], strategy="STRICT_PACK")
    assert pg.ready(timeout=5)

    @rt.remote(num_tpus=8)
    class SliceActor:
        def ping(self):
            return "ok"

    a = SliceActor.options(
        scheduling_strategy=rt.PlacementGroupSchedulingStrategy(
            placement_group=pg, placement_group_bundle_index=0
        )
    ).remote()
    assert rt.get(a.ping.remote(), timeout=10) == "ok"


def test_restart_preserves_call_ordering(ray_start_regular):
    """Sequence tracking survives actor restart."""
    rt = ray_start_regular

    @rt.remote(max_restarts=2)
    class P:
        def ping(self):
            return "alive"

    p = P.remote()
    for _ in range(3):
        assert rt.get(p.ping.remote(), timeout=10) == "alive"
    rt.kill(p, no_restart=False)
    time.sleep(0.3)
    for _ in range(3):
        assert rt.get(p.ping.remote(), timeout=10) == "alive"


def test_put_copies_numpy_buffer(ray_start_regular):
    """Mutating an array after put must not mutate the stored object."""
    import numpy as np

    rt = ray_start_regular
    arr = np.zeros(1000, dtype=np.float64)
    ref = rt.put(arr)
    arr[:] = 99.0
    stored = rt.get(ref)
    assert stored.sum() == 0.0


def test_hard_node_affinity_queues_when_busy(ray_start_cluster):
    """Hard affinity to a busy-but-feasible node queues instead of failing."""
    rt = ray_start_cluster
    from ray_tpu.core.ids import NodeID

    target = NodeID.from_hex(rt.nodes()[0]["NodeID"])

    @rt.remote(num_cpus=2, scheduling_strategy=rt.NodeAffinitySchedulingStrategy(node_id=target))
    def busy():
        time.sleep(0.5)
        return rt.get_runtime_context().node_id.hex()

    a = busy.remote()
    b = busy.remote()  # node busy now; must queue, not fail
    assert rt.get([a, b], timeout=15) == [target.hex(), target.hex()]
