"""Topology-aware hierarchical collectives: the two-level (ICI/DCN-analog)
schedule of ``ray_tpu.parallel.collectives`` — intra-node shm reduce at a
leader, segmented pipelined ring between node leaders, shm-key fan-out —
plus the in-place reduction kernels and the flat-ring equivalence contract.

Real process boundaries throughout: every cross-process case runs member
ACTORS on a multi-node :class:`Cluster` (distinct daemons, distinct node
stores), pinned per node with NodeAffinity so the rank→store grouping is
deterministic.
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.core import runtime as runtime_mod
from ray_tpu.core.cluster import Cluster, connect

OPS = ("sum", "prod", "min", "max", "mean")
_NP_OPS = {"sum": np.sum, "prod": np.prod, "min": np.min, "max": np.max,
           "mean": np.mean}


def _rank_input(rank: int, n: int) -> np.ndarray:
    # Values near 1 so prod stays finite at any size; distinct per rank so
    # min/max/broadcast orderings are actually exercised. Keep in sync with
    # Member._inp below (duplicated because the member class must pickle
    # self-contained by value into worker processes).
    return 1.0 + ((np.arange(n) * 13 + rank * 7) % 5) * (0.01 * (rank + 1))


def _expected(op: str, world: int, n: int) -> np.ndarray:
    return _NP_OPS[op](np.stack([_rank_input(r, n) for r in range(world)]),
                       axis=0)


def _member_cls():
    @ray_tpu.remote
    class Member:
        def __init__(self, rank, world):
            self.rank = rank
            self.world = world

        @staticmethod
        def _inp(rank, n):
            import numpy as np

            return 1.0 + ((np.arange(n) * 13 + rank * 7) % 5) * (
                0.01 * (rank + 1))

        def store(self):
            import os

            return os.environ.get("RAY_TPU_STORE_NAME", "")

        def join(self, group, hier=None, segment=None, timeout=None):
            overrides = {}
            if hier is not None:
                overrides["collective_hierarchy_enabled"] = hier
            if segment is not None:
                overrides["collective_segment_size"] = segment
            if timeout is not None:
                overrides["collective_timeout_s"] = timeout
            if overrides:
                from ray_tpu.core.config import Config, set_config

                set_config(Config(overrides))
            from ray_tpu.parallel import collectives as c

            c.init_collective_group(self.world, self.rank, backend="gloo",
                                    group_name=group)
            return True

        def allreduce(self, group, op, n):
            from ray_tpu.parallel import collectives as c

            return c.allreduce(self._inp(self.rank, n), op=op,
                               group_name=group)

        def allreduce_guarded(self, group, op, n):
            from ray_tpu.parallel import collectives as c

            try:
                c.allreduce(self._inp(self.rank, n), op=op, group_name=group)
                return "ok"
            except Exception as e:  # noqa: BLE001 — the NAME is the assert
                return type(e).__name__

        def surface(self, group):
            import numpy as np

            from ray_tpu.parallel import collectives as c

            out = {}
            base = np.arange(8.0) + self.rank
            out["bcast"] = c.broadcast(
                np.array([9.0, 9.5]) if self.rank == 1 else None,
                src_rank=1, group_name=group)
            out["gather"] = c.allgather(np.arange(4.0) * (self.rank + 1),
                                        group_name=group)
            out["rs"] = c.reducescatter(base, op="mean", group_name=group)
            out["a2a"] = c.alltoall(np.arange(8.0) * (self.rank + 1),
                                    group_name=group)
            c.barrier(group_name=group)
            out["scalar"] = float(c.allreduce(np.float64(self.rank + 1),
                                              group_name=group))
            # F-contiguous input: the leader's promoted/accumulated buffer
            # must stay attached to its flattened ring view.
            out["fcontig"] = c.allreduce(
                (np.arange(12.0).reshape(3, 4) * (self.rank + 1)).T,
                group_name=group)
            if self.rank == 0:
                c.send(np.array([7.5]), dst_rank=self.world - 1,
                       group_name=group)
            if self.rank == self.world - 1:
                out["p2p"] = float(c.recv(0, group_name=group)[0])
            return out

        def stats(self, group):
            from ray_tpu.parallel import collectives as c

            return c.get_group_stats(group)

        def die(self):
            import os

            os._exit(1)

    return Member


def _spawn(cluster, world):
    """``world`` members, pinned CONTIGUOUSLY across the cluster's nodes
    (rank r on node r*nodes//world) so the store grouping is deterministic:
    2 nodes × 4 ranks → ranks (0,1) share node 0's store, (2,3) node 1's."""
    Member = _member_cls()
    nodes = cluster.nodes
    members = []
    for r in range(world):
        node = nodes[r * len(nodes) // world]
        members.append(Member.options(
            num_cpus=1,
            scheduling_strategy=ray_tpu.NodeAffinitySchedulingStrategy(
                node_id=node.node_id)).remote(r, world))
    return members


def _require_stores(members, expect_distinct):
    stores = ray_tpu.get([m.store.remote() for m in members], timeout=120)
    if not all(stores):
        pytest.skip("native shm store unavailable on this host")
    assert len(set(stores)) == expect_distinct, stores
    return stores


# ====================== in-place reduction kernels ======================


def test_inplace_reduce_kernels_match_numpy_and_do_not_mutate():
    from ray_tpu.parallel.collectives import _REDUCE_OPS

    for dtype in (np.float64, np.float32, np.int32):
        arrs = [(np.arange(6) % 4 + 1).astype(dtype) * (r + 1)
                for r in range(3)]
        keep = [a.copy() for a in arrs]
        for op in OPS:
            ours = _REDUCE_OPS[op](arrs)
            ref = _NP_OPS[op](np.stack(arrs), axis=0)
            # Same dtype promotion as the old stack-then-reduce path
            # (sum/prod widen sub-word ints, mean of ints is float64).
            assert ours.dtype == ref.dtype, (op, dtype, ours.dtype, ref.dtype)
            np.testing.assert_allclose(ours, ref)
        for a, k in zip(arrs, keep):  # inputs never mutated
            np.testing.assert_array_equal(a, k)
    # 0-d contract (scalar allreduce rides through atleast_1d + reshape).
    assert float(_REDUCE_OPS["mean"]([np.float64(1.0), np.float64(3.0)])) == 2.0
    # float16 mean keeps np.mean's float32 intermediate: accumulating many
    # f16 contributions must not round per step.
    f16 = [np.full(64, 0.1, dtype=np.float16) for _ in range(32)]
    ours = _REDUCE_OPS["mean"](f16)
    ref = np.mean(np.stack(f16), axis=0)
    assert ours.dtype == np.float16
    np.testing.assert_array_equal(ours, ref)


def test_store_open_failure_keeps_shared_topology():
    """A rank whose own store failed to open publishes "" and loses only
    its shm TRANSPORT — its topology (and therefore its schedule and tag
    space) must still come from the shared KV-rendezvoused stores list, or
    it would run the flat ring against peers running the hierarchy."""
    from ray_tpu.parallel.collectives import _DistributedGroup, _MemberService

    stores = ["s", "s", "s", None]  # rank 3's open failed -> published ""
    svc_ok = _MemberService()
    svc_ok.shm = object()
    g_ok = _DistributedGroup(4, 0, ["a"] * 4, svc_ok, None,
                             stores=list(stores), hierarchy=True)
    g_bad = _DistributedGroup(4, 3, ["a"] * 4, _MemberService(), None,
                              stores=list(stores), hierarchy=True)
    assert g_ok._topo.nodes == g_bad._topo.nodes == [[0, 1, 2], [3]]
    assert g_ok._use_hier() and g_bad._use_hier()
    assert g_bad._shm is None  # transport gated, schedule shared
    # Segmentation policy agrees pairwise: rank 3's hops cross stores from
    # BOTH ends' perspective.
    assert g_ok._chunk_segments(3, 10, 8) == g_bad._chunk_segments(0, 10, 8)


def test_local_backend_reduce_ops_in_process(ray_start_regular):
    """The hub ``exchange`` path reduces through the same in-place kernels."""
    import threading

    from ray_tpu.parallel import collectives as col

    world = 3
    results = {}

    def member(rank):
        col.init_collective_group(world, rank, backend="local",
                                  group_name="ipk")
        results[rank] = {
            op: col.allreduce(_rank_input(rank, 32), op=op, group_name="ipk")
            for op in OPS}

    threads = [threading.Thread(target=member, args=(r,), daemon=True)
               for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not any(t.is_alive() for t in threads)
    for op in OPS:
        exp = _expected(op, world, 32)
        for rank in range(world):
            np.testing.assert_allclose(results[rank][op], exp)
    col.destroy_collective_group("ipk")


# ====================== two-level schedule ======================


def test_hier_2x2_allreduce_matches_flat_and_oracle():
    """2 nodes × 2 ranks: the hierarchical schedule must (a) produce
    allclose results to the flat ring for all five ops, (b) actually take
    the two-level path, and (c) move fewer cross-store (DCN-analog) bytes
    than the topology-blind ring."""
    cluster = Cluster(num_nodes=2, resources_per_node={"CPU": 4})
    try:
        core = connect(cluster.gcs_address)
        try:
            members = _spawn(cluster, 4)
            stores = _require_stores(members, expect_distinct=2)
            assert stores[0] == stores[1] and stores[2] == stores[3]
            ray_tpu.get([m.join.remote("hg", hier=True) for m in members],
                        timeout=180)
            ray_tpu.get([m.join.remote("fg", hier=False) for m in members],
                        timeout=180)
            n = 8192
            for op in OPS:
                h = ray_tpu.get(
                    [m.allreduce.remote("hg", op, n) for m in members],
                    timeout=180)
                f = ray_tpu.get(
                    [m.allreduce.remote("fg", op, n) for m in members],
                    timeout=180)
                exp = _expected(op, 4, n)
                for rank in range(4):
                    np.testing.assert_allclose(h[rank], exp, rtol=1e-10)
                    np.testing.assert_allclose(f[rank], h[rank], rtol=1e-10)
            hs = ray_tpu.get([m.stats.remote("hg") for m in members],
                             timeout=60)
            fs = ray_tpu.get([m.stats.remote("fg") for m in members],
                             timeout=60)
            assert sum(s["hier_rounds"] for s in hs) == 4 * len(OPS)
            assert sum(s["hier_rounds"] for s in fs) == 0
            assert sum(s["flat_rounds"] for s in fs) == 4 * len(OPS)
            hier_cross = sum(s["bytes_cross_store"] for s in hs)
            flat_cross = sum(s["bytes_cross_store"] for s in fs)
            assert 0 < hier_cross < flat_cross, (hier_cross, flat_cross)
        finally:
            core.shutdown()
            runtime_mod._global_runtime = None
    finally:
        cluster.shutdown()


def test_hier_2x2_full_surface():
    """Broadcast from a NON-LEADER root, allgather, reducescatter (mean),
    alltoall, barrier, scalar allreduce and p2p — all on one hierarchical
    2×2 group, back to back (tag isolation between schedules)."""
    cluster = Cluster(num_nodes=2, resources_per_node={"CPU": 2})
    try:
        core = connect(cluster.gcs_address)
        try:
            members = _spawn(cluster, 4)
            _require_stores(members, expect_distinct=2)
            ray_tpu.get([m.join.remote("sf", hier=True) for m in members],
                        timeout=180)
            results = ray_tpu.get([m.surface.remote("sf") for m in members],
                                  timeout=180)
            world = 4
            expect_rs = np.mean(
                np.stack([np.arange(8.0) + r for r in range(world)]), axis=0)
            for rank, out in enumerate(results):
                np.testing.assert_allclose(out["bcast"], [9.0, 9.5])
                for r in range(world):
                    np.testing.assert_allclose(out["gather"][r],
                                               np.arange(4.0) * (r + 1))
                np.testing.assert_allclose(
                    out["rs"], np.array_split(expect_rs, world)[rank])
                expect_a2a = np.concatenate(
                    [np.array_split(np.arange(8.0) * (s + 1), world)[rank]
                     for s in range(world)])
                np.testing.assert_allclose(out["a2a"], expect_a2a)
                assert out["scalar"] == sum(range(1, world + 1))
                np.testing.assert_allclose(
                    out["fcontig"],
                    np.arange(12.0).reshape(3, 4).T
                    * sum(range(1, world + 1)))
            assert results[world - 1]["p2p"] == 7.5
        finally:
            core.shutdown()
            runtime_mod._global_runtime = None
    finally:
        cluster.shutdown()


def test_segmented_ring_uneven_sizes():
    """Segment-pipelined ring correctness for sizes that divide evenly by
    neither the segment size nor the world size — including chunks smaller
    than one segment and EMPTY ring chunks (n < world) — on both the flat
    4-ring and the hierarchical 2-leader ring (tiny 4 KiB segments force
    many-segment pipelines)."""
    cluster = Cluster(num_nodes=2, resources_per_node={"CPU": 4})
    try:
        core = connect(cluster.gcs_address)
        try:
            members = _spawn(cluster, 4)
            _require_stores(members, expect_distinct=2)
            ray_tpu.get(
                [m.join.remote("sh", hier=True, segment=4096)
                 for m in members], timeout=180)
            ray_tpu.get(
                [m.join.remote("sfl", hier=False, segment=4096)
                 for m in members], timeout=180)
            for group in ("sh", "sfl"):
                for n in (1, 3, 1003, 100003):
                    for op in ("sum", "mean"):
                        got = ray_tpu.get(
                            [m.allreduce.remote(group, op, n)
                             for m in members], timeout=180)
                        exp = _expected(op, 4, n)
                        for rank in range(4):
                            np.testing.assert_allclose(got[rank], exp,
                                                       rtol=1e-10)
        finally:
            core.shutdown()
            runtime_mod._global_runtime = None
    finally:
        cluster.shutdown()


def test_leader_failure_surfaces_clean_error_on_all_ranks():
    """Kill the node-0 leader mid-group: every surviving rank's allreduce
    must raise within ~collective_timeout_s (set to 4s through the new
    knob), not hang for the old hardcoded 120s."""
    cluster = Cluster(num_nodes=2, resources_per_node={"CPU": 2})
    try:
        core = connect(cluster.gcs_address)
        try:
            members = _spawn(cluster, 4)
            _require_stores(members, expect_distinct=2)
            ray_tpu.get([m.join.remote("lf", hier=True, timeout=4.0)
                         for m in members], timeout=180)
            # Warm round proves the group works before the failure.
            warm = ray_tpu.get(
                [m.allreduce.remote("lf", "sum", 1024) for m in members],
                timeout=180)
            np.testing.assert_allclose(warm[0], _expected("sum", 4, 1024))
            try:
                ray_tpu.get(members[0].die.remote(), timeout=60)
            except Exception:  # noqa: BLE001 — worker death IS the point
                pass
            t0 = time.monotonic()
            errs = ray_tpu.get(
                [m.allreduce_guarded.remote("lf", "sum", 200_000)
                 for m in members[1:]], timeout=120)
            elapsed = time.monotonic() - t0
            assert all(e != "ok" for e in errs), errs
            # Fail-fast contract of collective_timeout_s: nowhere near the
            # old 120s default.
            assert elapsed < 60, elapsed
        finally:
            core.shutdown()
            runtime_mod._global_runtime = None
    finally:
        cluster.shutdown()


def test_asymmetric_nodes_two_plus_one():
    """Mixed-store group with UNEQUAL node sizes (2 ranks on node 0, a solo
    leader on node 1): the solo leader has no intra-node phase but still
    runs the leaders ring; results match the oracle for every op, and
    broadcast works from a rank on the multi-rank node."""
    cluster = Cluster(num_nodes=2, resources_per_node={"CPU": 2})
    try:
        core = connect(cluster.gcs_address)
        try:
            Member = _member_cls()
            nodes = cluster.nodes
            placement = [0, 0, 1]  # ranks 0,1 -> node 0; rank 2 solo
            members = [Member.options(
                num_cpus=1,
                scheduling_strategy=ray_tpu.NodeAffinitySchedulingStrategy(
                    node_id=nodes[placement[r]].node_id)).remote(r, 3)
                for r in range(3)]
            _require_stores(members, expect_distinct=2)
            ray_tpu.get([m.join.remote("asym", hier=True) for m in members],
                        timeout=180)
            for op in OPS:
                got = ray_tpu.get(
                    [m.allreduce.remote("asym", op, 777) for m in members],
                    timeout=180)
                exp = _expected(op, 3, 777)
                for rank in range(3):
                    np.testing.assert_allclose(got[rank], exp, rtol=1e-10)
            results = ray_tpu.get([m.surface.remote("asym") for m in members],
                                  timeout=180)
            for out in results:
                np.testing.assert_allclose(out["bcast"], [9.0, 9.5])
                # Solo leader + F-contiguous input: its astype'd accumulator
                # feeds the leaders ring through a reshape(-1) VIEW.
                np.testing.assert_allclose(
                    out["fcontig"], np.arange(12.0).reshape(3, 4).T * 6)
            assert results[2]["p2p"] == 7.5
            stats = ray_tpu.get([m.stats.remote("asym") for m in members],
                                timeout=60)
            assert sum(s["hier_rounds"] for s in stats) > 0
        finally:
            core.shutdown()
            runtime_mod._global_runtime = None
    finally:
        cluster.shutdown()


@pytest.mark.slow
def test_four_solo_nodes_degenerate_to_flat():
    """4 nodes × 1 rank: hierarchy enabled but every node is a singleton —
    the schedule must degenerate to the flat segmented ring (no two-level
    rounds) and still be correct."""
    cluster = Cluster(num_nodes=4, resources_per_node={"CPU": 1})
    try:
        core = connect(cluster.gcs_address)
        try:
            members = _spawn(cluster, 4)
            _require_stores(members, expect_distinct=4)
            ray_tpu.get([m.join.remote("solo", hier=True) for m in members],
                        timeout=180)
            got = ray_tpu.get(
                [m.allreduce.remote("solo", "sum", 5000) for m in members],
                timeout=180)
            exp = _expected("sum", 4, 5000)
            for rank in range(4):
                np.testing.assert_allclose(got[rank], exp, rtol=1e-10)
            stats = ray_tpu.get([m.stats.remote("solo") for m in members],
                                timeout=60)
            assert sum(s["hier_rounds"] for s in stats) == 0
            assert sum(s["flat_rounds"] for s in stats) == 4
        finally:
            core.shutdown()
            runtime_mod._global_runtime = None
    finally:
        cluster.shutdown()
