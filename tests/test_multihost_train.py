"""Multi-host Train: a global JAX mesh across real worker PROCESSES.

The seam the reference leaves to torch (``train/torch/config.py:64-100``
NCCL process groups) done TPU-natively: two worker processes on the
multiprocess cluster each own 4 virtual CPU devices, form ONE 8-device
global mesh via ``jax.distributed`` (coordinator address flowing through
the GCS KV — the control plane), and run the full sharded GPT-2-tiny train
step with data parallelism across the process boundary. Losses over two
steps must match a single-process 8-device oracle, which also proves the
gradient psum crossed processes correctly (step 2's loss depends on step
1's update).
"""

import sys

import cloudpickle
import numpy as np

import ray_tpu
from ray_tpu.core.cluster import Cluster, connect
from ray_tpu.core import runtime as runtime_mod

# Worker processes cannot import the tests package — ship this module's
# classes by value (what cloudpickle does automatically for __main__).
cloudpickle.register_pickle_by_value(sys.modules[__name__])

VOCAB, SEQ, GLOBAL_BATCH = 256, 64, 8


class TrainWorker:
    """One per-host training process (4 local devices, rank in a world of 2).

    Device-count/platform env arrives via ``runtime_env={"env_vars": ...}``
    — applied by the node daemon at process SPAWN, before the interpreter's
    sitecustomize can preload jax with the wrong config.
    """

    def __init__(self, rank: int, world: int):
        self.rank = rank
        self.world = world

    def reserve_coordinator(self) -> str:
        """Rank 0: pick a free port; the driver publishes it via GCS KV."""
        import socket

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return f"127.0.0.1:{port}"

    def init_distributed(self, coordinator: str) -> int:
        import jax

        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=self.world,
            process_id=self.rank,
        )
        jax.config.update("jax_default_matmul_precision", "highest")
        return len(jax.devices())  # global device count

    def train_two_steps(self, tokens_local: np.ndarray):
        """Run two sharded train steps; returns both global losses."""
        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu.models import transformer
        from ray_tpu.models.training import make_train_step
        from ray_tpu.parallel.mesh import MeshSpec, make_mesh
        from ray_tpu.parallel.sharding import ShardingRules

        mesh = make_mesh(MeshSpec(data=-1), devices=jax.devices())
        rules = ShardingRules()
        cfg = transformer.tiny(
            vocab_size=VOCAB, d_model=32, n_layers=2, n_heads=2, d_ff=64,
            max_seq_len=SEQ, vocab_multiple=128, attn_impl="dense",
            dtype=jnp.float32, param_dtype=jnp.float32,
        )
        bundle = make_train_step(
            loss_fn=lambda p, b: transformer.lm_loss(p, b, cfg, mesh=mesh, rules=rules),
            init_params_fn=lambda k: transformer.init_params(cfg, k),
            logical_params=transformer.logical_axes(cfg),
            mesh=mesh, rules=rules,
            optimizer=optax.adamw(1e-2),
            batch_logical=("batch", None),
        )
        params, opt_state = bundle.init(jax.random.key(0))
        # Each process contributes its local half of the global batch.
        batch = {"tokens": jax.make_array_from_process_local_data(
            bundle.batch_sharding, tokens_local)}
        losses = []
        for _ in range(2):
            params, opt_state, metrics = bundle.step(params, opt_state, batch)
            # loss is fully replicated — locally addressable on every process
            losses.append(float(metrics["loss"]))
        return losses


def _oracle_two_steps(tokens_global: np.ndarray):
    """Single-process 8-device oracle (the pytest process's CPU mesh)."""
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.models import transformer
    from ray_tpu.models.training import make_train_step
    from ray_tpu.parallel.mesh import MeshSpec, make_mesh
    from ray_tpu.parallel.sharding import ShardingRules

    devices = jax.devices("cpu")
    mesh = make_mesh(MeshSpec(data=-1), devices=devices)
    rules = ShardingRules()
    cfg = transformer.tiny(
        vocab_size=VOCAB, d_model=32, n_layers=2, n_heads=2, d_ff=64,
        max_seq_len=SEQ, vocab_multiple=128, attn_impl="dense",
        dtype=jnp.float32, param_dtype=jnp.float32,
    )
    bundle = make_train_step(
        loss_fn=lambda p, b: transformer.lm_loss(p, b, cfg, mesh=mesh, rules=rules),
        init_params_fn=lambda k: transformer.init_params(cfg, k),
        logical_params=transformer.logical_axes(cfg),
        mesh=mesh, rules=rules,
        optimizer=optax.adamw(1e-2),
        batch_logical=("batch", None),
    )
    params, opt_state = bundle.init(jax.random.key(0))
    batch = {"tokens": jax.device_put(jnp.asarray(tokens_global),
                                      bundle.batch_sharding)}
    losses = []
    for _ in range(2):
        params, opt_state, metrics = bundle.step(params, opt_state, batch)
        losses.append(float(metrics["loss"]))
    return losses


def test_two_process_global_mesh_matches_oracle():
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, VOCAB, (GLOBAL_BATCH, SEQ)).astype(np.int32)

    oracle = _oracle_two_steps(tokens)

    cluster = Cluster(num_nodes=2, resources_per_node={"CPU": 4})
    try:
        core = connect(cluster.gcs_address)
        try:
            worker_cls = ray_tpu.remote(TrainWorker)
            env_vars = {
                "JAX_PLATFORMS": "cpu",
                "JAX_NUM_CPU_DEVICES": "4",
                "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
                # Disable the axon sitecustomize's eager TPU-jax preload.
                "PALLAS_AXON_POOL_IPS": "",
            }
            workers = [
                worker_cls.options(
                    num_cpus=2, runtime_env={"env_vars": env_vars}
                ).remote(r, 2)
                for r in range(2)
            ]
            # Coordinator address flows through the control plane: rank 0
            # reserves it, the driver publishes to the GCS KV, rank 1 reads
            # it back (the reference broadcasts rank 0's addr the same way).
            coordinator = ray_tpu.get(workers[0].reserve_coordinator.remote(),
                                      timeout=120)
            core.gcs.kv_put("train/coordinator", coordinator.encode())
            addr = core.gcs.kv_get("train/coordinator").decode()
            # Both inits must be in flight together (the service blocks
            # until every process connects).
            counts = ray_tpu.get(
                [w.init_distributed.remote(addr) for w in workers],
                timeout=300)
            assert counts == [8, 8], f"global mesh wrong: {counts}"

            halves = [tokens[:GLOBAL_BATCH // 2], tokens[GLOBAL_BATCH // 2:]]
            refs = [w.train_two_steps.remote(h)
                    for w, h in zip(workers, halves)]
            losses = ray_tpu.get(refs, timeout=300)
            # Every process observed the same (replicated) global loss...
            np.testing.assert_allclose(losses[0], losses[1], rtol=1e-6)
            # ...and it matches the single-process oracle across BOTH steps
            # (step 2 proves the cross-process gradient psum was applied).
            np.testing.assert_allclose(losses[0], oracle, rtol=2e-4, atol=2e-4)
        finally:
            core.shutdown()
            runtime_mod._global_runtime = None
    finally:
        cluster.shutdown()
