"""Multi-host Train: a global JAX mesh across real worker PROCESSES.

The seam the reference leaves to torch (``train/torch/config.py:64-100``
NCCL process groups) done TPU-natively: two worker processes on the
multiprocess cluster each own 4 virtual CPU devices, form ONE 8-device
global mesh via ``jax.distributed`` (coordinator address flowing through
the GCS KV — the control plane), and run the full sharded GPT-2-tiny train
step with data parallelism across the process boundary. Losses over two
steps must match a single-process 8-device oracle, which also proves the
gradient psum crossed processes correctly (step 2's loss depends on step
1's update).
"""

import os
import sys

import cloudpickle
import numpy as np

import ray_tpu
from ray_tpu.core.cluster import Cluster, connect
from ray_tpu.core import runtime as runtime_mod

# Worker processes cannot import the tests package — ship this module's
# classes by value (what cloudpickle does automatically for __main__).
cloudpickle.register_pickle_by_value(sys.modules[__name__])

VOCAB, SEQ, GLOBAL_BATCH = 256, 64, 8


class TrainWorker:
    """One per-host training process (4 local devices, rank in a world of 2).

    Device-count/platform env arrives via ``runtime_env={"env_vars": ...}``
    — applied by the node daemon at process SPAWN, before the interpreter's
    sitecustomize can preload jax with the wrong config.
    """

    def __init__(self, rank: int, world: int):
        self.rank = rank
        self.world = world

    def reserve_coordinator(self) -> str:
        """Rank 0: pick a free port; the driver publishes it via GCS KV."""
        import socket

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return f"127.0.0.1:{port}"

    def init_distributed(self, coordinator: str) -> int:
        import jax

        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=self.world,
            process_id=self.rank,
        )
        jax.config.update("jax_default_matmul_precision", "highest")
        return len(jax.devices())  # global device count

    def train_two_steps(self, tokens_local: np.ndarray):
        """Run two sharded train steps; returns both global losses."""
        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu.models import transformer
        from ray_tpu.models.training import make_train_step
        from ray_tpu.parallel.mesh import MeshSpec, make_mesh
        from ray_tpu.parallel.sharding import ShardingRules

        mesh = make_mesh(MeshSpec(data=-1), devices=jax.devices())
        rules = ShardingRules()
        cfg = transformer.tiny(
            vocab_size=VOCAB, d_model=32, n_layers=2, n_heads=2, d_ff=64,
            max_seq_len=SEQ, vocab_multiple=128, attn_impl="dense",
            dtype=jnp.float32, param_dtype=jnp.float32,
        )
        bundle = make_train_step(
            loss_fn=lambda p, b: transformer.lm_loss(p, b, cfg, mesh=mesh, rules=rules),
            init_params_fn=lambda k: transformer.init_params(cfg, k),
            logical_params=transformer.logical_axes(cfg),
            mesh=mesh, rules=rules,
            optimizer=optax.adamw(1e-2),
            batch_logical=("batch", None),
        )
        params, opt_state = bundle.init(jax.random.key(0))
        # Each process contributes its local half of the global batch.
        batch = {"tokens": jax.make_array_from_process_local_data(
            bundle.batch_sharding, tokens_local)}
        losses = []
        for _ in range(2):
            params, opt_state, metrics = bundle.step(params, opt_state, batch)
            # loss is fully replicated — locally addressable on every process
            losses.append(float(metrics["loss"]))
        return losses


def _oracle_two_steps(tokens_global: np.ndarray):
    """Single-process 8-device oracle (the pytest process's CPU mesh)."""
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.models import transformer
    from ray_tpu.models.training import make_train_step
    from ray_tpu.parallel.mesh import MeshSpec, make_mesh
    from ray_tpu.parallel.sharding import ShardingRules

    devices = jax.devices("cpu")
    mesh = make_mesh(MeshSpec(data=-1), devices=devices)
    rules = ShardingRules()
    cfg = transformer.tiny(
        vocab_size=VOCAB, d_model=32, n_layers=2, n_heads=2, d_ff=64,
        max_seq_len=SEQ, vocab_multiple=128, attn_impl="dense",
        dtype=jnp.float32, param_dtype=jnp.float32,
    )
    bundle = make_train_step(
        loss_fn=lambda p, b: transformer.lm_loss(p, b, cfg, mesh=mesh, rules=rules),
        init_params_fn=lambda k: transformer.init_params(cfg, k),
        logical_params=transformer.logical_axes(cfg),
        mesh=mesh, rules=rules,
        optimizer=optax.adamw(1e-2),
        batch_logical=("batch", None),
    )
    params, opt_state = bundle.init(jax.random.key(0))
    batch = {"tokens": jax.device_put(jnp.asarray(tokens_global),
                                      bundle.batch_sharding)}
    losses = []
    for _ in range(2):
        params, opt_state, metrics = bundle.step(params, opt_state, batch)
        losses.append(float(metrics["loss"]))
    return losses


def test_two_process_global_mesh_matches_oracle():
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, VOCAB, (GLOBAL_BATCH, SEQ)).astype(np.int32)

    oracle = _oracle_two_steps(tokens)

    cluster = Cluster(num_nodes=2, resources_per_node={"CPU": 4})
    try:
        core = connect(cluster.gcs_address)
        try:
            worker_cls = ray_tpu.remote(TrainWorker)
            env_vars = {
                "JAX_PLATFORMS": "cpu",
                "JAX_NUM_CPU_DEVICES": "4",
                "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
                # Disable the axon sitecustomize's eager TPU-jax preload.
                "PALLAS_AXON_POOL_IPS": "",
            }
            workers = [
                worker_cls.options(
                    num_cpus=2, runtime_env={"env_vars": env_vars}
                ).remote(r, 2)
                for r in range(2)
            ]
            # Coordinator address flows through the control plane: rank 0
            # reserves it, the driver publishes to the GCS KV, rank 1 reads
            # it back (the reference broadcasts rank 0's addr the same way).
            coordinator = ray_tpu.get(workers[0].reserve_coordinator.remote(),
                                      timeout=120)
            core.gcs.kv_put("train/coordinator", coordinator.encode())
            addr = core.gcs.kv_get("train/coordinator").decode()
            # Both inits must be in flight together (the service blocks
            # until every process connects).
            counts = ray_tpu.get(
                [w.init_distributed.remote(addr) for w in workers],
                timeout=300)
            assert counts == [8, 8], f"global mesh wrong: {counts}"

            halves = [tokens[:GLOBAL_BATCH // 2], tokens[GLOBAL_BATCH // 2:]]
            refs = [w.train_two_steps.remote(h)
                    for w, h in zip(workers, halves)]
            losses = ray_tpu.get(refs, timeout=300)
            # Every process observed the same (replicated) global loss...
            np.testing.assert_allclose(losses[0], losses[1], rtol=1e-6)
            # ...and it matches the single-process oracle across BOTH steps
            # (step 2 proves the cross-process gradient psum was applied).
            np.testing.assert_allclose(losses[0], oracle, rtol=2e-4, atol=2e-4)
        finally:
            core.shutdown()
            runtime_mod._global_runtime = None
    finally:
        cluster.shutdown()


# ---------------------------------------------------------------------------
# Elastic fault tolerance (SURVEY hard-part #4; reference answer: whole-group
# restart from the last checkpoint, backend_executor.py:121 + FailureConfig)
# ---------------------------------------------------------------------------


def _elastic_loop(config):
    """Deterministic 'training': loss halves each step. Rank 1 kills its own
    PROCESS (kill -9 semantics: no cleanup, no finish() report) at step 3 of
    the FIRST incarnation; the restarted group must resume from the last
    checkpoint, not step 0."""
    import os

    from ray_tpu import train as rt_train

    ctx = rt_train.get_context()
    start_step, loss = 0, 64.0
    ckpt = rt_train.get_checkpoint()
    if ckpt is not None:
        state = ckpt.to_dict()
        start_step, loss = int(state["step"]) + 1, float(state["loss"])

    marker = config["marker"]
    for step in range(start_step, config["steps"]):
        loss = loss / 2.0
        if (ctx.get_world_rank() == 1 and step == 3
                and not os.path.exists(marker)):
            open(marker, "w").close()
            os._exit(1)  # hard死 — simulates a host/process loss
        rt_train.report(
            {"step": step, "loss": loss, "rank": ctx.get_world_rank()},
            checkpoint=(rt_train.Checkpoint.from_dict(
                {"step": step, "loss": loss})
                if ctx.get_world_rank() == 0 else None),
        )


def test_elastic_worker_death_restores_and_resumes(tmp_path):
    """Kill one worker process mid-training: the BackendExecutor detects the
    death (no hang on the round barrier), fit() tears the group down,
    restarts it, restores the last checkpoint, and the loss trajectory
    CONTINUES (values prove resume-from-checkpoint, not restart-from-0)."""
    from ray_tpu.train import (FailureConfig, JaxTrainer, RunConfig,
                               ScalingConfig)

    cluster = Cluster(num_nodes=2, resources_per_node={"CPU": 2})
    try:
        core = connect(cluster.gcs_address)
        try:
            marker = str(tmp_path / "killed-once")
            trainer = JaxTrainer(
                _elastic_loop,
                train_loop_config={"steps": 6, "marker": marker},
                scaling_config=ScalingConfig(num_workers=2,
                                             cpus_per_worker=1),
                run_config=RunConfig(
                    name="elastic",
                    storage_path=str(tmp_path / "results"),
                    failure_config=FailureConfig(max_failures=2),
                ),
            )
            result = trainer.fit()
            assert result.error is None, result.error
            losses = [m["loss"] for m in result.metrics_history]
            # Deterministic halving from 64.0: a restart-from-scratch would
            # repeat the early values; resume continues the series. The
            # kill at step 3 may or may not lose step 2/3's report, so
            # check: monotone halving, last value correct, and the series
            # NEVER rewinds upward (which restart-from-0 would do).
            assert losses[-1] == 64.0 / 2 ** 6, losses
            assert all(b < a for a, b in zip(losses, losses[1:])), losses
            assert os.path.exists(marker), "kill never happened"
        finally:
            core.shutdown()
            runtime_mod._global_runtime = None
    finally:
        cluster.shutdown()


def test_elastic_jax_distributed_world_reforms(tmp_path):
    """After a worker-process death, the restarted group re-forms the
    jax.distributed world (fresh coordinator, full device count) and a
    cross-process psum still produces the right value — XLA's fixed-world
    assumption handled by whole-group restart."""
    from ray_tpu.train import (FailureConfig, JaxConfig, JaxTrainer,
                               RunConfig, ScalingConfig)

    def loop(config):
        import os

        import jax
        import jax.numpy as jnp

        from ray_tpu import train as rt_train

        ctx = rt_train.get_context()
        if (ctx.get_world_rank() == 1
                and not os.path.exists(config["marker"])):
            open(config["marker"], "w").close()
            os._exit(1)
        n_global = len(jax.devices())
        # psum across the whole re-formed world
        from ray_tpu.parallel.mesh import MeshSpec, make_mesh

        mesh = make_mesh(MeshSpec(data=-1), devices=jax.devices())
        import numpy as np
        from jax.sharding import NamedSharding, PartitionSpec as P

        local = np.full((2,), float(ctx.get_world_rank() + 1))
        arr = jax.make_array_from_process_local_data(
            NamedSharding(mesh, P("data")), local)
        total = jax.jit(
            lambda x: jax.numpy.sum(x),
            out_shardings=NamedSharding(mesh, P()))(arr)
        rt_train.report({"devices": n_global, "total": float(total),
                         "incarnation": 2})

    cluster = Cluster(num_nodes=2, resources_per_node={"CPU": 2})
    try:
        core = connect(cluster.gcs_address)
        try:
            marker = str(tmp_path / "jx-killed-once")
            env_vars = {
                "JAX_PLATFORMS": "cpu",
                "JAX_NUM_CPU_DEVICES": "2",
                "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
                "PALLAS_AXON_POOL_IPS": "",
            }
            trainer = JaxTrainer(
                loop,
                train_loop_config={"marker": marker},
                backend_config=JaxConfig(init_distributed=True),
                scaling_config=ScalingConfig(
                    num_workers=2, cpus_per_worker=1,
                    runtime_env={"env_vars": env_vars}),
                run_config=RunConfig(
                    name="elastic-jax",
                    storage_path=str(tmp_path / "results"),
                    failure_config=FailureConfig(max_failures=2),
                ),
            )
            result = trainer.fit()
            assert result.error is None, result.error
            m = result.metrics
            # world re-formed: 2 procs x 2 devices; psum over per-rank
            # contributions (1+1) + (2+2) = 6
            assert m["devices"] == 4, m
            assert m["total"] == 6.0, m
            assert os.path.exists(marker)
        finally:
            core.shutdown()
            runtime_mod._global_runtime = None
    finally:
        cluster.shutdown()
