"""Topology-aware gang scheduling — planner, atomic reservation, preemption.

Covers the round-18 gang path: the ICI-locality planner
(``ClusterResourceScheduler.plan_gang``), atomic gang commit over pinned
revocable cap-N blocks (all-or-nothing, no partial gangs, no orphaned
blocks after daemon death), preemption classes (``gang_priority``), the
shape-indexed placement-group retry filter, the create/remove tombstone
race, and the simulated-cluster harness's determinism.
"""

import contextlib
import os
import threading
import time

import pytest

from ray_tpu.core.config import Config, set_config
from ray_tpu.core.ids import NodeID, PlacementGroupID
from ray_tpu.core.resources import (NodeResources, ResourceSet,
                                    cross_tier_edges, topology_labels,
                                    topology_of)
from ray_tpu.core.scheduler import ClusterResourceScheduler


@contextlib.contextmanager
def _cfg(**flags):
    """Env-backed config override, restored on exit."""
    old = {}
    for k, v in flags.items():
        key = f"RAY_TPU_{k.upper()}"
        old[key] = os.environ.get(key)
        os.environ[key] = str(v)
    set_config(Config())
    try:
        yield
    finally:
        for key, v in old.items():
            if v is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = v
        set_config(Config())


def _wait_for(predicate, timeout=60.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


# ====================== topology vocabulary ======================


def test_cross_tier_edges_counts_dcn_pairs():
    # All in one slice: every pair rides ICI.
    assert cross_tier_edges(["s0", "s0", "s0", "s0"]) == 0
    # 2+2 split: 6 total pairs, 2 intra -> 4 cross.
    assert cross_tier_edges(["s0", "s0", "s1", "s1"]) == 4
    # Fully scattered: every pair crosses.
    assert cross_tier_edges(["s0", "s1", "s2"]) == 3
    assert cross_tier_edges([]) == 0
    assert cross_tier_edges(["s0"]) == 0


def test_topology_of_labels_and_solo_fallback():
    pod, sl, tier = topology_of(topology_labels("podA", "slice3"))
    assert (pod, sl, tier) == ("podA", "slice3", "ici")
    # Unlabeled node: its own singleton slice, so a topology-aware plan
    # never assumes two unlabeled nodes share ICI.
    pod, sl, tier = topology_of({}, fallback="n42")
    assert (pod, sl) == ("pod0", "solo:n42")


# ====================== plan_gang (ICI-locality planner) ======================


def _topo_sched(slices, cpus=16):
    """slices: {slice_id: (pod, n_nodes)} -> (scheduler, {slice: [node_ids]})."""
    sched = ClusterResourceScheduler()
    by_slice = {}
    for slice_id, (pod, n) in slices.items():
        for _ in range(n):
            nid = NodeID.from_random()
            sched.add_node(nid, NodeResources(
                ResourceSet({"CPU": float(cpus)}),
                labels=topology_labels(pod, slice_id)))
            by_slice.setdefault(slice_id, []).append(nid)
    return sched, by_slice


def test_plan_gang_fits_single_slice_zero_edges():
    sched, by_slice = _topo_sched({"s0": ("p0", 4), "s1": ("p0", 4)})
    plan = sched.plan_gang([ResourceSet({"CPU": 8})] * 8)  # 64 CPU = 1 slice
    assert plan is not None and len(plan) == 8
    assert cross_tier_edges([sched.node_slice(n) for n in plan]) == 0


def test_plan_gang_best_fit_prefers_tightest_slice():
    # s0 is smaller but big enough: best-fit must take it, keeping the
    # large slice open for larger gangs.
    sched, by_slice = _topo_sched({"s0": ("p0", 2), "s1": ("p0", 8)})
    plan = sched.plan_gang([ResourceSet({"CPU": 16})] * 2)
    assert plan is not None
    assert set(plan) == set(by_slice["s0"])


def test_plan_gang_forced_spill_minimal_edges():
    # Gang of 6 full hosts > any slice (4 hosts) -> must spill, but onto
    # exactly TWO slices (4+2), not three: 8 cross edges, the minimum.
    sched, _ = _topo_sched({"s0": ("p0", 4), "s1": ("p0", 4),
                            "s2": ("p1", 4)})
    plan = sched.plan_gang([ResourceSet({"CPU": 16})] * 6)
    assert plan is not None
    slices = [sched.node_slice(n) for n in plan]
    assert len(set(slices)) == 2
    assert cross_tier_edges(slices) == 4 * 2  # 4-host group x 2-host group


def test_plan_gang_spill_prefers_used_pod():
    # Both spill candidates can absorb the remainder equally; the one in
    # the pod the gang already landed in must win (spill stays pod-local).
    sched, by_slice = _topo_sched({"s0": ("pA", 4), "s1": ("pA", 4),
                                   "s2": ("pB", 4)})
    plan = sched.plan_gang([ResourceSet({"CPU": 16})] * 6)
    pods = {topology_of({"topo.pod": "pA"} if n in by_slice["s0"] + by_slice["s1"]
                        else {"topo.pod": "pB"})[0] for n in plan}
    assert pods == {"pA"}


def test_plan_gang_strict_slice_requires_single_slice():
    sched, _ = _topo_sched({"s0": ("p0", 2), "s1": ("p0", 2)})
    reqs = [ResourceSet({"CPU": 16})] * 3  # 3 hosts > any one slice
    assert sched.plan_gang(reqs, strict_slice=True) is None
    # Relaxed (PACK) spills instead of failing.
    assert sched.plan_gang(reqs, strict_slice=False) is not None


def test_plan_gang_blind_ignores_slices():
    sched, _ = _topo_sched({"s0": ("p0", 2), "s1": ("p0", 2)})
    plan = sched.plan_gang([ResourceSet({"CPU": 16})] * 4,
                           topology_aware=False)
    assert plan is not None and len(plan) == 4
    # And None when the gang simply cannot fit.
    assert sched.plan_gang([ResourceSet({"CPU": 16})] * 5,
                           topology_aware=False) is None


def test_plan_gang_is_pure_planning():
    sched, _ = _topo_sched({"s0": ("p0", 2)})
    before = sched.available_resources()
    assert sched.plan_gang([ResourceSet({"CPU": 4})] * 2) is not None
    assert sched.available_resources() == before


# ====================== GCS gang path (SimCluster) ======================


def _sim(n, **kw):
    from ray_tpu.core.sim_cluster import SimCluster
    kw.setdefault("heartbeat", False)
    return SimCluster(n, **kw)


def _gang_blocks(svc, pg_id=None):
    return [b for b in svc._blocks.values()
            if b.pg_id is not None and (pg_id is None or b.pg_id == pg_id)]


def test_gang_commit_creates_pinned_blocks_and_remove_revokes():
    with _cfg(gang_scheduling_enabled=1, health_check_period_s=3600):
        cluster = _sim(8)  # one 8-node slice (hosts_per_slice=16 default)
        try:
            svc = cluster.svc
            total = svc.cluster_resources()["CPU"]
            pg = cluster.create_gang([{"CPU": 4.0}] * 4, strategy="PACK")
            assert svc.get_placement_group(pg)["state"] == "CREATED"
            blocks = _gang_blocks(svc, pg)
            assert blocks and sum(b.total for b in blocks) == 4
            # Daemon-side: the pushed blocks are pinned (idle-TTL exempt).
            adopted = [d for d in cluster.daemons if d.lease_table.stats()]
            assert adopted
            for d in adopted:
                assert all(st["pinned"]
                           for st in d.lease_table.stats().values())
                assert d.lease_table.sweep_idle(0.0) == []  # pinned: no sweep
            cluster.remove_gang(pg)
            assert not _gang_blocks(svc)
            assert svc.cluster_resources()["CPU"] == total
            assert all(st["revoked"] for d in cluster.daemons
                       for st in d.lease_table.stats().values())
        finally:
            cluster.shutdown()


def test_gang_atomicity_no_partial_on_infeasible():
    with _cfg(gang_scheduling_enabled=1, health_check_period_s=3600):
        cluster = _sim(4, cpus_per_node=8)
        try:
            svc = cluster.svc
            before = svc.cluster_resources()["CPU"]
            with pytest.raises(TimeoutError):
                # 5 full hosts on a 4-host cluster: must time out with
                # NOTHING reserved, not 4 bundles placed and one stuck.
                cluster.create_gang([{"CPU": 8.0}] * 5, timeout=0.3)
            assert svc.cluster_resources()["CPU"] == before
            assert not _gang_blocks(svc)
        finally:
            cluster.shutdown()


def test_gang_survives_member_daemon_sigkill():
    """A gang member's daemon dies mid-life: its cap-N blocks must be
    forgotten (not orphaned), the gang reschedules, and cluster capacity
    reconverges to the surviving nodes' total."""
    with _cfg(gang_scheduling_enabled=1, health_check_period_s=3600):
        cluster = _sim(6, cpus_per_node=8)
        try:
            svc = cluster.svc
            pg = cluster.create_gang([{"CPU": 8.0}] * 4)
            victim_node = cluster.gang_nodes(pg)[0]
            victim_idx = next(i for i, d in enumerate(cluster.daemons)
                              if d.node_id == victim_node)
            cluster.kill_node(victim_idx)  # SIGKILL posture, declared dead
            # No orphaned blocks on the dead node.
            assert all(b.node_id != victim_node for b in svc._blocks.values())
            # The gang re-placed onto survivors (2 spare hosts remain).
            assert _wait_for(lambda: svc.get_placement_group(pg)["state"]
                             == "CREATED", timeout=10)
            assert victim_node not in cluster.gang_nodes(pg)
            # Capacity reconverges: 5 surviving hosts, 4 reserved.
            avail = svc.scheduler.available_resources().get("CPU", 0)
            assert avail == 8.0
        finally:
            cluster.shutdown()


def test_gang_strict_pack_lands_in_one_slice():
    with _cfg(gang_scheduling_enabled=1, sim_hosts_per_slice=4,
              health_check_period_s=3600):
        cluster = _sim(12)  # 3 slices of 4
        try:
            pg = cluster.create_gang([{"CPU": 16.0}] * 4,
                                     strategy="STRICT_PACK")
            assert cluster.gang_cross_tier_edges(pg) == 0
            assert len(set(cluster.gang_nodes(pg))) == 4
        finally:
            cluster.shutdown()


def test_gang_disabled_reproduces_legacy_placement():
    with _cfg(gang_scheduling_enabled=0, health_check_period_s=3600):
        cluster = _sim(4, cpus_per_node=16)
        try:
            svc = cluster.svc
            # Legacy STRICT_PACK = strict ONE NODE (not one slice).
            pg = cluster.create_gang([{"CPU": 8.0}] * 2,
                                     strategy="STRICT_PACK")
            assert len(set(cluster.gang_nodes(pg))) == 1
            # And the legacy path mints no gang blocks.
            assert not _gang_blocks(svc)
        finally:
            cluster.shutdown()


def test_preemption_class_ordering_and_floor():
    with _cfg(gang_scheduling_enabled=1, health_check_period_s=3600):
        cluster = _sim(8, cpus_per_node=8)
        try:
            svc = cluster.svc
            low_old = cluster.create_gang([{"CPU": 8.0}] * 2, gang_priority=0)
            low_new = cluster.create_gang([{"CPU": 8.0}] * 2, gang_priority=0)
            mid = cluster.create_gang([{"CPU": 8.0}] * 2, gang_priority=50)
            high = cluster.create_gang([{"CPU": 8.0}] * 2, gang_priority=100)
            # Cluster full; serve (class 100) needs 2 hosts.
            n = svc.preempt_gangs({"CPU": 8.0}, count=2, min_priority=100)
            assert n == 1
            # Victim = lowest class, NEWEST first; >=min_priority untouched.
            assert svc.get_placement_group(low_new)["state"] == "PREEMPTED"
            assert svc.get_placement_group(low_old)["state"] == "CREATED"
            assert svc.get_placement_group(mid)["state"] == "CREATED"
            assert svc.get_placement_group(high)["state"] == "CREATED"
            assert not _gang_blocks(svc, low_new)  # blocks revoked
            # A lease against the preempted group fails FAST.
            from ray_tpu.core.task_spec import \
                PlacementGroupSchedulingStrategy
            with pytest.raises(RuntimeError, match="preempted"):
                svc.request_lease(
                    {"CPU": 1.0},
                    PlacementGroupSchedulingStrategy(
                        placement_group=low_new,
                        placement_group_bundle_index=0),
                    timeout=5.0)
            # Enough capacity already free: preemption is a no-op.
            assert svc.preempt_gangs({"CPU": 8.0}, count=2,
                                     min_priority=100) == 0
        finally:
            cluster.shutdown()


def test_preemption_disabled_by_flag():
    with _cfg(gang_scheduling_enabled=1, gang_preemption_enabled=0,
              health_check_period_s=3600):
        cluster = _sim(2, cpus_per_node=8)
        try:
            cluster.create_gang([{"CPU": 8.0}] * 2, gang_priority=0)
            assert cluster.svc.preempt_gangs({"CPU": 8.0}, count=1,
                                             min_priority=100) == 0
        finally:
            cluster.shutdown()


def test_create_remove_race_tombstone_no_leak():
    """remove_placement_group racing a blocked create: the create must NOT
    commit afterwards (a gang nobody can ever remove again = leaked
    capacity). The tombstone fails it cleanly once capacity arrives."""
    with _cfg(gang_scheduling_enabled=1, health_check_period_s=3600):
        from ray_tpu.core.gcs_server import GcsService
        svc = GcsService()
        try:
            pg_id = PlacementGroupID.from_random()
            err = []
            t = threading.Thread(
                target=lambda: err.append(
                    _raises(lambda: svc.create_placement_group(
                        pg_id, "", [{"CPU": 4.0}] * 2, "PACK",
                        timeout=30.0))))
            t.start()  # no nodes yet: parks in the retry loop
            _wait_for(lambda: t.is_alive(), timeout=5)
            time.sleep(0.1)
            svc.remove_placement_group(pg_id)  # unknown pg -> tombstone
            # Capacity arrives; the parked create wakes, sees the
            # tombstone, and fails instead of committing.
            svc.register_node(NodeID.from_random(), "127.0.0.1:0",
                              {"CPU": 64.0}, {})
            t.join(timeout=10)
            assert not t.is_alive()
            assert isinstance(err[0], RuntimeError)
            assert pg_id not in svc._pgs
            assert not _gang_blocks(svc)
            assert svc.scheduler.available_resources()["CPU"] == 64.0
        finally:
            svc.shutdown()


def _raises(fn):
    try:
        fn()
        return None
    except Exception as e:  # noqa: BLE001 — the exception IS the result
        return e


# ====================== in-process manager satellites ======================


class _StubRuntime:
    def __init__(self):
        self.scheduler = ClusterResourceScheduler()
        self.freed = 0

    def _on_resources_freed(self):
        self.freed += 1


def _manager(rt):
    from ray_tpu.core.placement_group import PlacementGroupManager
    return PlacementGroupManager(rt)


def test_retry_pending_shape_filter_skips_unfittable():
    rt = _StubRuntime()
    nid = NodeID.from_random()
    rt.scheduler.add_node(nid, NodeResources(ResourceSet({"CPU": 4})))
    mgr = _manager(rt)
    # A TPU gang can never fit on this CPU node: stays PENDING.
    tpu = mgr.create([{"TPU": 4.0}], "PACK")
    assert tpu.state == "PENDING"
    mgr.retry_pending()
    assert mgr.wake_stats == {"wakes": 0, "skips": 1}
    # A CPU release storm keeps skipping it (no full placement walk)...
    for _ in range(3):
        mgr.retry_pending()
    assert mgr.wake_stats["skips"] == 4 and mgr.wake_stats["wakes"] == 0
    # ...until a TPU node joins: one wake, group placed.
    rt.scheduler.add_node(NodeID.from_random(),
                          NodeResources(ResourceSet({"TPU": 8})))
    mgr.retry_pending()
    assert tpu.state == "CREATED"
    assert mgr.wake_stats["wakes"] == 1


def test_retry_pending_strict_pack_uses_total_shape():
    rt = _StubRuntime()
    rt.scheduler.add_node(NodeID.from_random(),
                          NodeResources(ResourceSet({"CPU": 4})))
    rt.scheduler.add_node(NodeID.from_random(),
                          NodeResources(ResourceSet({"CPU": 4})))
    mgr = _manager(rt)
    # Each bundle fits SOME node, but the STRICT_PACK total (6 CPU) fits
    # none -> the total-shape filter skips without attempting.
    g = mgr.create([{"CPU": 3.0}, {"CPU": 3.0}], "STRICT_PACK")
    assert g.state == "PENDING"
    mgr.retry_pending()
    assert mgr.wake_stats["skips"] == 1 and mgr.wake_stats["wakes"] == 0


def test_manager_remove_during_retry_rolls_back():
    """The 2PC race the commit guard closes: a group removed while its
    retry is mid-flight must not strand reservations."""
    rt = _StubRuntime()
    mgr = _manager(rt)
    g = mgr.create([{"CPU": 2.0}], "PACK")  # no nodes: PENDING
    assert g.state == "PENDING"
    g.state = "REMOVED"  # remove() won the race mid-retry
    rt.scheduler.add_node(NodeID.from_random(),
                          NodeResources(ResourceSet({"CPU": 4})))
    with mgr._lock:
        mgr._try_place_locked(g)  # the in-flight retry commits...
    # ...and the guard rolled it back: nothing stays allocated.
    assert rt.scheduler.available_resources()["CPU"] == 4.0
    assert all(b.node_id is None for b in g.bundles)


def test_manager_preempt_lower_orders_and_frees():
    with _cfg(gang_preemption_enabled=1):
        rt = _StubRuntime()
        for _ in range(2):
            rt.scheduler.add_node(NodeID.from_random(),
                                  NodeResources(ResourceSet({"CPU": 8})))
        mgr = _manager(rt)
        old = mgr.create([{"CPU": 8.0}], "PACK", gang_priority=0)
        new = mgr.create([{"CPU": 8.0}], "PACK", gang_priority=0)
        assert old.state == new.state == "CREATED"
        assert mgr.preempt_lower({"CPU": 8.0}, count=1, min_priority=100) == 1
        assert new.state == "PREEMPTED" and old.state == "CREATED"
        assert rt.scheduler.available_resources()["CPU"] == 8.0
        assert rt.freed == 1
        # when_ready on a preempted group refuses (caller recreates).
        assert mgr.when_ready(new.pg_id, lambda: None) is False


# ====================== serve-side preemption hook ======================


def test_gang_preemption_rate_limit_and_gate():
    from ray_tpu.serve.autoscaling import SERVE_GANG_PRIORITY, GangPreemption

    calls = []

    def preempt(shape, count, min_priority):
        calls.append((shape, count, min_priority))
        return 1

    with _cfg(gang_preemption_enabled=1):
        gp = GangPreemption(preempt, min_interval_s=10.0)
        assert gp.maybe_reclaim("d", {"TPU": 4.0}, 2, now=100.0) == 1
        assert calls == [({"TPU": 4.0}, 2, SERVE_GANG_PRIORITY)]
        # Within the window: rate-limited, no second strip.
        assert gp.maybe_reclaim("d", {"TPU": 4.0}, 2, now=105.0) == 0
        # Another deployment has its own window.
        assert gp.maybe_reclaim("e", {"TPU": 4.0}, 1, now=105.0) == 1
        # Past the window: allowed again.
        assert gp.maybe_reclaim("d", {"TPU": 4.0}, 1, now=111.0) == 1
        assert len(calls) == 3
        # count<=0 never calls out.
        assert gp.maybe_reclaim("d", {"TPU": 4.0}, 0, now=200.0) == 0
    with _cfg(gang_preemption_enabled=0):
        gp = GangPreemption(preempt)
        assert gp.maybe_reclaim("d", {"TPU": 4.0}, 2, now=300.0) == 0
        assert len(calls) == 3
    # A raising preempt callable is advisory: swallowed, returns 0.
    with _cfg(gang_preemption_enabled=1):
        gp = GangPreemption(lambda *a: 1 / 0)
        assert gp.maybe_reclaim("d", {"TPU": 4.0}, 1, now=400.0) == 0


# ====================== sim harness determinism / watchdog ======================


def _digest_run(n, seed):
    with _cfg(gang_scheduling_enabled=1, health_check_period_s=3600):
        cluster = _sim(n, seed=seed)
        try:
            digests = []
            for k in range(6):
                pg = cluster.create_gang([{"CPU": 4.0}] * 8,
                                         gang_priority=k % 3)
                digests.append(cluster.placement_digest(pg))
                digests.append(str(cluster.gang_cross_tier_edges(pg)))
            return "|".join(digests)
        finally:
            cluster.shutdown()


def test_sim_determinism_smoke():
    # CI smoke at 48 nodes: same seed -> identical placements; different
    # seed -> different node identities (the shuffle matters).
    assert _digest_run(48, seed=7) == _digest_run(48, seed=7)
    assert _digest_run(48, seed=7) != _digest_run(48, seed=8)


@pytest.mark.slow
def test_sim_determinism_300_nodes():
    assert _digest_run(300, seed=7) == _digest_run(300, seed=7)


def test_sim_watchdog_detects_silent_heartbeat_stop():
    from ray_tpu.core.sim_cluster import wait_for
    with _cfg(health_check_period_s=0.1, health_check_failure_threshold=3,
              sim_heartbeat_period_s=0.05):
        cluster = _sim(8, heartbeat=True)
        try:
            victim = cluster.daemons[3]
            assert wait_for(
                lambda: cluster.svc.heartbeat(victim.node_id) == "ok",
                timeout=10.0)
            cluster.stop_heartbeat(3)
            t0 = time.monotonic()
            assert wait_for(
                lambda: victim.node_id in cluster.svc._dead_nodes,
                timeout=15.0)
            # period * threshold = 0.3s budget; detection well under 5s.
            assert time.monotonic() - t0 < 10.0
            # The dead node left the scheduler; survivors keep placing.
            pg = cluster.create_gang([{"CPU": 4.0}] * 2)
            assert victim.node_id not in cluster.gang_nodes(pg)
        finally:
            cluster.shutdown()
