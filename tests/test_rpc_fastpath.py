"""Control-plane fast path regression tests.

Covers the four tentpole guarantees of the coalescing/cached-encoding RPC
layer (ISSUE 1):

(a) frames written through the coalescing sender decode identically to
    singleton sends — property-style round trip over mixed small / large /
    out-of-band frames;
(b) a blocking call on a freshly submitted request is never delayed by the
    coalescing window;
(c) the cached task-spec encoding invalidates when the actor handle or the
    resource spec changes (content-addressed digests);
(d) batched (coalesced) task-finish reports resolve every inlined return
    exactly once.
"""

import random
import socket
import threading
import time

import numpy as np
import pytest

from ray_tpu.core import rpc, serialization
from ray_tpu.core.ids import ActorID, JobID, TaskID
from ray_tpu.core.rpc import (RpcClient, RpcServer, _dumps_frame,
                              _FrameSender, _LEN, _recv_frame, _SockReader)
from ray_tpu.core.task_spec import (SpecCacheMiss, SpecEncoder,
                                    SpecTemplateStore, TaskArg, TaskOptions,
                                    TaskSpec, TaskType, spec_var_fields)


# ---------------------------------------------------------------------------
# (a) coalesced frames decode identically to singletons
# ---------------------------------------------------------------------------


def _mixed_messages(seed: int, n: int):
    """Mixed small / large / out-of-band message population."""
    rng = random.Random(seed)
    msgs = []
    for i in range(n):
        pick = rng.random()
        if pick < 0.4:
            data = {"i": i, "s": "x" * rng.randrange(0, 200)}
        elif pick < 0.7:
            data = list(range(rng.randrange(0, 64)))
        elif pick < 0.9:
            # Above OOB_MIN_BYTES: stripped from the pickle stream and
            # streamed raw after the wrapper frame.
            data = np.arange(rng.randrange(40_000, 80_000), dtype=np.float64)
        else:
            data = b"y" * rng.randrange(300_000, 400_000)
        msgs.append(("req", i, "echo", data))
    return msgs


def _roundtrip_through_sender(msgs, window_s):
    """Write every message through ONE _FrameSender over a socketpair
    (coalescing on), read them back with the framed receiver."""
    a, b = socket.socketpair()
    try:
        sender = _FrameSender(a, window_s=window_s)
        got = []
        done = threading.Event()

        def read_loop():
            reader = _SockReader(b)
            try:
                for _ in msgs:
                    got.append(_recv_frame(reader))
            finally:
                done.set()

        t = threading.Thread(target=read_loop, daemon=True)
        t.start()
        for m in msgs:
            frame, bufs, raws = _dumps_frame(m)
            sender.send([_LEN.pack(len(frame)), frame, *bufs], raws,
                        urgent=False)
        sender.flush()
        assert done.wait(30), "receiver did not drain all frames"
        return got
    finally:
        a.close()
        b.close()


@pytest.mark.parametrize("window_s", [0.0, 0.002])
def test_coalesced_frames_decode_identically(window_s):
    msgs = _mixed_messages(seed=7, n=60)
    got = _roundtrip_through_sender(msgs, window_s)
    assert len(got) == len(msgs)
    for sent, rec in zip(msgs, got):
        assert rec[0] == sent[0] and rec[1] == sent[1] and rec[2] == sent[2]
        sd, rd = sent[3], rec[3]
        if isinstance(sd, np.ndarray):
            assert np.array_equal(np.asarray(rd), sd)
        elif isinstance(sd, (bytes, bytearray)):
            assert bytes(rd) == bytes(sd)
        else:
            assert rd == sd


def test_concurrent_senders_coalesce_without_corruption():
    """Many threads hammering one sender: frames interleave atomically (no
    torn frames), every frame arrives exactly once, and at least some
    syscalls carried more than one frame."""
    a, b = socket.socketpair()
    try:
        rpc.reset_send_stats()
        sender = _FrameSender(a, window_s=0.0)
        n_threads, per_thread = 8, 40
        total = n_threads * per_thread
        got = []
        done = threading.Event()

        def read_loop():
            reader = _SockReader(b)
            for _ in range(total):
                got.append(_recv_frame(reader))
            done.set()

        threading.Thread(target=read_loop, daemon=True).start()

        def send_many(tid):
            for i in range(per_thread):
                m = ("note", 0, "m", (tid, i, "p" * (i % 50)))
                frame, bufs, raws = _dumps_frame(m)
                sender.send([_LEN.pack(len(frame)), frame, *bufs], raws,
                            urgent=False)

        threads = [threading.Thread(target=send_many, args=(t,), daemon=True)
                   for t in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        assert done.wait(30)
        seen = {d[3][:2] for d in got}
        assert len(seen) == total  # every frame exactly once, none torn
        stats = rpc.send_stats()
        assert stats["frames"] >= total
        assert stats["syscalls"] < stats["frames"]  # some batching happened
    finally:
        a.close()
        b.close()


def test_raw_release_fires_exactly_once_through_sender():
    """Raw release hooks fire exactly once after the coalesced write."""
    a, b = socket.socketpair()
    try:
        sender = _FrameSender(a, window_s=0.0)
        fired = []
        payload = np.arange(100_000, dtype=np.float64)  # > OOB_MIN_BYTES
        raw = rpc.Raw(payload, release=lambda: fired.append(1))
        frame, bufs, raws = _dumps_frame(("note", 0, "m", raw))
        assert raws, "Raw wrapper should have been collected"
        got = []

        def read_loop():
            got.append(_recv_frame(_SockReader(b)))

        t = threading.Thread(target=read_loop, daemon=True)
        t.start()
        sender.send([_LEN.pack(len(frame)), frame, *bufs], raws)
        t.join(15)
        assert fired == [1]
        assert np.array_equal(
            np.frombuffer(got[0][3], dtype=np.float64), payload)
    finally:
        a.close()
        b.close()


# ---------------------------------------------------------------------------
# (b) blocking calls never wait on the coalescing window
# ---------------------------------------------------------------------------


class _Echo:
    def echo(self, x):
        return x

    def ping(self):
        return "pong"


def test_blocking_call_not_delayed_by_window():
    """Even with an absurd window forced on and the connection marked hot,
    urgent request frames and the pre-wait flush keep blocking calls fast."""
    server = RpcServer(_Echo(), name="win")
    client = RpcClient(server.address)
    try:
        client.call("ping", timeout=10)  # connect + warm
        # Force a huge window on the CLIENT's sender and mark it hot, as if
        # heavy coalescing had just happened.
        sender = client._sender
        sender._window = 0.5
        sender._hot_until = time.monotonic() + 60.0
        t0 = time.perf_counter()
        for _ in range(5):
            assert client.call("echo", 1, timeout=10) == 1
        elapsed = time.perf_counter() - t0
        assert elapsed < 0.4, (
            f"blocking calls took {elapsed:.3f}s — delayed by the window")
    finally:
        client.close()
        server.stop()


def test_flush_releases_window_wait():
    """A non-urgent frame sitting in a window wait goes out immediately on
    flush() rather than after the full window."""
    a, b = socket.socketpair()
    try:
        sender = _FrameSender(a, window_s=5.0)
        sender._hot_until = time.monotonic() + 60.0  # arm the window
        # Prime: a first frame makes the NEXT drain see a hot connection.
        frame, _, _ = _dumps_frame(("note", 0, "warm", None))
        sender.send([_LEN.pack(len(frame)), frame], urgent=False)
        reader = _SockReader(b)
        _recv_frame(reader)

        got = []
        done = threading.Event()

        def read_one():
            got.append(_recv_frame(reader))
            done.set()

        threading.Thread(target=read_one, daemon=True).start()
        frame, _, _ = _dumps_frame(("note", 0, "slow", 42))
        t = threading.Thread(
            target=lambda: sender.send([_LEN.pack(len(frame)), frame],
                                       urgent=False), daemon=True)
        t0 = time.perf_counter()
        t.start()
        time.sleep(0.05)  # let it enter the window wait
        sender.flush()
        assert done.wait(3), "flush did not release the window wait"
        assert time.perf_counter() - t0 < 2.0  # far below the 5s window
        assert got[0][3] == 42
    finally:
        a.close()
        b.close()


# ---------------------------------------------------------------------------
# (c) cached task-spec encoding + invalidation
# ---------------------------------------------------------------------------


def _make_spec(options=None, caller="caller-1", actor=None, seq=0,
               args=(3,)):
    job = JobID.from_int(1)
    return TaskSpec(
        task_id=TaskID.for_task(job),
        job_id=job,
        task_type=TaskType.ACTOR_TASK if actor is not None
        else TaskType.NORMAL_TASK,
        function_id="fn:f:abcd",
        function_name="f",
        args=[TaskArg(value=a) for a in args],
        kwargs={},
        options=options or TaskOptions(),
        actor_id=actor,
        actor_method="m" if actor is not None else None,
        sequence_number=seq,
        caller_id=caller,
        owner_addr="127.0.0.1:1",
    )


def test_spec_roundtrip_and_template_memo():
    opts = TaskOptions(resources={"CPU": 1.0})
    enc = SpecEncoder(cap=16)
    store = SpecTemplateStore(cap=16)
    s1 = _make_spec(options=opts, seq=1)
    s2 = _make_spec(options=opts, seq=2, args=(99,))
    d1, t1 = enc.encode_template(s1)
    d2, _t2 = enc.encode_template(s2)
    assert d1 == d2  # same callable -> same template
    assert enc.encode_hits == 1 and enc.encode_misses == 1
    store.register(d1, t1)
    for s in (s1, s2):
        dec = store.decode((d1, enc.encode_vars(s)))
        assert dec.sequence_number == s.sequence_number
        assert dec.args[0].value == s.args[0].value
        assert dec.options.resources == {"CPU": 1.0}
        assert dec.function_id == s.function_id
        assert dec.owner_addr == s.owner_addr
        # Full fidelity against the legacy whole-spec pickle.
        legacy = serialization.loads(serialization.dumps(s))
        assert spec_var_fields(dec) == spec_var_fields(legacy)


def test_spec_cache_invalidates_on_resource_change():
    enc = SpecEncoder(cap=16)
    d1, _ = enc.encode_template(
        _make_spec(options=TaskOptions(resources={"CPU": 1.0})))
    d2, t2 = enc.encode_template(
        _make_spec(options=TaskOptions(resources={"CPU": 2.0})))
    assert d1 != d2, "changed resource spec must change the digest"
    store = SpecTemplateStore(cap=16)
    store.register(d2, t2)
    dec = store.decode(
        (d2, enc.encode_vars(
            _make_spec(options=TaskOptions(resources={"CPU": 2.0})))))
    assert dec.options.resources == {"CPU": 2.0}


def test_spec_cache_invalidates_on_actor_handle_change():
    enc = SpecEncoder(cap=16)
    opts = TaskOptions()
    a1 = ActorID(b"\x01" * 16)
    a2 = ActorID(b"\x02" * 16)
    d1, _ = enc.encode_template(_make_spec(options=opts, actor=a1))
    d2, _ = enc.encode_template(_make_spec(options=opts, actor=a2))
    assert d1 != d2, "a different actor must change the digest"
    # Same actor, different handle (caller_id) -> also a fresh digest.
    d3, _ = enc.encode_template(
        _make_spec(options=opts, actor=a1, caller="caller-2"))
    assert d3 != d1


def test_spec_store_miss_raises_and_legacy_bytes_pass_through():
    store = SpecTemplateStore(cap=4)
    enc = SpecEncoder(cap=4)
    spec = _make_spec()
    with pytest.raises(SpecCacheMiss):
        store.decode((b"\x00" * 16, enc.encode_vars(spec)))
    dec = store.decode(serialization.dumps(spec))
    assert dec.function_name == "f" and dec.args[0].value == 3


def test_spec_store_eviction_is_bounded():
    store = SpecTemplateStore(cap=4)
    enc = SpecEncoder(cap=64)
    digests = []
    for i in range(8):
        s = _make_spec(options=TaskOptions(resources={"CPU": float(i + 1)}))
        d, t = enc.encode_template(s)
        store.register(d, t)
        digests.append((d, s))
    # Oldest evicted -> SpecCacheMiss; newest still decode.
    with pytest.raises(SpecCacheMiss):
        store.decode((digests[0][0], enc.encode_vars(digests[0][1])))
    d, s = digests[-1]
    assert store.decode((d, enc.encode_vars(s))).options.resources == {
        "CPU": 8.0}


# ---------------------------------------------------------------------------
# (d) batched finish reports resolve every inlined return exactly once
# ---------------------------------------------------------------------------


class _SlowStart:
    """Handler whose replies are released in a burst, forcing the server's
    reply sender to coalesce many small finish reports."""

    def __init__(self):
        self.gate = threading.Event()

    def open_gate(self):
        self.gate.set()
        return True

    def finish(self, i):
        self.gate.wait(20)
        return {"i": i, "value": i * 2}


def test_batched_finish_reports_resolve_exactly_once():
    handler = _SlowStart()
    server = RpcServer(handler, name="batch", max_workers=32)
    client = RpcClient(server.address)
    try:
        n = 24
        counts = [0] * n
        futs = [client.call_async("finish", i) for i in range(n)]
        for i, f in enumerate(futs):
            f.add_done_callback(
                lambda fut, i=i: counts.__setitem__(i, counts[i] + 1))
        # Release all handlers at once: their replies land on the reply
        # sender back-to-back and coalesce into scatter-gather batches.
        assert client.call("open_gate", timeout=10) is True
        for i, f in enumerate(futs):
            assert f.result(timeout=30) == {"i": i, "value": i * 2}
        time.sleep(0.1)
        assert counts == [1] * n, "every reply must resolve exactly once"
    finally:
        client.close()
        server.stop()


def test_rpc_send_stats_shape():
    stats = rpc.send_stats()
    for key in ("frames", "syscalls", "bytes", "frames_per_syscall"):
        assert key in stats


def test_lazy_lineage_rebuild_does_not_leak_arg_refs():
    """Cached-template tasks rebuild their lineage pickle lazily INSIDE
    _package_results's collecting_refs scope; the rebuild must use a
    private collection scope so the spec's argument refs are never
    registered as contained-in-return (they would pin the caller as a
    borrower of refs the return value doesn't hold)."""
    from ray_tpu.core.ids import ObjectID
    from ray_tpu.core.object_ref import ObjectRef
    from ray_tpu.core.worker_main import _lineage_bytes

    ref = ObjectRef(ObjectID.nil(), owner_hint="127.0.0.1:9")
    spec = _make_spec(args=({"nested": ref},))
    with serialization.collecting_refs() as outer:
        blob = _lineage_bytes(spec)
    assert outer == [], "lineage rebuild leaked arg refs into outer scope"
    # Sanity: the same dump WITHOUT the private scope does collect — the
    # guard above is meaningful.
    with serialization.collecting_refs() as outer2:
        serialization.dumps(spec)
    assert outer2, "expected the unshielded dump to collect the nested ref"
    # And the blob still round-trips to a full spec.
    dec = serialization.loads(blob)
    assert dec.args[0].value["nested"].id == ref.id


def test_strict_serial_admission_tolerates_long_execution():
    """Strict serial ordering holds the admission cursor for a call's whole
    runtime; a successor's starvation deadline must treat an EXECUTING
    predecessor as progress (a legitimately slow method is not a lost
    sequence number) — while a true gap still times out."""
    from ray_tpu.core.ids import ActorID as AID
    from ray_tpu.core.worker_main import WorkerService, _ActorState

    state = _ActorState(AID.nil(), object(), max_concurrency=1)
    svc = object.__new__(WorkerService)  # only _admit_in_order is used

    s0 = _make_spec(seq=0)
    s1 = _make_spec(seq=1)
    # A real pipelined client reports its lowest UNACKED seq: s0 is still
    # executing (unacked), so window_min must be 0 — the transport-less
    # default (own seq) would wrongly fast-forward admission past s0.
    s1.window_min = 0
    # seq0 admitted without bumping (strict): cursor held, executing marked.
    svc._admit_in_order(state, s0, bump=False)
    assert state.executing.get(s0.caller_id) == 0

    errors, done = [], threading.Event()

    def successor():
        try:
            # Far below the wall time we hold seq0 "executing": would raise
            # TimeoutError without the executing-progress rule.
            svc._admit_in_order(state, s1, timeout=1.2)
        except BaseException as e:  # noqa: BLE001
            errors.append(e)
        finally:
            done.set()

    t = threading.Thread(target=successor, daemon=True)
    t.start()
    time.sleep(2.5)  # longer than the successor's starvation timeout
    assert not done.is_set(), "successor should still be waiting on seq0"
    # seq0 "finishes": clear executing, bump, notify (run_actor_task's
    # strict finally).
    with state.cv:
        del state.executing[s0.caller_id]
        state.next_seq[s0.caller_id] = 1
        state.cv.notify_all()
    assert done.wait(10) and not errors, errors

    # True gap (nothing executing, cursor stuck): times out.
    s3 = _make_spec(seq=3)
    s3.window_min = 1  # seqs 1-2 claimed outstanding but never arrive
    with pytest.raises(TimeoutError):
        svc._admit_in_order(state, s3, timeout=1.0)
