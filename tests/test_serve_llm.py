"""Continuous-batching LLM serving tests (ISSUE 9).

Engine-level: the slotted continuous-batching ``LLMEngine`` must be
token-identical to the single-sequence ``Generator`` oracle under staggered
concurrent arrivals, retire/refill slots under load, shed with ``Saturated``
at the admission queue limit while in-flight requests complete, and keep
decode-rate counters per-request. Serve-level: the same engine behind
``llm_deployment`` through the full data plane (handle → router → replica),
plus KV-occupancy-aware routing units on the Router itself.
"""

import threading
import time

import jax
import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.models import generate, transformer
from ray_tpu.serve.errors import Saturated
from ray_tpu.serve.handle import Router
from ray_tpu.serve.llm import LLMEngine, llm_deployment


@pytest.fixture(scope="module")
def tiny_model():
    cfg = transformer.tiny(max_seq_len=64)
    params = transformer.init_params(cfg, jax.random.key(0))
    return cfg, params


@pytest.fixture(scope="module")
def oracle(tiny_model):
    """Single-sequence reference decode (memoized — it is the slow path)."""
    cfg, params = tiny_model
    gen = generate.Generator(params, cfg)
    memo = {}

    def run(prompt, n, temperature=0.0, seed=0):
        key = (tuple(prompt), n, temperature, seed)
        if key not in memo:
            memo[key] = gen.generate(
                list(prompt), max_new_tokens=n,
                temperature=temperature, seed=seed)
        return memo[key]

    return run


@pytest.fixture(scope="module")
def engine(tiny_model):
    """Shared slots=2 engine — tests drain it before finishing."""
    cfg, params = tiny_model
    eng = LLMEngine(params, cfg, prompt_buckets=(16,), chunk=4, slots=2,
                    max_queue=0, name="test")
    eng.warmup()
    return eng


PROMPTS = [[7, 3, 11], [2, 4, 6, 8, 10], [1] * 9, [5, 9] * 7]


def _drained(eng):
    s = eng.stats()
    return s["slots_busy"] == 0 and s["queue_depth"] == 0


class TestEngineEquivalence:
    def test_greedy_staggered_matches_single_sequence(self, engine, oracle):
        """Mixed-length prompts arriving staggered into 2 slots decode
        token-identically to the batch-1 oracle."""
        outs = [None] * len(PROMPTS)
        errs = []

        def client(i):
            try:
                time.sleep(i * 0.01)  # staggered arrivals
                outs[i] = engine.generate(PROMPTS[i], max_new_tokens=12)
            except BaseException as e:  # noqa: BLE001
                errs.append(e)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(len(PROMPTS))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs
        for i, p in enumerate(PROMPTS):
            assert outs[i] == oracle(p, 12), f"prompt {i} diverged"
        assert _drained(engine)

    def test_slot_retire_refill_under_load(self, engine, oracle):
        """3x more requests than slots: every slot retires and refills, all
        outputs stay oracle-equal, and the engine drains clean."""
        jobs = [(PROMPTS[i % len(PROMPTS)], 8 + (i % 3) * 4)
                for i in range(6)]
        outs = [None] * len(jobs)
        errs = []

        def client(i):
            try:
                outs[i] = engine.generate(jobs[i][0], max_new_tokens=jobs[i][1])
            except BaseException as e:  # noqa: BLE001
                errs.append(e)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(len(jobs))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs
        for i, (p, n) in enumerate(jobs):
            assert outs[i] == oracle(p, n), f"request {i} diverged"
        assert _drained(engine)

    def test_sampled_deterministic_beside_greedy_traffic(self, engine):
        """A sampled request's tokens depend only on its seed — identical
        alone and batched beside concurrent greedy traffic."""
        alone = engine.generate(PROMPTS[0], max_new_tokens=12,
                                temperature=0.8, seed=123)
        outs = {}

        def greedy():
            outs["greedy"] = engine.generate(PROMPTS[1], max_new_tokens=12)

        def sampled():
            outs["sampled"] = engine.generate(PROMPTS[0], max_new_tokens=12,
                                              temperature=0.8, seed=123)

        threads = [threading.Thread(target=greedy),
                   threading.Thread(target=sampled)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert outs["sampled"] == alone
        assert _drained(engine)

    def test_per_request_decode_counters(self, engine):
        """decode_tps is per-request (the old engine-level counters raced);
        the aggregate under the lock sums every delivered token."""
        with engine._agg_lock:
            base = engine.decode_tokens
        results = [{}, {}]

        def client(i):
            list(engine.stream(PROMPTS[i], max_new_tokens=8,
                               result=results[i]))

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for r in results:
            assert r["finish_reason"] == "stop"
            assert r["decode_tps"] > 0
        with engine._agg_lock:
            assert engine.decode_tokens == base + 16
        assert engine.decode_tokens_per_sec() > 0

    def test_cancellation_frees_slot(self, engine, oracle):
        """Abandoning a stream mid-generation frees its slot immediately for
        the next admission."""
        g = iter(engine.stream(PROMPTS[2], max_new_tokens=32))
        assert next(g) == oracle(PROMPTS[2], 32)[0]
        g.close()
        assert _drained(engine)
        assert engine.generate(PROMPTS[0], max_new_tokens=8) == \
            oracle(PROMPTS[0], 8)

    def test_max_new_tokens_zero(self, engine):
        res = {}
        assert list(engine.stream(PROMPTS[0], max_new_tokens=0,
                                  result=res)) == []
        assert res["finish_reason"] == "stop"

    def test_empty_prompt_raises(self, engine):
        with pytest.raises(ValueError, match="empty prompt"):
            engine.generate([], max_new_tokens=4)


class TestAdmissionControl:
    def test_saturated_shed_while_inflight_completes(self, tiny_model, oracle):
        """slots=1, max_queue=1: one decoding + one queued fills the engine;
        the next submit sheds with ``Saturated`` and BOTH in-flight requests
        still complete oracle-equal. After they drain, submits succeed."""
        cfg, params = tiny_model
        eng = LLMEngine(params, cfg, prompt_buckets=(16,), chunk=4, slots=1,
                        max_queue=1, name="shed")
        eng.warmup()

        g1 = iter(eng.stream(PROMPTS[0], max_new_tokens=12))
        first = next(g1)  # drives a step: request 1 now holds the only slot
        g2 = iter(eng.stream(PROMPTS[1], max_new_tokens=8))  # queued

        with pytest.raises(Saturated):
            eng.generate(PROMPTS[2], max_new_tokens=4)

        assert [first] + list(g1) == oracle(PROMPTS[0], 12)
        assert list(g2) == oracle(PROMPTS[1], 8)
        assert _drained(eng)
        assert eng.generate(PROMPTS[2], max_new_tokens=4) == \
            oracle(PROMPTS[2], 4)


class _StubReplica:
    def __init__(self, key):
        class _Id:
            @staticmethod
            def hex():
                return key

        self.actor_id = _Id()


def _mk_router(replicas, load):
    r = Router.__new__(Router)
    r._name = "stub"
    r._replicas = replicas
    r._replica_load = load
    r._model_ids = {}
    r._ongoing = {}
    r._max_ongoing = 100
    r._lock = threading.Lock()
    r._last_refresh = time.monotonic()  # fresh — _refresh() is a no-op
    r._version = 0
    return r


class TestOccupancyRouting:
    def test_slots_exhausted(self):
        r = _mk_router([], {
            "full": {"slots_total": 4.0, "slots_busy": 4.0},
            "free": {"slots_total": 4.0, "slots_busy": 1.0},
            "plain": {"ongoing": 2.0},
        })
        assert r._slots_exhausted("full")
        assert not r._slots_exhausted("free")
        assert not r._slots_exhausted("plain")  # non-engine replica
        assert not r._slots_exhausted("unknown")

    def test_pick_prefers_free_slots(self):
        reps = [_StubReplica("full"), _StubReplica("free")]
        r = _mk_router(reps, {
            "full": {"slots_total": 2.0, "slots_busy": 2.0,
                     "queue_depth": 0.0},
            "free": {"slots_total": 2.0, "slots_busy": 0.0,
                     "queue_depth": 0.0},
        })
        for _ in range(10):
            best, key = r._pick()
            assert key == "free"
            r._dec(key)

    def test_all_shedding_requires_every_replica_over_limit(self):
        from ray_tpu.core.config import config

        limit = config().serve_admission_queue_limit
        assert limit > 0  # default knob enables shedding
        reps = [_StubReplica("a"), _StubReplica("b")]
        over = {"slots_total": 1.0, "slots_busy": 1.0,
                "queue_depth": float(limit)}
        under = dict(over, queue_depth=float(limit) - 1)
        assert _mk_router(reps, {"a": over, "b": over})._all_shedding(reps)
        assert not _mk_router(reps, {"a": over, "b": under})._all_shedding(reps)
        # A replica that doesn't report a queue (plain deployment) never sheds.
        assert not _mk_router(reps, {"a": over})._all_shedding(reps)
        assert not _mk_router(
            reps, {"a": over, "b": {"ongoing": 1.0}})._all_shedding(reps)

    def test_pick_sheds_when_all_over_limit(self):
        from ray_tpu.core.config import config

        limit = float(config().serve_admission_queue_limit)
        reps = [_StubReplica("a"), _StubReplica("b")]
        load = {"slots_total": 1.0, "slots_busy": 1.0, "queue_depth": limit}
        r = _mk_router(reps, {"a": load, "b": load})
        with pytest.raises(Saturated):
            r._pick()


@pytest.fixture
def serve_instance(ray_start_regular):
    yield serve
    serve.shutdown()


class TestServeDataPlane:
    def test_concurrent_streams_contract_and_occupancy(self, serve_instance,
                                                       tiny_model, oracle):
        """Concurrent streaming through handle → router → replica keeps the
        response contract and oracle-equal tokens; the replica's slot
        occupancy surfaces in the controller snapshot for routing."""
        cfg, _params = tiny_model
        LM = llm_deployment(
            cfg, lambda: transformer.init_params(cfg, jax.random.key(0)),
            name="LM", slots=2, chunk=4)
        handle = serve.run(LM.bind())

        outs = [None] * 3
        errs = []

        def client(i):
            try:
                toks = []
                last = None
                for item in handle.options(stream=True).remote(
                        {"prompt_ids": PROMPTS[i], "max_new_tokens": 8}):
                    assert {"token", "index", "decode_tps"} <= set(item)
                    assert item["index"] == len(toks)
                    toks.append(item["token"])
                    last = item
                assert last is not None
                assert last["finish_reason"] == "stop"
                outs[i] = toks
            except BaseException as e:  # noqa: BLE001
                errs.append(e)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs
        for i in range(3):
            assert outs[i] == oracle(PROMPTS[i], 8), f"stream {i} diverged"

        # KV-occupancy metrics reach the controller snapshot (poll: the
        # controller merges get_state once per poll period).
        from ray_tpu.serve.controller import get_or_create_controller

        controller = get_or_create_controller()
        deadline = time.monotonic() + 10
        load = {}
        while time.monotonic() < deadline:
            _v, table = ray_tpu.get(
                controller.get_snapshot.remote(-1, 0.0))
            load = table.get("LM", {}).get("replica_load", {})
            if load:
                break
            time.sleep(0.1)
        assert load, "replica_load never reached the controller snapshot"
        stats = next(iter(load.values()))
        assert stats["slots_total"] == 2.0
        assert stats["queue_depth"] == 0.0
        assert "slots_busy" in stats
