"""Compiled DAG tests (reference: python/ray/dag tests).

The per-call overhead killer: a chain of actor stages compiled onto mutable
shm channels must produce identical results to plain actor calls and beat
their per-call latency.
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.dag import Channel, ChannelClosed, ChannelTimeout, InputNode


class TestChannel:
    def test_write_read_roundtrip(self):
        ch = Channel(capacity=1 << 16)
        try:
            ch.write({"x": 1, "y": [1, 2, 3]})
            assert ch.read(timeout=5) == {"x": 1, "y": [1, 2, 3]}
        finally:
            ch.destroy()

    def test_backpressure_blocks_second_write(self):
        ch = Channel(capacity=1 << 16)
        try:
            ch.write(1)
            with pytest.raises(ChannelTimeout):
                ch.write(2, timeout=0.2)
            assert ch.read(timeout=5) == 1
            ch.write(2)  # now the slot is free
            assert ch.read(timeout=5) == 2
        finally:
            ch.destroy()

    def test_cross_attach_by_name(self):
        ch = Channel(capacity=1 << 16)
        try:
            reader = Channel(ch.name, capacity=1 << 16, create=False)
            ch.write("hello")
            assert reader.read(timeout=5) == "hello"
        finally:
            ch.destroy()


class TestCompiledDAG:
    def test_two_stage_chain_matches_plain_calls(self, ray_start_regular):
        @ray_tpu.remote
        class Doubler:
            def apply(self, x):
                return x * 2

        @ray_tpu.remote
        class AddTen:
            def apply(self, x):
                return x + 10

        a, b = Doubler.remote(), AddTen.remote()
        dag = b.apply.bind(a.apply.bind(InputNode()))
        compiled = dag.experimental_compile()
        try:
            for i in range(20):
                assert compiled.execute(i).get(timeout=30) == i * 2 + 10
        finally:
            compiled.teardown()

    def test_pipelined_executes(self, ray_start_regular):
        """Multiple in-flight executes drain FIFO."""

        @ray_tpu.remote
        class Sq:
            def apply(self, x):
                return x * x

        s = Sq.remote()
        compiled = s.apply.bind(InputNode()).experimental_compile()
        try:
            refs = [compiled.execute(i) for i in range(3)]
            assert [r.get(timeout=30) for r in refs] == [0, 1, 4]
        finally:
            compiled.teardown()

    def test_stage_error_propagates(self, ray_start_regular):
        @ray_tpu.remote
        class Fragile:
            def apply(self, x):
                if x == 13:
                    raise ValueError("unlucky")
                return x

        f = Fragile.remote()
        compiled = f.apply.bind(InputNode()).experimental_compile()
        try:
            assert compiled.execute(1).get(timeout=30) == 1
            with pytest.raises(RuntimeError, match="unlucky"):
                compiled.execute(13).get(timeout=30)
            # The loop survives the error.
            assert compiled.execute(2).get(timeout=30) == 2
        finally:
            compiled.teardown()

    def test_compiled_beats_rpc_latency_multiprocess(self):
        """The point of aDAG: per-call overhead well under actor-task RPC.

        Measured on the MULTIPROCESS runtime — the channel path bypasses
        spec pickling, per-call RPC, and result sealing. (In-process actor
        calls are already ~100µs thread handoffs; the win is cross-process.)
        """
        from ray_tpu.core import runtime as runtime_mod
        from ray_tpu.core.cluster import Cluster, connect

        cluster = Cluster(num_nodes=1, resources_per_node={"CPU": 2})
        try:
            core = connect(cluster.gcs_address)
            try:
                @ray_tpu.remote
                class Echo:
                    def apply(self, x):
                        return x

                e = Echo.remote()
                ray_tpu.get(e.apply.remote(0), timeout=120)  # warm worker
                n = 50
                t0 = time.perf_counter()
                for i in range(n):
                    ray_tpu.get(e.apply.remote(i), timeout=60)
                plain = (time.perf_counter() - t0) / n

                e2 = Echo.remote()
                ray_tpu.get(e2.apply.remote(0), timeout=120)
                compiled = e2.apply.bind(InputNode()).experimental_compile()
                try:
                    assert compiled.execute(41).get(timeout=60) == 41  # warm
                    t0 = time.perf_counter()
                    for i in range(n):
                        assert compiled.execute(i).get(timeout=60) == i
                    fast = (time.perf_counter() - t0) / n
                finally:
                    compiled.teardown()
                # Round 3's direct task transport cut plain actor RPC from
                # ~5ms to well under 1ms, so the old 2× margin is no longer
                # guaranteed on a loaded 1-core CI box — the property that
                # matters is that the channel path still wins at all.
                assert fast < plain, (fast, plain)
            finally:
                core.shutdown()
                runtime_mod._global_runtime = None
        finally:
            cluster.shutdown()


class TestCompiledDAGValidation:
    def test_same_actor_twice_rejected(self, ray_start_regular):
        @ray_tpu.remote
        class A:
            def f(self, x):
                return x

            def g(self, x):
                return x

        a = A.remote()
        dag = a.g.bind(a.f.bind(InputNode()))
        with pytest.raises(ValueError, match="DISTINCT actors"):
            dag.experimental_compile()

    def test_bytes_payload_round_trips(self, ray_start_regular):
        @ray_tpu.remote
        class Rev:
            def apply(self, b):
                return b[::-1]

        r = Rev.remote()
        compiled = r.apply.bind(InputNode()).experimental_compile()
        try:
            assert compiled.execute(b"\x00abc\xff").get(timeout=30) == b"\xffcba\x00"
        finally:
            compiled.teardown()

    def test_async_actor_rejected_at_compile(self, ray_start_regular):
        @ray_tpu.remote
        class Async:
            async def apply(self, x):
                return x

        a = Async.remote()
        with pytest.raises(TypeError, match="async actors"):
            a.apply.bind(InputNode()).experimental_compile()


class TestSocketChannels:
    """Cross-node DAG channels (reference: experimental/channel.py:51 —
    aDAG channels run cross-node; shm cannot)."""

    def test_socket_channel_roundtrip_and_backpressure(self, ray_start_regular):
        import threading

        from ray_tpu.dag.channel import ChannelClosed, SocketChannel

        ch = SocketChannel()
        reader_out = []

        def consume():
            try:
                while True:
                    reader_out.append(ch_reader.read(timeout=30))
            except ChannelClosed:
                reader_out.append("closed")

        # distinct endpoint objects, attached by name (as pickling would)
        ch_reader = SocketChannel(ch.name, create=False)
        t = threading.Thread(target=consume)
        t.start()
        for i in range(5):
            ch.write({"i": i}, timeout=30)
        ch.close()
        t.join(timeout=30)
        assert reader_out == [{"i": 0}, {"i": 1}, {"i": 2}, {"i": 3},
                              {"i": 4}, "closed"]

    def test_compiled_dag_over_sockets_multiprocess(self):
        """A 2-stage compiled DAG with FORCED socket channels across real
        worker processes: same results as the shm path."""
        from ray_tpu.core import runtime as runtime_mod
        from ray_tpu.core.cluster import Cluster, connect

        cluster = Cluster(num_nodes=2, resources_per_node={"CPU": 2})
        try:
            core = connect(cluster.gcs_address)
            try:
                @ray_tpu.remote
                class AddOne:
                    def apply(self, x):
                        return x + 1

                @ray_tpu.remote
                class Double:
                    def apply(self, x):
                        return x * 2

                a, d = AddOne.remote(), Double.remote()
                ray_tpu.get([a.apply.remote(0), d.apply.remote(0)],
                            timeout=120)
                dag = d.apply.bind(a.apply.bind(InputNode()))
                compiled = dag.experimental_compile(channel_type="socket")
                try:
                    for i in range(8):
                        assert compiled.execute(i).get(timeout=60) == (i + 1) * 2
                finally:
                    compiled.teardown()
            finally:
                core.shutdown()
                runtime_mod._global_runtime = None
        finally:
            cluster.shutdown()


class TestDeviceChannel:
    """Device-tier aDAG transport (SURVEY §2.1: on-device buffers with
    double-buffered host DMA; reference: experimental/channel.py
    accelerator channels)."""

    def test_array_roundtrip_lands_on_device(self):
        import jax
        import jax.numpy as jnp

        from ray_tpu.dag.device_channel import DeviceChannel

        ch = DeviceChannel(capacity=8 * 1024 * 1024)
        try:
            src = jnp.arange(1024, dtype=jnp.float32).reshape(32, 32)
            ch.write(src)
            out = ch.read()
            assert isinstance(out, jax.Array)
            np.testing.assert_array_equal(np.asarray(out), np.asarray(src))
            # numpy in -> jax.Array out (the channel re-devices payloads;
            # jax's default x64-off mode narrows f64 on device_put)
            ch.write(np.ones((4, 4), np.float64))
            out2 = ch.read()
            assert isinstance(out2, jax.Array)
            np.testing.assert_allclose(np.asarray(out2), np.ones((4, 4)))
        finally:
            ch.destroy()

    def test_ping_pong_double_buffering(self):
        import jax.numpy as jnp

        from ray_tpu.dag.device_channel import DeviceChannel

        ch = DeviceChannel(capacity=1024 * 1024)
        try:
            # TWO writes proceed without any read — the ping-pong slots
            # are the double buffer (a single-slot channel would block).
            ch.write(jnp.full((8,), 1.0))
            ch.write(jnp.full((8,), 2.0))
            a = ch.read()
            b = ch.read()
            assert float(a[0]) == 1.0 and float(b[0]) == 2.0
            # Third write only lands after slot 0 was acked (it was).
            ch.write(jnp.full((8,), 3.0))
            assert float(ch.read()[0]) == 3.0
        finally:
            ch.destroy()

    def test_control_payloads_and_close(self):
        from ray_tpu.dag.channel import ChannelClosed
        from ray_tpu.dag.device_channel import DeviceChannel

        ch = DeviceChannel(capacity=1024 * 1024)
        try:
            ch.write({"lr": 0.1, "step": 3})  # non-array: pickled path
            assert ch.read() == {"lr": 0.1, "step": 3}
            ch.close()
            with pytest.raises(ChannelClosed):
                ch.read()
        finally:
            ch.destroy()

    def test_compiled_dag_over_device_channels(self, ray_start_regular):
        """Two actor stages exchanging DEVICE arrays: each stage's method
        receives a jax.Array (not pickled numpy) and the pipeline result
        matches the plain call chain."""
        import jax
        import jax.numpy as jnp

        import ray_tpu
        from ray_tpu.dag import InputNode

        @ray_tpu.remote
        class Scale:
            def __init__(self, k):
                self.k = k

            def apply(self, x):
                assert isinstance(x, jax.Array), type(x)
                return self.k * x

        a = Scale.remote(2.0)
        b = Scale.remote(10.0)
        with InputNode() as inp:
            dag = b.apply.bind(a.apply.bind(inp))
        compiled = dag.experimental_compile(channel_type="device")
        try:
            x = jnp.arange(8, dtype=jnp.float32)
            for i in range(4):
                out = compiled.execute(x + i).get(timeout=60)
                np.testing.assert_allclose(
                    np.asarray(out), np.asarray((x + i) * 20.0))
        finally:
            compiled.teardown()
