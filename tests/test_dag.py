"""Compiled DAG tests (reference: python/ray/dag tests).

The per-call overhead killer: a chain of actor stages compiled onto mutable
shm channels must produce identical results to plain actor calls and beat
their per-call latency.
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.dag import (Channel, ChannelClosed, ChannelTimeout, InputNode,
                         MultiOutputNode)


class TestChannel:
    def test_write_read_roundtrip(self):
        ch = Channel(capacity=1 << 16)
        try:
            ch.write({"x": 1, "y": [1, 2, 3]})
            assert ch.read(timeout=5) == {"x": 1, "y": [1, 2, 3]}
        finally:
            ch.destroy()

    def test_backpressure_blocks_second_write(self):
        # slots=1 restores the strict capacity-1 lock-step channel.
        ch = Channel(capacity=1 << 16, slots=1)
        try:
            ch.write(1)
            with pytest.raises(ChannelTimeout):
                ch.write(2, timeout=0.2)
            assert ch.read(timeout=5) == 1
            ch.write(2)  # now the slot is free
            assert ch.read(timeout=5) == 2
        finally:
            ch.destroy()

    def test_ring_pipelines_n_writes_then_backpressures(self):
        ch = Channel(capacity=1 << 16, slots=4)
        try:
            for i in range(4):  # the whole ring fills without a reader
                ch.write(i, timeout=5)
            with pytest.raises(ChannelTimeout):
                ch.write(99, timeout=0.2)  # slot 0 still unacked
            assert ch.read(timeout=5) == 0  # one ack frees one slot
            ch.write(4, timeout=5)
            assert [ch.read(timeout=5) for _ in range(4)] == [1, 2, 3, 4]
        finally:
            ch.destroy()

    def test_ring_fifo_across_wraparound(self):
        ch = Channel(capacity=1 << 16, slots=3)
        try:
            out = []
            for i in range(11):  # > 3 full ring revolutions
                ch.write(i, timeout=5)
                out.append(ch.read(timeout=5))
            assert out == list(range(11))
        finally:
            ch.destroy()

    def test_cross_attach_by_name(self):
        ch = Channel(capacity=1 << 16)
        try:
            reader = Channel(ch.name, capacity=1 << 16, create=False)
            ch.write("hello")
            assert reader.read(timeout=5) == "hello"
        finally:
            ch.destroy()


class TestCompiledDAG:
    def test_two_stage_chain_matches_plain_calls(self, ray_start_regular):
        @ray_tpu.remote
        class Doubler:
            def apply(self, x):
                return x * 2

        @ray_tpu.remote
        class AddTen:
            def apply(self, x):
                return x + 10

        a, b = Doubler.remote(), AddTen.remote()
        dag = b.apply.bind(a.apply.bind(InputNode()))
        compiled = dag.experimental_compile()
        try:
            for i in range(20):
                assert compiled.execute(i).get(timeout=30) == i * 2 + 10
        finally:
            compiled.teardown()

    def test_pipelined_executes(self, ray_start_regular):
        """Multiple in-flight executes drain FIFO."""

        @ray_tpu.remote
        class Sq:
            def apply(self, x):
                return x * x

        s = Sq.remote()
        compiled = s.apply.bind(InputNode()).experimental_compile()
        try:
            refs = [compiled.execute(i) for i in range(3)]
            assert [r.get(timeout=30) for r in refs] == [0, 1, 4]
        finally:
            compiled.teardown()

    def test_stage_error_propagates(self, ray_start_regular):
        @ray_tpu.remote
        class Fragile:
            def apply(self, x):
                if x == 13:
                    raise ValueError("unlucky")
                return x

        f = Fragile.remote()
        compiled = f.apply.bind(InputNode()).experimental_compile()
        try:
            assert compiled.execute(1).get(timeout=30) == 1
            with pytest.raises(RuntimeError, match="unlucky"):
                compiled.execute(13).get(timeout=30)
            # The loop survives the error.
            assert compiled.execute(2).get(timeout=30) == 2
        finally:
            compiled.teardown()

    def test_compiled_beats_rpc_latency_multiprocess(self):
        """The point of aDAG: per-call overhead well under actor-task RPC.

        Measured on the MULTIPROCESS runtime — the channel path bypasses
        spec pickling, per-call RPC, and result sealing. (In-process actor
        calls are already ~100µs thread handoffs; the win is cross-process.)
        """
        from ray_tpu.core import runtime as runtime_mod
        from ray_tpu.core.cluster import Cluster, connect

        cluster = Cluster(num_nodes=1, resources_per_node={"CPU": 2})
        try:
            core = connect(cluster.gcs_address)
            try:
                @ray_tpu.remote
                class Echo:
                    def apply(self, x):
                        return x

                e = Echo.remote()
                ray_tpu.get(e.apply.remote(0), timeout=120)  # warm worker
                n = 50
                t0 = time.perf_counter()
                for i in range(n):
                    ray_tpu.get(e.apply.remote(i), timeout=60)
                plain = (time.perf_counter() - t0) / n

                e2 = Echo.remote()
                ray_tpu.get(e2.apply.remote(0), timeout=120)
                compiled = e2.apply.bind(InputNode()).experimental_compile()
                try:
                    assert compiled.execute(41).get(timeout=60) == 41  # warm
                    t0 = time.perf_counter()
                    for i in range(n):
                        assert compiled.execute(i).get(timeout=60) == i
                    fast = (time.perf_counter() - t0) / n
                finally:
                    compiled.teardown()
                # Round 3's direct task transport cut plain actor RPC from
                # ~5ms to well under 1ms, so the old 2× margin is no longer
                # guaranteed on a loaded 1-core CI box — the property that
                # matters is that the channel path still wins at all.
                assert fast < plain, (fast, plain)
            finally:
                core.shutdown()
                runtime_mod._global_runtime = None
        finally:
            cluster.shutdown()


class TestFanOutFanIn:
    """Graph shapes beyond linear chains (reference: multi-arg bind +
    MultiOutputNode in python/ray/dag)."""

    def test_diamond_matches_plain_calls(self, ray_start_regular):
        """input → pre → (left, right) → merge: per-edge channels, fan-out
        broadcast, fan-in gather — result identical to the task path."""

        @ray_tpu.remote
        class Pre:
            def apply(self, x):
                return x + 1

        @ray_tpu.remote
        class Left:
            def apply(self, x):
                return x * 2

        @ray_tpu.remote
        class Right:
            def apply(self, x):
                return x * 3

        @ray_tpu.remote
        class Merge:
            def apply(self, a, b):
                return (a, b)

        pre, lt, rt, mg = Pre.remote(), Left.remote(), Right.remote(), Merge.remote()
        with InputNode() as inp:
            p = pre.apply.bind(inp)
            dag = mg.apply.bind(lt.apply.bind(p), rt.apply.bind(p))
        compiled = dag.experimental_compile()
        try:
            for i in range(10):
                assert compiled.execute(i).get(timeout=30) == \
                    ((i + 1) * 2, (i + 1) * 3)
        finally:
            compiled.teardown()

    def test_multi_output_node_yields_tuples(self, ray_start_regular):
        @ray_tpu.remote
        class Double:
            def apply(self, x):
                return x * 2

        @ray_tpu.remote
        class Square:
            def apply(self, x):
                return x * x

        d, s = Double.remote(), Square.remote()
        with InputNode() as inp:
            dag = MultiOutputNode([d.apply.bind(inp), s.apply.bind(inp)])
        compiled = dag.experimental_compile()
        try:
            refs = [compiled.execute(i) for i in range(6)]
            assert [r.get(timeout=30) for r in refs] == \
                [(i * 2, i * i) for i in range(6)]
        finally:
            compiled.teardown()

    def test_constant_bind_args(self, ray_start_regular):
        @ray_tpu.remote
        class AffineOp:
            def apply(self, x, scale, offset):
                return x * scale + offset

        a = AffineOp.remote()
        compiled = a.apply.bind(InputNode(), 10, 7).experimental_compile()
        try:
            assert compiled.execute(3).get(timeout=30) == 37
        finally:
            compiled.teardown()

    def test_fan_in_error_passes_through_merge(self, ray_start_regular):
        """An upstream failure forwards through downstream stages so the
        driver sees the ORIGINATING stage's error, and the DAG survives."""

        @ray_tpu.remote
        class Fragile:
            def apply(self, x):
                if x == 13:
                    raise ValueError("unlucky-upstream")
                return x

        @ray_tpu.remote
        class Stable:
            def apply(self, x):
                return x

        @ray_tpu.remote
        class Merge:
            def apply(self, a, b):
                return a + b

        f, s, m = Fragile.remote(), Stable.remote(), Merge.remote()
        with InputNode() as inp:
            dag = m.apply.bind(f.apply.bind(inp), s.apply.bind(inp))
        compiled = dag.experimental_compile()
        try:
            assert compiled.execute(1).get(timeout=30) == 2
            with pytest.raises(RuntimeError, match="unlucky-upstream"):
                compiled.execute(13).get(timeout=30)
            assert compiled.execute(2).get(timeout=30) == 4
        finally:
            compiled.teardown()


class TestBurstPipelining:
    def test_burst_fifo_and_index_mapping(self, ray_start_regular):
        """>1 tick in flight per edge: a burst submitted before any fetch
        drains FIFO, and DAGRef index→result mapping holds under
        out-of-order gets."""

        @ray_tpu.remote
        class Sq:
            def apply(self, x):
                return x * x

        s = Sq.remote()
        compiled = s.apply.bind(InputNode()).experimental_compile(
            channel_slots=4)
        try:
            # 8 in flight = the full pipeline capacity at slots=4 (input
            # ring + output ring); a capacity-1 design would deadlock here
            # because execute() #2 already needs the driver to fetch.
            refs = [compiled.execute(i) for i in range(8)]
            # Fetch out of order: late index first forces the FIFO drain
            # to buffer earlier results; each ref must still map to ITS
            # tick.
            assert refs[7].get(timeout=30) == 49
            assert refs[0].get(timeout=30) == 0
            assert [refs[i].get(timeout=30) for i in (6, 3, 5)] == \
                [36, 9, 25]
            assert [r.get(timeout=30) for r in refs] == \
                [i * i for i in range(8)]
        finally:
            compiled.teardown()

    def test_teardown_under_load(self, ray_start_regular):
        """Teardown with unfetched in-flight ticks: the drain must let the
        stage loops exit on the pill (no mid-read unlink), leaving the
        loop refs completed."""

        @ray_tpu.remote
        class Slowish:
            def apply(self, x):
                time.sleep(0.005)
                return x

        s = Slowish.remote()
        compiled = s.apply.bind(InputNode()).experimental_compile(
            channel_slots=4)
        refs = [compiled.execute(i) for i in range(4)]
        assert refs[0].get(timeout=30) == 0
        compiled.teardown()  # 3 ticks never fetched
        # The resident loops saw the pill and exited cleanly.
        assert ray_tpu.get(compiled._loop_refs, timeout=30) == ["closed"]
        with pytest.raises(RuntimeError, match="torn down"):
            compiled.execute(99)

    def test_partial_multi_output_gather_survives_timeout(
            self, ray_start_regular):
        """A get() that times out after consuming SOME leaves of a
        MultiOutputNode tick must not lose them: the retry resumes at the
        first unread leaf and every later tick's tuple stays aligned."""

        @ray_tpu.remote
        class Fast:
            def apply(self, x):
                return ("fast", x)

        @ray_tpu.remote
        class Slow:
            def apply(self, x):
                time.sleep(0.4)
                return ("slow", x)

        f, s = Fast.remote(), Slow.remote()
        with InputNode() as inp:
            dag = MultiOutputNode([f.apply.bind(inp), s.apply.bind(inp)])
        compiled = dag.experimental_compile()
        try:
            ref0 = compiled.execute(0)
            # Fast's leaf is consumed, then Slow's read times out.
            with pytest.raises(ChannelTimeout):
                ref0.get(timeout=0.1)
            ref1 = compiled.execute(1)
            assert ref0.get(timeout=30) == (("fast", 0), ("slow", 0))
            assert ref1.get(timeout=30) == (("fast", 1), ("slow", 1))
        finally:
            compiled.teardown()

    def test_partial_input_write_rolls_back_on_timeout(
            self, ray_start_regular):
        """execute() hitting backpressure on ONE fan-out input edge must
        publish to NO edge: without the two-phase commit the fast sibling
        edge runs a tick ahead and every later merge mixes ticks."""

        @ray_tpu.remote
        class Fast:
            def apply(self, x):
                return x

        @ray_tpu.remote
        class Slow:
            def apply(self, x):
                time.sleep(0.25)
                return x

        @ray_tpu.remote
        class Merge:
            def apply(self, a, b):
                assert a == b, (a, b)  # tick alignment invariant
                return a

        f, s, m = Fast.remote(), Slow.remote(), Merge.remote()
        with InputNode() as inp:
            # Fast bound FIRST: its input edge is written before Slow's,
            # which is the order that desyncs without rollback.
            dag = m.apply.bind(f.apply.bind(inp), s.apply.bind(inp))
        compiled = dag.experimental_compile(channel_slots=1)
        try:
            refs = [compiled.execute(i, timeout=10) for i in range(2)]
            # Slow is busy with tick 0, its 1-slot input ring holds tick 1
            # -> this execute must time out WITHOUT feeding Fast's edge.
            with pytest.raises(ChannelTimeout):
                compiled.execute(99, timeout=0.1)
            assert [r.get(timeout=30) for r in refs] == [0, 1]
            # Post-timeout ticks stay aligned (Merge asserts a == b).
            refs2 = [compiled.execute(i, timeout=30) for i in (5, 6)]
            assert [r.get(timeout=30) for r in refs2] == [5, 6]
        finally:
            compiled.teardown()

    def test_dag_tick_histogram_records(self, ray_start_regular):
        from ray_tpu.core.metrics_export import dag_tick_hist

        @ray_tpu.remote
        class Echo:
            def apply(self, x):
                return x

        e = Echo.remote()
        compiled = e.apply.bind(InputNode()).experimental_compile()
        try:
            before = sum(dag_tick_hist()._totals.values())
            for i in range(5):
                assert compiled.execute(i).get(timeout=30) == i
            after = sum(dag_tick_hist()._totals.values())
            assert after - before == 5
        finally:
            compiled.teardown()


class TestWorkerChannelLifecycle:
    def test_worker_detaches_channel_fds_on_loop_exit(self):
        """The worker-side leak fix: a stage worker's attached channel
        endpoints (mmap + backing fd per channel) are closed when its
        resident loop exits at teardown — previously every compiled DAG
        leaked two fds per stage worker, forever."""
        from ray_tpu.core import runtime as runtime_mod
        from ray_tpu.core.cluster import Cluster, connect

        cluster = Cluster(num_nodes=1, resources_per_node={"CPU": 3})
        try:
            core = connect(cluster.gcs_address)
            try:
                @ray_tpu.remote
                class Probe:
                    def apply(self, x):
                        return x

                    def chan_fds(self):
                        import os as _os

                        n = 0
                        for fd in _os.listdir("/proc/self/fd"):
                            try:
                                tgt = _os.readlink(f"/proc/self/fd/{fd}")
                            except OSError:
                                continue
                            if "rtpu-chan" in tgt or "rtpu-schan" in tgt \
                                    or "rtpu-devchan" in tgt:
                                n += 1
                        return n

                a, b = Probe.remote(), Probe.remote()
                ray_tpu.get([a.apply.remote(0), b.apply.remote(0)],
                            timeout=120)
                for _round in range(2):  # repeated compiles must not accrete
                    dag = b.apply.bind(a.apply.bind(InputNode()))
                    compiled = dag.experimental_compile()
                    try:
                        assert compiled.execute(7).get(timeout=60) == 7
                    finally:
                        compiled.teardown()
                # The loops exited and detached: no channel-backed fds
                # survive in either stage worker.
                assert ray_tpu.get(a.chan_fds.remote(), timeout=60) == 0
                assert ray_tpu.get(b.chan_fds.remote(), timeout=60) == 0
            finally:
                core.shutdown()
                runtime_mod._global_runtime = None
        finally:
            cluster.shutdown()


class TestCompiledDAGValidation:
    def test_same_actor_twice_rejected(self, ray_start_regular):
        @ray_tpu.remote
        class A:
            def f(self, x):
                return x

            def g(self, x):
                return x

        a = A.remote()
        dag = a.g.bind(a.f.bind(InputNode()))
        with pytest.raises(ValueError, match="DISTINCT actors"):
            dag.experimental_compile()

    def test_bytes_payload_round_trips(self, ray_start_regular):
        @ray_tpu.remote
        class Rev:
            def apply(self, b):
                return b[::-1]

        r = Rev.remote()
        compiled = r.apply.bind(InputNode()).experimental_compile()
        try:
            assert compiled.execute(b"\x00abc\xff").get(timeout=30) == b"\xffcba\x00"
        finally:
            compiled.teardown()

    def test_async_actor_rejected_at_compile(self, ray_start_regular):
        @ray_tpu.remote
        class Async:
            async def apply(self, x):
                return x

        a = Async.remote()
        with pytest.raises(TypeError, match="async actors"):
            a.apply.bind(InputNode()).experimental_compile()


class TestSocketChannels:
    """Cross-node DAG channels (reference: experimental/channel.py:51 —
    aDAG channels run cross-node; shm cannot)."""

    def test_socket_channel_roundtrip_and_backpressure(self, ray_start_regular):
        import threading

        from ray_tpu.dag.channel import ChannelClosed, SocketChannel

        ch = SocketChannel()
        reader_out = []

        def consume():
            try:
                while True:
                    reader_out.append(ch_reader.read(timeout=30))
            except ChannelClosed:
                reader_out.append("closed")

        # distinct endpoint objects, attached by name (as pickling would)
        ch_reader = SocketChannel(ch.name, create=False)
        t = threading.Thread(target=consume)
        t.start()
        for i in range(5):
            ch.write({"i": i}, timeout=30)
        ch.close()
        t.join(timeout=30)
        assert reader_out == [{"i": 0}, {"i": 1}, {"i": 2}, {"i": 3},
                              {"i": 4}, "closed"]

    def test_windowed_acks_pipeline_writes(self, ray_start_regular):
        """Credit-based flow control: the writer runs a full window of
        frames ahead of the reader's acks (the capacity-1 design stalled
        on an ack round-trip per frame), then blocks on credit
        exhaustion."""
        import threading

        from ray_tpu.dag.channel import SocketChannel

        ch = SocketChannel(window=4)
        ch_reader = SocketChannel(ch.name, create=False)
        started = threading.Event()

        def accept_only():
            # Bind the reader role (so the writer can connect) but DON'T
            # read yet — no acks flow.
            ch_reader._become_reader(timeout=30)
            started.set()

        t = threading.Thread(target=accept_only)
        t.start()
        try:
            # A full window of writes completes with ZERO acks on the wire.
            for i in range(4):
                ch.write({"i": i}, timeout=10)
            started.wait(10)
            # The 5th blocks on credit exhaustion...
            with pytest.raises(ChannelTimeout):
                ch.write({"i": 4}, timeout=0.3)
            # ...until the reader drains one frame (one ack = one credit).
            assert ch_reader.read(timeout=10) == {"i": 0}
            ch.write({"i": 4}, timeout=10)
            assert [ch_reader.read(timeout=10) for _ in range(4)] == \
                [{"i": i} for i in range(1, 5)]
        finally:
            t.join(timeout=10)
            ch.destroy()
            ch_reader.destroy()

    @pytest.mark.slow
    def test_socket_dag_burst_pipelining_multidaemon(self):
        """Cross-daemon compiled DAG over FORCED socket channels: a burst
        submitted ahead of any fetch pipelines through the windowed acks
        and drains FIFO (the per-frame-ack design serialized this)."""
        from ray_tpu.core import runtime as runtime_mod
        from ray_tpu.core.cluster import Cluster, connect

        cluster = Cluster(num_nodes=2, resources_per_node={"CPU": 2})
        try:
            core = connect(cluster.gcs_address)
            try:
                @ray_tpu.remote
                class AddOne:
                    def apply(self, x):
                        return x + 1

                @ray_tpu.remote
                class Double:
                    def apply(self, x):
                        return x * 2

                a, d = AddOne.remote(), Double.remote()
                ray_tpu.get([a.apply.remote(0), d.apply.remote(0)],
                            timeout=120)
                dag = d.apply.bind(a.apply.bind(InputNode()))
                compiled = dag.experimental_compile(channel_type="socket")
                try:
                    refs = [compiled.execute(i) for i in range(12)]
                    assert [r.get(timeout=60) for r in refs] == \
                        [(i + 1) * 2 for i in range(12)]
                finally:
                    compiled.teardown()
            finally:
                core.shutdown()
                runtime_mod._global_runtime = None
        finally:
            cluster.shutdown()

    def test_compiled_dag_over_sockets_multiprocess(self):
        """A 2-stage compiled DAG with FORCED socket channels across real
        worker processes: same results as the shm path."""
        from ray_tpu.core import runtime as runtime_mod
        from ray_tpu.core.cluster import Cluster, connect

        cluster = Cluster(num_nodes=2, resources_per_node={"CPU": 2})
        try:
            core = connect(cluster.gcs_address)
            try:
                @ray_tpu.remote
                class AddOne:
                    def apply(self, x):
                        return x + 1

                @ray_tpu.remote
                class Double:
                    def apply(self, x):
                        return x * 2

                a, d = AddOne.remote(), Double.remote()
                ray_tpu.get([a.apply.remote(0), d.apply.remote(0)],
                            timeout=120)
                dag = d.apply.bind(a.apply.bind(InputNode()))
                compiled = dag.experimental_compile(channel_type="socket")
                try:
                    for i in range(8):
                        assert compiled.execute(i).get(timeout=60) == (i + 1) * 2
                finally:
                    compiled.teardown()
            finally:
                core.shutdown()
                runtime_mod._global_runtime = None
        finally:
            cluster.shutdown()


class TestDeviceChannel:
    """Device-tier aDAG transport (SURVEY §2.1: on-device buffers with
    double-buffered host DMA; reference: experimental/channel.py
    accelerator channels)."""

    def test_array_roundtrip_lands_on_device(self):
        import jax
        import jax.numpy as jnp

        from ray_tpu.dag.device_channel import DeviceChannel

        ch = DeviceChannel(capacity=8 * 1024 * 1024)
        try:
            src = jnp.arange(1024, dtype=jnp.float32).reshape(32, 32)
            ch.write(src)
            out = ch.read()
            assert isinstance(out, jax.Array)
            np.testing.assert_array_equal(np.asarray(out), np.asarray(src))
            # numpy in -> jax.Array out (the channel re-devices payloads;
            # jax's default x64-off mode narrows f64 on device_put)
            ch.write(np.ones((4, 4), np.float64))
            out2 = ch.read()
            assert isinstance(out2, jax.Array)
            np.testing.assert_allclose(np.asarray(out2), np.ones((4, 4)))
        finally:
            ch.destroy()

    def test_ping_pong_double_buffering(self):
        import jax.numpy as jnp

        from ray_tpu.dag.device_channel import DeviceChannel

        ch = DeviceChannel(capacity=1024 * 1024)
        try:
            # TWO writes proceed without any read — the ping-pong slots
            # are the double buffer (a single-slot channel would block).
            ch.write(jnp.full((8,), 1.0))
            ch.write(jnp.full((8,), 2.0))
            a = ch.read()
            b = ch.read()
            assert float(a[0]) == 1.0 and float(b[0]) == 2.0
            # Third write only lands after slot 0 was acked (it was).
            ch.write(jnp.full((8,), 3.0))
            assert float(ch.read()[0]) == 3.0
        finally:
            ch.destroy()

    def test_control_payloads_and_close(self):
        from ray_tpu.dag.channel import ChannelClosed
        from ray_tpu.dag.device_channel import DeviceChannel

        ch = DeviceChannel(capacity=1024 * 1024)
        try:
            ch.write({"lr": 0.1, "step": 3})  # non-array: pickled path
            assert ch.read() == {"lr": 0.1, "step": 3}
            ch.close()
            with pytest.raises(ChannelClosed):
                ch.read()
        finally:
            ch.destroy()

    def test_compiled_dag_over_device_channels(self, ray_start_regular):
        """Two actor stages exchanging DEVICE arrays: each stage's method
        receives a jax.Array (not pickled numpy) and the pipeline result
        matches the plain call chain."""
        import jax
        import jax.numpy as jnp

        import ray_tpu
        from ray_tpu.dag import InputNode

        @ray_tpu.remote
        class Scale:
            def __init__(self, k):
                self.k = k

            def apply(self, x):
                assert isinstance(x, jax.Array), type(x)
                return self.k * x

        a = Scale.remote(2.0)
        b = Scale.remote(10.0)
        with InputNode() as inp:
            dag = b.apply.bind(a.apply.bind(inp))
        compiled = dag.experimental_compile(channel_type="device")
        try:
            x = jnp.arange(8, dtype=jnp.float32)
            for i in range(4):
                out = compiled.execute(x + i).get(timeout=60)
                np.testing.assert_allclose(
                    np.asarray(out), np.asarray((x + i) * 20.0))
        finally:
            compiled.teardown()
