"""Chunked node-to-node object transfer + daemon-side spill.

Round-3 object plane (reference: ``object_manager.cc:812`` chunked
push/pull, ``pull_manager.cc:801`` budgeted pulls,
``local_object_manager.cc:110`` spill): big objects cross nodes as bounded
chunk frames, land in the puller's shm arena and register as NEW locations
(broadcast fan-out), and objects larger than the arena live on the spill
shelf — so a 1 GiB-class object moves with bounded memory on both sides.
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.core.cluster import Cluster, connect
from ray_tpu.core import runtime as runtime_mod


def _wait_for(predicate, timeout=60.0, interval=0.2):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


@pytest.fixture
def two_nodes():
    cluster = Cluster(num_nodes=2, resources_per_node={"CPU": 2})
    core = connect(cluster.gcs_address)
    yield cluster, core
    core.shutdown()
    runtime_mod._global_runtime = None
    cluster.shutdown()


def test_chunked_pull_cross_node_registers_new_location(two_nodes):
    cluster, core = two_nodes
    # ~24 MB > pull_chunk_size (8 MB): crosses as a chunk pipeline.
    arr = np.arange(3_000_000, dtype=np.float64)
    ref = ray_tpu.put(arr)
    origin_locs = core._gcs_rpc.call("locate_object", ref.id.binary())
    assert len(origin_locs) == 1
    origin_node = origin_locs[0][0]
    other = next(h for h in cluster.nodes if h.node_id != origin_node)

    @ray_tpu.remote(scheduling_strategy=ray_tpu.NodeAffinitySchedulingStrategy(
        node_id=other.node_id, soft=False))
    def consume(a):
        return float(a.sum())

    assert ray_tpu.get(consume.remote(ref), timeout=300) == float(arr.sum())
    # The puller sealed its copy into the second node's arena and registered
    # the replica — the broadcast-tree property.
    assert _wait_for(lambda: len(
        core._gcs_rpc.call("locate_object", ref.id.binary())) >= 2, timeout=30)


def test_object_larger_than_arena_spills_and_crosses_nodes():
    """An object bigger than the WHOLE shm arena: put spills chunk-wise to
    the daemon's disk shelf; a consumer on another node chunk-pulls it back
    out of the spill file."""
    cluster = Cluster(
        num_nodes=2, resources_per_node={"CPU": 2},
        system_config={"object_store_memory": 16 * 1024 * 1024},
    )
    try:
        core = connect(cluster.gcs_address)
        try:
            arr = np.arange(5_000_000, dtype=np.float64)  # ~40 MB > 16 MB arena
            ref = ray_tpu.put(arr)
            locs = core._gcs_rpc.call("locate_object", ref.id.binary())
            assert len(locs) == 1
            origin_node = locs[0][0]
            # Replica actually lives on the spill shelf, not in shm.
            meta = core._daemons.get(locs[0][1]).call(
                "object_meta", ref.id.binary())
            assert meta is not None and meta["where"] == "spill", meta
            # Drop the driver's cached value: the consumer must pull bytes.
            with core._cache_lock:
                core._cache.pop(ref.id, None)
            other = next(h for h in cluster.nodes
                         if h.node_id != origin_node)

            @ray_tpu.remote(
                scheduling_strategy=ray_tpu.NodeAffinitySchedulingStrategy(
                    node_id=other.node_id, soft=False))
            def consume(a):
                return float(a[0]), float(a[-1]), int(a.shape[0])

            first, last, n = ray_tpu.get(consume.remote(ref), timeout=300)
            assert (first, last, n) == (0.0, 4_999_999.0, 5_000_000)
        finally:
            core.shutdown()
            runtime_mod._global_runtime = None
    finally:
        cluster.shutdown()


def test_broadcast_fans_out_across_nodes():
    cluster = Cluster(num_nodes=4, resources_per_node={"CPU": 1})
    try:
        core = connect(cluster.gcs_address)
        try:
            arr = np.ones(2_500_000)  # ~20 MB
            ref = ray_tpu.put(arr)

            @ray_tpu.remote(
                scheduling_strategy=ray_tpu.SpreadSchedulingStrategy())
            def consume(a):
                return float(a.sum())

            out = ray_tpu.get([consume.remote(ref) for _ in range(4)],
                              timeout=600)
            assert out == [2_500_000.0] * 4
            # More than one node ended up holding a replica.
            assert _wait_for(lambda: len(core._gcs_rpc.call(
                "locate_object", ref.id.binary())) >= 2, timeout=30)
        finally:
            core.shutdown()
            runtime_mod._global_runtime = None
    finally:
        cluster.shutdown()
