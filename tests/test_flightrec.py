"""Flight recorder, health watchdog, and postmortem-debug tests.

Unit half: the mmap ring format (roundtrip, wraparound, torn slots,
SIGKILL survival), pure watchdog classification/transition logic, and
postmortem timeline assembly from synthetic rings.

Integration half (real multiprocess cluster): SIGSTOP a node daemon
mid-load and watch the watchdog flip it ``stalled`` then back to
``healthy`` on SIGCONT; SIGKILL a worker mid-task and reconstruct its
lifecycle edges from its ring; and the full chaos demo — kill -9 a node
daemon under serve load, then ``ray-tpu debug`` merges rings + GCS
tables into one timeline that names the dead component, shows its lease
state, and cross-links an affected request by trace id.
"""

import json
import os
import signal
import struct
import subprocess
import sys
import time

import pytest

import ray_tpu
from ray_tpu.core import health
from ray_tpu.core import runtime as runtime_mod
from ray_tpu.core.cluster import Cluster, connect
from ray_tpu.devtools import postmortem
from ray_tpu.util import flightrec


def _wait_for(predicate, timeout=60.0, interval=0.2):
    deadline = time.time() + timeout
    while time.time() < deadline:
        value = predicate()
        if value:
            return value
        time.sleep(interval)
    return None


# ====================== ring format (unit) ======================


class TestRing:
    def test_roundtrip(self, tmp_path):
        path = str(tmp_path / "driver-1.ring")
        rec = flightrec.FlightRecorder(path, "driver", ring_kb=8)
        rec.record("task", "t-1", "start f trace=aabb")
        rec.record("lease", "blk-0", "carve free=2")
        rec.close()
        ring = flightrec.read_ring(path)
        assert ring["component"] == "driver"
        assert ring["pid"] == os.getpid()
        assert ring["written"] == 2
        assert [e["category"] for e in ring["events"]] == ["task", "lease"]
        assert ring["events"][0]["subject"] == "t-1"
        assert "trace=aabb" in ring["events"][0]["detail"]

    def test_wraparound_keeps_newest(self, tmp_path):
        path = str(tmp_path / "w-1.ring")
        rec = flightrec.FlightRecorder(path, "w", ring_kb=8)  # 64 slots
        for i in range(100):
            rec.record("task", f"t{i}", "x")
        rec.close()
        ring = flightrec.read_ring(path)
        assert ring["nslots"] == 64
        assert ring["written"] == 100
        assert len(ring["events"]) == 64
        # Oldest surviving record is seq 37 (100 - 64 + 1); newest is 100.
        assert ring["events"][0]["seq"] == 37
        assert ring["events"][0]["subject"] == "t36"
        assert ring["events"][-1]["seq"] == 100

    def test_oversize_fields_truncate_not_fail(self, tmp_path):
        path = str(tmp_path / "big-1.ring")
        rec = flightrec.FlightRecorder(path, "big", ring_kb=8)
        rec.record("task", "s" * 100, "d" * 300)
        rec.close()
        ring = flightrec.read_ring(path)
        assert ring["events"][0]["subject"] == "s" * flightrec.SUBJECT_MAX
        assert ring["events"][0]["detail"] == "d" * flightrec.DETAIL_MAX

    def test_torn_slot_skipped(self, tmp_path):
        path = str(tmp_path / "torn-1.ring")
        rec = flightrec.FlightRecorder(path, "torn", ring_kb=8)
        for i in range(5):
            rec.record("task", f"t{i}", "x")
        rec.close()
        # Corrupt slot index 2 (seq 3) with an absurd sequence number — the
        # shape a write torn by SIGKILL decodes to at worst.
        with open(path, "r+b") as f:
            data = bytearray(f.read())
            struct.pack_into("<Q", data, 64 + 2 * flightrec.SLOT_SIZE,
                             10 ** 15)
            f.seek(0)
            f.write(data)
        ring = flightrec.read_ring(path)
        assert [e["seq"] for e in ring["events"]] == [1, 2, 4, 5]

    def test_rejects_foreign_and_truncated_files(self, tmp_path):
        junk = tmp_path / "junk.ring"
        junk.write_bytes(b"not a ring at all" + b"\0" * 64)
        with pytest.raises(ValueError):
            flightrec.read_ring(str(junk))
        short = tmp_path / "short.ring"
        short.write_bytes(b"\0" * 8)
        with pytest.raises(ValueError):
            flightrec.read_ring(str(short))

    def test_ring_survives_sigkill(self, tmp_path):
        """The kernel owns the dirty mmap pages: a SIGKILLed process's last
        events are readable with no flush having ever run."""
        path = str(tmp_path / "victim-0.ring")
        code = (
            "import os, signal\n"
            "from ray_tpu.util import flightrec\n"
            f"rec = flightrec.FlightRecorder({path!r}, 'victim', ring_kb=8)\n"
            "rec.record('task', 't-9', 'start doomed')\n"
            "rec.record('lease', 'blk-3', 'carve free=1')\n"
            "os.kill(os.getpid(), signal.SIGKILL)\n"
        )
        proc = subprocess.run([sys.executable, "-c", code], cwd="/root/repo")
        assert proc.returncode == -signal.SIGKILL
        ring = flightrec.read_ring(path)
        assert ring["written"] == 2
        details = [e["detail"] for e in ring["events"]]
        assert "start doomed" in details[0]
        # No orderly shutdown record — the process never got to say goodbye.
        assert not any("shutdown" in d for d in details)


# ====================== watchdog (unit) ======================


_BOUNDS = dict(node_bounds=(2.5, 30.0), comp_bounds=(2.5, 30.0))


class TestWatchdog:
    def test_classify_pure(self):
        assert health.classify(0.1, 2.5, 30.0) == health.HEALTHY
        assert health.classify(5.0, 2.5, 30.0) == health.STALLED
        assert health.classify(31.0, 2.5, 30.0) == health.DEAD
        assert health.classify(None, 2.5, 30.0) == health.DEAD

    def test_node_stall_and_recovery(self):
        seen = []
        wd = health.HealthWatchdog(on_transition=seen.append)
        t0 = 1000.0
        wd.tick(node_ages={"n1": 0.5}, dead_nodes=set(), components=[],
                now=t0, **_BOUNDS)
        assert not seen  # subjects start healthy: no transition
        wd.tick(node_ages={"n1": 5.0}, dead_nodes=set(), components=[],
                now=t0 + 5, **_BOUNDS)
        assert seen[-1]["old"] == health.HEALTHY
        assert seen[-1]["new"] == health.STALLED
        wd.tick(node_ages={"n1": 0.2}, dead_nodes=set(), components=[],
                now=t0 + 6, **_BOUNDS)
        assert seen[-1]["new"] == health.HEALTHY
        assert wd.states()[0]["state"] == health.HEALTHY

    def test_vanished_component_is_dead(self):
        wd = health.HealthWatchdog()
        t0 = 1000.0
        comp = (("n1", "worker", 42), t0 - 1.0, t0 - 1.0)
        wd.tick(node_ages={"n1": 0.1}, dead_nodes=set(), components=[comp],
                now=t0, **_BOUNDS)
        trs = wd.tick(node_ages={"n1": 0.1}, dead_nodes=set(),
                      components=[], now=t0 + 1, **_BOUNDS)
        assert any(tr["kind"] == "component" and tr["new"] == health.DEAD
                   for tr in trs)

    def test_dead_host_kills_its_components(self):
        wd = health.HealthWatchdog()
        t0 = 1000.0
        comp = (("n1", "worker", 42), t0, t0)  # perfectly fresh report
        trs = wd.tick(node_ages={}, dead_nodes={"n1"}, components=[comp],
                      now=t0 + 1, **_BOUNDS)
        states = {tuple(s["key"]): s["state"] for s in wd.states()}
        assert states[("node", "n1")] == health.DEAD
        assert states[("component", "n1", "worker", 42)] == health.DEAD
        assert any(tr["kind"] == "component" for tr in trs)

    def test_dead_retention_prunes(self):
        wd = health.HealthWatchdog(dead_retention_s=0.5)
        t0 = 1000.0
        wd.tick(node_ages={"n1": 0.1}, dead_nodes=set(), components=[],
                now=t0, **_BOUNDS)
        wd.tick(node_ages={}, dead_nodes=set(), components=[],
                now=t0 + 1, **_BOUNDS)  # vanished -> dead
        assert wd.states()[0]["state"] == health.DEAD
        wd.tick(node_ages={}, dead_nodes=set(), components=[],
                now=t0 + 2, **_BOUNDS)  # past retention -> pruned
        assert wd.states() == []


# ====================== postmortem (unit) ======================


class TestPostmortem:
    def test_build_and_format(self, tmp_path):
        rec = flightrec.FlightRecorder(
            str(tmp_path / f"driver-{os.getpid()}.ring"), "driver")
        rec.record("task", "t-1", "start f trace=cafe01")
        rec.record("serve", "echo", "admit -> r0 trace=cafe01")
        rec.record("process", "driver", "shutdown")
        rec.close()
        gcs_events = [
            {"type": "health_transition", "kind": "node", "subject": "n1",
             "old": "healthy", "new": "dead", "time": time.time()},
            {"state": "FINISHED", "name": "f", "time": time.time(),
             "trace_id": "cafe01"},
        ]
        tl = postmortem.build_timeline(
            session_dir=str(tmp_path), gcs_events=gcs_events,
            health_states=[{"kind": "node", "key": ["node", "n1"],
                            "state": "dead"}])
        proc = tl["processes"][0]
        assert proc["alive"] and proc["component"] == "driver"
        # Trace cross-link spans the ring AND the GCS side table.
        assert len(tl["traces"]["cafe01"]) == 3
        linked = postmortem.events_for_trace(tl, "cafe01")
        assert {e["process"] for e in linked} == {
            f"driver:{os.getpid()}", "gcs-table"}
        assert any("watchdog" in d for d in tl["diagnosis"])
        text = postmortem.format_timeline(tl)
        assert "trace cafe01" in text
        assert "admit -> r0" in text

    def test_clean_exit_is_not_a_death(self, tmp_path):
        code = (
            "from ray_tpu.util import flightrec\n"
            f"import os\n"
            f"rec = flightrec.FlightRecorder(os.path.join({str(tmp_path)!r},"
            f" f'worker-{{os.getpid()}}.ring'), 'worker')\n"
            "rec.record('task', 't-1', 'finish')\n"
            "rec.record('process', 'worker', 'shutdown')\n"
            "rec.close()\n"
        )
        subprocess.run([sys.executable, "-c", code], cwd="/root/repo",
                       check=True)
        tl = postmortem.build_timeline(session_dir=str(tmp_path))
        assert tl["processes"][0]["clean_exit"]
        assert tl["diagnosis"] == []

    def test_prometheus_parse_and_select(self):
        text = ("# HELP ray_tpu_gcs_sched state\n"
                "# TYPE ray_tpu_gcs_sched gauge\n"
                'ray_tpu_gcs_sched{counter="leases"} 3\n'
                'ray_tpu_component_health{kind="node",state="dead"} 1\n'
                "plain_metric 1.5\n"
                "garbage line without value\n")
        series = postmortem.parse_prometheus(text)
        assert postmortem.select(series, "ray_tpu_gcs_sched")[0]["value"] == 3
        assert postmortem.select(series, "ray_tpu_component_health",
                                 state="dead")
        assert not postmortem.select(series, "ray_tpu_component_health",
                                     state="healthy")
        assert postmortem.select(series, "plain_metric")[0]["value"] == 1.5

    def test_debug_cli_offline(self, tmp_path):
        """`ray-tpu debug --session DIR --json` works with rings alone —
        no GCS required for a postmortem."""
        rec = flightrec.FlightRecorder(
            str(tmp_path / f"driver-{os.getpid()}.ring"), "driver")
        rec.record("task", "t-1", "start f")
        rec.close()
        out = subprocess.run(
            [sys.executable, "-m", "ray_tpu.scripts", "debug",
             "--session", str(tmp_path), "--json"],
            capture_output=True, text=True, cwd="/root/repo")
        assert out.returncode == 0, out.stderr
        tl = json.loads(out.stdout)
        assert tl["processes"][0]["pid"] == os.getpid()
        assert any(e["subject"] == "t-1" for e in tl["events"])


# ====================== cluster integration ======================


def _node_health(client, hexid):
    for s in client.call("health_states"):
        if s["kind"] == "node" and s["key"][1] == hexid:
            return s["state"]
    return None


# Module-level so the recorded function name stays short — a closure's
# qualname ("test_x.<locals>.f") would truncate past the ring's 40-char
# name budget.
@ray_tpu.remote(max_retries=0)
def _linger():
    time.sleep(300)


def test_sigstop_daemon_flips_stalled_then_healthy(tmp_path, monkeypatch):
    """SIGSTOP a node daemon mid-load: heartbeats freeze, the watchdog
    classifies the node `stalled` (NOT dead — its socket is still open),
    the gauge reflects it, and SIGCONT recovers it to `healthy`."""
    monkeypatch.setenv(flightrec.ENV_SESSION_DIR, str(tmp_path))
    # NOTE: the GCS runs with a 1s export interval while this (driver)
    # process keeps the 10s default, so the watchdog flaps the `driver`
    # component — deliberate config skew; assertions only read `node` kind.
    # Push the death bound far out so the stall window is wide enough to
    # observe and SIGCONT always lands before `dead`.
    cluster = Cluster(num_nodes=1, resources_per_node={"CPU": 2},
                      system_config={"health_check_failure_threshold": 60,
                                     "metrics_export_interval_s": 1.0})
    core = connect(cluster.gcs_address)
    try:
        @ray_tpu.remote
        def ping():
            return os.getpid()

        assert ray_tpu.get([ping.remote() for _ in range(4)], timeout=60)
        from ray_tpu.core.rpc import RpcClient

        client = RpcClient(cluster.gcs_address)
        daemon = cluster.nodes[0]
        hexid = daemon.node_id.hex()
        try:
            assert _wait_for(
                lambda: _node_health(client, hexid) == "healthy", 20)
            daemon.proc.send_signal(signal.SIGSTOP)
            try:
                # stall bound = period(1s) * factor(2.5) -> ~2.5s + tick lag
                assert _wait_for(
                    lambda: _node_health(client, hexid) == "stalled", 20)

                # The gauge ships on the GCS exporter tick — up to one
                # export interval behind the state change.
                def stalled_series():
                    series = postmortem.parse_prometheus(
                        client.call("metrics_text"))
                    return postmortem.select(
                        series, "ray_tpu_component_health", kind="node",
                        subject_node=hexid, state="stalled")

                assert _wait_for(stalled_series, 20)
            finally:
                daemon.proc.send_signal(signal.SIGCONT)
            assert _wait_for(
                lambda: _node_health(client, hexid) == "healthy", 20)
        finally:
            client.close()
    finally:
        core.shutdown()
        runtime_mod._global_runtime = None
        cluster.shutdown()


def test_sigkill_worker_postmortem_ring(tmp_path, monkeypatch):
    """kill -9 a worker mid-task: its ring shows the task start edge with
    no finish, and the postmortem names the dead worker."""
    monkeypatch.setenv(flightrec.ENV_SESSION_DIR, str(tmp_path))
    cluster = Cluster(num_nodes=1, resources_per_node={"CPU": 2})
    core = connect(cluster.gcs_address)
    try:
        ref = _linger.remote()

        def started():
            for path in flightrec.discover_rings(str(tmp_path)):
                try:
                    ring = flightrec.read_ring(path)
                except (OSError, ValueError):
                    continue
                if ring["component"] != "worker":
                    continue
                for e in ring["events"]:
                    if e["category"] == "task" and "start _linger" in e["detail"]:
                        return ring["pid"]
            return None

        pid = _wait_for(started, 60)
        assert pid, "worker never recorded the task-start edge"
        os.kill(pid, signal.SIGKILL)
        with pytest.raises(Exception):
            ray_tpu.get(ref, timeout=60)

        # The pid stays a zombie (alive to kill(pid, 0)) until the daemon's
        # reaper waits on it — poll until the postmortem sees it gone.
        def reaped():
            timeline = postmortem.build_timeline(session_dir=str(tmp_path))
            v = [p for p in timeline["processes"] if p["pid"] == pid]
            return timeline if v and not v[0]["alive"] else None

        tl = _wait_for(reaped, 30)
        assert tl, "killed worker never left the process table (unreaped?)"
        victim = [p for p in tl["processes"] if p["pid"] == pid][0]
        assert not victim["clean_exit"]
        assert any(f"worker:{pid}" in d for d in tl["diagnosis"])
        task_events = [e for e in tl["events"]
                       if e["process"] == f"worker:{pid}"
                       and e["category"] == "task"]
        assert any("start _linger" in e["detail"] for e in task_events)
        assert not any(e["detail"].startswith(("finish", "FAIL"))
                       for e in task_events)
    finally:
        core.shutdown()
        runtime_mod._global_runtime = None
        cluster.shutdown()


def test_chaos_daemon_kill_debug_timeline(tmp_path, monkeypatch):
    """The acceptance demo: kill -9 a node daemon under serve load, then
    `ray-tpu debug` merges every surviving ring with the GCS tables into a
    timeline that (a) names the dead component, (b) shows its last events
    including lease state and DAG channel records, (c) cross-links at
    least one request by trace id — and the watchdog flips the node to
    `dead` with the metric reflecting it."""
    monkeypatch.setenv(flightrec.ENV_SESSION_DIR, str(tmp_path))
    cluster = Cluster(num_nodes=2, resources_per_node={"CPU": 3},
                      system_config={"metrics_export_interval_s": 1.0})
    core = connect(cluster.gcs_address)
    try:
        from ray_tpu import serve
        from ray_tpu.dag import InputNode

        @serve.deployment(num_replicas=2)
        def echo(x):
            return {"v": x["v"] * 2}

        h = serve.run(echo.bind(), route_prefix="/chaos")
        for i in range(6):
            assert h.remote({"v": i}).result()["v"] == i * 2

        # Overlapping plain tasks force lease carves on both daemons so
        # whichever node dies has in-flight lease state in its ring.
        @ray_tpu.remote(num_cpus=1)
        def hold(i):
            time.sleep(0.3)
            return i

        assert ray_tpu.get([hold.remote(i) for i in range(12)],
                           timeout=60) == list(range(12))

        # One compiled-DAG run so channel lifecycle records land in rings.
        @ray_tpu.remote
        class Stage:
            def apply(self, x):
                return x + 1

        stage = Stage.remote()
        compiled = stage.apply.bind(InputNode()).experimental_compile()
        try:
            assert compiled.execute(41).get(timeout=60) == 42
        finally:
            compiled.teardown()

        # Kill a daemon that actually carved leases (hosts replicas /
        # actors) so its ring carries in-flight lease state — placement
        # decides which node that is, so pick by ring content.
        def daemon_with_leases():
            pids = {}
            for path in flightrec.discover_rings(str(tmp_path)):
                try:
                    ring = flightrec.read_ring(path)
                except (OSError, ValueError):
                    continue
                if ring["component"] == "node_daemon" and any(
                        e["category"] == "lease" for e in ring["events"]):
                    pids[ring["pid"]] = True
            for i, handle in enumerate(cluster.nodes):
                if handle.proc.pid in pids:
                    return i + 1  # 1-based so index 0 is truthy
            return None

        victim_slot = _wait_for(daemon_with_leases, 30)
        assert victim_slot, "no node daemon recorded lease activity"
        victim_idx = victim_slot - 1
        victim = cluster.nodes[victim_idx]
        hexid = victim.node_id.hex()
        daemon_pid = victim.proc.pid
        cluster.kill_node(victim_idx, sig=signal.SIGKILL)

        from ray_tpu.core.rpc import RpcClient

        client = RpcClient(cluster.gcs_address)
        try:
            assert _wait_for(
                lambda: _node_health(client, hexid) == "dead", 30)

            def dead_series():
                series = postmortem.parse_prometheus(
                    client.call("metrics_text"))
                return postmortem.select(
                    series, "ray_tpu_component_health", kind="node",
                    subject_node=hexid, state="dead")

            assert _wait_for(dead_series, 20)
        finally:
            client.close()

        out = subprocess.run(
            [sys.executable, "-m", "ray_tpu.scripts", "debug",
             "--session", str(tmp_path), "--gcs", cluster.gcs_address,
             "--json"],
            capture_output=True, text=True, cwd="/root/repo")
        assert out.returncode == 0, out.stderr
        tl = json.loads(out.stdout)

        # (a) the dead daemon is named.
        assert any(f"node_daemon:{daemon_pid}" in d
                   for d in tl["diagnosis"]), tl["diagnosis"]
        assert any(s.get("state") == "dead" and s.get("kind") == "node"
                   and s["key"][1] == hexid for s in tl["health"])
        # (b) its ring carries lease state; channel records made the merge.
        daemon_events = [e for e in tl["events"]
                         if e["process"] == f"node_daemon:{daemon_pid}"]
        assert any(e["category"] == "lease" for e in daemon_events)
        assert any(e["category"] == "channel" for e in tl["events"])
        # (c) at least one serve admission cross-links by trace id.
        admits = [e for e in tl["events"]
                  if e["category"] == "serve" and "admit" in e["detail"]
                  and "trace=" in e["detail"]]
        assert admits, "no trace-linked serve admissions recorded"
        linked = [tid for tid, idxs in tl["traces"].items()
                  if any(tl["events"][i]["category"] == "serve"
                         for i in idxs)]
        assert linked, "no request trace cross-linked in the timeline"
        # The human rendering names the dead process too.
        text = subprocess.run(
            [sys.executable, "-m", "ray_tpu.scripts", "debug",
             "--session", str(tmp_path)],
            capture_output=True, text=True, cwd="/root/repo").stdout
        assert f"node_daemon:{daemon_pid}" in text and "DEAD" in text
    finally:
        core.shutdown()
        runtime_mod._global_runtime = None
        cluster.shutdown()


# ====================== bench smoke (CI wiring) ======================


@pytest.mark.slow
class TestFlightBenchSmoke:
    def test_flight_overhead_quick(self, tmp_path):
        """`bench.py --flight-overhead --quick` in a child interpreter:
        schema sanity only — a single quick trial is too noisy to assert
        within_noise (the committed BENCH_obs_r03.json comes from the
        full 3-trial run)."""
        out = tmp_path / "BENCH_obs_smoke.json"
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        r = subprocess.run(
            [sys.executable, os.path.join(repo, "bench.py"),
             "--flight-overhead", "--quick", "--out", str(out)],
            capture_output=True, text=True, timeout=600,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert r.returncode == 0, r.stderr[-2000:]
        res = json.loads(out.read_text())["results"]
        for key in ("task_seq_per_s_flight_on", "task_seq_per_s_flight_off",
                    "record_ns_flight_on", "record_ns_flight_off",
                    "overhead_pct", "within_noise"):
            assert key in res, key
        # The recorder's hot path stays near the ~1us/event budget even on
        # a loaded CI box, and the disabled path is just a flag check.
        assert 0 < res["record_ns_flight_on"] < 20_000
        assert 0 < res["record_ns_flight_off"] < res["record_ns_flight_on"]
        assert res["task_seq_per_s_flight_on"] > 0
