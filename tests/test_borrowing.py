"""Distributed borrower-protocol tests (reference_count.h:61).

The owner of an object must defer cluster-wide frees while any other
process holds a live borrow — whether the ref crossed in task args, inside
a returned object, or sits in actor state — and must collect borrows from
processes that die without deregistering. The model test drives random
borrow/forward/drop sequences against a live multiprocess cluster and
checks both directions: no premature free (every read from a live holder
succeeds) and no leak (owner-side borrower/contained state fully drains
once every holder is gone).
"""

import gc
import random
import time

import pytest

import ray_tpu
from ray_tpu.core.cluster import Cluster, connect
from ray_tpu.core import runtime as runtime_mod


@pytest.fixture(scope="module")
def mp_cluster():
    cluster = Cluster(num_nodes=2, resources_per_node={"CPU": 2})
    yield cluster
    cluster.shutdown()


@pytest.fixture()
def driver(mp_cluster):
    core = connect(mp_cluster.gcs_address)
    yield core
    core.shutdown()
    runtime_mod._global_runtime = None


@ray_tpu.remote
class Holder:
    def __init__(self):
        self.refs = {}

    def store(self, name, boxed_ref):
        self.refs[name] = boxed_ref
        return True

    def read(self, name):
        return ray_tpu.get(self.refs[name][0], timeout=60)

    def fetch_box(self, name):
        """Forward the ref onward (still boxed) without dereferencing."""
        return self.refs[name]

    def drop(self, name):
        self.refs.pop(name)
        gc.collect()
        return True


def _drained(core, timeout=30.0):
    """Owner-side borrower/contained state fully empty."""
    deadline = time.time() + timeout
    rc = core.reference_counter
    while time.time() < deadline:
        gc.collect()
        with rc._lock:
            if not rc._borrowers and not rc._contained:
                return True
        time.sleep(0.25)
    return False


def test_actor_state_borrow_survives_driver_drop(driver):
    h = Holder.remote()
    ref = ray_tpu.put({"v": 7})
    assert ray_tpu.get(h.store.remote("a", [ref]), timeout=120)
    del ref
    gc.collect()
    time.sleep(0.5)
    assert ray_tpu.get(h.read.remote("a"), timeout=60) == {"v": 7}
    ray_tpu.get(h.drop.remote("a"), timeout=60)
    assert _drained(driver)


def test_ref_returned_inside_object(driver):
    @ray_tpu.remote
    def make():
        inner = ray_tpu.put("inner-payload")
        return {"ref": inner}

    box = ray_tpu.get(make.remote(), timeout=120)
    assert ray_tpu.get(box["ref"], timeout=60) == "inner-payload"


def test_forwarding_chain(driver):
    """driver -> A (stored) -> driver drops -> A forwards to B -> A drops:
    B must still read the value; then B drops and the owner drains."""
    a, b = Holder.remote(), Holder.remote()
    ref = ray_tpu.put(list(range(32)))
    ray_tpu.get(a.store.remote("x", [ref]), timeout=120)
    del ref
    gc.collect()
    time.sleep(0.5)
    box = ray_tpu.get(a.fetch_box.remote("x"), timeout=60)
    ray_tpu.get(b.store.remote("x", box), timeout=60)
    del box
    ray_tpu.get(a.drop.remote("x"), timeout=60)
    time.sleep(0.5)
    assert ray_tpu.get(b.read.remote("x"), timeout=60) == list(range(32))
    ray_tpu.get(b.drop.remote("x"), timeout=60)
    assert _drained(driver)


def test_kill_borrower_mid_use(driver):
    """A borrower dying without deregistering must not leak the object
    forever (sweep collects it), and must not affect other borrowers."""
    a, doomed = Holder.remote(), Holder.remote()
    ref = ray_tpu.put({"big": list(range(500))})
    ray_tpu.get(a.store.remote("k", [ref]), timeout=120)
    ray_tpu.get(doomed.store.remote("k", [ref]), timeout=60)
    del ref
    gc.collect()
    time.sleep(0.5)
    ray_tpu.kill(doomed)
    time.sleep(1.0)
    # surviving borrower still reads
    out = ray_tpu.get(a.read.remote("k"), timeout=60)
    assert out == {"big": list(range(500))}
    ray_tpu.get(a.drop.remote("k"), timeout=60)
    # the dead borrower's registration is swept (<= ~2 sweep periods)
    assert _drained(driver, timeout=60.0)


def test_borrow_model_random_sequences(driver):
    """Model-based: random put/store/forward/drop ops; after every op a
    random live holder must read the true value (no premature free), and
    at the end the owner's borrower/contained state drains (no leak)."""
    rng = random.Random(1234)
    actors = [Holder.remote() for _ in range(3)]
    # model: name -> {"value": v, "holders": set of actor idx, "driver": ref or None}
    model = {}
    next_id = 0

    for step in range(60):
        op = rng.choice(["put", "store", "forward", "drop_driver",
                         "drop_actor", "read"])
        if op == "put" or not model:
            name = f"obj{next_id}"
            next_id += 1
            value = {"name": name, "data": [rng.random() for _ in range(8)]}
            model[name] = {"value": value,
                           "holders": set(),
                           "driver": ray_tpu.put(value)}
        elif op == "store":
            name = rng.choice(list(model))
            ent = model[name]
            if ent["driver"] is None:
                continue
            idx = rng.randrange(len(actors))
            ray_tpu.get(actors[idx].store.remote(name, [ent["driver"]]),
                        timeout=120)
            ent["holders"].add(idx)
        elif op == "forward":
            candidates = [(n, e) for n, e in model.items() if e["holders"]]
            if not candidates:
                continue
            name, ent = rng.choice(candidates)
            src = rng.choice(sorted(ent["holders"]))
            dst = rng.randrange(len(actors))
            box = ray_tpu.get(actors[src].fetch_box.remote(name), timeout=60)
            ray_tpu.get(actors[dst].store.remote(name, box), timeout=60)
            del box
            ent["holders"].add(dst)
        elif op == "drop_driver":
            name = rng.choice(list(model))
            model[name]["driver"] = None
            gc.collect()
        elif op == "drop_actor":
            candidates = [(n, e) for n, e in model.items() if e["holders"]]
            if not candidates:
                continue
            name, ent = rng.choice(candidates)
            idx = rng.choice(sorted(ent["holders"]))
            ray_tpu.get(actors[idx].drop.remote(name), timeout=60)
            ent["holders"].discard(idx)
        elif op == "read":
            candidates = [(n, e) for n, e in model.items() if e["holders"]]
            if not candidates:
                continue
            name, ent = rng.choice(candidates)
            idx = rng.choice(sorted(ent["holders"]))
            got = ray_tpu.get(actors[idx].read.remote(name), timeout=60)
            assert got == ent["value"], f"step {step}: {name} corrupted"
        # prune fully-dropped entries from the model
        for name in [n for n, e in model.items()
                     if e["driver"] is None and not e["holders"]]:
            model.pop(name)

    # teardown: drop everything, owner state must drain
    for name, ent in model.items():
        for idx in sorted(ent["holders"]):
            ray_tpu.get(actors[idx].drop.remote(name), timeout=60)
        ent["driver"] = None
    model.clear()
    gc.collect()
    assert _drained(driver, timeout=60.0)
