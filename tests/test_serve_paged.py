"""Paged KV cache, prefix reuse, disaggregation, and affinity routing
(ISSUE 11).

Engine-level: the paged ``PagedLLMEngine`` must be token-identical to the
single-sequence ``Generator`` oracle (the slotted engine's own oracle) cold
AND warm — a prefix-cache hit changes FLOPs, never tokens; hit lengths must
land exactly on hash-block boundaries; COW tail forks must decode in
isolation and drop every refcount at retire (``active_blocks() == 0`` is
the leak-check invariant — the suite's ``RAY_TPU_LEAK_CHECK_ENABLED=1``
teardown guard covers the thread/fd half). Disaggregated: the
prefill→lane→decode pipeline keeps the same oracle equality and joins its
workers on ``close()``. Router-level: stale-load eviction on snapshot
shrink and prefix-affinity picks, as units on ``Router`` itself.
"""

import threading
import time

import jax
import pytest

import ray_tpu
from ray_tpu.models import generate, transformer
from ray_tpu.serve.handle import DeploymentHandle, Router
from ray_tpu.serve.llm import DisaggregatedLLMEngine, PagedLLMEngine
from ray_tpu.util.blockhash import prefix_head_hash

BT = 8  # test block size: small enough to exercise multi-block prompts


@pytest.fixture(scope="module")
def tiny_model():
    cfg = transformer.tiny(max_seq_len=64)
    params = transformer.init_params(cfg, jax.random.key(0))
    return cfg, params


@pytest.fixture(scope="module")
def oracle(tiny_model):
    """Single-sequence reference decode (memoized — it is the slow path)."""
    cfg, params = tiny_model
    gen = generate.Generator(params, cfg)
    memo = {}

    def run(prompt, n, temperature=0.0, seed=0):
        key = (tuple(prompt), n, temperature, seed)
        if key not in memo:
            memo[key] = gen.generate(
                list(prompt), max_new_tokens=n,
                temperature=temperature, seed=seed)
        return memo[key]

    return run


@pytest.fixture(scope="module")
def paged(tiny_model):
    """Shared paged engine; pool sized so no test's chains evict another's
    (hit-length deltas below assume no LRU eviction)."""
    cfg, params = tiny_model
    eng = PagedLLMEngine(params, cfg, prompt_buckets=(16, 32), chunk=4,
                         slots=2, max_queue=0, name="paged-test",
                         block_tokens=BT, pool_blocks=129)
    eng.warmup()
    return eng


def _hit_delta(eng, prompt, n, **kw):
    """Run one request and return (tokens, kv_hit_tokens delta)."""
    before = eng.kv.stats()["kv_hit_tokens"]
    out = eng.generate(list(prompt), max_new_tokens=n, **kw)
    return out, eng.kv.stats()["kv_hit_tokens"] - before


PROMPTS = [[7, 3, 11], [2, 4, 6, 8, 10], [1] * 9, [5, 9] * 7,
           list(range(100, 125))]  # last spans the 32 bucket


class TestPagedOracleEquivalence:
    def test_greedy_concurrent_across_buckets(self, paged, oracle):
        """Mixed-length prompts (both compile buckets) arriving staggered
        into 2 slots decode token-identically to the batch-1 oracle."""
        outs = [None] * len(PROMPTS)
        errs = []

        def client(i):
            try:
                time.sleep(i * 0.01)
                outs[i] = paged.generate(PROMPTS[i], max_new_tokens=12)
            except BaseException as e:  # noqa: BLE001
                errs.append(e)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(len(PROMPTS))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs
        for i, p in enumerate(PROMPTS):
            assert outs[i] == oracle(p, 12), f"prompt {i} diverged"
        assert paged.kv.active_blocks() == 0

    def test_warm_repeat_hits_and_matches(self, paged, oracle):
        """A repeated prompt hits its own retired chain — fewer prefill
        FLOPs, identical tokens."""
        p = list(range(200, 220))  # 20 tokens: 2 full blocks + tail
        cold, h0 = _hit_delta(paged, p, 8)
        warm, h1 = _hit_delta(paged, p, 8)
        assert cold == warm == oracle(p, 8)
        assert h0 == 0
        # Chain 28 tokens: full-block hit 24, capped tail walk adds ≤ bt-1;
        # at minimum both full blocks of the prompt hit.
        assert h1 >= 2 * BT

    def test_sampled_matches_oracle(self, paged, oracle):
        p = PROMPTS[1]
        out = paged.generate(p, max_new_tokens=12, temperature=0.8, seed=123)
        assert out == oracle(p, 12, temperature=0.8, seed=123)

    def test_out_of_vocab_prompt_rejected(self, paged):
        """An out-of-range id would gather a NaN embedding that OUTLIVES the
        request in the shared pool (trash block + cached chain) — admission
        must reject it before it reaches the device."""
        with pytest.raises(ValueError, match="token ids"):
            paged.generate([1, 2, 256], max_new_tokens=4)
        with pytest.raises(ValueError, match="token ids"):
            paged.generate([-1, 2, 3], max_new_tokens=4)


class TestPrefixBoundaries:
    """Hit lengths land exactly on hash-block boundaries: a shared prefix
    one token short of a block hits nothing; at the boundary it hits the
    whole block; past it, still only the full blocks."""

    BASE = [31 + 2 * i for i in range(24)]  # 3 full blocks, distinctive

    @pytest.fixture(scope="class")
    def base_chain(self, paged, oracle):
        out = paged.generate(list(self.BASE), max_new_tokens=12)
        assert out == oracle(self.BASE, 12)
        return list(self.BASE) + out  # 36 tokens: 4 full blocks + tail(4)

    @pytest.mark.parametrize("shared,expected_hit", [
        (BT - 1, 0),        # one short of a block: nothing stable to hit
        (BT, BT),           # exactly one block
        (BT + 1, BT),       # one past: the odd token is re-prefilled
        (2 * BT, 2 * BT),
        (3 * BT, 3 * BT),
    ])
    def test_hit_at_offset(self, paged, oracle, base_chain, shared,
                           expected_hit):
        # Divergent suffix unique per offset so probes can't hit each other
        # (ids stay < vocab 256 — the engine rejects out-of-range tokens).
        probe = base_chain[:shared] + [220 + shared, 241, 242]
        out, hit = _hit_delta(paged, probe, 4)
        assert out == oracle(probe, 4), f"shared={shared} diverged"
        assert hit == expected_hit
        assert paged.kv.active_blocks() == 0

    def test_full_chain_tail_hit(self, paged, oracle):
        """Extending a whole retired chain (the multi-turn case) also hits
        the registered partial tail block, not just full blocks."""
        base = [171 + i for i in range(12)]
        out = paged.generate(base, max_new_tokens=6)
        assert out == oracle(base, 6)
        chain = base + out  # 18 tokens: 2 full blocks + 2-token tail
        probe = chain + [251, 252, 253]
        out, hit = _hit_delta(paged, probe, 4)
        assert out == oracle(probe, 4)
        assert hit == len(chain)  # 16 full + 2 tail


class TestCOWForkIsolation:
    def test_forked_tails_decode_independently(self, paged, oracle):
        """Two forks of one retired conversation share its partial tail
        block copy-on-write: both decode oracle-identically (no
        cross-contamination through the shared block) and every refcount
        drops to zero at retire."""
        base = [131 + i for i in range(12)]  # 12 tokens: 1 full block + tail
        out = paged.generate(base, max_new_tokens=6)
        chain = base + out  # 18 tokens: 2 full blocks + 2-token tail
        cows0 = paged.kv.stats()["kv_cow_copies"]
        forks = [chain + [211, 212, 213], chain + [221, 222, 223]]
        outs = [None, None]
        errs = []

        def client(i):
            try:
                outs[i] = paged.generate(forks[i], max_new_tokens=8)
            except BaseException as e:  # noqa: BLE001
                errs.append(e)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs
        for i in range(2):
            assert outs[i] == oracle(forks[i], 8), f"fork {i} diverged"
        # Each fork hit the 2-token tail -> one private COW copy apiece.
        assert paged.kv.stats()["kv_cow_copies"] - cows0 >= 2
        # Leak-check invariant: nothing stays pinned after retire.
        assert paged.kv.active_blocks() == 0
        assert paged.kv._ref == {}

    def test_stats_surface(self, paged):
        s = paged.stats()
        for key in ("kv_blocks_total", "kv_blocks_active", "kv_blocks_cached",
                    "kv_blocks_free", "kv_hit_tokens", "kv_miss_tokens",
                    "kv_cow_copies"):
            assert key in s
        assert s["kv_blocks_total"] == 128.0
        assert (s["kv_blocks_active"] + s["kv_blocks_cached"]
                + s["kv_blocks_free"]) == s["kv_blocks_total"]


class TestPagedMetrics:
    def test_kv_metrics_exported(self, paged):
        from ray_tpu.core.metrics_export import (metrics_enabled,
                                                 serve_kv_block_occupancy,
                                                 serve_kv_hit_tokens_total)

        if not metrics_enabled():
            pytest.skip("metrics_export_enabled off")
        p = [61 + i for i in range(18)]
        paged.generate(p, max_new_tokens=4)
        paged.generate(p, max_new_tokens=4)  # warm: flushes hit tokens
        tags = {"deployment": paged.name}
        assert serve_kv_hit_tokens_total().get(tags) >= 2 * BT
        occ = serve_kv_block_occupancy()
        by_state = {s: occ.get({**tags, "state": s})
                    for s in ("active", "cached", "free")}
        assert sum(by_state.values()) == 128.0
        assert by_state["cached"] > 0  # retired chains stay reusable

    def test_ttft_phase_split(self, paged):
        from ray_tpu.core.metrics_export import (metrics_enabled,
                                                 serve_ttft_hist)

        if not metrics_enabled():
            pytest.skip("metrics_export_enabled off")
        paged.generate([91, 92, 93], max_new_tokens=4)
        h = serve_ttft_hist()
        snap = dict(h._snapshot()["samples"])
        counts = {}
        for tags, (_buckets, _sum, count) in snap.items():
            t = dict(tags)
            if t.get("deployment") == paged.name:
                counts[t["phase"]] = count
        for phase in ("total", "queued", "prefill", "decode"):
            assert counts.get(phase, 0) > 0, f"missing phase {phase}"


class TestCancelMidDispatchRace:
    def test_cancel_between_dispatch_and_commit_leaks_nothing(self, paged,
                                                              oracle):
        """_dispatch_prefill runs outside _state_lock; a cancel landing
        between the device dispatch and the block-table commit must neither
        leak the freshly pinned blocks (commit overwriting a freed slot)
        nor publish prefix digests pointing at freed blocks."""
        victim_prompt = [44 + 2 * i for i in range(2 * BT + 3)]
        state = {}
        orig_fn = paged._pg.prefill_fn

        def hooked(bucket):
            pf = orig_fn(bucket)

            def run(*args):
                out = pf(*args)
                req = state.get("victim")
                if req is not None and not req.done:
                    paged._cancel(req)  # lands inside the race window
                return out

            return run

        paged._pg.prefill_fn = hooked
        try:
            req = paged.submit(victim_prompt, max_new_tokens=6)
            state["victim"] = req
            out = list(paged.drive(req))
        finally:
            paged._pg.prefill_fn = orig_fn
            state["victim"] = None
        assert req.finish_reason == "cancelled"
        assert out == []  # cancelled before any decode chunk
        # The pins taken for the cancelled admission were dropped...
        assert paged.kv.active_blocks() == 0
        # ...and nothing was registered against the dropped blocks: a
        # same-prefix probe must miss the cache yet match the oracle.
        probe = victim_prompt + [201]
        out, hit = _hit_delta(paged, probe, 6)
        assert hit == 0
        assert out == oracle(probe, 6)
        assert paged.kv.active_blocks() == 0


class _StubReplica:
    def __init__(self, key):
        class _Id:
            @staticmethod
            def hex():
                return key

        self.actor_id = _Id()


def _mk_router(replicas, load):
    r = Router.__new__(Router)
    r._name = "stub"
    r._replicas = replicas
    r._replica_load = load
    r._model_ids = {}
    r._ongoing = {}
    r._max_ongoing = 100
    r._lock = threading.Lock()
    r._last_refresh = time.monotonic()  # fresh — _refresh() is a no-op
    r._version = 0
    return r


class _FakeController:
    """get_snapshot.remote returns the canned table directly; the test
    monkeypatches ray_tpu.get to the identity so Router._refresh consumes
    it without a live controller actor."""

    def __init__(self, version, table):
        outer = self

        class _Method:
            @staticmethod
            def remote(_version, _timeout):
                return outer._version, outer._table

        self._version = version
        self._table = table
        self.get_snapshot = _Method()


class TestRouterStaleEviction:
    def test_refresh_evicts_departed_replicas(self, monkeypatch):
        """Shrinking replica set: ongoing counts, load entries, and affinity
        pins for replicas gone from the snapshot are evicted — a stale entry
        must not keep steering (or starving) the pow-2 pick."""
        monkeypatch.setattr(ray_tpu, "get", lambda x, **kw: x)
        a, b = _StubReplica("a"), _StubReplica("b")
        r = _mk_router([a, b], {})
        r._ongoing = {"a": 3, "b": 2}
        r._affinity_map().update({b"h-a": "a", b"h-b": "b"})
        r._controller = _FakeController(1, {"stub": {
            "replicas": [b],
            "max_ongoing_requests": 100,
            "model_ids": {},
            # Controller-side load table still carries the dead replica.
            "replica_load": {"a": {"slots_busy": 4.0, "slots_total": 4.0},
                             "b": {"slots_busy": 1.0, "slots_total": 4.0}},
        }})
        r._refresh(block=True)
        assert r._replicas == [b]
        assert r._ongoing == {"b": 2}
        assert r._replica_load == {"b": {"slots_busy": 1.0,
                                         "slots_total": 4.0}}
        assert r._affinity_map() == {b"h-b": "b"}
        # Picks route only to the survivor afterwards.
        for _ in range(5):
            _best, key = r._pick()
            assert key == "b"
            r._dec(key)


class TestPrefixAffinityRouting:
    def test_pick_prefers_affinity_replica(self):
        """An affinity-pinned replica wins the pick outright — even when
        pow-2 would prefer the other (lower ongoing) replica."""
        reps = [_StubReplica("a"), _StubReplica("b")]
        r = _mk_router(reps, {})
        r._affinity_map()[b"h1"] = "b"
        r._ongoing = {"a": 0, "b": 5}  # pow-2 would choose a
        for _ in range(10):
            _best, key = r._pick(prefix_hash=b"h1")
            assert key == "b"
            r._dec(key)

    def test_first_pick_records_affinity(self):
        reps = [_StubReplica("a"), _StubReplica("b")]
        r = _mk_router(reps, {})
        _best, key = r._pick(prefix_hash=b"h2")
        assert r._affinity_map()[b"h2"] == key
        # The same prefix sticks to that replica even though its ongoing
        # count is now higher than the other's.
        _best, key2 = r._pick(prefix_hash=b"h2")
        assert key2 == key

    def test_affinity_migrates_off_exhausted_replica(self):
        """A pinned replica reporting a full slot set loses the pick; the
        pow-2 winner inherits the pin (the prefix re-caches there)."""
        reps = [_StubReplica("a"), _StubReplica("b")]
        r = _mk_router(reps, {
            "b": {"slots_total": 2.0, "slots_busy": 2.0},
            "a": {"slots_total": 2.0, "slots_busy": 0.0},
        })
        r._affinity_map()[b"h3"] = "b"
        _best, key = r._pick(prefix_hash=b"h3")
        assert key == "a"
        assert r._affinity_map()[b"h3"] == "a"

    def test_affinity_map_lru_bound(self):
        r = _mk_router([_StubReplica("a")], {})
        r.AFFINITY_CAP = 3
        with r._lock:
            for i in range(5):
                r._note_affinity(b"k%d" % i, "a")
        assert list(r._affinity_map()) == [b"k2", b"k3", b"k4"]

    def test_handle_affinity_hash(self):
        from ray_tpu.core.config import config

        cfg = config()
        if not cfg.serve_prefix_affinity_enabled:
            pytest.skip("serve_prefix_affinity_enabled off")
        bt = int(cfg.serve_kv_block_tokens)
        prompt = list(range(2 * bt + 3))
        h = DeploymentHandle._affinity_hash([{"prompt_ids": prompt}])
        assert h == prefix_head_hash(
            prompt, bt, int(cfg.serve_prefix_affinity_blocks))
        assert h is not None
        # Sub-block prompts and non-LLM payloads produce no affinity key.
        assert DeploymentHandle._affinity_hash(
            [{"prompt_ids": prompt[:bt - 1]}]) is None
        assert DeploymentHandle._affinity_hash(["plain-arg"]) is None
        assert DeploymentHandle._affinity_hash([]) is None


class TestDisaggregated:
    @pytest.fixture(scope="class")
    def disagg(self, tiny_model):
        cfg, params = tiny_model
        eng = DisaggregatedLLMEngine(
            params, cfg, prompt_buckets=(16, 32), chunk=4, slots=2,
            max_queue=0, name="disagg-test", block_tokens=BT,
            pool_blocks=65)
        eng.warmup()
        yield eng
        eng.close()
        eng.close()  # idempotent
        assert not [t for t in threading.enumerate()
                    if t.name.startswith("disagg-test-disagg")]

    def test_greedy_matches_oracle(self, disagg, oracle):
        for p in PROMPTS[:3]:
            assert disagg.generate(p, max_new_tokens=8) == oracle(p, 8)
        assert disagg.decode.kv.active_blocks() == 0
        assert disagg.prefill.kv.active_blocks() == 0

    def test_shared_prefix_hits_prefill_cache(self, disagg, oracle):
        """Requests sharing a 2-block prefix pay its prefill FLOPs once on
        the prefill engine; every output stays oracle-equal."""
        prefix = [151 + i for i in range(2 * BT)]
        prompts = [prefix + [231 + i] for i in range(3)]
        before = disagg.stats()["prefill_kv_hit_tokens"]
        outs = [None] * 3
        errs = []

        def client(i):
            try:
                outs[i] = disagg.generate(prompts[i], max_new_tokens=6)
            except BaseException as e:  # noqa: BLE001
                errs.append(e)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs
        for i in range(3):
            assert outs[i] == oracle(prompts[i], 6), f"request {i} diverged"
        # At least the two later arrivals hit the first's full blocks.
        assert disagg.stats()["prefill_kv_hit_tokens"] - before >= \
            2 * (2 * BT)
        assert disagg.decode.kv.active_blocks() == 0

    def test_sampled_matches_oracle(self, disagg, oracle):
        p = PROMPTS[1]
        out = disagg.generate(p, max_new_tokens=8, temperature=0.7, seed=9)
        assert out == oracle(p, 8, temperature=0.7, seed=9)

    def test_send_failure_poisons_one_request_only(self, disagg, oracle):
        """A lane.send failure (non-timeout) resolves ONLY its own ticket as
        an error and unqueues it from the handoff FIFO — later requests must
        pair with their own payloads instead of inheriting the dead
        ticket's, and the stream reports finish_reason "error"."""
        orig_send = disagg.lane.send
        calls = {"n": 0}

        def flaky(meta, k, v, timeout=30.0):
            calls["n"] += 1
            if calls["n"] == 1:
                raise ValueError("payload exceeds lane capacity")
            return orig_send(meta, k, v, timeout=timeout)

        disagg.lane.send = flaky
        try:
            result = {}
            with pytest.raises(ValueError, match="lane capacity"):
                list(disagg.stream([61, 62, 63], max_new_tokens=4,
                                   result=result))
            assert result["finish_reason"] == "error"
            p = [64, 65, 66, 67]
            assert disagg.generate(p, max_new_tokens=6) == oracle(p, 6)
        finally:
            disagg.lane.send = orig_send
        assert disagg.decode.kv.active_blocks() == 0
        assert disagg.prefill.kv.active_blocks() == 0
