"""raylint + lockcheck: the static pass trips on seeded violations, the
whole tree is clean against the checked-in baseline, and the runtime
validator catches a provoked inversion.

The clean-tree test IS the CI gate: a new lock inversion, blocking call
under a lock, untimed wait, swallowed exception, RPC-surface typo, or
unknown config knob anywhere in ray_tpu/ fails tier-1 until fixed or
explicitly accepted with ``--update-baseline``.
"""

import os
import textwrap
import threading

import pytest

from ray_tpu.devtools import lint
from ray_tpu.devtools import lockcheck


def _write(tmp_path, rel, src):
    p = tmp_path / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(src))
    return p


def _checks(findings):
    return {f.check for f in findings}


# ---------------------------------------------------------------------------
# seeded fixture snippets — each must trip its check
# ---------------------------------------------------------------------------


def test_lock_order_cycle_detected(tmp_path):
    _write(tmp_path, "mod.py", """
        import threading

        class Inverted:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def path1(self):
                with self._a:
                    with self._b:
                        pass

            def path2(self):
                with self._b:
                    self._helper()   # interprocedural: _helper takes _a

            def _helper(self):
                with self._a:
                    pass
        """)
    findings = lint.lint_tree(str(tmp_path))
    cycles = [f for f in findings if f.check == "lock-order"]
    assert cycles, findings
    assert "Inverted._a" in cycles[0].message
    assert "Inverted._b" in cycles[0].message


def test_self_deadlock_detected(tmp_path):
    _write(tmp_path, "mod.py", """
        import threading

        class Re:
            def __init__(self):
                self._l = threading.Lock()
                self._cv = threading.Condition(self._l)

            def bad(self):
                with self._l:
                    with self._cv:   # same underlying non-reentrant lock
                        pass
        """)
    findings = lint.lint_tree(str(tmp_path))
    assert any(f.check == "lock-order" and "self-deadlock" in f.detail
               for f in findings), findings


def test_blocking_under_lock_detected(tmp_path):
    _write(tmp_path, "mod.py", """
        import subprocess
        import threading
        import time

        class B:
            def __init__(self):
                self._lock = threading.Lock()
                self._other = threading.Condition()
                self.sock = None
                self.peer = None

            def bad(self):
                with self._lock:
                    time.sleep(0.1)
                    self.sock.recv(4096)
                    self.peer.call("ping")
                    self._other.wait(1.0)
                    subprocess.check_output(["true"])
                    open("/tmp/x")
        """)
    findings = [f for f in lint.lint_tree(str(tmp_path))
                if f.check == "blocking-under-lock"]
    kinds = {f.detail.split(":")[0] for f in findings}
    assert {"sleep", "socket", "rpc", "wait", "subprocess",
            "file-io"} <= kinds, findings


def test_wait_on_own_condition_not_flagged(tmp_path):
    _write(tmp_path, "mod.py", """
        import threading

        class Ok:
            def __init__(self):
                self._lock = threading.Lock()
                self._cv = threading.Condition(self._lock)

            def fine(self):
                with self._lock:
                    self._cv.wait(timeout=1.0)  # releases _lock: not blocking
        """)
    findings = lint.lint_tree(str(tmp_path))
    assert not [f for f in findings if f.check == "blocking-under-lock"], \
        findings


def test_untimed_wait_detected(tmp_path):
    _write(tmp_path, "mod.py", """
        import threading

        class W:
            def __init__(self):
                self._ev = threading.Event()

            def park(self, fut):
                self._ev.wait()
                return fut.result()
        """)
    findings = [f for f in lint.lint_tree(str(tmp_path))
                if f.check == "untimed-wait"]
    assert len(findings) == 2, findings
    assert {f.detail.split(":")[0] for f in findings} == {"wait", "result"}


def test_swallowed_exception_detected_and_log_swallowed_not(tmp_path):
    _write(tmp_path, "mod.py", """
        def loop():
            try:
                step()
            except Exception:
                pass

        def fixed(logger):
            try:
                step()
            except Exception:
                log_swallowed(logger, "step in loop")
        """)
    findings = [f for f in lint.lint_tree(str(tmp_path))
                if f.check == "swallowed-exception"]
    assert len(findings) == 1 and findings[0].scope == "loop", findings


def test_rpc_surface_unknown_method_detected(tmp_path):
    _write(tmp_path, "svc.py", """
        class FooService:
            def ping(self):
                return "pong"

        def serve():
            service = FooService()
            return RpcServer(service, name="foo")

        def use(client):
            client.call("ping")               # resolves
            client.call("not_a_method")       # typo: flagged
            client.notify("_private")         # private: flagged
        """)
    findings = [f for f in lint.lint_tree(str(tmp_path))
                if f.check == "rpc-surface"]
    details = {f.detail for f in findings}
    assert details == {"unknown:not_a_method", "private:_private"}, findings


def test_config_knob_checks(tmp_path):
    _write(tmp_path, "core/config.py", """
        class _Flag:
            def __init__(self, default):
                self.default = default

        class Config:
            # a documented, used knob
            good_knob = _Flag(1)
            orphan_knob = _Flag(2)
        """)
    _write(tmp_path, "user.py", """
        from core.config import config

        def f():
            cfg = config()
            return cfg.good_knob + cfg.not_a_knob
        """)
    findings = [f for f in lint.lint_tree(str(tmp_path))
                if f.check == "config-knob"]
    details = {f.detail for f in findings}
    assert "unknown:not_a_knob" in details, findings
    assert "unused:orphan_knob" in details, findings
    assert "undocumented:orphan_knob" in details, findings
    assert not any("good_knob" in d for d in details), findings


def test_pragma_suppresses_reviewed_false_positive(tmp_path):
    _write(tmp_path, "mod.py", """
        import threading
        import time

        class P:
            def __init__(self):
                self._lock = threading.Lock()

            def reviewed(self):
                with self._lock:
                    # raylint: ignore[blocking-under-lock] — bounded 1ms
                    time.sleep(0.001)
        """)
    findings = lint.lint_tree(str(tmp_path))
    assert not [f for f in findings if f.check == "blocking-under-lock"], \
        findings


# ---------------------------------------------------------------------------
# baseline workflow + the CI gate
# ---------------------------------------------------------------------------


def test_baseline_update_then_clean(tmp_path):
    _write(tmp_path, "mod.py", """
        def loop():
            try:
                step()
            except Exception:
                pass
        """)
    baseline = tmp_path / "baseline.txt"
    # dirty against an empty baseline
    rc = lint.main([str(tmp_path), "--baseline", str(baseline), "-q"])
    assert rc == 1
    # accept, then clean
    rc = lint.main([str(tmp_path), "--baseline", str(baseline),
                    "--update-baseline"])
    assert rc == 0
    rc = lint.main([str(tmp_path), "--baseline", str(baseline), "-q"])
    assert rc == 0
    # a NEW finding fails again; the accepted one stays accepted
    _write(tmp_path, "mod2.py", """
        def loop2():
            try:
                step()
            except Exception:
                pass
        """)
    rc = lint.main([str(tmp_path), "--baseline", str(baseline), "-q"])
    assert rc == 1


def test_tree_is_clean_against_checked_in_baseline():
    """THE tier-1 gate: `python -m ray_tpu.devtools.lint` on the real tree
    must exit 0 against the committed baseline."""
    rc = lint.main(["-q"])
    assert rc == 0, ("raylint found NEW violations — fix them or accept "
                     "deliberately with --update-baseline")


def test_tree_scan_covers_known_hot_modules():
    """The scan actually sees the concurrency-heavy modules (guards against
    a silently-wrong default scan root)."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(lint.__file__)))
    linter = lint.Linter(root)
    linter.run()
    scanned = set(linter.src_lines)
    assert {"core/gcs_server.py", "core/core_worker.py", "core/rpc.py",
            "parallel/collectives.py", "core/object_store.py"} <= scanned
    # the RPC surface map found every service handler
    assert {"GcsService", "NodeDaemon", "WorkerService", "_OwnerService",
            "_MemberService"} <= set(linter.services)
    # the knob registry was located
    assert linter.flags and linter.flag_path == "core/config.py"


# ---------------------------------------------------------------------------
# runtime lockcheck
# ---------------------------------------------------------------------------


@pytest.fixture
def checked():
    installed_before = lockcheck.installed()
    lockcheck.install(fresh_graph=not installed_before)
    before = len(lockcheck.violations())
    yield lockcheck
    # drop violations this test provoked on purpose, then restore state
    with lockcheck._state_lock:
        del lockcheck._violations[before:]
    if not installed_before:
        lockcheck.uninstall()


def test_lockcheck_catches_cross_thread_inversion(checked):
    A = threading.Lock()
    B = threading.Lock()
    caught = []

    def t1():
        with A:
            with B:
                pass

    def t2():
        try:
            with B:
                with A:
                    pass
        except lockcheck.LockOrderError as e:
            caught.append(e)

    th = threading.Thread(target=t1)
    th.start()
    th.join()
    th = threading.Thread(target=t2)
    th.start()
    th.join()
    assert caught, "inversion not raised"
    assert "inversion" in str(caught[0])
    assert any("inversion" in v for v in lockcheck.violations())


def test_lockcheck_consistent_order_and_reentrancy_ok(checked):
    A = threading.Lock()
    B = threading.Lock()
    R = threading.RLock()
    for _ in range(3):
        with A:
            with B:
                with R:
                    with R:  # reentrant: fine
                        pass
    cv = threading.Condition(A)
    hits = []

    def waiter():
        with cv:
            cv.wait(timeout=5.0)
            hits.append(1)

    th = threading.Thread(target=waiter)
    th.start()
    import time

    time.sleep(0.1)
    with cv:
        cv.notify_all()
    th.join()
    assert hits == [1]


def test_lockcheck_self_deadlock(checked):
    L = threading.Lock()
    with pytest.raises(lockcheck.LockOrderError, match="self-deadlock"):
        with L:
            L.acquire()
