"""raylint v2: cross-process RPC wait-cycle analysis, thread/resource
lifecycle checks, and the runtime leak validator.

Seeded fixtures trip each new check; the real tree must stay clean against
the checked-in baseline (the PR 4 gate already enforces that — these tests
add coverage guards proving the NEW passes actually see the hot modules);
leakcheck units prove the dynamic half names leaked threads/fds with their
allocation sites.
"""

import os
import textwrap
import threading

import pytest

from ray_tpu.devtools import leakcheck, lint


def _write(tmp_path, rel, src):
    p = tmp_path / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(src))
    return p


# ---------------------------------------------------------------------------
# rpc-cycle: cross-process wait cycles
# ---------------------------------------------------------------------------

_CYCLE_SRC = """
    import threading

    class GcsService:
        def __init__(self):
            self._daemons = Pool()
            self._server = RpcServer(self)

        def kill_node(self, addr):
            # handler blocks on an RPC whose handler can call back here
            return self._daemons.get(addr).call("drain")

    class NodeDaemon:
        def __init__(self):
            self._gcs = Client()
            self._lock = threading.Lock()
            self._server = RpcServer(self)

        def drain(self):
            return self._helper()

        def _helper(self):
            with self._lock:
                return self._gcs.call("kill_node", "self")
    """


def test_rpc_cycle_detected(tmp_path):
    _write(tmp_path, "svc.py", _CYCLE_SRC)
    findings = [f for f in lint.lint_tree(str(tmp_path))
                if f.check == "rpc-cycle"]
    cycles = [f for f in findings if f.detail.startswith("cycle:")]
    assert cycles, findings
    assert "GcsService.kill_node" in cycles[0].message
    assert "NodeDaemon.drain" in cycles[0].message
    # the interprocedural hop (drain -> _helper -> .call) was followed, and
    # the lock held across the in-cycle RPC edge is flagged too
    held = [f for f in findings if f.detail.startswith("lock-held:")]
    assert held and "NodeDaemon._lock" in held[0].message, findings


def test_rpc_cycle_notify_edge_is_not_a_wait_edge(tmp_path):
    _write(tmp_path, "svc.py", _CYCLE_SRC.replace(
        '.call("drain")', '.notify("drain")'))
    findings = [f for f in lint.lint_tree(str(tmp_path))
                if f.check == "rpc-cycle"]
    # one hop became fire-and-forget: nobody parks, no cycle
    assert not [f for f in findings if f.detail.startswith("cycle:")], \
        findings


def test_rpc_lock_composition_without_handler_cycle(tmp_path):
    _write(tmp_path, "svc.py", """
        import threading

        class GcsService:
            def __init__(self):
                self._daemons = Pool()
                self._lock = threading.Lock()
                self._server = RpcServer(self)

            def update(self):
                with self._lock:
                    return self._daemons.get("x").call("apply")

            def read_state(self):
                with self._lock:
                    return 1

        class NodeDaemon:
            def __init__(self):
                self._gcs = Client()
                self._server = RpcServer(self)

            def apply(self):
                return self._gcs.call("read_state")
        """)
    findings = [f for f in lint.lint_tree(str(tmp_path))
                if f.check == "rpc-cycle"]
    # no handler->handler cycle (read_state has no outgoing edge) ...
    assert not [f for f in findings if f.detail.startswith("cycle:")]
    # ... but update blocks on apply while holding _lock, and apply calls
    # back into read_state, which NEEDS _lock: composed deadlock
    lock_rpc = [f for f in findings if f.detail.startswith("lock-rpc:")]
    assert lock_rpc, findings
    assert "GcsService._lock" in lock_rpc[0].message
    assert "GcsService.read_state" in lock_rpc[0].message


def test_rpc_cycle_lock_held_site_not_shadowed_by_unlocked_site(tmp_path):
    # the SAME edge dispatched twice — once lock-free, once under a lock:
    # collapsing to the first site must not hide the lock-held finding
    _write(tmp_path, "svc.py", """
        import threading

        class GcsService:
            def __init__(self):
                self._daemons = Pool()
                self._lock = threading.Lock()
                self._server = RpcServer(self)

            def kill_node(self, addr):
                self._daemons.get(addr).call("drain")   # lock-free first
                with self._lock:
                    return self._daemons.get(addr).call("drain")

        class NodeDaemon:
            def __init__(self):
                self._gcs = Client()
                self._server = RpcServer(self)

            def drain(self):
                return self._gcs.call("kill_node", "self")
        """)
    findings = [f for f in lint.lint_tree(str(tmp_path))
                if f.check == "rpc-cycle"]
    held = [f for f in findings if f.detail.startswith("lock-held:")]
    assert held and "GcsService._lock" in held[0].message, findings


def test_rpc_cycle_pragma_suppression(tmp_path):
    _write(tmp_path, "svc.py", _CYCLE_SRC.replace(
        'return self._daemons.get(addr).call("drain")',
        '# raylint: ignore[rpc-cycle] — reviewed: daemon never calls back\n'
        '            return self._daemons.get(addr).call("drain")'))
    findings = [f for f in lint.lint_tree(str(tmp_path))
                if f.check == "rpc-cycle" and f.detail.startswith("cycle:")]
    assert not findings, findings


# ---------------------------------------------------------------------------
# thread-leak
# ---------------------------------------------------------------------------


def test_unjoined_nondaemon_attr_thread_detected(tmp_path):
    _write(tmp_path, "mod.py", """
        import threading

        class Leaky:
            def __init__(self):
                self._t = threading.Thread(target=self._run)
                self._t.start()

            def _run(self):
                pass
        """)
    findings = [f for f in lint.lint_tree(str(tmp_path))
                if f.check == "thread-leak"]
    assert len(findings) == 1 and findings[0].detail == "unjoined:_t", \
        findings


def test_annotated_assign_thread_site_is_seen(tmp_path):
    _write(tmp_path, "mod.py", """
        import threading

        class Typed:
            def __init__(self):
                self._t: threading.Thread = threading.Thread(target=print)
                self._t.start()
        """)
    findings = [f for f in lint.lint_tree(str(tmp_path))
                if f.check == "thread-leak"]
    assert len(findings) == 1 and findings[0].detail == "unjoined:_t", \
        findings


def test_joined_daemonized_and_local_threads(tmp_path):
    _write(tmp_path, "mod.py", """
        import threading

        class Fine:
            def __init__(self):
                self._t = threading.Thread(target=self._run)
                self._t.start()
                self._d = threading.Thread(target=self._run, daemon=True)
                self._late = threading.Thread(target=self._run)
                self._late.daemon = True

            def _run(self):
                pass

            def shutdown(self):
                self._stop()

            def _stop(self):
                self._t.join(timeout=2.0)   # reachable via shutdown()

        def local_joined():
            t = threading.Thread(target=print)
            t.start()
            t.join()

        def local_leaky():
            t = threading.Thread(target=print)
            t.start()

        def anonymous_leaky():
            threading.Thread(target=print).start()
        """)
    findings = [f for f in lint.lint_tree(str(tmp_path))
                if f.check == "thread-leak"]
    details = {f.detail for f in findings}
    assert details == {"local:t", "anonymous-thread"}, findings
    assert {f.scope for f in findings} == {"local_leaky",
                                           "anonymous_leaky"}, findings


# ---------------------------------------------------------------------------
# resource-leak
# ---------------------------------------------------------------------------


def test_shm_acquire_without_release_detected(tmp_path):
    _write(tmp_path, "mod.py", """
        from multiprocessing.shared_memory import SharedMemory

        class Seg:
            def __init__(self):
                self._seg = SharedMemory(name="x", create=True, size=64)
        """)
    findings = [f for f in lint.lint_tree(str(tmp_path))
                if f.check == "resource-leak"]
    assert len(findings) == 1
    assert findings[0].detail == "unreleased:shm:_seg", findings


def test_released_resources_and_fd_cache_are_clean(tmp_path):
    _write(tmp_path, "mod.py", """
        import os
        import socket
        from multiprocessing.shared_memory import SharedMemory

        class Fine:
            def __init__(self):
                self._seg = SharedMemory(name="x", create=True, size=64)
                self._sock = socket.socket()
                self._fds = {}
                self._fds["k"] = os.open("/tmp/x", os.O_RDONLY)

            def _open_more(self, key):
                fd = os.open(key, os.O_RDONLY)
                self._fds[key] = fd

            def close(self):
                self._seg.close()
                self._seg.unlink()
                self._sock.close()
                for fd in self._fds.values():
                    os.close(fd)
                self._fds.clear()

        def local_closed():
            s = socket.socket()
            s.close()

        def local_escapes():
            s = socket.socket()
            return s
        """)
    findings = [f for f in lint.lint_tree(str(tmp_path))
                if f.check == "resource-leak"]
    assert not findings, findings


def test_local_socket_leak_detected_and_pragma(tmp_path):
    _write(tmp_path, "mod.py", """
        import socket

        def leaky():
            s = socket.socket()
            s.connect(("127.0.0.1", 1))

        def reviewed():
            # raylint: ignore[resource-leak] — reviewed: process-lifetime
            s = socket.socket()
            s.connect(("127.0.0.1", 1))
        """)
    findings = [f for f in lint.lint_tree(str(tmp_path))
                if f.check == "resource-leak"]
    assert len(findings) == 1 and findings[0].scope == "leaky", findings
    assert findings[0].detail == "local:socket:s"


# ---------------------------------------------------------------------------
# baseline round-trip with the new checks
# ---------------------------------------------------------------------------


def test_baseline_round_trip_new_checks(tmp_path):
    _write(tmp_path, "svc.py", _CYCLE_SRC)
    baseline = tmp_path / "baseline.txt"
    rc = lint.main([str(tmp_path), "--baseline", str(baseline), "-q"])
    assert rc == 1  # dirty vs empty baseline
    assert lint.main([str(tmp_path), "--baseline", str(baseline),
                      "--update-baseline"]) == 0
    assert lint.main([str(tmp_path), "--baseline", str(baseline),
                      "--check-baseline", "-q"]) == 0  # accepted
    _write(tmp_path, "mod2.py", """
        import threading

        class Leaky2:
            def __init__(self):
                self._t = threading.Thread(target=print)
                self._t.start()
        """)
    rc = lint.main([str(tmp_path), "--baseline", str(baseline), "-q"])
    assert rc == 1  # the NEW thread-leak fails; accepted cycle stays quiet


# ---------------------------------------------------------------------------
# report runtime: shared AST cache + --profile timings
# ---------------------------------------------------------------------------


def test_ast_cache_and_profile_timings(tmp_path):
    p = _write(tmp_path, "mod.py", "x = 1\n")
    t1, _ = lint._parse_cached(str(p))
    t2, _ = lint._parse_cached(str(p))
    assert t1 is t2  # cached: same tree object, no re-parse
    p.write_text("x = 2\n")
    t3, _ = lint._parse_cached(str(p))
    assert t3 is not t1  # edit invalidates

    root = os.path.dirname(os.path.dirname(os.path.abspath(lint.__file__)))
    linter = lint.Linter(root)
    linter.run()
    assert {"parse", "scan", "lock-order", "rpc-cycle", "thread-leak",
            "resource-leak", "total"} <= set(linter.timings)
    # full-tree lint stays fast enough to run inside tier-1
    assert linter.timings["total"] < 15.0, linter.timings


# ---------------------------------------------------------------------------
# coverage guard: the new passes actually see the hot modules
# ---------------------------------------------------------------------------


def test_new_checks_cover_hot_modules():
    root = os.path.dirname(os.path.dirname(os.path.abspath(lint.__file__)))
    linter = lint.Linter(root)
    findings = linter.run()

    by_name = {}
    for c in linter.classes:
        by_name.setdefault(c.name, c)

    # resource scan saw the daemon's spill-chunk fd cache — and the
    # shutdown path releases it (the leak this PR fixed stays fixed)
    nd = by_name["NodeDaemon"]
    assert any(s.attr == "_spill_fds" and s.is_dict and s.kind == "fd"
               for s in nd.resource_sites)
    assert not any(f.check == "resource-leak" and "_spill_fds" in f.detail
                   for f in findings)

    # thread scan saw the metrics exporter thread
    exp = by_name["MetricsExporter"]
    assert any(s.attr == "_thread" for s in exp.thread_sites)

    # the inter-process graph has real blocking edges between the services
    edges = set()
    for svc, info in linter.services.items():
        for m, sites in linter._service_rpc_closure(info).items():
            if m not in info.public_methods:
                continue
            for site in sites:
                tgt = linter._resolve_service(site.recv)
                if tgt and site.kind == "call" and \
                        site.method in linter.services[tgt].public_methods:
                    edges.add((f"{svc}.{m}", f"{tgt}.{site.method}"))
    assert ("NodeDaemon.execute_task", "WorkerService.run_task") in edges
    assert any(src.startswith("GcsService.") for src, _ in edges)

    # and the whole tree is currently wait-cycle free
    assert not [f for f in findings if f.check == "rpc-cycle"], \
        [f.render() for f in findings if f.check == "rpc-cycle"]


# ---------------------------------------------------------------------------
# leakcheck: the runtime half
# ---------------------------------------------------------------------------


@pytest.fixture
def leak_installed():
    was = leakcheck.installed()
    leakcheck.install()
    yield leakcheck
    if not was:
        leakcheck.uninstall()


def test_leakcheck_names_thread_leak_with_site(leak_installed):
    before = leakcheck.snapshot()
    ev = threading.Event()
    t = threading.Thread(target=ev.wait, daemon=True, name="leaky-thread")
    t.start()
    try:
        leaks = leakcheck.check(before, settle_s=0.1)
        assert any("leaky-thread" in l for l in leaks), leaks
        # allocation site points at THIS file
        assert any("test_devtools_lint2.py" in l for l in leaks), leaks
    finally:
        ev.set()
        t.join()
    assert leakcheck.check(before, settle_s=2.0) == []


def test_leakcheck_names_fd_leak_with_site(leak_installed):
    before = leakcheck.snapshot()
    fd = os.open("/tmp", os.O_RDONLY)
    try:
        leaks = leakcheck.check(before, settle_s=0.05)
        assert any(f"fd {fd}" in l for l in leaks), leaks
        assert any("os.open" in l and "test_devtools_lint2.py" in l
                   for l in leaks), leaks
    finally:
        os.close(fd)
    assert leakcheck.check(before, settle_s=0.5) == []


def test_leakcheck_clean_teardown_is_clean(leak_installed):
    before = leakcheck.snapshot()
    t = threading.Thread(target=lambda: None)
    t.start()
    t.join()
    fd = os.open("/tmp", os.O_RDONLY)
    os.close(fd)
    import socket as socket_mod

    s = socket_mod.socket()
    s.close()
    assert leakcheck.check(before, settle_s=1.0) == []
