"""Podracer-scale RL: rollout lanes, inference actors, LLM post-training.

Covers the transport/equivalence contracts behind ``BENCH_rl_r01.json``:
the DAG rollout lane must move the SAME fragments the task path moves,
Sebulba inference must pick the SAME actions Anakin picks (the runner
keeps its key stream; only the forward moves), backpressure must block
producers instead of dropping fragments, and env-runner death must be
survivable mid-iteration on both transports.
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import (
    ImpalaConfig,
    InferencePool,
    LLMRL,
    LLMRLConfig,
    RolloutLanes,
    SingleAgentEnvRunner,
)
from ray_tpu.rllib.rl_module import spec_for_env


def cartpole():
    import gymnasium as gym

    return gym.make("CartPole-v1")


# Shared with the in-process runner threads: arming "fail" makes exactly
# one env step raise (the box is reset by the raising wrapper), which
# poisons one rollout-lane tick.
_FLAKY_BOX = {"fail": False}


def _flaky_cartpole():
    import gymnasium as gym

    class _OneShotFailure(gym.Wrapper):
        def step(self, action):
            if _FLAKY_BOX["fail"]:
                _FLAKY_BOX["fail"] = False
                raise RuntimeError("injected env failure")
            return self.env.step(action)

    return _OneShotFailure(gym.make("CartPole-v1"))


FRAGMENT_COLS = ("obs", "actions", "logp", "values", "rewards",
                 "terminateds", "valids", "bootstrap_value",
                 "bootstrap_obs")


class TestRolloutLanes:
    def test_lane_vs_task_fragment_equivalence(self, ray_start_regular):
        """The lane transport is a transport: a runner sampled through a
        compiled-DAG tick yields bitwise the same fragment as an
        identically-seeded runner sampled over the task path."""
        runner_cls = ray_tpu.remote(SingleAgentEnvRunner)
        lane_runner = runner_cls.remote(cartpole, num_envs=2, seed=7)
        task_runner = runner_cls.remote(cartpole, num_envs=2, seed=7)
        lanes = RolloutLanes([lane_runner], num_steps=8, depth=1)
        try:
            for _ in range(3):  # stays equal across consecutive fragments
                (lane_frag,) = lanes.next(timeout=30.0)
                task_frag = ray_tpu.get(task_runner.sample.remote(8))
                for col in FRAGMENT_COLS:
                    assert np.array_equal(
                        np.asarray(lane_frag[col]),
                        np.asarray(task_frag[col])), col
                assert "metrics" in lane_frag  # metrics ride the fragment
        finally:
            lanes.teardown()
            ray_tpu.kill(lane_runner)
            ray_tpu.kill(task_runner)

    def test_lane_backpressure_never_drops_fragments(self, ray_start_regular):
        """A slow consumer backpressures the lane; every fragment still
        arrives, in order: each tick's first observation must equal the
        previous tick's bootstrap obs per runner (a dropped or reordered
        fragment breaks the env-state continuity chain)."""
        runner_cls = ray_tpu.remote(SingleAgentEnvRunner)
        runners = [runner_cls.remote(cartpole, num_envs=2, seed=11 + i)
                   for i in range(2)]
        lanes = RolloutLanes(runners, num_steps=4, depth=2)
        try:
            lanes.fill()
            time.sleep(0.3)  # learner stalls; producers block on the ring
            prev = None
            for _ in range(6):
                frags = lanes.next(timeout=30.0)
                assert len(frags) == len(runners)
                if prev is not None:
                    for last, frag in zip(prev, frags):
                        assert np.array_equal(frag["obs"][0],
                                              last["bootstrap_obs"])
                prev = frags
        finally:
            lanes.teardown()
            for r in runners:
                ray_tpu.kill(r)


class TestInferenceActors:
    def test_inference_actions_match_runner_local(self, ray_start_regular):
        """Sebulba == Anakin on policy output: with the same weights and
        the same runner key stream, centralized batched inference samples
        bitwise-identical actions/log-probs (values are a separate vmapped
        forward — allclose)."""
        spec = spec_for_env(cartpole())
        local = SingleAgentEnvRunner(cartpole, num_envs=3, seed=21,
                                     spec=spec)
        pool = InferencePool(1, spec, seed=0, num_clients=1)
        pool.set_weights(local.get_weights())
        remote = SingleAgentEnvRunner(cartpole, num_envs=3, seed=21,
                                      spec=spec,
                                      inference=pool.handle_for(0))
        try:
            local_frag = local.sample(12)
            remote_frag = remote.sample(12)
            assert np.array_equal(local_frag["actions"],
                                  remote_frag["actions"])
            assert np.array_equal(local_frag["logp"], remote_frag["logp"])
            np.testing.assert_allclose(local_frag["values"],
                                       remote_frag["values"],
                                       rtol=1e-5, atol=1e-5)
            # identical actions => identical trajectories
            assert np.array_equal(local_frag["obs"], remote_frag["obs"])
            assert np.array_equal(local_frag["rewards"],
                                  remote_frag["rewards"])
        finally:
            local.stop()
            remote.stop()
            pool.stop()

    def test_impala_trains_with_inference_pool(self, ray_start_regular):
        cfg = ImpalaConfig(env=cartpole, num_env_runners=2,
                           num_envs_per_runner=2,
                           rollout_fragment_length=8, seed=0,
                           rollout_lanes=True, num_inference_actors=1)
        algo = cfg.build()
        try:
            result = algo.train()
            assert result["num_updates"] >= 1
            assert np.isfinite(result["loss"])
            assert result["timesteps_total"] > 0
            assert result["learner_idle_s"] >= 0.0
        finally:
            algo.stop()


class TestRunnerDeath:
    def _kill_and_train(self, algo):
        algo.train()
        victim = algo._runners[0]
        survivors = list(algo._runners[1:])
        ray_tpu.kill(victim)
        # Two more iterations must complete with a respawned runner.
        before = algo._timesteps
        algo.train()
        result = algo.train()
        assert algo._timesteps > before
        assert np.isfinite(result["loss"])
        assert len(algo._runners) == 2
        assert algo._runners[0] is not victim
        assert all(r in algo._runners for r in survivors)
        assert all(ray_tpu.get(r.ping.remote(), timeout=10.0)
                   for r in algo._runners)

    def test_impala_task_path_survives_runner_death(self, ray_start_regular):
        """ActorError from an in-flight ``sample`` respawns the runner with
        current weights and relaunches its in-flight quota."""
        cfg = ImpalaConfig(env=cartpole, num_env_runners=2,
                           num_envs_per_runner=2,
                           rollout_fragment_length=8, seed=0,
                           rollout_lanes=False)
        algo = cfg.build()
        try:
            self._kill_and_train(algo)
        finally:
            algo.stop()

    def test_impala_lane_mode_recovers_from_stage_failure(
            self, ray_start_regular):
        """A failing stage poisons its tick (the DAG delivers the stage
        error to the driver); IMPALA tears the lane down, pings the fleet,
        respawns any runner that won't answer and rebuilds the lane.
        Injects both failure kinds at once: one runner raises mid-sample,
        another has been killed (in-process kill stops RPC service but not
        the parked DAG loop, so only the ping-probe can see it)."""
        _FLAKY_BOX["fail"] = False
        cfg = ImpalaConfig(env=_flaky_cartpole, num_env_runners=2,
                           num_envs_per_runner=2,
                           rollout_fragment_length=8, seed=0,
                           rollout_lanes=True, sample_timeout_s=30.0)
        algo = cfg.build()
        try:
            algo.train()
            victim = algo._runners[1]
            keeper = algo._runners[0]
            ray_tpu.kill(victim)
            _FLAKY_BOX["fail"] = True  # next env step raises once
            before = algo._timesteps
            algo.train()
            result = algo.train()
            assert algo._timesteps > before
            assert np.isfinite(result["loss"])
            assert not _FLAKY_BOX["fail"], "stage failure never fired"
            assert algo._runners[0] is keeper
            assert algo._runners[1] is not victim
            # No ping here: the rebuilt lane has re-parked both runners in
            # its DAG loop, where regular RPCs queue behind the loop.
        finally:
            algo.stop()

    def test_appo_survives_runner_death(self, ray_start_regular):
        from ray_tpu.rllib import APPOConfig

        cfg = APPOConfig(env=cartpole, num_env_runners=2,
                         num_envs_per_runner=2,
                         rollout_fragment_length=8, seed=0,
                         rollout_lanes=False)
        algo = cfg.build()
        try:
            self._kill_and_train(algo)
        finally:
            algo.stop()


class TestLLMRL:
    def test_reward_improves_deterministically(self, ray_start_regular):
        """The clipped-surrogate post-training loop must raise the mean
        sampled reward under a fixed seed (first-third vs last-third of
        iterations, strictly)."""
        algo = LLMRL(LLMRLConfig(seed=0, num_generators=2))
        try:
            rewards = [algo.train()["reward_mean"] for _ in range(6)]
        finally:
            algo.stop()
        k = len(rewards) // 3
        first = sum(rewards[:k]) / k
        last = sum(rewards[-k:]) / k
        assert last > first, rewards
