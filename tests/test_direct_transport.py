"""Direct task transport — lease reuse, owner-served objects, crash reclaim.

The round-3 hot-path redesign (reference:
``src/ray/core_worker/transport/direct_task_transport.cc:24,197,241``):
clients lease a worker from the daemon once per scheduling key, push tasks
straight to the worker process (the daemon is out of the request AND reply
path), keep the leased worker across tasks while demand continues, and
release after the idle TTL. Inline-small objects are served by their OWNER's
in-process store (``ownership_based_object_directory.cc`` analog) without a
daemon seal.
"""

import os
import signal
import subprocess
import sys
import time

import pytest

import ray_tpu
from ray_tpu.core.cluster import Cluster, connect
from ray_tpu.core import runtime as runtime_mod
from ray_tpu.core.rpc import RpcClient


@pytest.fixture(scope="module")
def mp_cluster():
    cluster = Cluster(num_nodes=2, resources_per_node={"CPU": 2})
    yield cluster
    cluster.shutdown()


@pytest.fixture
def driver(mp_cluster):
    core = connect(mp_cluster.gcs_address)
    yield core
    core.shutdown()
    runtime_mod._global_runtime = None


def _wait_for(predicate, timeout=60.0, interval=0.2):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


def test_sequential_tasks_reuse_leased_worker(driver):
    """Back-to-back tasks of one scheduling key run on the SAME worker
    process without a per-task GCS lease round trip (worker-lease reuse,
    direct_task_transport.cc:197 OnWorkerIdle)."""

    @ray_tpu.remote
    def pid():
        return os.getpid()

    first = ray_tpu.get(pid.remote(), timeout=120)
    # Let any OTHER hot leases (prior tests / warmup) expire: afterwards
    # exactly one worker is leased by the first call and every back-to-back
    # call reuses it (inter-call gap << idle TTL).
    time.sleep(1.5)
    first = ray_tpu.get(pid.remote(), timeout=60)
    pids = {ray_tpu.get(pid.remote(), timeout=60) for _ in range(10)}
    assert pids == {first}


def test_idle_lease_released_after_ttl(driver, mp_cluster):
    """A leased worker's resources return to the cluster after the idle TTL
    (no demand → no held lease)."""

    @ray_tpu.remote
    def nop():
        return None

    ray_tpu.get(nop.remote(), timeout=120)
    gcs = RpcClient(mp_cluster.gcs_address)
    try:
        assert _wait_for(
            lambda: gcs.call("available_resources").get("CPU", 0) == 4.0,
            timeout=15)
    finally:
        gcs.close()


def test_driver_kill9_reclaims_leases_and_workers(mp_cluster):
    """kill -9 a driver holding reused leases: the GCS releases its
    connection-scoped leases and the daemons kill its directly-leased
    workers (the reference ties leases to the gRPC channel)."""
    script = f"""
import os, time
os.environ["JAX_PLATFORMS"] = "cpu"
import ray_tpu
from ray_tpu.core.cluster import connect

core = connect({mp_cluster.gcs_address!r})

@ray_tpu.remote
def spin():
    time.sleep(600)

for _ in range(3):
    spin.remote()
print("SUBMITTED", flush=True)
time.sleep(600)
"""
    proc = subprocess.Popen([sys.executable, "-c", script],
                            stdout=subprocess.PIPE, cwd=os.path.dirname(
                                os.path.dirname(os.path.abspath(__file__))))
    try:
        deadline = time.time() + 120
        while time.time() < deadline:
            line = proc.stdout.readline()
            if b"SUBMITTED" in line:
                break
        else:
            pytest.fail("driver never submitted")
        gcs = RpcClient(mp_cluster.gcs_address)
        try:
            # Leases actually held by the spinning tasks.
            assert _wait_for(
                lambda: gcs.call("available_resources").get("CPU", 4.0) <= 1.0,
                timeout=60)
            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=10)
            # Conn-scoped lease release + daemon worker reclaim.
            assert _wait_for(
                lambda: gcs.call("available_resources").get("CPU", 0) == 4.0,
                timeout=60)
        finally:
            gcs.close()
    finally:
        if proc.poll() is None:
            proc.kill()


def test_owner_served_small_objects_cross_process(driver):
    """Inline-small task returns have no daemon replica — a ref passed to a
    task on another process resolves through the OWNER's service."""

    @ray_tpu.remote
    def make():
        return {"k": 42}

    ref = make.remote()
    assert ray_tpu.get(ref, timeout=120) == {"k": 42}
    # No GCS location row (the object lives in the owner's cache only).
    assert driver._gcs_rpc.call("locate_object", ref.id.binary()) == []

    @ray_tpu.remote
    def use(d):
        return d["k"] + 1

    assert ray_tpu.get(use.remote(ref), timeout=120) == 43


def test_owner_served_put_cross_process(driver):
    """Small put() objects are owner-served too."""
    ref = ray_tpu.put([1, 2, 3])
    assert driver._gcs_rpc.call("locate_object", ref.id.binary()) == []

    @ray_tpu.remote
    def total(xs):
        return sum(xs)

    assert ray_tpu.get(total.remote(ref), timeout=120) == 6


def test_streaming_generator_items_arrive_before_completion(driver):
    """Generator items are pushed to the owner AS PRODUCED
    (core_worker.cc:3199 analog): the first item is observable while the
    task is still running — round 2 buffered the whole stream until
    task completion."""

    @ray_tpu.remote(num_returns="streaming")
    def ticker():
        for i in range(4):
            yield i
            time.sleep(1.0)

    gen = ticker.remote()
    t0 = time.time()
    it = iter(gen)
    first_ref = next(it)
    first = ray_tpu.get(first_ref, timeout=120)
    first_latency = time.time() - t0
    rest = [ray_tpu.get(r, timeout=120) for r in it]
    total = time.time() - t0
    assert first == 0 and rest == [1, 2, 3]
    # The task runs ~4s; the first item must arrive well before the end.
    assert first_latency < total - 1.5, (first_latency, total)


def test_streaming_generator_error_surfaces_mid_stream(driver):
    @ray_tpu.remote(num_returns="streaming", max_retries=0)
    def bad():
        yield 1
        raise ValueError("stream kaboom")

    refs = list(bad.remote())
    assert ray_tpu.get(refs[0], timeout=120) == 1
    with pytest.raises(ValueError, match="stream kaboom"):
        ray_tpu.get(refs[1], timeout=120)


def test_streaming_generator_backpressure_bounds_producer(driver, mp_cluster):
    """A fast producer stalls once it runs a full window ahead of a slow
    consumer (reference: _generator_backpressure_num_objects)."""

    @ray_tpu.remote(num_returns="streaming")
    def firehose(n):
        for i in range(n):
            yield i

    # window (64) * 3 items: producer must block on the progress probe
    # until the consumer advances; the stream still completes correctly.
    gen = firehose.remote(192)
    seen = []
    for ref in gen:
        seen.append(ray_tpu.get(ref, timeout=120))
    assert seen == list(range(192))


def test_serve_streams_tokens_cross_process(driver):
    """Serve's streaming handle rides the incremental generator path on the
    MULTIPROCESS runtime: tokens reach the client while the replica is
    still generating (the reference's Serve token streaming over
    streaming-generator returns)."""
    from ray_tpu import serve

    @serve.deployment
    def tokens(x):
        for i in range(3):
            yield {"tok": i}
            time.sleep(0.8)

    h = serve.run(tokens.bind(), name="stream-mp")
    try:
        t0 = time.time()
        arrivals = []
        for chunk in h.options(stream=True).remote({"n": 3}):
            arrivals.append((chunk, time.time() - t0))
        assert [c["tok"] for c, _ in arrivals] == [0, 1, 2]
        # First token observable before the replica finished (~2.4s run).
        assert arrivals[0][1] < arrivals[-1][1] - 0.7, arrivals
    finally:
        serve.shutdown()


def test_admit_in_order_pipelined_races():
    """Unit-level: the server's admission protocol under a pipelined client.

    Pool threads can reach _admit_in_order in ANY arrival order; the
    window_min baseline (task_spec.py window_min) must still admit strictly
    by sequence number, never rewind the cursor, and fast-forward past
    client-side-dropped seqs (reference contract:
    sequential_actor_submit_queue.cc)."""
    import threading

    from ray_tpu.core.ids import ActorID, JobID, TaskID
    from ray_tpu.core.task_spec import TaskOptions, TaskSpec, TaskType
    from ray_tpu.core.worker_main import WorkerService, _ActorState

    aid = ActorID.from_random()
    state = _ActorState(aid, object(), max_concurrency=1)
    svc = WorkerService.__new__(WorkerService)  # only _admit_in_order used

    def spec(seq, window_min):
        return TaskSpec(
            task_id=TaskID.for_task(JobID.from_int(1), aid),
            job_id=JobID.from_int(1), task_type=TaskType.ACTOR_TASK,
            function_id="f", function_name="A", args=[], kwargs={},
            options=TaskOptions(), actor_id=aid, actor_method="m",
            sequence_number=seq, caller_id="h1", window_min=window_min)

    admitted = []
    lock = threading.Lock()

    def admit(seq, wm):
        svc._admit_in_order(state, spec(seq, wm), timeout=10.0)
        with lock:
            admitted.append(seq)

    # Burst 0..7 (window_min=0) arriving in a hostile order: later seqs
    # first. Each runs on its own thread like the server's pool.
    order = [3, 1, 7, 0, 5, 2, 6, 4]
    threads = [threading.Thread(target=admit, args=(s, 0)) for s in order]
    for t in threads:
        t.start()
        time.sleep(0.02)  # force distinct arrival times in the worst order
    for t in threads:
        t.join(timeout=30)
    assert admitted == list(range(8)), admitted

    # Fresh incarnation mid-stream: first arrival is seq 11 but the
    # handle's lowest outstanding is 10 -> 11 must wait for 10.
    state2 = _ActorState(aid, object(), max_concurrency=1)
    admitted.clear()
    def admit2(seq, wm):
        svc._admit_in_order(state2, spec(seq, wm), timeout=10.0)
        with lock:
            admitted.append(seq)
    t11 = threading.Thread(target=admit2, args=(11, 10))
    t10 = threading.Thread(target=admit2, args=(10, 10))
    t11.start(); time.sleep(0.05); t10.start()
    t11.join(timeout=30); t10.join(timeout=30)
    assert admitted == [10, 11], admitted

    # Client dropped seq 12 before sending (serialization failure):
    # seq 13 carries window_min=13 and must not starve behind the gap.
    t13 = threading.Thread(target=admit2, args=(13, 13))
    t13.start(); t13.join(timeout=30)
    assert admitted == [10, 11, 13], admitted


def test_admit_interior_gap_with_skip():
    """An interior dropped seq (older calls still in flight) is closed by
    the skip_actor_seq control message, not window_min."""
    import threading

    from ray_tpu.core.ids import ActorID, JobID, TaskID
    from ray_tpu.core.task_spec import TaskOptions, TaskSpec, TaskType
    from ray_tpu.core.worker_main import WorkerService, _ActorState

    aid = ActorID.from_random()
    state = _ActorState(aid, object(), max_concurrency=1)
    svc = WorkerService.__new__(WorkerService)
    svc._actors_lock = threading.Lock()
    svc._actors = {aid: state}

    def spec(seq, wm):
        return TaskSpec(
            task_id=TaskID.for_task(JobID.from_int(1), aid),
            job_id=JobID.from_int(1), task_type=TaskType.ACTOR_TASK,
            function_id="f", function_name="A", args=[], kwargs={},
            options=TaskOptions(), actor_id=aid, actor_method="m",
            sequence_number=seq, caller_id="h", window_min=wm)

    admitted = []
    lock = threading.Lock()

    def admit(seq, wm):
        svc._admit_in_order(state, spec(seq, wm), timeout=10.0)
        with lock:
            admitted.append(seq)

    # seq 0 admitted; seq 1 in flight (slow); seq 2 dropped client-side;
    # seq 3 sent with window_min=1 (1 still outstanding).
    admit(0, 0)
    t3 = threading.Thread(target=admit, args=(3, 1))
    t3.start()
    time.sleep(0.1)
    svc.skip_actor_seq(aid.binary(), "h", 2)   # client reports the gap
    t1 = threading.Thread(target=admit, args=(1, 1))
    t1.start()
    t1.join(timeout=30)
    t3.join(timeout=30)
    assert admitted == [0, 1, 3], admitted


def test_unpicklable_actor_arg_does_not_wedge_handle(driver):
    """A call with an unserializable argument fails cleanly AND later calls
    on the same handle still run (interior-gap skip end-to-end)."""
    import threading as _threading

    @ray_tpu.remote
    class Echo:
        def val(self, x):
            return x if not hasattr(x, "acquire") else "lock"

    e = Echo.remote()
    assert ray_tpu.get(e.val.remote(1), timeout=120) == 1
    bad = e.val.remote(_threading.Lock())  # cannot pickle
    with pytest.raises(Exception):
        ray_tpu.get(bad, timeout=60)
    # handle must not be wedged behind the dropped seq
    assert ray_tpu.get(e.val.remote(2), timeout=60) == 2
    assert ray_tpu.get([e.val.remote(i) for i in range(3, 8)],
                       timeout=60) == list(range(3, 8))
