"""Parallel object-plane read path: batched multi-ref get, multi-source
striped pulls, and location-push wakeups — plus the memory-store LRU /
restore-capacity satellites.

Reference analogs: the owner-resolved batched get of
``core_worker.cc`` ``GetObjects``, chunked multi-source pulls of
``pull_manager.cc``, and the object-location pubsub of
``ownership_based_object_directory.cc``.
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.core import runtime as runtime_mod
from ray_tpu.core.cluster import Cluster, connect
from ray_tpu.core.config import Config, set_config
from ray_tpu.core.ids import ObjectID
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.object_store import MemoryStore
from ray_tpu.core.serialization import serialize


@pytest.fixture
def fresh_config():
    """Install a pristine Config for store unit tests; restore after."""
    def install(**overrides):
        set_config(Config(overrides))

    install()
    yield install
    set_config(Config())


@pytest.fixture
def two_nodes():
    cluster = Cluster(num_nodes=2, resources_per_node={"CPU": 2})
    core = connect(cluster.gcs_address)
    yield cluster, core
    core.shutdown()
    runtime_mod._global_runtime = None
    cluster.shutdown()


@ray_tpu.remote
class _Owner:
    """Holds small objects in ITS in-process store (owner-served fetches)
    and seals payloads under caller-chosen ids at a chosen time."""

    def make(self, n, size):
        import os

        return [ray_tpu.put(os.urandom(size)) for _ in range(n)]

    def make_tagged(self, tags):
        return {t: ray_tpu.put(f"value-{t}".encode()) for t in tags}

    def seal_after(self, oid_bytes, delay, size):
        from ray_tpu.core.runtime import get_runtime

        payload = serialize(b"s" * size).to_bytes()
        time.sleep(delay)
        get_runtime().seal_payload(ObjectID(oid_bytes), payload)
        return time.monotonic()

    def seal_replica(self, ref_list):
        """Seal a replica of an EXISTING object on this actor's node."""
        from ray_tpu.core.runtime import get_runtime

        value = ray_tpu.get(ref_list[0])
        get_runtime().seal_serialized(ref_list[0].id, serialize(value))
        return True


# ====================== batched get ======================


def test_batched_get_preserves_caller_order_with_mixed_refs(two_nodes):
    _cluster, core = two_nodes
    owner = _Owner.remote()
    tags = [f"t{i}" for i in range(8)]
    remote_refs = ray_tpu.get(owner.make_tagged.remote(tags), timeout=120)
    local_ref = ray_tpu.put(b"local-hit")

    @ray_tpu.remote
    def produce():
        return b"task-return"

    task_ref = produce.remote()
    # Mixed batch: cache hits, owner-served misses (dropped below), a
    # pending task return, and DUPLICATES — values must come back in
    # caller order.
    batch = [remote_refs["t3"], local_ref, remote_refs["t0"], task_ref,
             remote_refs["t3"], remote_refs["t7"], local_ref]
    with core._cache_lock:
        for r in remote_refs.values():
            core._cache.pop(r.id, None)
    values = ray_tpu.get(batch, timeout=120)
    assert values == [b"value-t3", b"local-hit", b"value-t0",
                      b"task-return", b"value-t3", b"value-t7",
                      b"local-hit"]


def test_batched_get_uses_one_locate_round_trip(two_nodes):
    _cluster, core = two_nodes
    # Node-sealed (non-inline) objects so resolution needs locations.
    refs = [ray_tpu.put(np.arange(40_000) + i) for i in range(6)]

    @ray_tpu.remote
    def touch(x):
        return float(x[0])

    ray_tpu.get([touch.remote(r) for r in refs], timeout=120)
    with core._cache_lock:
        for r in refs:
            core._cache.pop(r.id, None)
    before = core.get_stats()["locate_calls"]
    out = ray_tpu.get(refs, timeout=120)
    assert [int(v[0]) for v in out] == list(range(6))
    # ONE locate_object_batch call resolved all six misses.
    assert core.get_stats()["locate_calls"] - before == 1


def test_batched_get_first_error_in_caller_order(two_nodes):
    _cluster, _core = two_nodes

    @ray_tpu.remote
    def boom_value():
        raise ValueError("first in caller order")

    @ray_tpu.remote
    def boom_type():
        raise TypeError("second in caller order")

    @ray_tpu.remote
    def ok():
        return 1

    err1, err2 = boom_value.remote(), boom_type.remote()
    good = [ok.remote() for _ in range(3)]
    with pytest.raises(ValueError, match="first in caller order"):
        ray_tpu.get([good[0], err1, good[1], err2, good[2]], timeout=120)
    # A batch whose only failure comes later still raises that one.
    with pytest.raises(TypeError, match="second in caller order"):
        ray_tpu.get([good[0], good[1], err2], timeout=120)


def test_batched_get_timeout_still_raises(two_nodes):
    _cluster, _core = two_nodes
    never = ObjectRef(ObjectID.for_put())  # nothing will ever seal this
    ok = ray_tpu.put(b"x")
    t0 = time.time()
    with pytest.raises(ray_tpu.GetTimeoutError):
        ray_tpu.get([ok, never], timeout=1.0)
    assert time.time() - t0 < 30.0


def test_task_with_many_ref_args_resolves_concurrently(two_nodes):
    _cluster, core = two_nodes
    owner = _Owner.remote()
    refs = ray_tpu.get(owner.make.remote(6, 2048), timeout=120)
    with core._cache_lock:
        for r in refs:
            core._cache.pop(r.id, None)

    @ray_tpu.remote
    def concat(*parts):
        return sum(len(p) for p in parts)

    assert ray_tpu.get(concat.remote(*refs), timeout=120) == 6 * 2048


def test_dependency_error_propagates_through_batched_args(two_nodes):
    _cluster, _core = two_nodes

    @ray_tpu.remote
    def boom():
        raise RuntimeError("dep failed")

    @ray_tpu.remote
    def ok():
        return 2

    @ray_tpu.remote
    def add(a, b):
        return a + b

    with pytest.raises(RuntimeError, match="dep failed"):
        ray_tpu.get(add.remote(ok.remote(), boom.remote()), timeout=120)


# ====================== multi-source striped pulls ======================


def _make_two_replica_object(cluster, core, n_doubles):
    """A node-sealed object with a second replica sealed on node 1."""
    arr = np.arange(n_doubles, dtype=np.float64)
    ref = ray_tpu.put(arr)
    origin = core._gcs_rpc.call("locate_object", ref.id.binary())[0][0]
    other = next(h for h in cluster.nodes if h.node_id != origin)

    @ray_tpu.remote(scheduling_strategy=ray_tpu.NodeAffinitySchedulingStrategy(
        node_id=other.node_id, soft=False))
    def seal_replica(ref_list):
        from ray_tpu.core.runtime import get_runtime

        value = ray_tpu.get(ref_list[0])
        get_runtime().seal_serialized(ref_list[0].id, serialize(value))
        return True

    assert ray_tpu.get(seal_replica.remote([ref]), timeout=300)
    deadline = time.time() + 60
    while time.time() < deadline:
        if len(core._gcs_rpc.call("locate_object", ref.id.binary())) >= 2:
            break
        time.sleep(0.2)
    locs = core._gcs_rpc.call("locate_object", ref.id.binary())
    assert len(locs) >= 2, locs
    return arr, ref, other


def test_multi_source_pull_completes_and_matches(two_nodes):
    cluster, core = two_nodes
    # ~24 MB: above stripe_min_size (16 MB) -> striped across 2 replicas.
    arr, ref, _other = _make_two_replica_object(cluster, core, 3_000_000)
    with core._cache_lock:
        core._cache.pop(ref.id, None)
    out = ray_tpu.get(ref, timeout=300)
    assert isinstance(out, np.ndarray)
    assert out.shape == arr.shape and out[0] == 0.0
    assert float(out.sum()) == float(arr.sum())


def test_stripe_reassigns_ranges_when_a_replica_daemon_dies(two_nodes):
    cluster, core = two_nodes
    arr, ref, other = _make_two_replica_object(cluster, core, 3_000_000)
    # Kill the replica daemon AFTER location registration: the GCS health
    # check hasn't noticed yet, so the stripe opens against BOTH sources
    # and the dead one's ranges must reassign to the survivor.
    idx = next(i for i, h in enumerate(cluster.nodes)
               if h.node_id == other.node_id)
    cluster.kill_node(idx)
    core._daemons.invalidate(other.address)
    assert len(core._gcs_rpc.call("locate_object", ref.id.binary())) >= 2
    with core._cache_lock:
        core._cache.pop(ref.id, None)
    out = ray_tpu.get(ref, timeout=300)
    assert float(out.sum()) == float(arr.sum())


def test_pull_into_multi_reassigns_and_aborts(two_nodes):
    """PullManager-level: a dead source's ranges reassign to the survivor;
    with NO live source the pull aborts (returns False)."""
    from ray_tpu.core.object_transfer import PullManager

    cluster, core = two_nodes
    arr, ref, _other = _make_two_replica_object(cluster, core, 3_000_000)
    locs = core._gcs_rpc.call("locate_object", ref.id.binary())
    addrs = [a for _n, a, _s in locs]
    size = serialize(arr).framed_size()
    pull = PullManager(core._daemons)
    pull._stripe_min = 0  # force striping regardless of size

    dest = bytearray(size)
    assert pull.pull_into_multi(addrs + ["127.0.0.1:9"], ref.id.binary(),
                                size, dest)  # dead third source: survivors
    from ray_tpu.core.serialization import deserialize, SerializedObject

    out = deserialize(SerializedObject.from_bytes(bytes(dest)))
    assert float(out.sum()) == float(arr.sum())
    # A REACHABLE source that doesn't hold the object (stale location)
    # answers chunk requests with None — its claimed ranges must requeue
    # to the real holder, not vanish (a lost range deadlocks the pull).
    solo = np.arange(3_000_000, dtype=np.float64) * 2.0
    solo_ref = ray_tpu.put(solo)  # sealed on the driver's node only
    solo_size = serialize(solo).framed_size()
    holder = [a for _n, a, _s in core._gcs_rpc.call(
        "locate_object", solo_ref.id.binary())]
    objectless = [a for a in addrs if a not in holder]
    assert objectless, "need one daemon without the replica"
    dest2 = bytearray(solo_size)
    assert pull.pull_into_multi(objectless + holder, solo_ref.id.binary(),
                                solo_size, dest2)
    out2 = deserialize(SerializedObject.from_bytes(bytes(dest2)))
    assert float(out2.sum()) == float(solo.sum())
    # No source holds the object at all -> clean abort, not a hang.
    stale = bytearray(1024)
    assert not pull.pull_into_multi(addrs, ObjectID.for_put().binary(),
                                    1024, stale)
    # All sources dead -> full abort, not a hang.
    assert not pull.pull_into_multi(["127.0.0.1:9", "127.0.0.1:11"],
                                    ref.id.binary(), size, bytearray(size))


# ====================== location-push wakeups ======================


def test_sealed_late_get_wakes_on_push_not_poll(two_nodes):
    _cluster, core = two_nodes
    owner = _Owner.remote()
    ray_tpu.get(owner.make.remote(1, 8), timeout=120)  # actor warm
    before = core.get_stats()
    oid = ObjectID.for_put()
    seal_fut = owner.seal_after.remote(oid.binary(), 0.15, 256 * 1024)
    value = ray_tpu.get(ObjectRef(oid), timeout=60)
    t_return = time.monotonic()
    t_seal = ray_tpu.get(seal_fut, timeout=60)
    assert value == b"s" * 256 * 1024
    after = core.get_stats()
    # The waiter woke on the location push: no legacy backoff sleeps, at
    # least one push wakeup, and the locate poll stayed at its low-rate
    # fallback cadence instead of one RPC per backoff step.
    assert after["backoff_sleeps"] == before["backoff_sleeps"]
    assert after["push_wakeups"] > before["push_wakeups"]
    assert after["locate_calls"] - before["locate_calls"] <= 5
    # Seal-to-return latency is push-driven (old poll: up to 100ms backoff).
    assert t_return - t_seal < 0.1, f"woke {t_return - t_seal:.3f}s after seal"


def test_sealed_late_get_with_subscription_disabled_falls_back_to_poll(
        two_nodes):
    _cluster, core = two_nodes
    set_config(Config({"location_sub_enabled": False}))
    try:
        owner = _Owner.remote()
        before = core.get_stats()
        oid = ObjectID.for_put()
        owner.seal_after.remote(oid.binary(), 0.1, 64 * 1024)
        value = ray_tpu.get(ObjectRef(oid), timeout=60)
        assert value == b"s" * 64 * 1024
        after = core.get_stats()
        assert after["backoff_sleeps"] > before["backoff_sleeps"]
        assert after["push_wakeups"] == before["push_wakeups"]
    finally:
        set_config(Config())


def test_subscriber_thread_exits_when_idle(two_nodes):
    _cluster, core = two_nodes
    owner = _Owner.remote()
    oid = ObjectID.for_put()
    owner.seal_after.remote(oid.binary(), 0.05, 4 * 1024)
    ray_tpu.get(ObjectRef(oid), timeout=60)
    assert core._loc_sub_running  # just used it
    deadline = time.time() + 15
    while core._loc_sub_running and time.time() < deadline:
        time.sleep(0.2)
    assert not core._loc_sub_running  # idle-exit: no standing GCS poll


# ====================== memory-store satellites ======================


def _payload(n):
    return b"p" * n


def test_evict_spills_least_recently_used_not_oldest(fresh_config):
    fresh_config(object_store_memory=4000, use_native_store=False)
    store = MemoryStore(capacity_bytes=4000)
    a, b = ObjectID.for_put(), ObjectID.for_put()
    store.put(a, _payload(1500))
    store.put(b, _payload(1500))
    store.get_serialized(a)  # A is now more recently USED than B
    c = ObjectID.for_put()
    store.put(c, _payload(1500))  # over capacity: one entry must spill
    with store._lock:
        assert store._objects[b].serialized is None, "LRU victim is B"
        assert store._objects[a].serialized is not None
        assert store._objects[c].serialized is not None
    # The spilled entry still resolves (restore path).
    assert bytes(store.get(b)) == _payload(1500)


def test_restore_of_spilled_entry_triggers_eviction(fresh_config):
    fresh_config(object_store_memory=4000, use_native_store=False)
    store = MemoryStore(capacity_bytes=4000)
    a, b, c = (ObjectID.for_put() for _ in range(3))
    store.put(a, _payload(1500))
    store.put(b, _payload(1500))
    store.put(c, _payload(1500))  # spills A (least recently used)
    with store._lock:
        assert store._objects[a].serialized is None
    value = store.get(a)  # restore pushes _used over capacity
    assert bytes(value) == _payload(1500)
    with store._lock:
        assert store._used <= store._capacity, (
            "restore must re-evict down to capacity")
        assert store._objects[a].serialized is not None, (
            "the just-restored entry must not bounce straight back out")
        assert any(store._objects[oid].serialized is None for oid in (b, c))


def test_deser_cache_is_bounded_lru(fresh_config):
    fresh_config(deser_cache_entries=8, use_native_store=False)
    store = MemoryStore(capacity_bytes=1 << 20)
    oids = [ObjectID.for_put() for _ in range(20)]
    hot = oids[0]
    store.put(hot, b"hot")
    store.get(hot)
    for oid in oids[1:]:
        store.put(oid, b"cold")
        store.get(oid)
        store.get(hot)  # keep the hot entry most recently used
    with store._lock:
        assert len(store._deser_cache) <= 8
        assert hot in store._deser_cache, "LRU must keep the hot entry"


def test_in_process_fetch_args_concurrent_and_ordered():
    ray_tpu.init(resources={"CPU": 4})
    try:
        refs = [ray_tpu.put(i) for i in range(6)]

        @ray_tpu.remote
        def gather(*xs):
            return list(xs)

        assert ray_tpu.get(gather.remote(*refs), timeout=60) == list(range(6))

        @ray_tpu.remote
        def boom():
            raise KeyError("dep")

        with pytest.raises(KeyError):
            ray_tpu.get(gather.remote(refs[0], boom.remote(), refs[1]),
                        timeout=60)
    finally:
        ray_tpu.shutdown()


# ====================== pubsub filters / per-oid wait lists ======================


def test_subscribe_object_locations_server_side_filter():
    """The GCS-side subscription filter: only the subscribed oids come
    back, and the cursor advances past filtered misses so they are never
    rescanned."""
    from ray_tpu.core.gcs_server import GcsService

    svc = GcsService()
    try:
        a, b = b"a" * 28, b"b" * 28
        svc._publish("object_locations", (a, None, "addr1", 1))
        svc._publish("object_locations", (b, None, "addr2", 2))
        end, msgs = svc.subscribe_object_locations(0, 1.0, [a])
        assert end == 2 and [m[0] for m in msgs] == [a]
        # Filter matches nothing: empty reply, cursor consumed the misses.
        cur, msgs = svc.subscribe_object_locations(0, 0.1, [b"x" * 28])
        assert msgs == [] and cur == 2
        # Unfiltered subscribe keeps the firehose contract.
        end, msgs = svc.subscribe_object_locations(0, 1.0)
        assert [m[0] for m in msgs] == [a, b]
    finally:
        svc.shutdown()


def test_subscribe_object_locations_per_oid_wait_lists():
    """A parked filtered subscribe wakes ONLY when one of ITS oids seals:
    seals of other objects (which used to wake every parked poll on one
    condvar) leave it asleep, and generic channel polls park per channel."""
    import threading

    from ray_tpu.core.gcs_server import GcsService

    svc = GcsService()
    try:
        target = b"c" * 28
        done = {}

        def park():
            done["r"] = svc.subscribe_object_locations(0, 10.0, [target])

        t = threading.Thread(target=park, daemon=True)
        t.start()
        time.sleep(0.2)
        for i in range(5):  # unrelated seals: the parked poll must not wake
            svc._publish("object_locations", (bytes([i]) * 28, None, "n", 1))
        svc._publish("node", ("ALIVE", "beef", "addr"))  # other channel too
        time.sleep(0.3)
        assert "r" not in done
        t0 = time.monotonic()
        svc._publish("object_locations", (target, None, "addr3", 3))
        t.join(timeout=5)
        assert not t.is_alive()
        assert time.monotonic() - t0 < 2.0
        cursor, msgs = done["r"]
        assert [m[0] for m in msgs] == [target]
        assert cursor == 6  # advanced past every filtered miss
    finally:
        svc.shutdown()
