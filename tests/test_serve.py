"""Serve tests, modeled on the reference's ``python/ray/serve/tests/``:
deploy/call/scale/delete lifecycle, composition, routing, autoscaling,
batching, streaming, HTTP ingress.
"""

import json
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture
def serve_instance(ray_start_regular):
    yield serve
    serve.shutdown()


class TestDeployLifecycle:
    def test_function_deployment(self, serve_instance):
        @serve.deployment
        def square(x):
            return x["v"] * x["v"]

        h = serve.run(square.bind())
        assert h.remote({"v": 7}).result() == 49

    def test_class_deployment_with_init_args(self, serve_instance):
        @serve.deployment
        class Adder:
            def __init__(self, base):
                self.base = base

            def __call__(self, x):
                return self.base + x["v"]

            def sub(self, x):
                return x["v"] - self.base

        h = serve.run(Adder.bind(100))
        assert h.remote({"v": 5}).result() == 105
        assert h.options(method_name="sub").remote({"v": 5}).result() == -95

    def test_num_replicas_and_scale(self, serve_instance):
        @serve.deployment(num_replicas=3)
        def f(x):
            return 1

        serve.run(f.bind())
        info = serve.status()
        assert info["f"]["num_replicas"] == 3
        serve.delete("f")
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and "f" in serve.status():
            time.sleep(0.05)
        assert "f" not in serve.status()

    def test_redeploy_updates(self, serve_instance):
        @serve.deployment
        def g(x):
            return "v1"

        h = serve.run(g.bind())
        assert h.remote({}).result() == "v1"

        @serve.deployment(name="g")
        def g2(x):
            return "v2"

        h2 = serve.run(g2.bind())
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if h2.remote({}).result() == "v2":
                break
            time.sleep(0.1)
        assert h2.remote({}).result() == "v2"

    def test_composition(self, serve_instance):
        @serve.deployment
        class Preprocess:
            def __call__(self, x):
                return x["v"] * 2

        @serve.deployment
        class Ingress:
            def __init__(self, pre):
                self.pre = pre

            def __call__(self, x):
                doubled = self.pre.remote(x).result()
                return doubled + 1

        h = serve.run(Ingress.bind(Preprocess.bind()))
        assert h.remote({"v": 10}).result() == 21

    def test_user_config_reconfigure(self, serve_instance):
        @serve.deployment(user_config={"threshold": 5})
        class Thresh:
            def __init__(self):
                self.threshold = None

            def reconfigure(self, cfg):
                self.threshold = cfg["threshold"]

            def __call__(self, x):
                return x["v"] > self.threshold

        h = serve.run(Thresh.bind())
        assert h.remote({"v": 10}).result() is True
        assert h.remote({"v": 3}).result() is False


class TestRoutingAndScaling:
    def test_pow2_spreads_load(self, serve_instance):
        import os
        import threading

        @serve.deployment(num_replicas=2, max_ongoing_requests=4)
        class Who:
            def __init__(self):
                self.ident = id(self)

            def __call__(self, x):
                time.sleep(0.05)
                return self.ident

        h = serve.run(Who.bind())
        results = []
        threads = [
            # concurrent callers so pow-2 sees real queue depth
            __import__("threading").Thread(
                target=lambda: results.append(h.remote({}).result())
            )
            for _ in range(16)
        ]
        [t.start() for t in threads]
        [t.join() for t in threads]
        assert len(set(results)) == 2, "both replicas should have served"

    def test_autoscaling_up(self, serve_instance):
        @serve.deployment(
            num_replicas="auto",
            autoscaling_config={
                "min_replicas": 1,
                "max_replicas": 4,
                "target_ongoing_requests": 1.0,
            },
            max_ongoing_requests=2,
        )
        def slow(x):
            time.sleep(0.4)
            return 1

        h = serve.run(slow.bind())
        assert serve.status()["slow"]["num_replicas"] == 1
        import threading

        threads = [
            threading.Thread(target=lambda: h.remote({}).result()) for _ in range(8)
        ]
        [t.start() for t in threads]
        deadline = time.monotonic() + 10
        scaled = False
        while time.monotonic() < deadline:
            if serve.status()["slow"]["num_replicas"] >= 2:
                scaled = True
                break
            time.sleep(0.05)
        [t.join() for t in threads]
        assert scaled, f"autoscaler never scaled up: {serve.status()}"


class TestBatchingAndStreaming:
    def test_batch_decorator(self, serve_instance):
        seen_sizes = []

        @serve.deployment(max_ongoing_requests=64)
        class Model:
            @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.05)
            def handle(self, xs):
                seen_sizes.append(len(xs))
                return [x * 10 for x in xs]

            def __call__(self, x):
                return self.handle(x["v"])

        h = serve.run(Model.bind())
        import threading

        results = {}

        def call(i):
            results[i] = h.remote({"v": i}).result()

        threads = [threading.Thread(target=call, args=(i,)) for i in range(8)]
        [t.start() for t in threads]
        [t.join() for t in threads]
        assert results == {i: i * 10 for i in range(8)}

    def test_streaming_response(self, serve_instance):
        @serve.deployment
        def streamer(x):
            for i in range(x["n"]):
                yield {"chunk": i}

        h = serve.run(streamer.bind())
        chunks = list(h.options(stream=True).remote({"n": 4}))
        assert chunks == [{"chunk": i} for i in range(4)]


class TestHttpProxy:
    def test_http_roundtrip_and_404(self, serve_instance):
        import httpx

        @serve.deployment
        def model(payload):
            return {"doubled": payload["v"] * 2}

        serve.run(model.bind(), route_prefix="/model", _start_proxy=True, http_port=18431)
        r = httpx.post("http://127.0.0.1:18431/model", json={"v": 21}, timeout=10)
        assert r.status_code == 200
        assert r.json() == {"doubled": 42}
        r = httpx.get("http://127.0.0.1:18431/nope", timeout=10)
        assert r.status_code == 404


class TestMultiplexing:
    """Model multiplexing (serve/_private/multiplex.py analog)."""

    def test_lru_loading_and_context(self, serve_instance):
        from ray_tpu import serve

        loads = []

        @serve.deployment(num_replicas=1)
        class Models:
            @serve.multiplexed(max_num_models_per_replica=2)
            def get_model(self, model_id: str):
                loads.append(model_id)
                return {"id": model_id}

            def __call__(self, payload):
                mid = serve.get_multiplexed_model_id()
                model = self.get_model(mid)
                return {"served_by": model["id"], "ctx": mid}

        handle = serve.run(Models.bind())
        r1 = handle.options(multiplexed_model_id="m1").remote({}).result(timeout_s=60)
        assert r1 == {"served_by": "m1", "ctx": "m1"}
        r2 = handle.options(multiplexed_model_id="m2").remote({}).result(timeout_s=60)
        assert r2["served_by"] == "m2"
        # Cached: repeat m1 loads nothing new.
        handle.options(multiplexed_model_id="m1").remote({}).result(timeout_s=60)
        # Third model evicts the LRU entry (m2 after the m1 re-touch).
        handle.options(multiplexed_model_id="m3").remote({}).result(timeout_s=60)
        handle.options(multiplexed_model_id="m2").remote({}).result(timeout_s=60)
        assert loads == ["m1", "m2", "m3", "m2"]

    def test_missing_model_id_raises(self, serve_instance):
        from ray_tpu import serve

        @serve.deployment(num_replicas=1)
        class M:
            @serve.multiplexed()
            def get_model(self, model_id: str):
                return model_id

            def __call__(self, payload):
                return self.get_model()

        handle = serve.run(M.bind())
        with pytest.raises(Exception, match="no model id"):
            handle.remote({}).result(timeout_s=60)


class TestGrpcIngress:
    """Generic gRPC ingress (reference: serve gRPC proxy + serve.proto)."""

    def test_unary_and_streaming(self, serve_instance):
        import grpc
        from ray_tpu import serve
        from ray_tpu.serve import api as serve_api
        from ray_tpu.serve.grpc_proxy import (
            _decode_payload_field,
            _encode_payload_field,
        )

        @serve.deployment(num_replicas=1)
        class Math:
            def __call__(self, payload):
                return {"doubled": payload["x"] * 2}

            def countdown(self, payload):
                for i in range(payload["n"], 0, -1):
                    yield {"i": i}

        serve.run(Math.bind(), _start_grpc_proxy=True)
        addr = serve_api.grpc_proxy_address()
        assert addr is not None

        channel = grpc.insecure_channel(addr)
        import json

        unary = channel.unary_unary(
            "/ray_tpu.serve.RayTpuServe/Call",
            request_serializer=_encode_payload_field,
            response_deserializer=_decode_payload_field,
        )
        reply = unary(json.dumps({"x": 21}).encode(),
                      metadata=(("application", "Math"),), timeout=60)
        assert json.loads(reply.decode()) == {"doubled": 42}

        stream = channel.unary_stream(
            "/ray_tpu.serve.RayTpuServe/CallStream",
            request_serializer=_encode_payload_field,
            response_deserializer=_decode_payload_field,
        )
        items = [json.loads(chunk.decode()) for chunk in stream(
            json.dumps({"n": 3}).encode(),
            metadata=(("application", "Math"), ("method", "countdown")),
            timeout=60)]
        assert items == [{"i": 3}, {"i": 2}, {"i": 1}]

        # Missing application metadata -> INVALID_ARGUMENT.
        with pytest.raises(grpc.RpcError) as err:
            unary(b"{}", timeout=30)
        assert err.value.code() == grpc.StatusCode.INVALID_ARGUMENT
        # Unknown application -> prompt NOT_FOUND (no blocking bootstrap).
        with pytest.raises(grpc.RpcError) as err:
            unary(b"{}", metadata=(("application", "NoSuchApp"),), timeout=30)
        assert err.value.code() == grpc.StatusCode.NOT_FOUND
        # Pickle payloads rejected unless the ingress opted in.
        with pytest.raises(grpc.RpcError) as err:
            unary(b"{}", metadata=(("application", "Math"),
                                   ("payload-type", "pickle")),
                  timeout=30)
        assert err.value.code() == grpc.StatusCode.INVALID_ARGUMENT
        channel.close()


class TestCompiledPipeline:
    """serve.run_pipeline: the deployment call chain precompiled into
    resident DAG lanes over the stage replicas (dag_pipeline.py)."""

    def test_compiled_matches_sequential(self, serve_instance):
        @serve.deployment
        class Tokenize:
            def __call__(self, text):
                return text.split()

        @serve.deployment
        class Count:
            def __call__(self, tokens):
                return len(tokens)

        @serve.deployment
        class Format:
            def __call__(self, n):
                return {"tokens": n}

        stages = [Tokenize, Count, Format]
        seq = serve.run_pipeline(stages, compiled=False)
        want = [seq.remote(f"a b c {'x ' * i}").result(timeout_s=60)
                for i in range(4)]
        comp = serve.run_pipeline(stages, compiled=True)
        try:
            assert comp.num_lanes == 1
            got = [comp.remote(f"a b c {'x ' * i}").result(timeout_s=60)
                   for i in range(4)]
            assert got == want == [{"tokens": 3 + i} for i in range(4)]
        finally:
            comp.shutdown()

    def test_pipeline_burst_and_replica_bookkeeping(self, serve_instance):
        @serve.deployment
        class AddOne:
            def __call__(self, x):
                return x + 1

        @serve.deployment
        class Double:
            def __call__(self, x):
                return x * 2

        handle = serve.run_pipeline([AddOne, Double], compiled=True)
        try:
            # Burst ahead of any result(): ticks pipeline through the ring
            # edges and drain FIFO per request.
            resps = [handle.remote(i) for i in range(6)]
            assert [r.result(timeout_s=60) for r in resps] == \
                [(i + 1) * 2 for i in range(6)]
            # The dag_call path keeps the replica latency histogram warm
            # (the metrics plane's serve deployment view stays truthful).
            from ray_tpu.core.metrics_export import serve_request_hist

            totals = serve_request_hist()._totals
            assert sum(n for k, n in totals.items()
                       if ("deployment", "AddOne") in k) >= 6
        finally:
            handle.shutdown()

    def test_pipeline_function_stage_and_shutdown_idempotent(
            self, serve_instance):
        @serve.deployment
        def upper(s):
            return s.upper()

        @serve.deployment
        def exclaim(s):
            return s + "!"

        handle = serve.run_pipeline([upper, exclaim], compiled=True)
        assert handle.remote("hey").result(timeout_s=60) == "HEY!"
        handle.shutdown()
        handle.shutdown()  # idempotent
        with pytest.raises(RuntimeError, match="shut down"):
            handle.remote("again")
