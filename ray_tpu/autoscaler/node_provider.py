"""Node providers — the cloud seam of the autoscaler.

Analog of the reference's v2 provider layer
(``python/ray/autoscaler/v2/instance_manager/``, cloud plugins under
``python/ray/autoscaler/{gcp,aws,...}``, and the load-bearing test provider
``_private/fake_multi_node/node_provider.py`` — SURVEY §4.3). The
``FakeNodeProvider`` backs autoscaler tests by adding virtual nodes to the
in-process runtime; ``TPUPodNodeProvider`` is the GCE/TPU-pod shape (API
calls gated — zero-egress images stub them).
"""

from __future__ import annotations

import threading
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class NodeType:
    """One launchable instance shape (reference: ``available_node_types`` in
    the cluster YAML — ``autoscaler/ray-schema.json``)."""

    name: str
    resources: Dict[str, float]
    min_workers: int = 0
    max_workers: int = 10
    labels: Dict[str, str] = field(default_factory=dict)


@dataclass
class NodeInstance:
    instance_id: str
    node_type: str
    resources: Dict[str, float]
    status: str = "RUNNING"  # PENDING | RUNNING | TERMINATED
    node_id: Optional[object] = None  # runtime NodeID once joined


class NodeProvider:
    """Reference: ``autoscaler/node_provider.py`` interface."""

    def create_node(self, node_type: NodeType) -> NodeInstance:
        raise NotImplementedError

    def terminate_node(self, instance: NodeInstance) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[NodeInstance]:
        raise NotImplementedError


class FakeNodeProvider(NodeProvider):
    """Adds/removes virtual nodes on the live runtime (the single-host
    multi-node trick — ``cluster_utils.py:135 Cluster``)."""

    def __init__(self, runtime=None):
        from ray_tpu.core.runtime import get_runtime

        self._runtime = runtime or get_runtime()
        self._instances: Dict[str, NodeInstance] = {}
        self._lock = threading.Lock()

    def create_node(self, node_type: NodeType) -> NodeInstance:
        node_id = self._runtime.add_node(
            resources=dict(node_type.resources),
            labels={"node-type": node_type.name, **node_type.labels},
        )
        inst = NodeInstance(
            instance_id=f"fake-{uuid.uuid4().hex[:8]}",
            node_type=node_type.name,
            resources=dict(node_type.resources),
            node_id=node_id,
        )
        with self._lock:
            self._instances[inst.instance_id] = inst
        return inst

    def terminate_node(self, instance: NodeInstance) -> None:
        with self._lock:
            inst = self._instances.pop(instance.instance_id, None)
        if inst is not None and inst.node_id is not None:
            self._runtime.remove_node(inst.node_id)
            inst.status = "TERMINATED"

    def non_terminated_nodes(self) -> List[NodeInstance]:
        with self._lock:
            return [i for i in self._instances.values() if i.status == "RUNNING"]


class LocalDaemonNodeProvider(NodeProvider):
    """Launches REAL node-daemon processes against a live multiprocess
    cluster (the in-repo analog of the reference's load-bearing
    ``_private/fake_multi_node`` provider): a scale-up is an actual
    ``ray_tpu.core.node_daemon`` subprocess registering with the GCS; a
    scale-down SIGTERMs it and the health check reaps the membership row."""

    def __init__(self, gcs_address: str):
        self.gcs_address = gcs_address
        self._instances: Dict[str, NodeInstance] = {}
        self._procs: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def create_node(self, node_type: NodeType) -> NodeInstance:
        import json
        import subprocess
        import sys

        from ray_tpu.core.cluster import _read_tagged_line
        from ray_tpu.core.ids import NodeID

        labels = {"node-type": node_type.name, **node_type.labels}
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.core.node_daemon",
             "--gcs", self.gcs_address,
             "--resources", json.dumps(dict(node_type.resources)),
             "--labels", json.dumps(labels)],
            stdout=subprocess.PIPE,
        )
        _read_tagged_line(proc, "NODE_ADDRESS")
        node_id = NodeID.from_hex(_read_tagged_line(proc, "NODE_ID"))
        _read_tagged_line(proc, "STORE_NAME")
        inst = NodeInstance(
            instance_id=f"daemon-{uuid.uuid4().hex[:8]}",
            node_type=node_type.name,
            resources=dict(node_type.resources),
            node_id=node_id,
        )
        with self._lock:
            self._instances[inst.instance_id] = inst
            self._procs[inst.instance_id] = proc
        return inst

    def terminate_node(self, instance: NodeInstance) -> None:
        import signal as _signal

        with self._lock:
            inst = self._instances.pop(instance.instance_id, None)
            proc = self._procs.pop(instance.instance_id, None)
        if proc is not None:
            try:
                proc.send_signal(_signal.SIGTERM)
                proc.wait(timeout=15)
            except Exception:  # noqa: BLE001 — already gone / stuck
                proc.kill()
        if inst is not None:
            inst.status = "TERMINATED"

    def non_terminated_nodes(self) -> List[NodeInstance]:
        with self._lock:
            return [i for i in self._instances.values()
                    if i.status == "RUNNING"]

    def shutdown(self) -> None:
        for inst in self.non_terminated_nodes():
            self.terminate_node(inst)


class TPUPodNodeProvider(NodeProvider):
    """GCE TPU-VM provider (reference: ``autoscaler/gcp/`` + the TPU pod
    handling in ``_private/accelerators/tpu.py``). All cloud interaction
    funnels through an injectable ``runner(argv) -> str`` (default: the
    real ``gcloud`` CLI via subprocess) so deployments swap in their
    transport and tests mock it — no hidden egress."""

    def __init__(self, project: str, zone: str,
                 runtime_version: str = "tpu-ubuntu2204-base",
                 runner: Optional[Any] = None, runtime=None):
        self.project = project
        self.zone = zone
        self.runtime_version = runtime_version
        self._runner = runner or self._subprocess_runner
        # Control-plane view used to bind cloud instances to the NodeIDs
        # their daemons register with (the daemon's bootstrap must pass
        # --labels '{"instance-id": "<vm name>"}'); without the binding
        # the autoscaler could never scale a cloud node DOWN.
        self._runtime = runtime
        self._instances: Dict[str, NodeInstance] = {}
        self._last_poll: Dict[str, float] = {}  # describe rate limit
        self._lock = threading.Lock()

    # Minimum seconds between `describe` polls per booting instance — the
    # reconcile loop runs at sub-second ticks and must not hammer the API.
    POLL_INTERVAL_S = 10.0

    @staticmethod
    def _subprocess_runner(argv: List[str]) -> str:  # pragma: no cover
        import subprocess

        out = subprocess.run(argv, capture_output=True, text=True,
                             timeout=600)
        if out.returncode != 0:
            raise RuntimeError(
                f"{' '.join(argv[:6])}... failed: {out.stderr[-500:]}")
        return out.stdout

    def _gcloud(self, *args: str) -> str:
        return self._runner(["gcloud", *args, f"--project={self.project}"])

    def create_node(self, node_type: NodeType) -> NodeInstance:
        accel = node_type.labels.get("tpu-accelerator-type", "v5litepod-4")
        name = f"rtpu-{uuid.uuid4().hex[:8]}"
        self._gcloud(
            "compute", "tpus", "tpu-vm", "create", name,
            f"--zone={self.zone}", f"--accelerator-type={accel}",
            f"--version={self.runtime_version}", "--format=json",
        )
        inst = NodeInstance(instance_id=name, node_type=node_type.name,
                            resources=dict(node_type.resources),
                            status="PENDING")
        with self._lock:
            self._instances[name] = inst
        self._refresh_state(inst, force=True)
        return inst

    def _refresh_state(self, inst: NodeInstance, force: bool = False) -> None:
        import json
        import time as _time

        now = _time.monotonic()
        if not force and now - self._last_poll.get(inst.instance_id, 0.0) \
                < self.POLL_INTERVAL_S:
            return
        self._last_poll[inst.instance_id] = now
        try:
            raw = self._gcloud(
                "compute", "tpus", "tpu-vm", "describe", inst.instance_id,
                f"--zone={self.zone}", "--format=json",
            )
            state = json.loads(raw).get("state", "")
        except Exception:  # noqa: BLE001 — deleted / transient API error
            return
        if state == "READY":
            inst.status = "RUNNING"
        elif state in ("DELETING", "TERMINATED", "PREEMPTED"):
            inst.status = "TERMINATED"

    def terminate_node(self, instance: NodeInstance) -> None:
        self._gcloud(
            "compute", "tpus", "tpu-vm", "delete", instance.instance_id,
            f"--zone={self.zone}", "--quiet",
        )
        with self._lock:
            inst = self._instances.pop(instance.instance_id, None)
        if inst is not None:
            inst.status = "TERMINATED"

    def non_terminated_nodes(self) -> List[NodeInstance]:
        with self._lock:
            instances = list(self._instances.values())
        for inst in instances:
            if inst.status == "PENDING":
                self._refresh_state(inst)
            if inst.status == "RUNNING" and inst.node_id is None:
                self._bind_node_id(inst)
        return [i for i in instances if i.status != "TERMINATED"]

    def _bind_node_id(self, inst: NodeInstance) -> None:
        """Match the VM to the NodeID its daemon registered with (by the
        ``instance-id`` label the bootstrap passes) so the idle check and
        scale-down see a real cluster node."""
        if self._runtime is None:
            return
        try:
            for n in self._runtime._gcs_rpc.call("list_nodes", timeout=30.0):
                if (n.get("alive")
                        and n.get("labels", {}).get("instance-id")
                        == inst.instance_id):
                    inst.node_id = n["node_id"]  # NodeID object on the wire
                    return
        except Exception:  # noqa: BLE001 — bind again next tick
            pass
