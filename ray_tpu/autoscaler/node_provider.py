"""Node providers — the cloud seam of the autoscaler.

Analog of the reference's v2 provider layer
(``python/ray/autoscaler/v2/instance_manager/``, cloud plugins under
``python/ray/autoscaler/{gcp,aws,...}``, and the load-bearing test provider
``_private/fake_multi_node/node_provider.py`` — SURVEY §4.3). The
``FakeNodeProvider`` backs autoscaler tests by adding virtual nodes to the
in-process runtime; ``TPUPodNodeProvider`` is the GCE/TPU-pod shape (API
calls gated — zero-egress images stub them).
"""

from __future__ import annotations

import threading
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class NodeType:
    """One launchable instance shape (reference: ``available_node_types`` in
    the cluster YAML — ``autoscaler/ray-schema.json``)."""

    name: str
    resources: Dict[str, float]
    min_workers: int = 0
    max_workers: int = 10
    labels: Dict[str, str] = field(default_factory=dict)


@dataclass
class NodeInstance:
    instance_id: str
    node_type: str
    resources: Dict[str, float]
    status: str = "RUNNING"  # PENDING | RUNNING | TERMINATED
    node_id: Optional[object] = None  # runtime NodeID once joined


class NodeProvider:
    """Reference: ``autoscaler/node_provider.py`` interface."""

    def create_node(self, node_type: NodeType) -> NodeInstance:
        raise NotImplementedError

    def terminate_node(self, instance: NodeInstance) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[NodeInstance]:
        raise NotImplementedError


class FakeNodeProvider(NodeProvider):
    """Adds/removes virtual nodes on the live runtime (the single-host
    multi-node trick — ``cluster_utils.py:135 Cluster``)."""

    def __init__(self, runtime=None):
        from ray_tpu.core.runtime import get_runtime

        self._runtime = runtime or get_runtime()
        self._instances: Dict[str, NodeInstance] = {}
        self._lock = threading.Lock()

    def create_node(self, node_type: NodeType) -> NodeInstance:
        node_id = self._runtime.add_node(
            resources=dict(node_type.resources),
            labels={"node-type": node_type.name, **node_type.labels},
        )
        inst = NodeInstance(
            instance_id=f"fake-{uuid.uuid4().hex[:8]}",
            node_type=node_type.name,
            resources=dict(node_type.resources),
            node_id=node_id,
        )
        with self._lock:
            self._instances[inst.instance_id] = inst
        return inst

    def terminate_node(self, instance: NodeInstance) -> None:
        with self._lock:
            inst = self._instances.pop(instance.instance_id, None)
        if inst is not None and inst.node_id is not None:
            self._runtime.remove_node(inst.node_id)
            inst.status = "TERMINATED"

    def non_terminated_nodes(self) -> List[NodeInstance]:
        with self._lock:
            return [i for i in self._instances.values() if i.status == "RUNNING"]


class TPUPodNodeProvider(NodeProvider):
    """GCE TPU-pod provider shape (reference: ``autoscaler/gcp/`` + TPU pod
    handling). Actual GCE calls require credentials/egress; the command
    surface is kept so a deployment can fill in ``_gcloud``."""

    def __init__(self, project: str, zone: str, runtime_version: str = "tpu-ubuntu2204-base"):
        self.project = project
        self.zone = zone
        self.runtime_version = runtime_version
        self._instances: Dict[str, NodeInstance] = {}

    def _gcloud(self, *args: str) -> str:  # pragma: no cover - needs egress
        raise NotImplementedError(
            "TPUPodNodeProvider requires GCE access; subclass and implement "
            "_gcloud (e.g. `gcloud compute tpus tpu-vm ...`) for deployment"
        )

    def create_node(self, node_type: NodeType) -> NodeInstance:  # pragma: no cover
        accel = node_type.labels.get("tpu-accelerator-type", "v5litepod-4")
        name = f"rtpu-{uuid.uuid4().hex[:8]}"
        self._gcloud(
            "compute", "tpus", "tpu-vm", "create", name,
            f"--zone={self.zone}", f"--accelerator-type={accel}",
            f"--version={self.runtime_version}",
        )
        inst = NodeInstance(instance_id=name, node_type=node_type.name,
                            resources=dict(node_type.resources))
        self._instances[name] = inst
        return inst

    def terminate_node(self, instance: NodeInstance) -> None:  # pragma: no cover
        self._gcloud(
            "compute", "tpus", "tpu-vm", "delete", instance.instance_id,
            f"--zone={self.zone}", "--quiet",
        )
        self._instances.pop(instance.instance_id, None)

    def non_terminated_nodes(self) -> List[NodeInstance]:
        return [i for i in self._instances.values() if i.status == "RUNNING"]
