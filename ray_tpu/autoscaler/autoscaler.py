"""Autoscaler — demand-driven cluster sizing (v2 shape).

Analog of the reference's autoscaler v2 (``python/ray/autoscaler/v2/
autoscaler.py:42`` + ``scheduler.py`` bin-packing + ``instance_manager``;
SURVEY §7: "build the v2 shape only"): a reconcile loop reads pending
resource demand from the runtime (parked infeasible work), bin-packs it onto
configured node types, launches through the provider, retries the parked
work, and terminates nodes idle past the timeout (respecting min_workers).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ray_tpu.autoscaler.node_provider import NodeInstance, NodeProvider, NodeType
from ray_tpu.utils.logging import get_logger

logger = get_logger("autoscaler")


@dataclass
class AutoscalerConfig:
    node_types: List[NodeType] = field(default_factory=list)
    idle_timeout_s: float = 60.0
    update_interval_s: float = 0.1
    max_launch_batch: int = 8


def _fits(resources: Dict[str, float], demand: Dict[str, float]) -> bool:
    return all(resources.get(k, 0.0) >= v for k, v in demand.items())


def bin_pack(
    demands: List[Dict[str, float]], node_types: List[NodeType],
    existing: Dict[str, int],
    pending_capacity: Optional[List[Dict[str, float]]] = None,
) -> Dict[str, int]:
    """Choose node launches covering ``demands`` (reference:
    ``resource_demand_scheduler.py`` first-fit-decreasing). Returns
    node_type -> count to launch, respecting max_workers.
    ``pending_capacity``: resources of launches already in flight (cloud
    nodes still booting) — credited against demand so a slow boot doesn't
    trigger a duplicate VM on every reconcile tick."""
    to_launch: Dict[str, int] = {}
    # virtual free capacity of planned launches (incl. in-flight boots)
    planned: List[Dict[str, float]] = [dict(c) for c in pending_capacity or ()]
    for demand in sorted(demands, key=lambda d: -sum(d.values())):
        placed = False
        for cap in planned:
            if _fits(cap, demand):
                for k, v in demand.items():
                    cap[k] -= v
                placed = True
                break
        if placed:
            continue
        for nt in node_types:
            count = existing.get(nt.name, 0) + to_launch.get(nt.name, 0)
            if count >= nt.max_workers:
                continue
            if _fits(nt.resources, demand):
                to_launch[nt.name] = to_launch.get(nt.name, 0) + 1
                cap = dict(nt.resources)
                for k, v in demand.items():
                    cap[k] -= v
                planned.append(cap)
                placed = True
                break
        if not placed:
            logger.warning("demand %s unsatisfiable by any node type", demand)
    return to_launch


class _NodeResourceView:
    """Duck-types the scheduler's NodeResources for the busy check."""

    class _Set:
        def __init__(self, d):
            self._d = dict(d)

        def to_dict(self):
            return dict(self._d)

    def __init__(self, state: Dict):
        self.total = self._Set(state["total"])
        self.available = self._Set(state["available"])


class GcsAutoscalerView:
    """Runtime adapter for a MULTIPROCESS cluster: demand and per-node
    resource state come from the GCS over RPC (the reference's
    gcs_autoscaler_state_manager report), so the same Autoscaler loop
    drives a live cluster of real daemon processes."""

    def __init__(self, core=None):
        from ray_tpu.core.runtime import get_runtime

        self._core = core or get_runtime()
        self.autoscaling_enabled = False
        self.scheduler = self  # node_resources lives here

    def pending_resource_demands(self) -> List[Dict[str, float]]:
        return self._core._gcs_rpc.call("pending_resource_demands",
                                        timeout=30.0)

    def pending_block_capacity(self) -> List[Dict[str, float]]:
        """Outstanding capacity-block grants (granted to a daemon, not yet
        carved into running leases) — credited as pending capacity so a
        block in flight doesn't double-launch a node."""
        try:
            return self._core._gcs_rpc.call("pending_block_capacity",
                                            timeout=30.0)
        except Exception:  # noqa: BLE001 — older GCS without the RPC
            return []

    def retry_infeasible(self) -> None:
        # Queued lease requests wake on the GCS scheduler CV when the new
        # node registers — nothing to do driver-side.
        return None

    def node_resources(self, node_id):
        state = self._core._gcs_rpc.call(
            "node_resource_state", node_id.binary(), timeout=30.0)
        return _NodeResourceView(state) if state else None


class Autoscaler:
    def __init__(
        self,
        provider: NodeProvider,
        config: AutoscalerConfig,
        runtime=None,
    ):
        from ray_tpu.core.runtime import get_runtime

        self.provider = provider
        self.config = config
        self.runtime = runtime or get_runtime()
        self._types = {nt.name: nt for nt in config.node_types}
        self._idle_since: Dict[str, float] = {}
        self._running = False
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        self.runtime.autoscaling_enabled = True
        self._running = True
        self._satisfy_min_workers()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        if self._thread:
            self._thread.join(timeout=5)
        self.runtime.autoscaling_enabled = False

    def _loop(self) -> None:
        while self._running:
            try:
                self.update()
            except Exception:
                logger.exception("autoscaler update failed")
            time.sleep(self.config.update_interval_s)

    # -- one reconcile pass (reference: autoscaler.py:374 update()) ----------
    def update(self) -> None:
        demands = self.runtime.pending_resource_demands()
        existing: Dict[str, int] = {}
        pending_capacity: List[Dict[str, float]] = []
        for inst in self.provider.non_terminated_nodes():
            existing[inst.node_type] = existing.get(inst.node_type, 0) + 1
            if inst.status == "PENDING":
                # Still booting: its capacity is on the way — count it so a
                # slow cloud boot doesn't launch a duplicate every tick.
                pending_capacity.append(dict(inst.resources))
        # Granted-but-unadopted capacity blocks (batched daemon leases) are
        # capacity already carved out of the cluster for queued work: credit
        # them too, or each outstanding block reads as unmet demand and
        # double-launches a node. getattr: older runtimes lack the hook.
        block_capacity = getattr(self.runtime, "pending_block_capacity", None)
        if block_capacity is not None:
            try:
                pending_capacity.extend(
                    dict(c) for c in block_capacity() or ())
            except Exception:  # noqa: BLE001 — advisory credit only
                logger.debug("pending_block_capacity read failed",
                             exc_info=True)

        if demands:
            launches = bin_pack(demands, list(self._types.values()), existing,
                                pending_capacity=pending_capacity)
            launched = 0
            for type_name, count in launches.items():
                for _ in range(min(count, self.config.max_launch_batch)):
                    self.provider.create_node(self._types[type_name])
                    launched += 1
            if launched:
                logger.info("launched %d nodes for %d demands", launched, len(demands))
                self.runtime.retry_infeasible()

        self._terminate_idle(existing)

    def _satisfy_min_workers(self) -> None:
        existing: Dict[str, int] = {}
        for inst in self.provider.non_terminated_nodes():
            existing[inst.node_type] = existing.get(inst.node_type, 0) + 1
        for nt in self._types.values():
            for _ in range(max(0, nt.min_workers - existing.get(nt.name, 0))):
                self.provider.create_node(nt)

    def _terminate_idle(self, existing: Dict[str, int]) -> None:
        now = time.monotonic()
        for inst in list(self.provider.non_terminated_nodes()):
            if inst.node_id is None:
                continue
            nt = self._types.get(inst.node_type)
            if nt and existing.get(inst.node_type, 0) <= nt.min_workers:
                continue
            if self._node_busy(inst):
                self._idle_since.pop(inst.instance_id, None)
                continue
            first_idle = self._idle_since.setdefault(inst.instance_id, now)
            if now - first_idle >= self.config.idle_timeout_s:
                logger.info("terminating idle node %s", inst.instance_id)
                self.provider.terminate_node(inst)
                self._idle_since.pop(inst.instance_id, None)
                existing[inst.node_type] = existing.get(inst.node_type, 1) - 1

    def _node_busy(self, inst: NodeInstance) -> bool:
        """A node is busy while any of its resources are allocated."""
        nr = self.runtime.scheduler.node_resources(inst.node_id)
        if nr is None:
            return False
        total = nr.total.to_dict()
        avail = nr.available.to_dict()
        return any(avail.get(k, 0.0) < v for k, v in total.items())
