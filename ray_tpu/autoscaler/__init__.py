from ray_tpu.autoscaler.autoscaler import Autoscaler, AutoscalerConfig, bin_pack
from ray_tpu.autoscaler.node_provider import (
    FakeNodeProvider,
    NodeInstance,
    NodeProvider,
    NodeType,
    TPUPodNodeProvider,
)

__all__ = [
    "Autoscaler",
    "AutoscalerConfig",
    "bin_pack",
    "NodeProvider",
    "NodeType",
    "NodeInstance",
    "FakeNodeProvider",
    "TPUPodNodeProvider",
]
