from ray_tpu.autoscaler.autoscaler import (
    Autoscaler,
    AutoscalerConfig,
    GcsAutoscalerView,
    bin_pack,
)
from ray_tpu.autoscaler.node_provider import (
    FakeNodeProvider,
    LocalDaemonNodeProvider,
    NodeInstance,
    NodeProvider,
    NodeType,
    TPUPodNodeProvider,
)

__all__ = [
    "Autoscaler",
    "AutoscalerConfig",
    "GcsAutoscalerView",
    "bin_pack",
    "NodeProvider",
    "NodeType",
    "NodeInstance",
    "FakeNodeProvider",
    "LocalDaemonNodeProvider",
    "TPUPodNodeProvider",
]
