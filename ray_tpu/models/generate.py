"""Autoregressive generation with KV cache.

The inference fast path the Serve replicas use. The reference serves models
through vLLM/framework engines; TPU-native the decode loop is two jitted XLA
programs with static shapes:

- ``prefill``: one full forward over the (padded) prompt, writing K/V for
  every layer into a preallocated cache [L, B, max_len, H, Dh];
- ``decode_step``: single-token forward reading the cache — O(1) FLOPs in
  context length per token instead of the O(ctx) full-window forward.

The cache is a pytree carried through ``lax.scan``-style stepping on the
host; batch/beam layouts stay static so both programs compile exactly once.
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.models.transformer import TransformerConfig
from ray_tpu.ops.layers import gelu, layer_norm, linear, rope


def init_cache(config: TransformerConfig, batch: int, max_len: Optional[int] = None) -> Dict:
    c = config
    max_len = max_len or c.max_seq_len
    shape = (c.n_layers, batch, max_len, c.n_heads, c.head_dim)
    return {
        "k": jnp.zeros(shape, c.dtype),
        "v": jnp.zeros(shape, c.dtype),
        "length": jnp.zeros((), jnp.int32),
    }


def _attend_cached(q, k_cache, v_cache, valid_len, *, scale: float):
    """q: [B, T, H, D] against cache [B, S, H, D]; positions >= valid_len are
    masked. For prefill T>1 a causal mask also applies within the window."""
    B, T, H, D = q.shape
    S = k_cache.shape[1]
    scores = jnp.einsum(
        "bthd,bshd->bhts", q, k_cache, preferred_element_type=jnp.float32
    ) * scale
    kv_pos = jnp.arange(S)[None, None, None, :]          # [1,1,1,S]
    q_pos = (valid_len - T) + jnp.arange(T)[None, None, :, None]
    mask = kv_pos <= q_pos                                # causal + validity
    scores = jnp.where(mask, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhts,bshd->bthd", probs, v_cache.astype(jnp.float32))
    return out.astype(q.dtype)


def _forward_cached(params, tokens, cache, config: TransformerConfig, start_pos):
    """Forward ``tokens`` [B, T] at positions [start_pos, start_pos+T),
    updating the cache. Returns (logits[B, T, V], new_cache)."""
    c = config
    cast = lambda p: p.astype(c.dtype)
    B, T = tokens.shape
    h = jnp.take(cast(params["tok_embed"]), tokens, axis=0)
    positions = start_pos + jnp.arange(T)
    if c.pos == "learned":
        h = h + cast(params["pos_embed"])[positions]
    scale = 1.0 / c.head_dim**0.5
    valid_len = start_pos + T

    new_k, new_v = [], []
    for layer in range(c.n_layers):
        bp = jax.tree.map(lambda p: cast(p[layer]), params["blocks"])
        x = layer_norm(h, bp["ln1_g"], bp["ln1_b"])
        q = jnp.einsum("btd,dhk->bthk", x, bp["wq"], preferred_element_type=jnp.float32).astype(c.dtype) + bp["bq"]
        k = jnp.einsum("btd,dhk->bthk", x, bp["wk"], preferred_element_type=jnp.float32).astype(c.dtype) + bp["bk"]
        v = jnp.einsum("btd,dhk->bthk", x, bp["wv"], preferred_element_type=jnp.float32).astype(c.dtype) + bp["bv"]
        if c.pos == "rope":
            q = rope(q, positions)
            k = rope(k, positions)
        k_cache = lax.dynamic_update_slice(
            cache["k"][layer], k, (0, start_pos, 0, 0)
        )
        v_cache = lax.dynamic_update_slice(
            cache["v"][layer], v, (0, start_pos, 0, 0)
        )
        new_k.append(k_cache)
        new_v.append(v_cache)
        o = _attend_cached(q, k_cache, v_cache, valid_len, scale=scale)
        o = jnp.einsum("bthk,hkd->btd", o, bp["wo"], preferred_element_type=jnp.float32).astype(c.dtype) + bp["bo"]
        h = h + o
        x = layer_norm(h, bp["ln2_g"], bp["ln2_b"])
        u = gelu(linear(x, bp["w_up"], bp["b_up"]))
        h = h + linear(u, bp["w_down"], bp["b_down"])

    h = layer_norm(h, cast(params["lnf_g"]), cast(params["lnf_b"]))
    w_out = params["tok_embed"].T if c.tie_embeddings else params["lm_head"]
    logits = jnp.einsum("btd,dv->btv", h, cast(w_out), preferred_element_type=jnp.float32)
    new_cache = {
        "k": jnp.stack(new_k),
        "v": jnp.stack(new_v),
        "length": jnp.asarray(valid_len, jnp.int32),
    }
    return logits, new_cache


class Generator:
    """Compiled prefill + decode for one (config, batch, max_len) shape.

    Two decode granularities:

    - ``_decode``: one token per dispatch — simple, but each host sync pays
      a full host↔device round trip (on a tunneled chip that is ~100 ms, on
      a colocated host ~100 µs).
    - ``_prefill_decode`` / ``_decode_chunk``: prefill fused with a
      ``lax.scan`` over K decode steps in ONE dispatch — the sampling loop
      lives on device, so K tokens cost one round trip. This is the serving
      fast path (`serve/llm.py`).
    """

    def __init__(self, params, config: TransformerConfig, *, batch: int = 1,
                 max_len: Optional[int] = None):
        self.params = params
        self.config = config
        self.batch = batch
        self.max_len = max_len or config.max_seq_len

        c = config

        @jax.jit
        def prefill(params, cache, tokens):  # tokens [B, P] (P static)
            return _forward_cached(params, tokens, cache, c, 0)

        @functools.partial(jax.jit, donate_argnums=(1,))
        def decode(params, cache, token, pos):  # token [B, 1]
            logits, cache = _forward_cached(params, token, cache, c, pos)
            return logits[:, -1], cache

        self._prefill = prefill
        self._decode = decode
        self._chunked = {}  # (chunk, sampled) -> (prefill_decode, decode_chunk)

    def chunked_fns(self, chunk: int, sampled: bool):
        """Jitted (prefill+scan-decode, scan-decode) pair for a chunk size."""
        key = (chunk, sampled)
        if key in self._chunked:
            return self._chunked[key]
        c = self.config

        def make_step(params, temp):
            # A FRESH closure per jit trace: lax.scan caches traced jaxprs
            # by (function identity, avals), so sharing one step function
            # across the two jitted wrappers would leak the first trace's
            # closure tracers into the second as stale constants.
            def step(carry, _):
                last, cache, pos, rng = carry
                real = last[:, : c.vocab_size]
                if sampled:
                    rng, sub = jax.random.split(rng)
                    nxt = jax.random.categorical(sub, real / temp, axis=-1)
                else:
                    nxt = jnp.argmax(real, axis=-1)
                logits, cache = _forward_cached(
                    params, nxt[:, None].astype(jnp.int32), cache, c, pos
                )
                return (logits[:, -1], cache, pos + 1, rng), nxt

            return step

        @functools.partial(jax.jit, donate_argnums=(1,))
        def prefill_decode(params, cache, padded, real_len, rng, temp):
            """One dispatch: full prefill + K sampled/greedy decode steps.

            ``padded`` [B, P]: prompt padded to a bucket; first-token logits
            are read at the REAL last position, and decode starts at
            ``real_len`` so pad garbage in the cache is overwritten before
            the causal mask could ever expose it.
            """
            logits, cache = _forward_cached(params, padded, cache, c, 0)
            last = jax.lax.dynamic_index_in_dim(
                logits, real_len - 1, axis=1, keepdims=False)   # [B, V]
            (last, cache, pos, rng), toks = lax.scan(
                make_step(params, temp), (last, cache, real_len, rng),
                None, length=chunk)
            return toks.T, last, cache, pos, rng                 # [B, chunk]

        @functools.partial(jax.jit, donate_argnums=(1,))
        def decode_chunk(params, cache, last, pos, rng, temp):
            (last, cache, pos, rng), toks = lax.scan(
                make_step(params, temp), (last, cache, pos, rng),
                None, length=chunk)
            return toks.T, last, cache, pos, rng

        self._chunked[key] = (prefill_decode, decode_chunk)
        return self._chunked[key]

    def generate(
        self,
        prompt_tokens,
        *,
        max_new_tokens: int = 32,
        temperature: float = 0.0,
        seed: int = 0,
        stream: bool = False,
    ):
        """Greedy (temperature=0) or sampled generation. Returns token list
        (or a generator of tokens when ``stream``)."""
        import numpy as np

        def run():
            prompt = jnp.asarray(np.asarray(prompt_tokens, np.int32)).reshape(self.batch, -1)
            P = prompt.shape[1]
            cache = init_cache(self.config, self.batch, self.max_len)
            logits, cache = self._prefill(self.params, cache, prompt)
            key = jax.random.key(seed)
            last = logits[:, -1]
            pos = P
            for _ in range(max_new_tokens):
                # mask vocab padding before picking
                last_real = last[:, : self.config.vocab_size]
                if temperature > 0:
                    key, sub = jax.random.split(key)
                    nxt = jax.random.categorical(sub, last_real / temperature, axis=-1)
                else:
                    nxt = jnp.argmax(last_real, axis=-1)
                yield int(nxt[0])
                if pos >= self.max_len:
                    return
                last, cache = self._decode(
                    self.params, cache, nxt[:, None].astype(jnp.int32), pos
                )
                pos += 1

        if stream:
            return run()
        return list(run())
