"""Autoregressive generation with KV cache.

The inference fast path the Serve replicas use. The reference serves models
through vLLM/framework engines; TPU-native the decode loop is two jitted XLA
programs with static shapes:

- ``prefill``: one full forward over the (padded) prompt, writing K/V for
  every layer into a preallocated cache [L, B, max_len, H, Dh];
- ``decode_step``: single-token forward reading the cache — O(1) FLOPs in
  context length per token instead of the O(ctx) full-window forward.

The cache is a pytree carried through ``lax.scan``-style stepping on the
host; batch/beam layouts stay static so both programs compile exactly once.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.models.transformer import TransformerConfig
from ray_tpu.ops.layers import gelu, layer_norm, linear, rope
from ray_tpu.ops.paged_attention import paged_attention


def resolve_attention_kernel(mode: Optional[str]) -> str:
    """Resolve the ``serve_paged_attention_kernel`` knob to a concrete mode:
    ``pallas`` (compiled kernel), ``interpret`` (Pallas interpret mode — the
    CPU tier-1 path exercising the same kernel), or ``gather`` (the XLA
    table-gather formulation). ``auto`` picks pallas on TPU and gather on
    CPU, where interpret-mode per-token dispatch would tax the test suite."""
    mode = (mode or "auto").lower()
    if mode == "auto":
        return "pallas" if jax.default_backend() == "tpu" else "gather"
    if mode not in ("pallas", "interpret", "gather"):
        raise ValueError(
            f"serve_paged_attention_kernel must be auto|pallas|interpret|"
            f"gather, got {mode!r}")
    return mode


def init_cache(config: TransformerConfig, batch: int, max_len: Optional[int] = None) -> Dict:
    c = config
    max_len = max_len or c.max_seq_len
    shape = (c.n_layers, batch, max_len, c.n_heads, c.head_dim)
    return {
        "k": jnp.zeros(shape, c.dtype),
        "v": jnp.zeros(shape, c.dtype),
        "length": jnp.zeros((), jnp.int32),
    }


def _attend_cached(q, k_cache, v_cache, valid_len, *, scale: float):
    """q: [B, T, H, D] against cache [B, S, H, D]; positions >= valid_len are
    masked. For prefill T>1 a causal mask also applies within the window.

    ``valid_len`` may be a scalar (every row at the same position — the
    single-sequence path) or a [B] vector (per-slot positions — the
    continuous-batching path, where each cache row holds an independent
    sequence at its own decode offset)."""
    B, T, H, D = q.shape
    S = k_cache.shape[1]
    scores = jnp.einsum(
        "bthd,bshd->bhts", q, k_cache, preferred_element_type=jnp.float32
    ) * scale
    kv_pos = jnp.arange(S)[None, None, None, :]          # [1,1,1,S]
    vl = jnp.asarray(valid_len)
    if vl.ndim:                                           # per-row [B]
        vl = vl.reshape(-1, 1, 1, 1)                      # [B,1,1,1]
    q_pos = (vl - T) + jnp.arange(T)[None, None, :, None]
    mask = kv_pos <= q_pos                                # causal + validity
    scores = jnp.where(mask, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhts,bshd->bthd", probs, v_cache.astype(jnp.float32))
    return out.astype(q.dtype)


def _forward_cached(params, tokens, cache, config: TransformerConfig, start_pos):
    """Forward ``tokens`` [B, T] at positions [start_pos, start_pos+T),
    updating the cache. Returns (logits[B, T, V], new_cache)."""
    c = config
    cast = lambda p: p.astype(c.dtype)
    B, T = tokens.shape
    h = jnp.take(cast(params["tok_embed"]), tokens, axis=0)
    positions = start_pos + jnp.arange(T)
    if c.pos == "learned":
        h = h + cast(params["pos_embed"])[positions]
    scale = 1.0 / c.head_dim**0.5
    valid_len = start_pos + T

    new_k, new_v = [], []
    for layer in range(c.n_layers):
        bp = jax.tree.map(lambda p: cast(p[layer]), params["blocks"])
        x = layer_norm(h, bp["ln1_g"], bp["ln1_b"])
        q = jnp.einsum("btd,dhk->bthk", x, bp["wq"], preferred_element_type=jnp.float32).astype(c.dtype) + bp["bq"]
        k = jnp.einsum("btd,dhk->bthk", x, bp["wk"], preferred_element_type=jnp.float32).astype(c.dtype) + bp["bk"]
        v = jnp.einsum("btd,dhk->bthk", x, bp["wv"], preferred_element_type=jnp.float32).astype(c.dtype) + bp["bv"]
        if c.pos == "rope":
            q = rope(q, positions)
            k = rope(k, positions)
        k_cache = lax.dynamic_update_slice(
            cache["k"][layer], k, (0, start_pos, 0, 0)
        )
        v_cache = lax.dynamic_update_slice(
            cache["v"][layer], v, (0, start_pos, 0, 0)
        )
        new_k.append(k_cache)
        new_v.append(v_cache)
        o = _attend_cached(q, k_cache, v_cache, valid_len, scale=scale)
        o = jnp.einsum("bthk,hkd->btd", o, bp["wo"], preferred_element_type=jnp.float32).astype(c.dtype) + bp["bo"]
        h = h + o
        x = layer_norm(h, bp["ln2_g"], bp["ln2_b"])
        u = gelu(linear(x, bp["w_up"], bp["b_up"]))
        h = h + linear(u, bp["w_down"], bp["b_down"])

    h = layer_norm(h, cast(params["lnf_g"]), cast(params["lnf_b"]))
    w_out = params["tok_embed"].T if c.tie_embeddings else params["lm_head"]
    logits = jnp.einsum("btd,dv->btv", h, cast(w_out), preferred_element_type=jnp.float32)
    new_cache = {
        "k": jnp.stack(new_k),
        "v": jnp.stack(new_v),
        "length": jnp.asarray(valid_len, jnp.int32),
    }
    return logits, new_cache


def init_slot_cache(config: TransformerConfig, slots: int,
                    max_len: Optional[int] = None) -> Dict:
    """KV cache for ``slots`` INDEPENDENT sequences: the batch dim is a slot
    index and ``lengths[s]`` replaces the single scalar ``length`` — each
    slot decodes at its own position (the continuous-batching layout)."""
    c = config
    max_len = max_len or c.max_seq_len
    shape = (c.n_layers, slots, max_len, c.n_heads, c.head_dim)
    return {
        "k": jnp.zeros(shape, c.dtype),
        "v": jnp.zeros(shape, c.dtype),
        "lengths": jnp.zeros((slots,), jnp.int32),
    }


def _forward_decode_slotted(params, tokens, k_cache, v_cache, lengths,
                            config: TransformerConfig):
    """One decode step for S independent slots: ``tokens`` [S, 1] at per-slot
    positions ``lengths`` [S]. Writes each slot's new K/V at its own position
    (scatter over the batch dim — ``dynamic_update_slice`` only takes scalar
    starts) and attends with per-slot validity. Returns
    (logits [S, 1, V], new_k, new_v); rows are fully independent, so an
    inactive slot's garbage output never contaminates its neighbours.
    """
    c = config
    cast = lambda p: p.astype(c.dtype)
    S, T = tokens.shape  # T == 1
    M = k_cache.shape[2]
    h = jnp.take(cast(params["tok_embed"]), tokens, axis=0)
    # Clamp the write position: a slot parked at the context cap (retired,
    # awaiting refill) must not scatter out of bounds. Its row's output is
    # dead either way — the clamp only keeps the scatter well-defined.
    pos = jnp.minimum(lengths, M - 1)
    positions = pos[:, None]                              # [S, 1]
    if c.pos == "learned":
        h = h + cast(params["pos_embed"])[positions]
    scale = 1.0 / c.head_dim**0.5
    rows = jnp.arange(S)
    valid_len = pos + 1                                   # new token attendable

    new_k, new_v = [], []
    for layer in range(c.n_layers):
        bp = jax.tree.map(lambda p: cast(p[layer]), params["blocks"])
        x = layer_norm(h, bp["ln1_g"], bp["ln1_b"])
        q = jnp.einsum("btd,dhk->bthk", x, bp["wq"], preferred_element_type=jnp.float32).astype(c.dtype) + bp["bq"]
        k = jnp.einsum("btd,dhk->bthk", x, bp["wk"], preferred_element_type=jnp.float32).astype(c.dtype) + bp["bk"]
        v = jnp.einsum("btd,dhk->bthk", x, bp["wv"], preferred_element_type=jnp.float32).astype(c.dtype) + bp["bv"]
        if c.pos == "rope":
            q = rope(q, positions)
            k = rope(k, positions)
        kc = k_cache[layer].at[rows, pos].set(k[:, 0])
        vc = v_cache[layer].at[rows, pos].set(v[:, 0])
        new_k.append(kc)
        new_v.append(vc)
        o = _attend_cached(q, kc, vc, valid_len, scale=scale)
        o = jnp.einsum("bthk,hkd->btd", o, bp["wo"], preferred_element_type=jnp.float32).astype(c.dtype) + bp["bo"]
        h = h + o
        x = layer_norm(h, bp["ln2_g"], bp["ln2_b"])
        u = gelu(linear(x, bp["w_up"], bp["b_up"]))
        h = h + linear(u, bp["w_down"], bp["b_down"])

    h = layer_norm(h, cast(params["lnf_g"]), cast(params["lnf_b"]))
    w_out = params["tok_embed"].T if c.tie_embeddings else params["lm_head"]
    logits = jnp.einsum("btd,dv->btv", h, cast(w_out), preferred_element_type=jnp.float32)
    return logits, jnp.stack(new_k), jnp.stack(new_v)


class SlottedGenerator:
    """Compiled slot-level prefill + batched decode for continuous batching.

    The serving engine's device half (``serve/llm.py LLMEngine``): S cache
    slots hold S independent sequences, and

    - :meth:`prefill_fn` — jitted per prompt bucket — writes ONE prompt's
      K/V into its slot (``insert_prefill``) and parks its next-token logits
      in the ``last`` [S, V] carry;
    - :meth:`decode_fn` — jitted once per chunk size — advances ALL slots by
      ``chunk`` tokens in ONE dispatch via ``lax.scan``: inactive slots are
      masked (their ``lengths`` freeze, their ``last``/key rows keep their
      values), greedy and sampled slots ride the same program through
      per-slot ``greedy``/``temps`` operands, so everything compiles exactly
      once per (bucket | chunk) regardless of the traffic mix.

    Device state is the ``(cache, last, keys)`` triple threaded through both
    functions with buffer donation — the engine must hold only the returned
    arrays.
    """

    def __init__(self, params, config: TransformerConfig, *, slots: int,
                 max_len: Optional[int] = None):
        self.params = params
        self.config = config
        self.slots = slots
        self.max_len = max_len or config.max_seq_len
        self.logits_dim = (params["tok_embed"].shape[0]
                          if config.tie_embeddings
                          else params["lm_head"].shape[-1])
        self._prefill_fns = {}  # bucket -> jitted insert_prefill
        self._decode_fns = {}   # chunk -> jitted decode_chunk

    def init_state(self):
        cache = init_slot_cache(self.config, self.slots, self.max_len)
        last = jnp.zeros((self.slots, self.logits_dim), jnp.float32)
        keys = jnp.zeros((self.slots, 2), jnp.uint32)
        return cache, last, keys

    def prefill_fn(self, bucket: int):
        """insert_prefill(params, cache, last, keys, padded [1,P], real_len,
        slot, seed) -> (cache, last, keys): one prompt's K/V into one slot."""
        fn = self._prefill_fns.get(bucket)
        if fn is not None:
            return fn
        c = self.config

        @functools.partial(jax.jit, donate_argnums=(1, 2, 3))
        def insert_prefill(params, cache, last, keys, padded, real_len, slot,
                           seed):
            P = padded.shape[1]
            tmp = {
                "k": jnp.zeros((c.n_layers, 1, P, c.n_heads, c.head_dim),
                               c.dtype),
                "v": jnp.zeros((c.n_layers, 1, P, c.n_heads, c.head_dim),
                               c.dtype),
                "length": jnp.zeros((), jnp.int32),
            }
            logits, tmp = _forward_cached(params, padded, tmp, c, 0)
            k_c = lax.dynamic_update_slice(cache["k"], tmp["k"],
                                           (0, slot, 0, 0, 0))
            v_c = lax.dynamic_update_slice(cache["v"], tmp["v"],
                                           (0, slot, 0, 0, 0))
            lengths = cache["lengths"].at[slot].set(real_len)
            row = jax.lax.dynamic_index_in_dim(
                logits, real_len - 1, axis=1, keepdims=False)      # [1, V]
            last = lax.dynamic_update_slice(last, row, (slot, 0))
            keys = lax.dynamic_update_slice(
                keys, jax.random.PRNGKey(seed)[None], (slot, 0))
            return {"k": k_c, "v": v_c, "lengths": lengths}, last, keys

        self._prefill_fns[bucket] = insert_prefill
        return insert_prefill

    def decode_fn(self, chunk: int):
        """decode_chunk(params, cache, last, keys, active, greedy, temps) ->
        (toks [S, chunk], cache, last, keys): ``chunk`` scan steps advancing
        every active slot, one dispatch."""
        fn = self._decode_fns.get(chunk)
        if fn is not None:
            return fn
        c = self.config

        @functools.partial(jax.jit, donate_argnums=(1, 2, 3))
        def decode_chunk(params, cache, last, keys, active, greedy, temps):
            adv = active.astype(jnp.int32)
            act_col = active[:, None]
            temp_safe = jnp.maximum(temps, 1e-6)[:, None]

            def step(carry, _):
                k_c, v_c, lengths, last, keys = carry
                real = last[:, : c.vocab_size]
                split = jax.vmap(jax.random.split)(keys)   # [S, 2, 2]
                keys2, subs = split[:, 0], split[:, 1]
                samp = jax.vmap(jax.random.categorical)(subs, real / temp_safe)
                nxt = jnp.where(greedy, jnp.argmax(real, axis=-1),
                                samp).astype(jnp.int32)
                logits, k_c, v_c = _forward_decode_slotted(
                    params, nxt[:, None], k_c, v_c, lengths, c)
                lengths = lengths + adv
                last = jnp.where(act_col, logits[:, -1], last)
                keys = jnp.where(act_col, keys2, keys)
                return (k_c, v_c, lengths, last, keys), nxt

            (k_c, v_c, lengths, last, keys), toks = lax.scan(
                step, (cache["k"], cache["v"], cache["lengths"], last, keys),
                None, length=chunk)
            return (toks.T, {"k": k_c, "v": v_c, "lengths": lengths}, last,
                    keys)

        self._decode_fns[chunk] = decode_chunk
        return decode_chunk


def init_block_pool(config: TransformerConfig, num_blocks: int,
                    block_tokens: int) -> Tuple[jax.Array, jax.Array]:
    """Shared paged KV pool: ``num_blocks`` fixed-size blocks of
    ``block_tokens`` K/V rows each, shared by every sequence through
    per-sequence block TABLES instead of private max_len slabs. Block 0 is
    the reserved TRASH block: freed table rows and pad positions point at
    it, so out-of-range scatter writes land somewhere harmless instead of
    corrupting a live sequence."""
    c = config
    shape = (c.n_layers, num_blocks, block_tokens, c.n_heads, c.head_dim)
    return jnp.zeros(shape, c.dtype), jnp.zeros(shape, c.dtype)


def _paged_attend(q, k_pool, v_pool, tables, lengths, *, scale, kernel):
    """Attention over the paged pool for one layer, switched by ``kernel``:
    the Pallas kernel streams only live blocks (compiled on TPU, interpret
    on CPU); ``gather`` is the legacy table-gather + dense-mask path."""
    if kernel in ("pallas", "interpret"):
        return paged_attention(q, k_pool, v_pool, tables, lengths,
                               scale=scale, interpret=kernel == "interpret")
    S, T = q.shape[:2]
    nb, bt, H, D = k_pool.shape
    nb_seq = tables.shape[1]
    kc = k_pool[tables].reshape(S, nb_seq * bt, H, D)
    vc = v_pool[tables].reshape(S, nb_seq * bt, H, D)
    return _attend_cached(q, kc, vc, lengths + T, scale=scale)


def _forward_prefill_paged(params, tokens, k_pool, v_pool, table, start_pos,
                           suffix_len, config: TransformerConfig,
                           block_tokens: int, kernel: str = "gather"):
    """Prefill ``tokens`` [1, P] (a SUFFIX bucket) at absolute positions
    [start_pos, start_pos+P) into the paged pool through ``table`` [NB].

    Prefix reuse is what makes ``start_pos`` nonzero: positions below it
    were written by earlier sequences sharing the same blocks, so attention
    gathers them back through the table without recomputing. Only the first
    ``suffix_len`` positions are real — pad writes redirect to trash block
    0 and pad queries are causally ahead of every real row, so their
    garbage never reaches a real position's softmax."""
    c = config
    cast = lambda p: p.astype(c.dtype)
    B, P = tokens.shape  # B == 1
    NB = table.shape[0]
    bt = block_tokens
    h = jnp.take(cast(params["tok_embed"]), tokens, axis=0)
    positions = start_pos + jnp.arange(P)
    if c.pos == "learned":
        h = h + cast(params["pos_embed"])[jnp.minimum(
            positions, c.max_seq_len - 1)][None]
    scale = 1.0 / c.head_dim**0.5
    lengths1 = jnp.reshape(start_pos, (1,)).astype(jnp.int32)
    write_ok = jnp.arange(P) < suffix_len
    blk = jnp.where(write_ok,
                    table[jnp.clip(positions // bt, 0, NB - 1)], 0)
    off = positions % bt

    for layer in range(c.n_layers):
        bp = jax.tree.map(lambda p: cast(p[layer]), params["blocks"])
        x = layer_norm(h, bp["ln1_g"], bp["ln1_b"])
        q = jnp.einsum("btd,dhk->bthk", x, bp["wq"], preferred_element_type=jnp.float32).astype(c.dtype) + bp["bq"]
        k = jnp.einsum("btd,dhk->bthk", x, bp["wk"], preferred_element_type=jnp.float32).astype(c.dtype) + bp["bk"]
        v = jnp.einsum("btd,dhk->bthk", x, bp["wv"], preferred_element_type=jnp.float32).astype(c.dtype) + bp["bv"]
        if c.pos == "rope":
            q = rope(q, positions[None])
            k = rope(k, positions[None])
        k_pool = k_pool.at[layer, blk, off].set(k[0])
        v_pool = v_pool.at[layer, blk, off].set(v[0])
        o = _paged_attend(q, k_pool[layer], v_pool[layer], table[None],
                          lengths1, scale=scale, kernel=kernel)
        o = jnp.einsum("bthk,hkd->btd", o, bp["wo"], preferred_element_type=jnp.float32).astype(c.dtype) + bp["bo"]
        h = h + o
        x = layer_norm(h, bp["ln2_g"], bp["ln2_b"])
        u = gelu(linear(x, bp["w_up"], bp["b_up"]))
        h = h + linear(u, bp["w_down"], bp["b_down"])

    h = layer_norm(h, cast(params["lnf_g"]), cast(params["lnf_b"]))
    w_out = params["tok_embed"].T if c.tie_embeddings else params["lm_head"]
    logits = jnp.einsum("btd,dv->btv", h, cast(w_out), preferred_element_type=jnp.float32)
    return logits, k_pool, v_pool


def _forward_decode_paged(params, tokens, k_pool, v_pool, tables, lengths,
                          config: TransformerConfig, block_tokens: int,
                          kernel: str = "gather"):
    """Decode ``tokens`` [S, T] for S sequences over the paged pool: slot
    s's token t sits at absolute position ``lengths[s] + t`` (T > 1 is the
    speculative-decoding verify), its K/V scattered into block
    ``tables[s, pos // bt]`` row ``pos % bt`` and attention run back through
    the table row. Inactive slots carry all-trash tables, so their writes
    land in block 0 and their outputs are dead.

    Positions at or past table capacity redirect their writes to trash
    block 0 rather than clamping onto the last cell — a slot at capacity
    must be finished as ``length_cap`` by the engine BEFORE dispatch, so
    in-range rows never see a silently overwritten chain; the redirect only
    shields parked/speculative overhang writes."""
    c = config
    cast = lambda p: p.astype(c.dtype)
    S, T = tokens.shape
    NB = tables.shape[1]
    bt = block_tokens
    max_len = NB * bt
    h = jnp.take(cast(params["tok_embed"]), tokens, axis=0)
    positions = lengths[:, None] + jnp.arange(T)[None, :]  # [S, T]
    write_ok = positions < max_len
    pos_c = jnp.minimum(positions, max_len - 1)
    if c.pos == "learned":
        h = h + cast(params["pos_embed"])[jnp.minimum(
            positions, c.max_seq_len - 1)]
    scale = 1.0 / c.head_dim**0.5
    rows = jnp.arange(S)[:, None]
    blk = jnp.where(write_ok, tables[rows, pos_c // bt], 0)
    off = pos_c % bt

    for layer in range(c.n_layers):
        bp = jax.tree.map(lambda p: cast(p[layer]), params["blocks"])
        x = layer_norm(h, bp["ln1_g"], bp["ln1_b"])
        q = jnp.einsum("btd,dhk->bthk", x, bp["wq"], preferred_element_type=jnp.float32).astype(c.dtype) + bp["bq"]
        k = jnp.einsum("btd,dhk->bthk", x, bp["wk"], preferred_element_type=jnp.float32).astype(c.dtype) + bp["bk"]
        v = jnp.einsum("btd,dhk->bthk", x, bp["wv"], preferred_element_type=jnp.float32).astype(c.dtype) + bp["bv"]
        if c.pos == "rope":
            q = rope(q, positions)
            k = rope(k, positions)
        k_pool = k_pool.at[layer, blk, off].set(k)
        v_pool = v_pool.at[layer, blk, off].set(v)
        o = _paged_attend(q, k_pool[layer], v_pool[layer], tables, lengths,
                          scale=scale, kernel=kernel)
        o = jnp.einsum("bthk,hkd->btd", o, bp["wo"], preferred_element_type=jnp.float32).astype(c.dtype) + bp["bo"]
        h = h + o
        x = layer_norm(h, bp["ln2_g"], bp["ln2_b"])
        u = gelu(linear(x, bp["w_up"], bp["b_up"]))
        h = h + linear(u, bp["w_down"], bp["b_down"])

    h = layer_norm(h, cast(params["lnf_g"]), cast(params["lnf_b"]))
    w_out = params["tok_embed"].T if c.tie_embeddings else params["lm_head"]
    logits = jnp.einsum("btd,dv->btv", h, cast(w_out), preferred_element_type=jnp.float32)
    return logits, k_pool, v_pool


class PagedGenerator:
    """Paged device half of the serving engine: same compile discipline as
    :class:`SlottedGenerator` (one program per prompt bucket, one per chunk
    size), but K/V lives in a SHARED block pool addressed through
    per-sequence block tables — the layout that makes hash-based prefix
    reuse, copy-on-write forks and prefill/decode KV handoff possible.

    Device state is ``(k_pool, v_pool, last, keys)`` threaded with buffer
    donation; block tables and per-slot lengths are plain numpy operands
    owned by the host-side :class:`KVBlockManager` + engine.
    """

    def __init__(self, params, config: TransformerConfig, *, slots: int,
                 num_blocks: int, block_tokens: int,
                 max_len: Optional[int] = None,
                 attention_kernel: str = "auto",
                 draft_params=None,
                 draft_config: Optional[TransformerConfig] = None):
        self.params = params
        self.config = config
        self.slots = slots
        self.max_len = max_len or config.max_seq_len
        self.block_tokens = int(block_tokens)
        if self.max_len % self.block_tokens:
            raise ValueError(
                f"max_len {self.max_len} not a multiple of "
                f"serve_kv_block_tokens {self.block_tokens}")
        self.blocks_per_seq = self.max_len // self.block_tokens
        self.num_blocks = int(num_blocks)
        self.attention_kernel = resolve_attention_kernel(attention_kernel)
        if (draft_params is None) != (draft_config is None):
            raise ValueError("draft_params and draft_config go together")
        if draft_config is not None and (
                draft_config.vocab_size != config.vocab_size):
            raise ValueError(
                f"draft vocab {draft_config.vocab_size} != target vocab "
                f"{config.vocab_size} — speculative verify needs one vocab")
        self.draft_params = draft_params
        self.draft_config = draft_config
        self.logits_dim = (params["tok_embed"].shape[0]
                          if config.tie_embeddings
                          else params["lm_head"].shape[-1])
        self._prefill_fns = {}   # suffix bucket -> jitted paged prefill
        self._decode_fns = {}    # chunk -> jitted paged decode
        self._extract_fns = {}   # nb -> jitted block gather (KV handoff out)
        self._insert_fns = {}    # nb -> jitted block scatter (KV handoff in)
        self._copy_fn = None
        self._draft_prefill_fns = {}  # suffix bucket -> jitted draft prefill
        self._spec_decode_fns = {}    # (chunk, k) -> jitted spec decode

    def init_state(self):
        k_pool, v_pool = init_block_pool(self.config, self.num_blocks,
                                         self.block_tokens)
        last = jnp.zeros((self.slots, self.logits_dim), jnp.float32)
        keys = jnp.zeros((self.slots, 2), jnp.uint32)
        return k_pool, v_pool, last, keys

    def init_draft_state(self):
        """Draft-model pool mirroring the target pool's block geometry: the
        SAME block tables index both, so advance/rollback bookkeeping is
        shared and speculation adds zero KVBlockManager state."""
        return init_block_pool(self.draft_config, self.num_blocks,
                               self.block_tokens)

    def prefill_fn(self, bucket: int):
        """paged_prefill(params, k_pool, v_pool, last, keys, table [NB],
        padded [1,P], start_pos, suffix_len, slot, seed) -> (k_pool, v_pool,
        last, keys): prefill the SUFFIX bucket at start_pos (the prefix-hit
        length) and park last-token logits + PRNG key in the slot rows."""
        fn = self._prefill_fns.get(bucket)
        if fn is not None:
            return fn
        c = self.config
        bt = self.block_tokens
        kernel = self.attention_kernel

        @functools.partial(jax.jit, donate_argnums=(1, 2, 3, 4))
        def paged_prefill(params, k_pool, v_pool, last, keys, table, padded,
                          start_pos, suffix_len, slot, seed):
            logits, k_pool, v_pool = _forward_prefill_paged(
                params, padded, k_pool, v_pool, table, start_pos,
                suffix_len, c, bt, kernel=kernel)
            row = jax.lax.dynamic_index_in_dim(
                logits, suffix_len - 1, axis=1, keepdims=False)     # [1, V]
            last = lax.dynamic_update_slice(last, row, (slot, 0))
            keys = lax.dynamic_update_slice(
                keys, jax.random.PRNGKey(seed)[None], (slot, 0))
            return k_pool, v_pool, last, keys

        self._prefill_fns[bucket] = paged_prefill
        return paged_prefill

    def decode_fn(self, chunk: int):
        """paged_decode(params, k_pool, v_pool, last, keys, tables [S,NB],
        lengths [S], active, greedy, temps) -> (toks [S, chunk], k_pool,
        v_pool, last, keys): ``chunk`` scan steps advancing every active
        slot through its block table in one dispatch."""
        fn = self._decode_fns.get(chunk)
        if fn is not None:
            return fn
        c = self.config
        bt = self.block_tokens
        kernel = self.attention_kernel

        @functools.partial(jax.jit, donate_argnums=(1, 2, 3, 4))
        def paged_decode(params, k_pool, v_pool, last, keys, tables, lengths,
                         active, greedy, temps):
            adv = active.astype(jnp.int32)
            act_col = active[:, None]
            temp_safe = jnp.maximum(temps, 1e-6)[:, None]

            def step(carry, _):
                k_p, v_p, lens, last, keys = carry
                real = last[:, : c.vocab_size]
                split = jax.vmap(jax.random.split)(keys)   # [S, 2, 2]
                keys2, subs = split[:, 0], split[:, 1]
                samp = jax.vmap(jax.random.categorical)(subs, real / temp_safe)
                nxt = jnp.where(greedy, jnp.argmax(real, axis=-1),
                                samp).astype(jnp.int32)
                logits, k_p, v_p = _forward_decode_paged(
                    params, nxt[:, None], k_p, v_p, tables, lens, c, bt,
                    kernel=kernel)
                lens = lens + adv
                last = jnp.where(act_col, logits[:, -1], last)
                keys = jnp.where(act_col, keys2, keys)
                return (k_p, v_p, lens, last, keys), nxt

            (k_pool, v_pool, _lens, last, keys), toks = lax.scan(
                step, (k_pool, v_pool, jnp.asarray(lengths), last, keys),
                None, length=chunk)
            return toks.T, k_pool, v_pool, last, keys

        self._decode_fns[chunk] = paged_decode
        return paged_decode

    def draft_prefill_fn(self, bucket: int):
        """draft_prefill(draft_params, kd_pool, vd_pool, table [NB],
        padded [1,P], start_pos, suffix_len) -> (kd_pool, vd_pool): run the
        DRAFT model over the same suffix bucket through the same block
        table so its pool holds draft-KV for every position the target
        holds — the draft chain in :meth:`spec_decode_fn` then starts from
        a warm cache. Logits are discarded (the first proposal conditions
        on the verified tail, not on prefill output)."""
        fn = self._draft_prefill_fns.get(bucket)
        if fn is not None:
            return fn
        dc = self.draft_config
        bt = self.block_tokens
        kernel = self.attention_kernel

        @functools.partial(jax.jit, donate_argnums=(1, 2))
        def draft_prefill(draft_params, kd_pool, vd_pool, table, padded,
                          start_pos, suffix_len):
            _, kd_pool, vd_pool = _forward_prefill_paged(
                draft_params, padded, kd_pool, vd_pool, table, start_pos,
                suffix_len, dc, bt, kernel=kernel)
            return kd_pool, vd_pool

        self._draft_prefill_fns[bucket] = draft_prefill
        return draft_prefill

    def spec_decode_fn(self, chunk: int, k: int):
        """Speculative decode: ``chunk`` scan steps, each proposing ``k``
        draft tokens and verifying them in ONE batched target forward.

        spec_decode(params, draft_params, k_pool, v_pool, kd_pool, vd_pool,
        last, keys, tables, lengths, active, greedy, temps, spec_on, tail,
        pending, use_pending) -> (toks [S, chunk, k+1], counts [S, chunk],
        accepted [S, chunk], k_pool, v_pool, kd_pool, vd_pool, last, keys,
        tail, pending, use_pending).

        Per step and slot: token n0 comes from ``last`` (or the carried
        rejection replacement when ``use_pending``); the draft runs k+1
        single-token forwards — forward 0 re-consumes ``tail`` (the last
        accepted token) at position len-1, an idempotent KV rewrite that
        also fills the one draft-KV hole a fully-accepted previous step
        leaves, then forwards 1..k consume n0, d_1, ..., d_{k-1} and emit
        proposals d_1..d_k with their logits. The target verifies
        [n0, d_1..d_k] in one [S, k+1] forward. Acceptance is rejection
        sampling — u < p(d)/q(d) preserves the target distribution for ANY
        draft; the greedy path is exact argmax prefix match — and the slot
        advances 1 + a tokens where a is the accepted prefix length. On
        rejection at a < k, a replacement is drawn from the normalized
        residual max(p - q, 0) (greedy: target argmax) and carried as
        ``pending`` to be next step's n0; on full acceptance ``last``
        becomes the verify logits at position k. Only valid positions
        (< lengths + 1 + a) survive in the pools — overhang writes are
        overwritten by the next step before they become attendable, and
        retirement publishes only real tokens.

        ``toks[s, t, :counts[s, t]]`` are the emitted tokens of step t.
        ``spec_on`` False (acceptance EWMA below floor, or no table
        headroom for chunk*(k+1)) degrades the slot to the plain one-token
        path inside the same program: proposals are force-rejected so
        a == 0 and exactly n0 is emitted per step."""
        key_ck = (chunk, k)
        fn = self._spec_decode_fns.get(key_ck)
        if fn is not None:
            return fn
        if self.draft_config is None:
            raise ValueError("spec_decode_fn requires a draft model")
        if k < 1:
            raise ValueError("serve_spec_tokens must be >= 1 when "
                             "speculative decoding is enabled")
        c = self.config
        dc = self.draft_config
        bt = self.block_tokens
        kernel = self.attention_kernel
        V = c.vocab_size

        @functools.partial(jax.jit, donate_argnums=(2, 3, 4, 5, 6, 7))
        def spec_decode(params, draft_params, k_pool, v_pool, kd_pool,
                        vd_pool, last, keys, tables, lengths, active, greedy,
                        temps, spec_on, tail, pending, use_pending):
            adv_gate = active.astype(jnp.int32)
            act_col = active[:, None]
            temp_safe = jnp.maximum(temps, 1e-6)[:, None]

            def step(carry, _):
                (k_p, v_p, kd_p, vd_p, lens, last, keys, tail, pending,
                 use_pending) = carry
                nsub = 2 * k + 3
                split = jax.vmap(
                    lambda kk: jax.random.split(kk, nsub))(keys)
                keys2 = split[:, 0]
                sub_n0 = split[:, 1]
                sub_draft = split[:, 2:2 + k]            # [S, k, 2]
                sub_acc = split[:, 2 + k:2 + 2 * k]      # [S, k, 2]
                sub_res = split[:, 2 + 2 * k]            # [S, 2]

                real = last[:, :V]
                samp = jax.vmap(jax.random.categorical)(
                    sub_n0, real / temp_safe)
                n0 = jnp.where(
                    use_pending, pending,
                    jnp.where(greedy, jnp.argmax(real, axis=-1),
                              samp)).astype(jnp.int32)

                # Draft chain: k+1 single-token forwards through the SHARED
                # block tables into the draft pool.
                cur_tok = tail
                cur_pos = jnp.maximum(lens - 1, 0)
                proposals, dlogits = [], []
                for i in range(k + 1):
                    dl, kd_p, vd_p = _forward_decode_paged(
                        draft_params, cur_tok[:, None], kd_p, vd_p, tables,
                        cur_pos, dc, bt, kernel=kernel)
                    if i == 0:
                        # Forward 0 only (re)writes tail's draft KV at
                        # lens-1; its logits are superseded by n0's chain.
                        cur_tok, cur_pos = n0, lens
                        continue
                    dreal = dl[:, 0, :V]
                    d_samp = jax.vmap(jax.random.categorical)(
                        sub_draft[:, i - 1], dreal / temp_safe)
                    d_i = jnp.where(greedy, jnp.argmax(dreal, axis=-1),
                                    d_samp).astype(jnp.int32)
                    proposals.append(d_i)
                    dlogits.append(dreal)
                    cur_tok, cur_pos = d_i, lens + i

                # Single batched target verify over [n0, d_1..d_k].
                verify = jnp.stack([n0] + proposals, axis=1)   # [S, k+1]
                logits, k_p, v_p = _forward_decode_paged(
                    params, verify, k_p, v_p, tables, lens, c, bt,
                    kernel=kernel)
                treal = logits[:, :, :V]                       # [S, k+1, V]

                props = jnp.stack(proposals, axis=1)           # [S, k]
                dreal_all = jnp.stack(dlogits, axis=1)         # [S, k, V]
                # Greedy acceptance: exact argmax prefix match. Sampled:
                # u < p(d)/q(d) (target/draft probability of the proposal).
                match = props == jnp.argmax(treal[:, :k], axis=-1)
                tcol = temp_safe[:, :, None]
                p_probs = jax.nn.softmax(treal[:, :k] / tcol, axis=-1)
                q_probs = jax.nn.softmax(dreal_all / tcol, axis=-1)
                p_d = jnp.take_along_axis(
                    p_probs, props[..., None], axis=-1)[..., 0]
                q_d = jnp.take_along_axis(
                    q_probs, props[..., None], axis=-1)[..., 0]
                u = jax.vmap(jax.vmap(
                    lambda kk: jax.random.uniform(kk)))(sub_acc)
                samp_ok = u * jnp.maximum(q_d, 1e-30) < p_d
                ok = jnp.where(greedy[:, None], match, samp_ok)
                ok = ok & spec_on[:, None] & active[:, None]
                run = jnp.cumprod(ok.astype(jnp.int32), axis=1)
                a = jnp.sum(run, axis=1)                       # [S] in 0..k
                full = a == k
                adv = (1 + a) * adv_gate
                lens_new = lens + adv

                # Replacement at the rejection point: residual sampling
                # max(p - q, 0) keeps the OVERALL emitted distribution equal
                # to the target's (greedy: plain target argmax).
                t_at_a = jnp.take_along_axis(
                    treal, a[:, None, None], axis=1)[:, 0]     # [S, V]
                q_at_a = jnp.take_along_axis(
                    dreal_all, jnp.minimum(a, k - 1)[:, None, None],
                    axis=1)[:, 0]
                p_a = jax.nn.softmax(t_at_a / temp_safe, axis=-1)
                q_a = jax.nn.softmax(q_at_a / temp_safe, axis=-1)
                resid = jnp.maximum(p_a - q_a, 0.0)
                rsum = jnp.sum(resid, axis=-1, keepdims=True)
                resid = jnp.where(rsum > 0, resid / rsum, p_a)
                r_samp = jax.vmap(jax.random.categorical)(
                    sub_res, jnp.log(resid + 1e-30))
                repl = jnp.where(greedy, jnp.argmax(t_at_a, axis=-1),
                                 r_samp).astype(jnp.int32)

                tail_new = jnp.take_along_axis(
                    verify, a[:, None], axis=1)[:, 0]
                tail = jnp.where(active, tail_new, tail)
                pending = jnp.where(active, repl, pending)
                # A spec_on slot that rejected carries the residual draw as
                # next step's n0 (use_pending); a fully-accepted slot
                # refreshes `last` from verify position k. A spec_OFF slot
                # never really rejected (the gate force-fails acceptance),
                # so the residual draw would be the WRONG distribution —
                # it refreshes `last` from verify position 0 (its n0's
                # logits, exactly the plain decode chain) and drops any
                # pending carry.
                use_pending = jnp.where(active, ~full & spec_on,
                                        use_pending)
                refresh = active & (full | ~spec_on)
                row_idx = jnp.where(spec_on, k, 0)
                row = jnp.take_along_axis(
                    logits, row_idx[:, None, None], axis=1)[:, 0]
                last = jnp.where(refresh[:, None], row, last)
                keys = jnp.where(act_col, keys2, keys)
                return ((k_p, v_p, kd_p, vd_p, lens_new, last, keys, tail,
                         pending, use_pending),
                        (verify, adv, a * adv_gate))

            carry0 = (k_pool, v_pool, kd_pool, vd_pool,
                      jnp.asarray(lengths), last, keys, jnp.asarray(tail),
                      jnp.asarray(pending), jnp.asarray(use_pending))
            (k_pool, v_pool, kd_pool, vd_pool, _lens, last, keys, tail,
             pending, use_pending), (toks, counts, accepted) = lax.scan(
                step, carry0, None, length=chunk)
            return (toks.transpose(1, 0, 2), counts.T, accepted.T,
                    k_pool, v_pool, kd_pool, vd_pool, last, keys, tail,
                    pending, use_pending)

        self._spec_decode_fns[key_ck] = spec_decode
        return spec_decode

    def copy_fn(self):
        """copy_block(k_pool, v_pool, src, dst) -> (k_pool, v_pool): the
        copy-on-write primitive — duplicate one shared block (a prefix-hit
        partial tail) into a private block before divergent writes."""
        if self._copy_fn is None:

            @functools.partial(jax.jit, donate_argnums=(0, 1))
            def copy_block(k_pool, v_pool, src, dst):
                k_pool = k_pool.at[:, dst].set(k_pool[:, src])
                v_pool = v_pool.at[:, dst].set(v_pool[:, src])
                return k_pool, v_pool

            self._copy_fn = copy_block
        return self._copy_fn

    def extract_fn(self, nb: int):
        """extract(k_pool, v_pool, block_ids [nb]) -> (k [L,nb,bt,H,Dh], v):
        gather a finished prefill's blocks for the disaggregation handoff
        (the pool itself is NOT donated — the prefill engine keeps serving
        its prefix cache from it)."""
        fn = self._extract_fns.get(nb)
        if fn is None:

            @jax.jit
            def extract(k_pool, v_pool, block_ids):
                return k_pool[:, block_ids], v_pool[:, block_ids]

            fn = self._extract_fns[nb] = extract
        return fn

    def insert_fn(self, nb: int):
        """insert(k_pool, v_pool, k [L,nb,bt,H,Dh], v, block_ids [nb]) ->
        (k_pool, v_pool): scatter handed-off blocks into the decode pool —
        donated, so the upload lands in place of the old pool buffers."""
        fn = self._insert_fns.get(nb)
        if fn is None:

            @functools.partial(jax.jit, donate_argnums=(0, 1))
            def insert(k_pool, v_pool, k, v, block_ids):
                k_pool = k_pool.at[:, block_ids].set(k)
                v_pool = v_pool.at[:, block_ids].set(v)
                return k_pool, v_pool

            fn = self._insert_fns[nb] = insert
        return fn

    def set_last_fn(self):
        """set_last(last, keys, row [V], slot, seed) -> (last, keys): park a
        handed-off request's next-token logits + PRNG key in its decode
        slot (the decode-side half of the prefill handoff)."""
        if not hasattr(self, "_set_last_fn") or self._set_last_fn is None:

            @functools.partial(jax.jit, donate_argnums=(0, 1))
            def set_last(last, keys, row, slot, seed):
                last = lax.dynamic_update_slice(last, row[None], (slot, 0))
                keys = lax.dynamic_update_slice(
                    keys, jax.random.PRNGKey(seed)[None], (slot, 0))
                return last, keys

            self._set_last_fn = set_last
        return self._set_last_fn


class NoFreeBlocks(RuntimeError):
    """The pool cannot supply an allocation even after evicting every
    unpinned cached block — the caller should keep the request queued."""


class KVBlockManager:
    """Host-side bookkeeping for the paged KV pool: free list, refcounts,
    and the prefix-reuse hash table.

    Block states (block 0, the trash block, is never managed):

    - FREE: on ``_free``, content garbage;
    - ACTIVE: refcount > 0, pinned by one or more live sequences;
    - CACHED: refcount 0 but hash-retained — the block's content is a
      registered prefix and future lookups may hit it; evicted LRU-first
      when the free list runs dry.

    Full blocks are keyed by the chained digest of the token prefix ending
    at them (``util.blockhash``); a retired sequence's PARTIAL tail block is
    additionally keyed by ``(parent_digest, tail_token_tuple)`` so a
    follow-up turn (history + new text) can reuse it — hit tail blocks are
    handed out COPY-ON-WRITE (the engine duplicates them via
    ``PagedGenerator.copy_fn`` before the divergent suffix writes into
    them; full hit blocks are read-only to every sharer and share by
    refcount alone).

    Thread-safe behind one internal lock; never calls out while holding it
    (safe under the engine's state lock — lock order: engine state → here).
    """

    def __init__(self, num_blocks: int, block_tokens: int):
        import collections as _c
        import threading as _t

        if num_blocks < 2:
            raise ValueError("pool needs at least one block beyond trash")
        self.num_blocks = int(num_blocks)
        self.block_tokens = int(block_tokens)
        self._lock = _t.Lock()
        self._free = _c.deque(range(1, num_blocks))
        self._ref: Dict[int, int] = {}
        self._by_hash: Dict[bytes, int] = {}       # full-block digest -> id
        self._hash_of: Dict[int, bytes] = {}
        self._tail_by_key: Dict[tuple, int] = {}   # (parent, tokens) -> id
        self._tail_key_of: Dict[int, tuple] = {}
        # CACHED blocks in LRU order (oldest first).
        self._cached: "Dict[int, None]" = {}
        self.hit_tokens = 0
        self.miss_tokens = 0
        self.cow_copies = 0

    # -- allocation -----------------------------------------------------------
    def alloc(self, n: int) -> List[int]:
        """Take ``n`` blocks off the free list, evicting LRU cached blocks
        (dropping their hash entries) as needed; raises :class:`NoFreeBlocks`
        without side effects when the pool can't supply them."""
        with self._lock:
            if len(self._free) + len(self._cached) < n:
                raise NoFreeBlocks(
                    f"need {n} blocks; {len(self._free)} free + "
                    f"{len(self._cached)} cached of {self.num_blocks - 1}")
            out = []
            for _ in range(n):
                if self._free:
                    b = self._free.popleft()
                else:
                    b = next(iter(self._cached))   # LRU head
                    self._drop_cached_locked(b)
                self._ref[b] = 1
                out.append(b)
            return out

    def _drop_cached_locked(self, b: int) -> None:
        self._cached.pop(b, None)
        d = self._hash_of.pop(b, None)
        if d is not None and self._by_hash.get(d) == b:
            del self._by_hash[d]
        tk = self._tail_key_of.pop(b, None)
        if tk is not None and self._tail_by_key.get(tk) == b:
            del self._tail_by_key[tk]

    def release(self, block_ids: Sequence[int]) -> None:
        """Unpin blocks; at refcount 0 a hash-registered block becomes
        CACHED (reusable by future lookups, LRU-evictable), an unregistered
        one goes straight back to the free list."""
        with self._lock:
            for b in block_ids:
                r = self._ref.get(b, 0) - 1
                if r > 0:
                    self._ref[b] = r
                    continue
                self._ref.pop(b, None)
                if b in self._hash_of or b in self._tail_key_of:
                    self._cached.pop(b, None)
                    self._cached[b] = None         # move to MRU end
                else:
                    self._free.append(b)

    # -- prefix reuse ---------------------------------------------------------
    def lookup(self, tokens: Sequence[int]) -> Tuple[List[int], Optional[int], int]:
        """Longest reusable prefix of ``tokens``: returns ``(full_blocks,
        tail_block, hit_len)`` with every returned block PINNED (refcount
        bumped; caller must ``release`` them with the sequence). hit_len is
        capped at ``len(tokens) - 1`` so at least one suffix token is always
        recomputed — prefill must produce last-token logits.

        ``tail_block`` (a retired sequence's partial last block) is SHARED
        CONTENT: the caller must copy it before writing (COW)."""
        from ray_tpu.util import blockhash

        bt = self.block_tokens
        cap = len(tokens) - 1
        if cap <= 0:
            return [], None, 0
        digests = blockhash.block_hashes(tokens, bt, max_blocks=cap // bt)
        with self._lock:
            full: List[int] = []
            parent = blockhash.SEED
            for d in digests:
                b = self._by_hash.get(d)
                if b is None:
                    break
                full.append(b)
                parent = d
            k = len(full)
            hit_len = k * bt
            tail = None
            for t in range(min(bt - 1, cap - hit_len), 0, -1):
                key = (parent, tuple(int(x) for x in
                                     tokens[hit_len:hit_len + t]))
                b = self._tail_by_key.get(key)
                if b is not None:
                    tail = b
                    hit_len += t
                    break
            for b in full + ([tail] if tail is not None else []):
                if self._ref.get(b, 0) == 0:
                    self._cached.pop(b, None)      # CACHED -> ACTIVE
                self._ref[b] = self._ref.get(b, 0) + 1
            self.hit_tokens += hit_len
            self.miss_tokens += len(tokens) - hit_len
            return full, tail, hit_len

    def register_chain(self, tokens: Sequence[int], block_ids: Sequence[int],
                       n_real: int) -> None:
        """Publish a sequence's blocks into the reuse table: every block
        fully covered by the first ``n_real`` REAL tokens gets its chained
        digest, and the partial remainder (if any) gets a tail entry.
        First registration wins — a concurrent sequence that produced the
        same prefix keeps the existing mapping and its own blocks simply
        retire unhashed."""
        from ray_tpu.util import blockhash

        bt = self.block_tokens
        n_full = min(n_real // bt, len(block_ids))
        digests = blockhash.block_hashes(tokens[:n_real], bt,
                                         max_blocks=n_full)
        with self._lock:
            parent = blockhash.SEED
            for i, d in enumerate(digests):
                b = block_ids[i]
                if d not in self._by_hash and b not in self._hash_of \
                        and b not in self._tail_key_of:
                    self._by_hash[d] = b
                    self._hash_of[b] = d
                parent = d
            t = n_real - n_full * bt
            if t > 0 and n_full < len(block_ids):
                b = block_ids[n_full]
                key = (parent, tuple(int(x) for x in
                                     tokens[n_full * bt:n_real]))
                if key not in self._tail_by_key and b not in self._hash_of \
                        and b not in self._tail_key_of:
                    self._tail_by_key[key] = b
                    self._tail_key_of[b] = key

    def peek_hit_len(self, tokens: Sequence[int]) -> int:
        """Advisory hit length: same walk as :meth:`lookup` but pins nothing
        and skips the counters — the engine's admission-budget estimate."""
        from ray_tpu.util import blockhash

        bt = self.block_tokens
        cap = len(tokens) - 1
        if cap <= 0:
            return 0
        digests = blockhash.block_hashes(tokens, bt, max_blocks=cap // bt)
        with self._lock:
            hit_len = 0
            parent = blockhash.SEED
            for d in digests:
                if d not in self._by_hash:
                    break
                hit_len += bt
                parent = d
            for t in range(min(bt - 1, cap - hit_len), 0, -1):
                key = (parent, tuple(int(x) for x in
                                     tokens[hit_len:hit_len + t]))
                if key in self._tail_by_key:
                    return hit_len + t
            return hit_len

    def pin(self, block_ids: Sequence[int]) -> None:
        """Refcount-bump blocks the caller already holds ids for (CACHED ->
        ACTIVE as needed) — the KV-tier spill/migrate paths pin a retired
        chain before copying it off-device so eviction can't race the
        extract."""
        with self._lock:
            for b in block_ids:
                if self._ref.get(b, 0) == 0:
                    self._cached.pop(b, None)
                self._ref[b] = self._ref.get(b, 0) + 1

    def pin_chain(self, tokens: Sequence[int],
                  n_real: int) -> Tuple[List[int], int]:
        """Pin a registered chain EXACTLY as :meth:`register_chain` laid it
        out: every full block of ``tokens[:n_real]`` plus the exact partial
        tail entry. Unlike :meth:`lookup` (whose ``len - 1`` cap can never
        see a chain's own full-length tail), this is the export walk for
        spill/migration. Returns ``(pinned_ids, covered_tokens)`` — empty
        when even the first block is gone (counters untouched; not a serving
        lookup)."""
        from ray_tpu.util import blockhash

        bt = self.block_tokens
        n_full = n_real // bt
        digests = blockhash.block_hashes(tokens[:n_real], bt,
                                         max_blocks=n_full)
        with self._lock:
            ids: List[int] = []
            parent = blockhash.SEED
            for d in digests:
                b = self._by_hash.get(d)
                if b is None:
                    break
                ids.append(b)
                parent = d
            covered = len(ids) * bt
            if len(ids) == n_full and n_real > covered:
                key = (parent, tuple(int(x) for x in
                                     tokens[covered:n_real]))
                b = self._tail_by_key.get(key)
                if b is not None:
                    ids.append(b)
                    covered = n_real
            for b in ids:
                if self._ref.get(b, 0) == 0:
                    self._cached.pop(b, None)      # CACHED -> ACTIVE
                self._ref[b] = self._ref.get(b, 0) + 1
            return ids, covered

    def note_cow(self) -> None:
        with self._lock:
            self.cow_copies += 1

    # -- introspection --------------------------------------------------------
    def stats(self) -> Dict[str, float]:
        with self._lock:
            active = len(self._ref)
            return {
                "kv_blocks_total": float(self.num_blocks - 1),
                "kv_blocks_active": float(active),
                "kv_blocks_cached": float(len(self._cached)),
                "kv_blocks_free": float(len(self._free)),
                "kv_hit_tokens": float(self.hit_tokens),
                "kv_miss_tokens": float(self.miss_tokens),
                "kv_cow_copies": float(self.cow_copies),
            }

    def active_blocks(self) -> int:
        """Blocks pinned by live sequences — must drop to 0 when every
        request retires (the leak-check invariant)."""
        with self._lock:
            return len(self._ref)


class Generator:
    """Compiled prefill + decode for one (config, batch, max_len) shape.

    Two decode granularities:

    - ``_decode``: one token per dispatch — simple, but each host sync pays
      a full host↔device round trip (on a tunneled chip that is ~100 ms, on
      a colocated host ~100 µs).
    - ``_prefill_decode`` / ``_decode_chunk``: prefill fused with a
      ``lax.scan`` over K decode steps in ONE dispatch — the sampling loop
      lives on device, so K tokens cost one round trip. This is the serving
      fast path (`serve/llm.py`).
    """

    def __init__(self, params, config: TransformerConfig, *, batch: int = 1,
                 max_len: Optional[int] = None):
        self.params = params
        self.config = config
        self.batch = batch
        self.max_len = max_len or config.max_seq_len

        c = config

        @jax.jit
        def prefill(params, cache, tokens):  # tokens [B, P] (P static)
            return _forward_cached(params, tokens, cache, c, 0)

        @functools.partial(jax.jit, donate_argnums=(1,))
        def decode(params, cache, token, pos):  # token [B, 1]
            logits, cache = _forward_cached(params, token, cache, c, pos)
            return logits[:, -1], cache

        self._prefill = prefill
        self._decode = decode
        self._chunked = {}  # (chunk, sampled) -> (prefill_decode, decode_chunk)

    def chunked_fns(self, chunk: int, sampled: bool):
        """Jitted (prefill+scan-decode, scan-decode) pair for a chunk size."""
        key = (chunk, sampled)
        if key in self._chunked:
            return self._chunked[key]
        c = self.config

        def make_step(params, temp):
            # A FRESH closure per jit trace: lax.scan caches traced jaxprs
            # by (function identity, avals), so sharing one step function
            # across the two jitted wrappers would leak the first trace's
            # closure tracers into the second as stale constants.
            def step(carry, _):
                last, cache, pos, rng = carry
                real = last[:, : c.vocab_size]
                if sampled:
                    rng, sub = jax.random.split(rng)
                    nxt = jax.random.categorical(sub, real / temp, axis=-1)
                else:
                    nxt = jnp.argmax(real, axis=-1)
                logits, cache = _forward_cached(
                    params, nxt[:, None].astype(jnp.int32), cache, c, pos
                )
                return (logits[:, -1], cache, pos + 1, rng), nxt

            return step

        @functools.partial(jax.jit, donate_argnums=(1,))
        def prefill_decode(params, cache, padded, real_len, rng, temp):
            """One dispatch: full prefill + K sampled/greedy decode steps.

            ``padded`` [B, P]: prompt padded to a bucket; first-token logits
            are read at the REAL last position, and decode starts at
            ``real_len`` so pad garbage in the cache is overwritten before
            the causal mask could ever expose it.
            """
            logits, cache = _forward_cached(params, padded, cache, c, 0)
            last = jax.lax.dynamic_index_in_dim(
                logits, real_len - 1, axis=1, keepdims=False)   # [B, V]
            (last, cache, pos, rng), toks = lax.scan(
                make_step(params, temp), (last, cache, real_len, rng),
                None, length=chunk)
            return toks.T, last, cache, pos, rng                 # [B, chunk]

        @functools.partial(jax.jit, donate_argnums=(1,))
        def decode_chunk(params, cache, last, pos, rng, temp):
            (last, cache, pos, rng), toks = lax.scan(
                make_step(params, temp), (last, cache, pos, rng),
                None, length=chunk)
            return toks.T, last, cache, pos, rng

        self._chunked[key] = (prefill_decode, decode_chunk)
        return self._chunked[key]

    def generate(
        self,
        prompt_tokens,
        *,
        max_new_tokens: int = 32,
        temperature: float = 0.0,
        seed: int = 0,
        stream: bool = False,
    ):
        """Greedy (temperature=0) or sampled generation. Returns token list
        (or a generator of tokens when ``stream``)."""
        import numpy as np

        def run():
            prompt = jnp.asarray(np.asarray(prompt_tokens, np.int32)).reshape(self.batch, -1)
            P = prompt.shape[1]
            cache = init_cache(self.config, self.batch, self.max_len)
            logits, cache = self._prefill(self.params, cache, prompt)
            key = jax.random.key(seed)
            last = logits[:, -1]
            pos = P
            for _ in range(max_new_tokens):
                # mask vocab padding before picking
                last_real = last[:, : self.config.vocab_size]
                if temperature > 0:
                    key, sub = jax.random.split(key)
                    nxt = jax.random.categorical(sub, last_real / temperature, axis=-1)
                else:
                    nxt = jnp.argmax(last_real, axis=-1)
                # Accepted host-sync finding (lint baseline): this is the
                # single-sequence oracle/debug path — one token per yield
                # IS the contract, so the per-token sync stays. Batched
                # serving goes through the engines, which fetch per chunk.
                yield int(nxt[0])
                if pos >= self.max_len:
                    return
                last, cache = self._decode(
                    self.params, cache, nxt[:, None].astype(jnp.int32), pos
                )
                pos += 1

        if stream:
            return run()
        return list(run())
