"""Autoregressive generation with KV cache.

The inference fast path the Serve replicas use. The reference serves models
through vLLM/framework engines; TPU-native the decode loop is two jitted XLA
programs with static shapes:

- ``prefill``: one full forward over the (padded) prompt, writing K/V for
  every layer into a preallocated cache [L, B, max_len, H, Dh];
- ``decode_step``: single-token forward reading the cache — O(1) FLOPs in
  context length per token instead of the O(ctx) full-window forward.

The cache is a pytree carried through ``lax.scan``-style stepping on the
host; batch/beam layouts stay static so both programs compile exactly once.
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.models.transformer import TransformerConfig
from ray_tpu.ops.layers import gelu, layer_norm, linear, rope


def init_cache(config: TransformerConfig, batch: int, max_len: Optional[int] = None) -> Dict:
    c = config
    max_len = max_len or c.max_seq_len
    shape = (c.n_layers, batch, max_len, c.n_heads, c.head_dim)
    return {
        "k": jnp.zeros(shape, c.dtype),
        "v": jnp.zeros(shape, c.dtype),
        "length": jnp.zeros((), jnp.int32),
    }


def _attend_cached(q, k_cache, v_cache, valid_len, *, scale: float):
    """q: [B, T, H, D] against cache [B, S, H, D]; positions >= valid_len are
    masked. For prefill T>1 a causal mask also applies within the window.

    ``valid_len`` may be a scalar (every row at the same position — the
    single-sequence path) or a [B] vector (per-slot positions — the
    continuous-batching path, where each cache row holds an independent
    sequence at its own decode offset)."""
    B, T, H, D = q.shape
    S = k_cache.shape[1]
    scores = jnp.einsum(
        "bthd,bshd->bhts", q, k_cache, preferred_element_type=jnp.float32
    ) * scale
    kv_pos = jnp.arange(S)[None, None, None, :]          # [1,1,1,S]
    vl = jnp.asarray(valid_len)
    if vl.ndim:                                           # per-row [B]
        vl = vl.reshape(-1, 1, 1, 1)                      # [B,1,1,1]
    q_pos = (vl - T) + jnp.arange(T)[None, None, :, None]
    mask = kv_pos <= q_pos                                # causal + validity
    scores = jnp.where(mask, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhts,bshd->bthd", probs, v_cache.astype(jnp.float32))
    return out.astype(q.dtype)


def _forward_cached(params, tokens, cache, config: TransformerConfig, start_pos):
    """Forward ``tokens`` [B, T] at positions [start_pos, start_pos+T),
    updating the cache. Returns (logits[B, T, V], new_cache)."""
    c = config
    cast = lambda p: p.astype(c.dtype)
    B, T = tokens.shape
    h = jnp.take(cast(params["tok_embed"]), tokens, axis=0)
    positions = start_pos + jnp.arange(T)
    if c.pos == "learned":
        h = h + cast(params["pos_embed"])[positions]
    scale = 1.0 / c.head_dim**0.5
    valid_len = start_pos + T

    new_k, new_v = [], []
    for layer in range(c.n_layers):
        bp = jax.tree.map(lambda p: cast(p[layer]), params["blocks"])
        x = layer_norm(h, bp["ln1_g"], bp["ln1_b"])
        q = jnp.einsum("btd,dhk->bthk", x, bp["wq"], preferred_element_type=jnp.float32).astype(c.dtype) + bp["bq"]
        k = jnp.einsum("btd,dhk->bthk", x, bp["wk"], preferred_element_type=jnp.float32).astype(c.dtype) + bp["bk"]
        v = jnp.einsum("btd,dhk->bthk", x, bp["wv"], preferred_element_type=jnp.float32).astype(c.dtype) + bp["bv"]
        if c.pos == "rope":
            q = rope(q, positions)
            k = rope(k, positions)
        k_cache = lax.dynamic_update_slice(
            cache["k"][layer], k, (0, start_pos, 0, 0)
        )
        v_cache = lax.dynamic_update_slice(
            cache["v"][layer], v, (0, start_pos, 0, 0)
        )
        new_k.append(k_cache)
        new_v.append(v_cache)
        o = _attend_cached(q, k_cache, v_cache, valid_len, scale=scale)
        o = jnp.einsum("bthk,hkd->btd", o, bp["wo"], preferred_element_type=jnp.float32).astype(c.dtype) + bp["bo"]
        h = h + o
        x = layer_norm(h, bp["ln2_g"], bp["ln2_b"])
        u = gelu(linear(x, bp["w_up"], bp["b_up"]))
        h = h + linear(u, bp["w_down"], bp["b_down"])

    h = layer_norm(h, cast(params["lnf_g"]), cast(params["lnf_b"]))
    w_out = params["tok_embed"].T if c.tie_embeddings else params["lm_head"]
    logits = jnp.einsum("btd,dv->btv", h, cast(w_out), preferred_element_type=jnp.float32)
    new_cache = {
        "k": jnp.stack(new_k),
        "v": jnp.stack(new_v),
        "length": jnp.asarray(valid_len, jnp.int32),
    }
    return logits, new_cache


def init_slot_cache(config: TransformerConfig, slots: int,
                    max_len: Optional[int] = None) -> Dict:
    """KV cache for ``slots`` INDEPENDENT sequences: the batch dim is a slot
    index and ``lengths[s]`` replaces the single scalar ``length`` — each
    slot decodes at its own position (the continuous-batching layout)."""
    c = config
    max_len = max_len or c.max_seq_len
    shape = (c.n_layers, slots, max_len, c.n_heads, c.head_dim)
    return {
        "k": jnp.zeros(shape, c.dtype),
        "v": jnp.zeros(shape, c.dtype),
        "lengths": jnp.zeros((slots,), jnp.int32),
    }


def _forward_decode_slotted(params, tokens, k_cache, v_cache, lengths,
                            config: TransformerConfig):
    """One decode step for S independent slots: ``tokens`` [S, 1] at per-slot
    positions ``lengths`` [S]. Writes each slot's new K/V at its own position
    (scatter over the batch dim — ``dynamic_update_slice`` only takes scalar
    starts) and attends with per-slot validity. Returns
    (logits [S, 1, V], new_k, new_v); rows are fully independent, so an
    inactive slot's garbage output never contaminates its neighbours.
    """
    c = config
    cast = lambda p: p.astype(c.dtype)
    S, T = tokens.shape  # T == 1
    M = k_cache.shape[2]
    h = jnp.take(cast(params["tok_embed"]), tokens, axis=0)
    # Clamp the write position: a slot parked at the context cap (retired,
    # awaiting refill) must not scatter out of bounds. Its row's output is
    # dead either way — the clamp only keeps the scatter well-defined.
    pos = jnp.minimum(lengths, M - 1)
    positions = pos[:, None]                              # [S, 1]
    if c.pos == "learned":
        h = h + cast(params["pos_embed"])[positions]
    scale = 1.0 / c.head_dim**0.5
    rows = jnp.arange(S)
    valid_len = pos + 1                                   # new token attendable

    new_k, new_v = [], []
    for layer in range(c.n_layers):
        bp = jax.tree.map(lambda p: cast(p[layer]), params["blocks"])
        x = layer_norm(h, bp["ln1_g"], bp["ln1_b"])
        q = jnp.einsum("btd,dhk->bthk", x, bp["wq"], preferred_element_type=jnp.float32).astype(c.dtype) + bp["bq"]
        k = jnp.einsum("btd,dhk->bthk", x, bp["wk"], preferred_element_type=jnp.float32).astype(c.dtype) + bp["bk"]
        v = jnp.einsum("btd,dhk->bthk", x, bp["wv"], preferred_element_type=jnp.float32).astype(c.dtype) + bp["bv"]
        if c.pos == "rope":
            q = rope(q, positions)
            k = rope(k, positions)
        kc = k_cache[layer].at[rows, pos].set(k[:, 0])
        vc = v_cache[layer].at[rows, pos].set(v[:, 0])
        new_k.append(kc)
        new_v.append(vc)
        o = _attend_cached(q, kc, vc, valid_len, scale=scale)
        o = jnp.einsum("bthk,hkd->btd", o, bp["wo"], preferred_element_type=jnp.float32).astype(c.dtype) + bp["bo"]
        h = h + o
        x = layer_norm(h, bp["ln2_g"], bp["ln2_b"])
        u = gelu(linear(x, bp["w_up"], bp["b_up"]))
        h = h + linear(u, bp["w_down"], bp["b_down"])

    h = layer_norm(h, cast(params["lnf_g"]), cast(params["lnf_b"]))
    w_out = params["tok_embed"].T if c.tie_embeddings else params["lm_head"]
    logits = jnp.einsum("btd,dv->btv", h, cast(w_out), preferred_element_type=jnp.float32)
    return logits, jnp.stack(new_k), jnp.stack(new_v)


class SlottedGenerator:
    """Compiled slot-level prefill + batched decode for continuous batching.

    The serving engine's device half (``serve/llm.py LLMEngine``): S cache
    slots hold S independent sequences, and

    - :meth:`prefill_fn` — jitted per prompt bucket — writes ONE prompt's
      K/V into its slot (``insert_prefill``) and parks its next-token logits
      in the ``last`` [S, V] carry;
    - :meth:`decode_fn` — jitted once per chunk size — advances ALL slots by
      ``chunk`` tokens in ONE dispatch via ``lax.scan``: inactive slots are
      masked (their ``lengths`` freeze, their ``last``/key rows keep their
      values), greedy and sampled slots ride the same program through
      per-slot ``greedy``/``temps`` operands, so everything compiles exactly
      once per (bucket | chunk) regardless of the traffic mix.

    Device state is the ``(cache, last, keys)`` triple threaded through both
    functions with buffer donation — the engine must hold only the returned
    arrays.
    """

    def __init__(self, params, config: TransformerConfig, *, slots: int,
                 max_len: Optional[int] = None):
        self.params = params
        self.config = config
        self.slots = slots
        self.max_len = max_len or config.max_seq_len
        self.logits_dim = (params["tok_embed"].shape[0]
                          if config.tie_embeddings
                          else params["lm_head"].shape[-1])
        self._prefill_fns = {}  # bucket -> jitted insert_prefill
        self._decode_fns = {}   # chunk -> jitted decode_chunk

    def init_state(self):
        cache = init_slot_cache(self.config, self.slots, self.max_len)
        last = jnp.zeros((self.slots, self.logits_dim), jnp.float32)
        keys = jnp.zeros((self.slots, 2), jnp.uint32)
        return cache, last, keys

    def prefill_fn(self, bucket: int):
        """insert_prefill(params, cache, last, keys, padded [1,P], real_len,
        slot, seed) -> (cache, last, keys): one prompt's K/V into one slot."""
        fn = self._prefill_fns.get(bucket)
        if fn is not None:
            return fn
        c = self.config

        @functools.partial(jax.jit, donate_argnums=(1, 2, 3))
        def insert_prefill(params, cache, last, keys, padded, real_len, slot,
                           seed):
            P = padded.shape[1]
            tmp = {
                "k": jnp.zeros((c.n_layers, 1, P, c.n_heads, c.head_dim),
                               c.dtype),
                "v": jnp.zeros((c.n_layers, 1, P, c.n_heads, c.head_dim),
                               c.dtype),
                "length": jnp.zeros((), jnp.int32),
            }
            logits, tmp = _forward_cached(params, padded, tmp, c, 0)
            k_c = lax.dynamic_update_slice(cache["k"], tmp["k"],
                                           (0, slot, 0, 0, 0))
            v_c = lax.dynamic_update_slice(cache["v"], tmp["v"],
                                           (0, slot, 0, 0, 0))
            lengths = cache["lengths"].at[slot].set(real_len)
            row = jax.lax.dynamic_index_in_dim(
                logits, real_len - 1, axis=1, keepdims=False)      # [1, V]
            last = lax.dynamic_update_slice(last, row, (slot, 0))
            keys = lax.dynamic_update_slice(
                keys, jax.random.PRNGKey(seed)[None], (slot, 0))
            return {"k": k_c, "v": v_c, "lengths": lengths}, last, keys

        self._prefill_fns[bucket] = insert_prefill
        return insert_prefill

    def decode_fn(self, chunk: int):
        """decode_chunk(params, cache, last, keys, active, greedy, temps) ->
        (toks [S, chunk], cache, last, keys): ``chunk`` scan steps advancing
        every active slot, one dispatch."""
        fn = self._decode_fns.get(chunk)
        if fn is not None:
            return fn
        c = self.config

        @functools.partial(jax.jit, donate_argnums=(1, 2, 3))
        def decode_chunk(params, cache, last, keys, active, greedy, temps):
            adv = active.astype(jnp.int32)
            act_col = active[:, None]
            temp_safe = jnp.maximum(temps, 1e-6)[:, None]

            def step(carry, _):
                k_c, v_c, lengths, last, keys = carry
                real = last[:, : c.vocab_size]
                split = jax.vmap(jax.random.split)(keys)   # [S, 2, 2]
                keys2, subs = split[:, 0], split[:, 1]
                samp = jax.vmap(jax.random.categorical)(subs, real / temp_safe)
                nxt = jnp.where(greedy, jnp.argmax(real, axis=-1),
                                samp).astype(jnp.int32)
                logits, k_c, v_c = _forward_decode_slotted(
                    params, nxt[:, None], k_c, v_c, lengths, c)
                lengths = lengths + adv
                last = jnp.where(act_col, logits[:, -1], last)
                keys = jnp.where(act_col, keys2, keys)
                return (k_c, v_c, lengths, last, keys), nxt

            (k_c, v_c, lengths, last, keys), toks = lax.scan(
                step, (cache["k"], cache["v"], cache["lengths"], last, keys),
                None, length=chunk)
            return (toks.T, {"k": k_c, "v": v_c, "lengths": lengths}, last,
                    keys)

        self._decode_fns[chunk] = decode_chunk
        return decode_chunk


class Generator:
    """Compiled prefill + decode for one (config, batch, max_len) shape.

    Two decode granularities:

    - ``_decode``: one token per dispatch — simple, but each host sync pays
      a full host↔device round trip (on a tunneled chip that is ~100 ms, on
      a colocated host ~100 µs).
    - ``_prefill_decode`` / ``_decode_chunk``: prefill fused with a
      ``lax.scan`` over K decode steps in ONE dispatch — the sampling loop
      lives on device, so K tokens cost one round trip. This is the serving
      fast path (`serve/llm.py`).
    """

    def __init__(self, params, config: TransformerConfig, *, batch: int = 1,
                 max_len: Optional[int] = None):
        self.params = params
        self.config = config
        self.batch = batch
        self.max_len = max_len or config.max_seq_len

        c = config

        @jax.jit
        def prefill(params, cache, tokens):  # tokens [B, P] (P static)
            return _forward_cached(params, tokens, cache, c, 0)

        @functools.partial(jax.jit, donate_argnums=(1,))
        def decode(params, cache, token, pos):  # token [B, 1]
            logits, cache = _forward_cached(params, token, cache, c, pos)
            return logits[:, -1], cache

        self._prefill = prefill
        self._decode = decode
        self._chunked = {}  # (chunk, sampled) -> (prefill_decode, decode_chunk)

    def chunked_fns(self, chunk: int, sampled: bool):
        """Jitted (prefill+scan-decode, scan-decode) pair for a chunk size."""
        key = (chunk, sampled)
        if key in self._chunked:
            return self._chunked[key]
        c = self.config

        def make_step(params, temp):
            # A FRESH closure per jit trace: lax.scan caches traced jaxprs
            # by (function identity, avals), so sharing one step function
            # across the two jitted wrappers would leak the first trace's
            # closure tracers into the second as stale constants.
            def step(carry, _):
                last, cache, pos, rng = carry
                real = last[:, : c.vocab_size]
                if sampled:
                    rng, sub = jax.random.split(rng)
                    nxt = jax.random.categorical(sub, real / temp, axis=-1)
                else:
                    nxt = jnp.argmax(real, axis=-1)
                logits, cache = _forward_cached(
                    params, nxt[:, None].astype(jnp.int32), cache, c, pos
                )
                return (logits[:, -1], cache, pos + 1, rng), nxt

            return step

        @functools.partial(jax.jit, donate_argnums=(1,))
        def prefill_decode(params, cache, padded, real_len, rng, temp):
            """One dispatch: full prefill + K sampled/greedy decode steps.

            ``padded`` [B, P]: prompt padded to a bucket; first-token logits
            are read at the REAL last position, and decode starts at
            ``real_len`` so pad garbage in the cache is overwritten before
            the causal mask could ever expose it.
            """
            logits, cache = _forward_cached(params, padded, cache, c, 0)
            last = jax.lax.dynamic_index_in_dim(
                logits, real_len - 1, axis=1, keepdims=False)   # [B, V]
            (last, cache, pos, rng), toks = lax.scan(
                make_step(params, temp), (last, cache, real_len, rng),
                None, length=chunk)
            return toks.T, last, cache, pos, rng                 # [B, chunk]

        @functools.partial(jax.jit, donate_argnums=(1,))
        def decode_chunk(params, cache, last, pos, rng, temp):
            (last, cache, pos, rng), toks = lax.scan(
                make_step(params, temp), (last, cache, pos, rng),
                None, length=chunk)
            return toks.T, last, cache, pos, rng

        self._chunked[key] = (prefill_decode, decode_chunk)
        return self._chunked[key]

    def generate(
        self,
        prompt_tokens,
        *,
        max_new_tokens: int = 32,
        temperature: float = 0.0,
        seed: int = 0,
        stream: bool = False,
    ):
        """Greedy (temperature=0) or sampled generation. Returns token list
        (or a generator of tokens when ``stream``)."""
        import numpy as np

        def run():
            prompt = jnp.asarray(np.asarray(prompt_tokens, np.int32)).reshape(self.batch, -1)
            P = prompt.shape[1]
            cache = init_cache(self.config, self.batch, self.max_len)
            logits, cache = self._prefill(self.params, cache, prompt)
            key = jax.random.key(seed)
            last = logits[:, -1]
            pos = P
            for _ in range(max_new_tokens):
                # mask vocab padding before picking
                last_real = last[:, : self.config.vocab_size]
                if temperature > 0:
                    key, sub = jax.random.split(key)
                    nxt = jax.random.categorical(sub, last_real / temperature, axis=-1)
                else:
                    nxt = jnp.argmax(last_real, axis=-1)
                yield int(nxt[0])
                if pos >= self.max_len:
                    return
                last, cache = self._decode(
                    self.params, cache, nxt[:, None].astype(jnp.int32), pos
                )
                pos += 1

        if stream:
            return run()
        return list(run())
