"""Decoder-only transformer LM (GPT-2 family), TPU-shaped.

This is the flagship model for the Train north-star (BASELINE.json: GPT-2-124M
tokens/sec/chip). The reference has no model code of its own — it orchestrates
torch models (e.g. ``release/air_tests/air_benchmarks/workloads/``); here the
model is a first-class citizen designed for the MXU:

- params are plain pytrees; blocks are STACKED on a leading ``layers`` dim and
  the forward pass is a single ``lax.scan`` — one compiled block body, weight
  gathers pipelined by XLA, and the natural layout for pipeline parallelism
  (``layers`` → ``pipe`` mesh axis).
- every parameter and activation carries *logical* axis names resolved
  through ``parallel.sharding.ShardingRules`` — the same model runs DP, FSDP,
  megatron TP, sequence-parallel or any mix by swapping the rule table,
  never editing model code.
- compute dtype bf16 with f32 accumulation (matmul ``preferred_element_type``,
  f32 layernorm stats/softmax/loss); params kept in f32 by default (optimizer
  numerics), cast to bf16 at use.
- vocab padded to a multiple of 128 so the logits matmul tiles the MXU.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field, replace
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.ops.layers import gelu, layer_norm, linear, rope, softmax_cross_entropy
from ray_tpu.parallel.mesh import Mesh
from ray_tpu.parallel.sharding import ShardingRules, constrain


def pad_vocab(n: int, multiple: int = 128) -> int:
    return ((n + multiple - 1) // multiple) * multiple


@dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 50257
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    d_ff: int = 3072
    max_seq_len: int = 1024
    dtype: Any = jnp.bfloat16          # activation/compute dtype
    param_dtype: Any = jnp.float32     # storage dtype
    pos: str = "learned"               # "learned" (gpt2) | "rope" (llama-ish)
    tie_embeddings: bool = True
    attn_impl: str = "auto"            # "auto" | "dense" | "flash" | "ring" | "ulysses"
    remat: bool = False                # jax.checkpoint each block (HBM↔FLOPs)
    # remat policy: "full" recomputes everything; "dots" saves matmul outputs
    # and recomputes only cheap elementwise ops (usually faster, more HBM)
    remat_policy: str = "full"         # "full" | "dots"
    vocab_multiple: int = 128

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads

    @property
    def padded_vocab(self) -> int:
        return pad_vocab(self.vocab_size, self.vocab_multiple)

    def replace(self, **kw) -> "TransformerConfig":
        return replace(self, **kw)


def gpt2_small(**kw) -> TransformerConfig:
    """GPT-2 124M."""
    return TransformerConfig(**kw)


def gpt2_medium(**kw) -> TransformerConfig:
    return TransformerConfig(d_model=1024, n_layers=24, n_heads=16, d_ff=4096, **kw)


def gpt2_large(**kw) -> TransformerConfig:
    return TransformerConfig(d_model=1280, n_layers=36, n_heads=20, d_ff=5120, **kw)


def gpt2_xl(**kw) -> TransformerConfig:
    return TransformerConfig(d_model=1600, n_layers=48, n_heads=25, d_ff=6400, **kw)


def tiny(**kw) -> TransformerConfig:
    """Test-sized config (runs in ms on CPU)."""
    defaults = dict(
        vocab_size=256, d_model=64, n_layers=2, n_heads=4, d_ff=128,
        max_seq_len=64, dtype=jnp.float32, vocab_multiple=8,
    )
    defaults.update(kw)
    return TransformerConfig(**defaults)


# ---------------------------------------------------------------------------
# Parameters
# ---------------------------------------------------------------------------

def init_params(config: TransformerConfig, key: jax.Array) -> Dict:
    """GPT-2 init: normal(0.02), residual projections scaled by 1/sqrt(2N)."""
    c = config
    k = iter(jax.random.split(key, 16))
    dt = c.param_dtype
    std = 0.02
    res_std = std / (2 * c.n_layers) ** 0.5

    def nrm(key, shape, s=std):
        return (jax.random.normal(key, shape, jnp.float32) * s).astype(dt)

    L, D, H, Dh, F, V = c.n_layers, c.d_model, c.n_heads, c.head_dim, c.d_ff, c.padded_vocab

    blocks = {
        "ln1_g": jnp.ones((L, D), dt), "ln1_b": jnp.zeros((L, D), dt),
        "wq": nrm(next(k), (L, D, H, Dh)), "wk": nrm(next(k), (L, D, H, Dh)),
        "wv": nrm(next(k), (L, D, H, Dh)),
        "wo": nrm(next(k), (L, H, Dh, D), res_std),
        "bq": jnp.zeros((L, H, Dh), dt), "bk": jnp.zeros((L, H, Dh), dt),
        "bv": jnp.zeros((L, H, Dh), dt), "bo": jnp.zeros((L, D), dt),
        "ln2_g": jnp.ones((L, D), dt), "ln2_b": jnp.zeros((L, D), dt),
        "w_up": nrm(next(k), (L, D, F)), "b_up": jnp.zeros((L, F), dt),
        "w_down": nrm(next(k), (L, F, D), res_std), "b_down": jnp.zeros((L, D), dt),
    }
    params = {
        "tok_embed": nrm(next(k), (V, D)),
        "blocks": blocks,
        "lnf_g": jnp.ones((D,), dt), "lnf_b": jnp.zeros((D,), dt),
    }
    if c.pos == "learned":
        params["pos_embed"] = nrm(next(k), (c.max_seq_len, D), 0.01)
    if not c.tie_embeddings:
        params["lm_head"] = nrm(next(k), (D, V))
    return params


def logical_axes(config: TransformerConfig) -> Dict:
    """Pytree of logical axis names mirroring ``init_params`` output."""
    c = config
    blocks = {
        "ln1_g": ("layers", "embed"), "ln1_b": ("layers", "embed"),
        "wq": ("layers", "embed", "heads", "head_dim"),
        "wk": ("layers", "embed", "kv_heads", "head_dim"),
        "wv": ("layers", "embed", "kv_heads", "head_dim"),
        "wo": ("layers", "heads", "head_dim", "embed"),
        "bq": ("layers", "heads", "head_dim"), "bk": ("layers", "kv_heads", "head_dim"),
        "bv": ("layers", "kv_heads", "head_dim"), "bo": ("layers", "embed"),
        "ln2_g": ("layers", "embed"), "ln2_b": ("layers", "embed"),
        "w_up": ("layers", "embed", "mlp"), "b_up": ("layers", "mlp"),
        "w_down": ("layers", "mlp", "embed"), "b_down": ("layers", "embed"),
    }
    axes = {
        "tok_embed": ("vocab", "embed"),
        "blocks": blocks,
        "lnf_g": ("embed",), "lnf_b": ("embed",),
    }
    if c.pos == "learned":
        axes["pos_embed"] = (None, "embed")
    if not c.tie_embeddings:
        axes["lm_head"] = ("embed", "vocab")
    return axes


def param_count(params) -> int:
    return sum(int(p.size) for p in jax.tree.leaves(params))


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def _dense_attention(q, k, v, *, scale: float, cstr=None):
    """Causal full attention in f32. q/k/v: [B, L, H, Dh].

    ``cstr(x, logical)`` (optional) pins intermediate shardings: without
    it, the seq×tensor layout transition around the two einsums makes the
    SPMD partitioner fall back to "involuntary full rematerialization"
    (replicate-then-repartition) on the activation reshapes — a real
    all-to-all's worth of extra traffic on hardware.
    """
    l = q.shape[1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    if cstr is not None:
        scores = cstr(scores, ("batch", "heads", "seq_act", None))
    scores = scores * scale
    mask = jnp.tril(jnp.ones((l, l), bool))
    scores = jnp.where(mask[None, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32))
    if cstr is not None:
        out = cstr(out, ("batch", "seq_act", "heads", "head_dim"))
    return out.astype(q.dtype)


def _make_attention(config: TransformerConfig, mesh: Optional[Mesh],
                    rules: Optional[ShardingRules] = None):
    scale = 1.0 / config.head_dim ** 0.5
    impl = config.attn_impl
    # Largest power-of-two block ≤512 that divides the sequence, so the
    # kernel never silently falls back to dense for lengths like 1280.
    block = next((b for b in (512, 256, 128)
                  if config.max_seq_len % b == 0), None)
    if impl == "auto":
        # Flash wins on TPU from ~1k tokens (block-512 kernels beat the
        # dense path ~2x fwd+bwd at 2k-4k, measured on v5e); below that or
        # for ragged lengths the dense path is simpler and as fast.
        impl = ("flash" if config.max_seq_len >= 1024 and block is not None
                else "dense")
    if impl == "flash":
        import jax as _jax

        from ray_tpu.ops.flash_attention import flash_attention

        interpret = _jax.default_backend() != "tpu"
        blk = block or 128
        return lambda q, k, v: flash_attention(
            q, k, v, True, scale, blk, blk, interpret
        )
    if impl == "dense" or mesh is None:
        if mesh is not None and rules is not None:
            return functools.partial(
                _dense_attention, scale=scale,
                cstr=lambda x, logical: constrain(x, mesh, rules, logical))
        return functools.partial(_dense_attention, scale=scale)
    if impl == "ring":
        from ray_tpu.parallel.ring_attention import make_ring_attention

        return make_ring_attention(mesh, causal=True, scale=scale)
    if impl == "ulysses":
        from ray_tpu.parallel.ring_attention import make_ulysses_attention

        return make_ulysses_attention(mesh, causal=True, scale=scale)
    raise ValueError(f"unknown attn_impl {config.attn_impl!r}")


def make_block_fn(
    config: TransformerConfig,
    mesh: Optional[Mesh] = None,
    rules: Optional[ShardingRules] = None,
):
    """One transformer block as ``block(h, bp) -> h`` — THE layer body,
    shared by the scan-over-layers forward and the pipeline-parallel path
    (same math ⇒ PP losses match the non-PP oracle exactly). Sharding
    constraints no-op when mesh/rules are None (required inside shard_map,
    where per-device code cannot carry global sharding annotations)."""
    c = config
    cast = lambda p: p.astype(c.dtype)
    attention = _make_attention(c, mesh, rules)

    def cstr(x, logical):
        if mesh is not None and rules is not None:
            return constrain(x, mesh, rules, logical)
        return x

    def block(h, bp):
        positions = jnp.arange(h.shape[1])
        bp = jax.tree.map(cast, bp)
        x = layer_norm(h, bp["ln1_g"], bp["ln1_b"])
        q = jnp.einsum("bld,dhk->blhk", x, bp["wq"], preferred_element_type=jnp.float32).astype(c.dtype) + bp["bq"]
        kk = jnp.einsum("bld,dhk->blhk", x, bp["wk"], preferred_element_type=jnp.float32).astype(c.dtype) + bp["bk"]
        vv = jnp.einsum("bld,dhk->blhk", x, bp["wv"], preferred_element_type=jnp.float32).astype(c.dtype) + bp["bv"]
        if c.pos == "rope":
            q = rope(q, positions)
            kk = rope(kk, positions)
        q = cstr(q, ("batch", "seq_act", "heads", "head_dim"))
        kk = cstr(kk, ("batch", "seq_act", "kv_heads", "head_dim"))
        vv = cstr(vv, ("batch", "seq_act", "kv_heads", "head_dim"))
        o = attention(q, kk, vv)
        o = jnp.einsum("blhk,hkd->bld", o, bp["wo"], preferred_element_type=jnp.float32).astype(c.dtype) + bp["bo"]
        h = cstr(h + o, ("batch", "seq_act", None))

        x = layer_norm(h, bp["ln2_g"], bp["ln2_b"])
        u = linear(x, bp["w_up"], bp["b_up"])
        u = cstr(gelu(u), ("batch", "seq_act", "mlp"))
        d = linear(u, bp["w_down"], bp["b_down"])
        h = cstr(h + d, ("batch", "seq_act", None))
        return h

    return block


def make_tp_block_fn(config: TransformerConfig, mesh: Mesh,
                     rules: ShardingRules):
    """Per-DEVICE transformer block for use INSIDE ``shard_map`` (the
    pipeline body): tensor parallelism and sequence parallelism are written
    as explicit collectives instead of sharding constraints —

    - megatron TP: q/k/v/up projections are column-parallel (weights arrive
      with heads/mlp dims locally sliced), out/down projections are
      row-parallel with a ``lax.psum`` over the tensor axis before the
      (replicated) bias — the pattern §2.4 says the reference only reaches
      by delegating to DeepSpeed;
    - SP: ring attention over the seq axis (K/V blocks rotate via
      ``ppermute``, online softmax — parallel.ring_attention), with RoPE
      positions offset by the device's sequence block.

    With tensor=1 and seq=1 this degrades to exactly the plain block body
    (psum over a size-1 axis is identity; a 1-ring is dense attention), so
    the pipeline uses ONE body for every composition."""
    c = config
    cast = lambda p: p.astype(c.dtype)
    scale = 1.0 / c.head_dim ** 0.5
    tensor_axis = rules.heads if isinstance(rules.heads, str) else None
    seq_axis = rules.seq_act if isinstance(rules.seq_act, str) else None
    tp = mesh.shape[tensor_axis] if tensor_axis in mesh.shape else 1
    sp = mesh.shape[seq_axis] if seq_axis in mesh.shape else 1

    from ray_tpu.parallel.ring_attention import _ring_attention_local

    def attention(q, k, v):
        if sp > 1:
            return _ring_attention_local(
                q, k, v, axis_name=seq_axis, axis_size=sp, causal=True,
                scale=scale)
        return _dense_attention(q, k, v, scale=scale)

    def block(h, bp):
        bp = jax.tree.map(cast, bp)
        x = layer_norm(h, bp["ln1_g"], bp["ln1_b"])
        q = jnp.einsum("bld,dhk->blhk", x, bp["wq"],
                       preferred_element_type=jnp.float32).astype(c.dtype) + bp["bq"]
        kk = jnp.einsum("bld,dhk->blhk", x, bp["wk"],
                        preferred_element_type=jnp.float32).astype(c.dtype) + bp["bk"]
        vv = jnp.einsum("bld,dhk->blhk", x, bp["wv"],
                        preferred_element_type=jnp.float32).astype(c.dtype) + bp["bv"]
        if c.pos == "rope":
            off = (lax.axis_index(seq_axis) * h.shape[1]
                   if sp > 1 else 0)
            positions = off + jnp.arange(h.shape[1])
            q = rope(q, positions)
            kk = rope(kk, positions)
        o = attention(q, kk, vv)
        o = jnp.einsum("blhk,hkd->bld", o, bp["wo"],
                       preferred_element_type=jnp.float32)
        if tp > 1:
            o = lax.psum(o, tensor_axis)  # row-parallel reduce
        h = h + o.astype(c.dtype) + bp["bo"]

        x = layer_norm(h, bp["ln2_g"], bp["ln2_b"])
        u = linear(x, bp["w_up"], bp["b_up"])  # column-parallel: local slice
        u = gelu(u)
        d = jnp.einsum("blf,fd->bld", u, bp["w_down"],
                       preferred_element_type=jnp.float32)
        if tp > 1:
            d = lax.psum(d, tensor_axis)  # row-parallel reduce
        # Bias in f32 then cast — same order as ops.layers.linear.
        h = h + (d + bp["b_down"].astype(jnp.float32)).astype(c.dtype)
        return h

    return block


def forward(
    params: Dict,
    tokens: jax.Array,
    config: TransformerConfig,
    *,
    mesh: Optional[Mesh] = None,
    rules: Optional[ShardingRules] = None,
) -> jax.Array:
    """tokens [B, L] int32 → logits [B, L, padded_vocab] (compute dtype).

    When ``mesh``+``rules`` are provided, activations carry sharding
    constraints so XLA places the megatron collectives exactly where the
    recipe wants them (after attention out-proj / mlp down-proj).
    """
    c = config
    cast = lambda p: p.astype(c.dtype)

    def cstr(x, logical):
        if mesh is not None and rules is not None:
            return constrain(x, mesh, rules, logical)
        return x

    B, L = tokens.shape
    # Embedding lookup with an EXPLICIT table all-gather first: a gather
    # into a vocab(tensor)-sharded table forces the SPMD partitioner into
    # involuntary full rematerialization (replicate + repartition) inside
    # the op; constraining the table to (None, None) turns that into one
    # clean all-gather, and the activation constraint below re-shards the
    # result. (Megatron's masked-lookup+psum is the large-vocab
    # alternative; for GPT-2-class vocabs the gathered table is ~40MB bf16.)
    tbl = cstr(cast(params["tok_embed"]), (None, None))
    h = jnp.take(tbl, tokens, axis=0)
    positions = jnp.arange(L)
    if c.pos == "learned":
        h = h + cast(params["pos_embed"])[positions]
    h = cstr(h, ("batch", "seq_act", None))

    block_body = make_block_fn(c, mesh, rules)

    def block(h, bp):
        return block_body(h, bp), None

    if c.remat:
        if c.remat_policy == "dots":
            policy = jax.checkpoint_policies.dots_with_no_batch_dims_saveable
            block_fn = jax.checkpoint(block, policy=policy)
        else:
            block_fn = jax.checkpoint(block)
    else:
        block_fn = block
    h, _ = lax.scan(block_fn, h, params["blocks"])

    h = layer_norm(h, cast(params["lnf_g"]), cast(params["lnf_b"]))
    w_out = params["tok_embed"].T if c.tie_embeddings else params["lm_head"]
    logits = jnp.einsum("bld,dv->blv", h, cast(w_out), preferred_element_type=jnp.float32)
    logits = cstr(logits.astype(c.dtype), ("batch", "seq_act", "vocab"))
    return logits


def lm_loss(
    params: Dict,
    batch: Dict[str, jax.Array],
    config: TransformerConfig,
    *,
    mesh: Optional[Mesh] = None,
    rules: Optional[ShardingRules] = None,
):
    """Next-token LM loss. batch: {"tokens": [B, L]} (optionally "loss_mask").

    Positions beyond ``config.vocab_size`` (the pad region) never receive
    probability mass pressure from real labels; the pad logits train to -inf
    naturally.
    """
    tokens = batch["tokens"]
    logits = forward(params, tokens, config, mesh=mesh, rules=rules)
    labels = jnp.where(
        batch.get("loss_mask", jnp.ones_like(tokens))[:, 1:] > 0,
        tokens[:, 1:],
        -100,
    )
    loss, n = softmax_cross_entropy(logits[:, :-1], labels)
    return loss


def pp_lm_loss(
    params: Dict,
    batch: Dict[str, jax.Array],
    config: TransformerConfig,
    *,
    mesh: Mesh,
    rules: ShardingRules,
    num_microbatches: int,
):
    """``lm_loss`` with the block stack run as a GPipe pipeline over the
    ``pipe`` mesh axis (parallel.pipeline) — the capability the reference
    only gets by delegating to DeepSpeed (SURVEY §2.4), here differentiable
    end-to-end inside ONE jitted step. Embedding and LM head run replicated
    across pipe (identical inputs ⇒ identical math on every stage group);
    only the blocks hand activations stage-to-stage. Losses match the
    non-PP ``lm_loss`` exactly (same block body, same reduction)."""
    from jax.sharding import PartitionSpec as P

    from ray_tpu.parallel.pipeline import make_pipeline
    from ray_tpu.parallel.sharding import pytree_shardings

    c = config
    cast = lambda p: p.astype(c.dtype)
    tokens = batch["tokens"]
    B, L = tokens.shape
    assert B % num_microbatches == 0, (B, num_microbatches)
    dp = 1
    for ax in (rules.batch if isinstance(rules.batch, tuple)
               else (rules.batch,)):
        if ax is not None and ax in mesh.shape:
            dp *= mesh.shape[ax]
    assert B % dp == 0 and (B // dp) % num_microbatches == 0, (
        f"per-device batch {B}/{dp} must split evenly into "
        f"{num_microbatches} microbatches")

    # Explicit table all-gather before the lookup (see forward()): avoids
    # the partitioner's involuntary-remat fallback on sharded-table gather.
    tbl = constrain(cast(params["tok_embed"]), mesh, rules, (None, None))
    h = jnp.take(tbl, tokens, axis=0)
    if c.pos == "learned":
        h = h + cast(params["pos_embed"])[jnp.arange(L)]
    h = constrain(h, mesh, rules, ("batch", "seq_act", None))

    # The per-device block composes TP (psum on tensor) and SP (ring
    # attention on seq) inside the pipeline's shard_map; weights enter
    # tensor-sharded per their logical axes (embed replicated — the
    # fsdp gather happens once at the shard_map boundary).
    block = make_tp_block_fn(c, mesh, rules)
    pp_rules = rules.update(embed=None)
    param_specs = jax.tree.map(
        pp_rules.mesh_axes,
        logical_axes(c)["blocks"],
        is_leaf=lambda x: isinstance(x, tuple) and all(
            isinstance(e, (str, type(None))) for e in x),
    )
    batch_axes = rules.batch
    seq_ax = rules.seq_act if isinstance(rules.seq_act, str) else None
    x_spec = P(batch_axes, None, seq_ax, None)
    pipeline = make_pipeline(
        lambda bp, x: block(x, bp),
        mesh,
        num_microbatches=num_microbatches,
        pipe_axis=rules.layers,
        batch_axes=batch_axes,
        x_spec=x_spec,
        param_specs=param_specs,
        remat=c.remat,
    )
    mb = B // num_microbatches
    # Microbatch index on the TRAILING side of the split: a batch-sharded
    # [B, ...] reshapes into [mb, M, ...] with zero data movement (each
    # device's contiguous rows stay its own); the [M, mb, ...] layout
    # would force an involuntary-remat repartition (pipeline docstring).
    x4 = h.reshape(mb, num_microbatches, L, h.shape[-1])
    x4 = jax.lax.with_sharding_constraint(
        x4, jax.sharding.NamedSharding(mesh, x_spec))
    h = pipeline(params["blocks"], x4)
    h = h.reshape(B, L, h.shape[-1])
    h = constrain(h, mesh, rules, ("batch", "seq_act", None))

    h = layer_norm(h, cast(params["lnf_g"]), cast(params["lnf_b"]))
    w_out = params["tok_embed"].T if c.tie_embeddings else params["lm_head"]
    logits = jnp.einsum("bld,dv->blv", h, cast(w_out),
                        preferred_element_type=jnp.float32).astype(c.dtype)
    logits = constrain(logits, mesh, rules, ("batch", "seq_act", "vocab"))
    labels = jnp.where(
        batch.get("loss_mask", jnp.ones_like(tokens))[:, 1:] > 0,
        tokens[:, 1:],
        -100,
    )
    loss, _n = softmax_cross_entropy(logits[:, :-1], labels)
    return loss
