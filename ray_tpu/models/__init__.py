from ray_tpu.models import mlp, transformer
from ray_tpu.models.training import TrainStepBundle, make_eval_step, make_train_step
from ray_tpu.models.transformer import (
    TransformerConfig,
    gpt2_large,
    gpt2_medium,
    gpt2_small,
    gpt2_xl,
    tiny,
)

__all__ = [
    "mlp",
    "transformer",
    "TransformerConfig",
    "gpt2_small",
    "gpt2_medium",
    "gpt2_large",
    "gpt2_xl",
    "tiny",
    "make_train_step",
    "make_eval_step",
    "TrainStepBundle",
]
