"""Small MLP classifier — the MNIST e2e gate model (SURVEY §7 P4 gate #1)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class MLPConfig:
    in_dim: int = 784
    hidden: Tuple[int, ...] = (128, 128)
    n_classes: int = 10
    dtype: Any = jnp.float32


def init_params(config: MLPConfig, key: jax.Array) -> Dict:
    dims = (config.in_dim,) + config.hidden + (config.n_classes,)
    keys = jax.random.split(key, len(dims) - 1)
    layers = []
    for k, (a, b) in zip(keys, zip(dims[:-1], dims[1:])):
        w = jax.random.normal(k, (a, b), jnp.float32) * (2.0 / a) ** 0.5
        layers.append({"w": w.astype(config.dtype), "b": jnp.zeros((b,), config.dtype)})
    return {"layers": layers}


def logical_axes(config: MLPConfig) -> Dict:
    n = len(config.hidden) + 1
    return {"layers": [{"w": (None, "mlp"), "b": ("mlp",)} if i < n - 1
                       else {"w": ("mlp", None), "b": (None,)}
                       for i in range(n)]}


def forward(params: Dict, x: jax.Array, config: MLPConfig) -> jax.Array:
    h = x.astype(config.dtype)
    for i, layer in enumerate(params["layers"]):
        h = jnp.einsum("bd,df->bf", h, layer["w"], preferred_element_type=jnp.float32)
        h = (h + layer["b"].astype(jnp.float32)).astype(config.dtype)
        if i < len(params["layers"]) - 1:
            h = jax.nn.relu(h)
    return h


def classifier_loss(params: Dict, batch: Dict, config: MLPConfig):
    logits = forward(params, batch["x"], config).astype(jnp.float32)
    labels = batch["y"]
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, labels[:, None], axis=-1)[:, 0]
    return jnp.mean(logz - gold)
