"""SPMD training-step factory.

The TPU-native replacement for the reference's gradient path (torch DDP/NCCL
wired up by ``python/ray/train/torch/config.py:64-100`` — invisible to Ray,
SURVEY §3.4 step 5): here the whole update is ONE jitted XLA program over the
device mesh. Parameters/optimizer state carry NamedShardings derived from
logical axis rules; the batch is sharded on the data axes; XLA compiles in the
gradient reduce (psum over ``data``/``fsdp``) and any TP collectives. Nothing
to hand-schedule — layout drives the collectives.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from ray_tpu.parallel.mesh import Mesh
from ray_tpu.parallel.sharding import ShardingRules, logical_sharding, pytree_shardings


@dataclass
class TrainStepBundle:
    """Everything a Train worker needs to run sharded steps."""

    init: Callable[..., Tuple[Any, Any]]       # key -> (params, opt_state), sharded
    step: Callable[..., Tuple[Any, Any, Dict]]  # (params, opt, batch) -> (params, opt, metrics)
    param_shardings: Any
    opt_shardings: Any
    batch_sharding: Any
    mesh: Mesh


def make_train_step(
    *,
    loss_fn: Callable,              # (params, batch) -> scalar loss
    init_params_fn: Callable,       # (key) -> params
    logical_params: Any,            # pytree of logical axis tuples
    mesh: Mesh,
    rules: ShardingRules,
    optimizer: Optional[optax.GradientTransformation] = None,
    batch_logical: Tuple = ("batch", None),
    donate: bool = True,
) -> TrainStepBundle:
    """Build jitted, fully sharded (init, step) functions.

    ``loss_fn``/``init_params_fn`` must already close over model config (and
    mesh/rules if they use sharding constraints internally).
    """
    optimizer = optimizer or optax.adamw(3e-4)
    param_sh = pytree_shardings(logical_params, mesh, rules)
    batch_sh = logical_sharding(mesh, rules, batch_logical)
    repl = logical_sharding(mesh, rules, None)

    # Optimizer-state shardings mirror the params they track: any leaf of the
    # opt state with a param's shape gets that param's sharding (adam moments);
    # scalars (step counts) replicate.
    params_shape = jax.eval_shape(init_params_fn, jax.random.key(0))
    opt_shape = jax.eval_shape(optimizer.init, params_shape)
    flat_params = jax.tree.leaves_with_path(params_shape)
    flat_param_sh = {jax.tree_util.keystr(k): s for (k, _), s in zip(
        flat_params, jax.tree.leaves(param_sh, is_leaf=lambda x: hasattr(x, "spec")))}

    def opt_leaf_sharding(path, leaf):
        # Moment pytrees repeat the param tree structure under their own
        # prefix; match by the param-tree suffix of the path.
        key = jax.tree_util.keystr(path)
        for pkey, sh in flat_param_sh.items():
            if key.endswith(pkey) and len(pkey) > 0:
                return sh
        return repl

    opt_sh = jax.tree_util.tree_map_with_path(opt_leaf_sharding, opt_shape)

    @functools.partial(jax.jit, out_shardings=(param_sh, opt_sh))
    def init(key):
        params = init_params_fn(key)
        return params, optimizer.init(params)

    grad_fn = jax.value_and_grad(loss_fn)

    @functools.partial(
        jax.jit,
        in_shardings=(param_sh, opt_sh, batch_sh),
        out_shardings=(param_sh, opt_sh, repl),
        donate_argnums=(0, 1) if donate else (),
    )
    def step(params, opt_state, batch):
        loss, grads = grad_fn(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        gnorm = optax.global_norm(grads)
        return params, opt_state, {"loss": loss, "grad_norm": gnorm}

    return TrainStepBundle(
        init=init, step=step,
        param_shardings=param_sh, opt_shardings=opt_sh, batch_sharding=batch_sh,
        mesh=mesh,
    )


def make_eval_step(
    *,
    loss_fn: Callable,
    param_shardings: Any,
    batch_sharding: Any,
    mesh: Mesh,
):
    repl = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())

    @functools.partial(jax.jit, in_shardings=(param_shardings, batch_sharding),
                       out_shardings=repl)
    def eval_step(params, batch):
        return loss_fn(params, batch)

    return eval_step
